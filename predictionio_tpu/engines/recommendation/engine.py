"""Recommendation engine template: implicit/explicit ALS → top-N items.

Reference: examples/scala-parallel-recommendation (4 variants — DataSource
reads "rate"/"buy" events, custom-query/src/main/scala/DataSource.scala:24-80;
ALSAlgorithm.scala:50-120 delegates to MLlib ALS, predict = factor
dot-products + top-N; Serving = first).

TPU re-design: the DataSource reads one columnar EventFrame (no RDD), the
algorithm trains with models/als.py's batched-CG XLA program on ctx.mesh,
and the model keeps item factors device-resident so serving is one
matmul+top-k program per query batch.

Eval support mirrors the template's query/actual protocol: hold out each
fold's events per user; Query carries the user, Actual the held-out item
set (rated >= goal threshold).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.controller.metrics import OptionAverageMetric
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.store.event_store import EventStoreFacade
from predictionio_tpu.models import als
from predictionio_tpu.obs import devprof as _devprof


# -- query/result (reference Engine.scala of the template) ------------------


@dataclass
class Query:
    user: str
    num: int = 10
    # filter-by-category variant surface
    categories: Optional[list[str]] = None
    whitelist: Optional[list[str]] = None
    blacklist: Optional[list[str]] = None


@dataclass
class ItemScore:
    item: str
    score: float


@dataclass
class PredictedResult:
    item_scores: list[ItemScore] = field(default_factory=list)


@dataclass
class ActualResult:
    """Held-out relevant items for eval."""

    items: list[str] = field(default_factory=list)


# -- data source ------------------------------------------------------------


@dataclass
class DataSourceParams:
    app_name: str
    event_names: tuple[str, ...] = ("rate", "buy")
    rate_event: str = "rate"  # carries a "rating" property; others weight 1.0
    eval_k: int = 0  # >0 enables read_eval with k folds
    goal_threshold: float = 4.0  # rating >= threshold counts as relevant
    eval_num: int = 20  # top-N requested per eval query (≥ the metric's k)
    # read item $set properties for category filtering (the reference keeps
    # this in a separate filter-by-category variant; off by default so the
    # plain variant pays no extra event-store scan)
    read_item_categories: bool = False
    # cache the folded EventFrame keyed by (query, data version): repeated
    # trainings of an unchanged window skip the event scan+fold entirely
    # (data/view.py; reference DataView.scala:37-110)
    use_data_view: bool = False
    data_view_dir: Optional[str] = None  # default $PIO_FS_BASEDIR/view


@dataclass
class TrainingData(SanityCheck):
    rows: np.ndarray  # user idx per interaction
    cols: np.ndarray  # item idx
    vals: np.ndarray  # rating / implicit weight
    n_users: int
    n_items: int
    user_vocab: object  # BiMap str → int
    item_vocab: object
    # item row → category set, from item $set properties (reference
    # filter-by-category variant reads categories in its DataSource)
    item_categories: Optional[list[frozenset]] = None

    def sanity_check(self) -> None:
        if len(self.rows) == 0:
            raise ValueError(
                "no interaction events found (check appName/eventNames)"
            )
        if not np.isfinite(self.vals).all():
            raise ValueError("non-finite interaction values")


@dataclass
class EvalInfo:
    fold: int


class RecommendationDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params

    def _frame(self, ctx: RuntimeContext):
        frame_kwargs = dict(
            app_name=self.params.app_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(self.params.event_names),
            value_prop="rating",
            default_value=1.0,
        )
        if self.params.use_data_view:
            from predictionio_tpu.data.view import DataView

            frame = DataView(self.params.data_view_dir).find_frame(
                ctx.storage, **frame_kwargs
            )
        else:
            frame = EventStoreFacade(ctx.storage).find_frame(**frame_kwargs)
        # only the rate event carries a rating payload; every other
        # interaction type ("buy", "view"…) weighs 1.0 even if it happens
        # to have a "rating" property (reference custom-query DataSource
        # maps rate→rating, others→1)
        rate_code = frame.event_vocab.get(self.params.rate_event, -2)
        import dataclasses as _dc

        return _dc.replace(
            frame,
            value=np.where(frame.event_code == rate_code, frame.value, 1.0).astype(
                np.float32
            ),
        )

    def _item_categories(
        self, ctx: RuntimeContext, item_vocab
    ) -> Optional[list[frozenset]]:
        if not self.params.read_item_categories:
            return None
        store = EventStoreFacade(ctx.storage)
        props = store.aggregate_properties(
            app_name=self.params.app_name, entity_type="item"
        )
        if not props:
            return None
        out: list[frozenset] = [frozenset()] * len(item_vocab)
        for item_id, pmap in props.items():
            row = item_vocab.get(item_id)
            if row is not None:
                cats = pmap.get_opt("categories", list) or []
                out[row] = frozenset(cats)
        return out

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        frame = self._frame(ctx)
        rows, cols, vals = frame.interactions(dedupe="sum")
        return TrainingData(
            rows=rows,
            cols=cols,
            vals=vals,
            n_users=frame.n_entities,
            n_items=frame.n_targets,
            user_vocab=frame.entity_vocab,
            item_vocab=frame.target_vocab,
            item_categories=self._item_categories(ctx, frame.target_vocab),
        )

    def read_eval(self, ctx: RuntimeContext):
        """k-fold split by interaction index (reference e2
        CrossValidation.splitData:21 — fold = idx mod k)."""
        k = self.params.eval_k
        if k <= 0:
            raise ValueError("eval requires datasource params eval_k > 0")
        frame = self._frame(ctx)
        rows, cols, vals = frame.interactions(dedupe="sum")
        idx = np.arange(len(rows))
        inv_user = frame.entity_vocab.inverse()
        inv_item = frame.target_vocab.inverse()
        out = []
        for fold in range(k):
            test_mask = idx % k == fold
            td = TrainingData(
                rows=rows[~test_mask],
                cols=cols[~test_mask],
                vals=vals[~test_mask],
                n_users=frame.n_entities,
                n_items=frame.n_targets,
                user_vocab=frame.entity_vocab,
                item_vocab=frame.target_vocab,
            )
            qa = []
            t_rows, t_cols, t_vals = (
                rows[test_mask], cols[test_mask], vals[test_mask],
            )
            for u in np.unique(t_rows):
                m = (t_rows == u) & (t_vals >= self.params.goal_threshold)
                relevant = [inv_item(int(c)) for c in t_cols[m]]
                if relevant:
                    qa.append(
                        (
                            Query(
                                user=inv_user(int(u)),
                                num=self.params.eval_num,
                            ),
                            ActualResult(relevant),
                        )
                    )
            out.append((td, EvalInfo(fold=fold), qa))
        return out


# -- algorithm --------------------------------------------------------------


@dataclass
class ALSAlgorithmParams:
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    implicit_prefs: bool = True
    cg_iterations: int = 3
    seed: int = 3
    # > 0: snapshot factor state into MODELDATA every N iterations so an
    # interrupted train resumes (workflow/checkpoint.py); 0 disables
    checkpoint_every: int = 0
    # warm-start retrains from the variant's LIVE registry version
    # (ISSUE 9): the parent's factors are mapped onto the new vocab so a
    # periodic retrain reconverges WITH the online fold-in stream
    # instead of re-deriving everything from random init
    warm_start: bool = False
    # sharded serving (ISSUE 10): with > 1 visible device, keep factor
    # state row-sharded across a serving mesh (fleet.ShardedRuntime) so
    # the catalog can exceed one chip's HBM; recommend lowers as local
    # top-k per shard + global merge. Off by default — single-chip
    # serving keeps the PR-2 resident-matrix path.
    shard_serving: bool = False
    # serving dtype (ISSUE 11/14): "int8" quantizes BOTH factor
    # matrices per-row at model publish/fold-in (~1/3 the resident
    # bytes and factor stream; int8xint8->int32 scoring, scale-product
    # dequant in registers); "bf16" (ISSUE 14) is the middle ground —
    # half the bytes, bf16xbf16->f32 scoring. Scores shift by the
    # quantization/rounding error (~1% relative for int8 at serving
    # rank — see tests/test_recommend_pallas.py bounds), so both are
    # explicit opt-ins; "f32" keeps exact scoring. Applies to the
    # single-device staged state AND the sharded tier (ISSUE 14
    # brought ShardedRuntime to dtype parity).
    serve_dtype: str = "f32"


class ALSModel:
    """Trained factors + device-resident factor matrices for serving
    (reference template ALSModel.scala persists factor RDDs; here the
    serving-side copies live in HBM across queries)."""

    def __init__(
        self,
        factors: als.ALSFactors,
        item_categories: Optional[list[frozenset]] = None,
        serve_dtype: str = "f32",
    ):
        self.factors = factors
        self.item_categories = item_categories
        self.serve_dtype = serve_dtype
        self._serving_state = None  # als.ServingFactors when staged
        self._sharded_runtime = None  # fleet.ShardedRuntime when active
        self._stage_lock = threading.Lock()

    # device caches + lock are serving state, not part of the pickled model
    def __getstate__(self):
        return {
            "factors": self.factors,
            "item_categories": self.item_categories,
            "serve_dtype": self.serve_dtype,
        }

    def __setstate__(self, state):
        self.__init__(
            state["factors"],
            state.get("item_categories"),
            state.get("serve_dtype", "f32"),
        )

    def serving_state(self):
        """The staged serving-side factor state (ISSUE 11): pad-aligned
        for the fused recommend+top-k kernel, int8-quantized when
        serve_dtype opts in, resident across calls. Staged lazily under
        the stage lock (pipelined batches must not double-stage)."""
        with self._stage_lock:
            if self._serving_state is None:
                self._serving_state = als.stage_serving(
                    self.factors, serve_dtype=self.serve_dtype
                )
            return self._serving_state

    def adopt_serving(self, old_state, dirty_users=None, dirty_items=None):
        """Fold-in publish hook (online/foldin.py:_clone_model): carry
        the predecessor's staged serving state by publishing ONLY the
        tick's dirty rows device-side (quantize-at-fold-in for int8) —
        copy-on-write off shared buffers, donated into grown private
        ones — instead of re-staging a factor matrix per tick. Any
        failure leaves the state unstaged; the next query restages."""
        if old_state is None:
            return
        try:
            n_users = self.factors.user_factors.shape[0]
            n_items = self.factors.item_factors.shape[0]
            ur, uv = dirty_users if dirty_users is not None else (None, None)
            ir, iv = dirty_items if dirty_items is not None else (None, None)
            # a side that changed without row attribution cannot be
            # expressed as row writes — leave unstaged (lazy restage)
            if dirty_users is None and n_users != old_state.n_users:
                return
            if dirty_items is None and n_items != old_state.n_items:
                return
            self._serving_state = als.serving_publish_rows(
                old_state,
                user_rows=ur, user_vals=uv,
                item_rows=ir, item_vals=iv,
                n_users=n_users, n_items=n_items,
            )
        except Exception:
            self._serving_state = None

    def sharded_runtime(self):
        """The fleet sharded serving state, staged lazily on first use
        (ISSUE 10) via the shared `fleet.stage_serving_runtime` helper
        (>= 2 visible devices; PIO_SERVE_HBM_BYTES per-device budget).
        The single-device outcome is cached as False so the serving hot
        path doesn't re-probe jax.devices() under the lock per batch."""
        with self._stage_lock:
            if self._sharded_runtime is False:
                return None
            if self._sharded_runtime is None:
                from predictionio_tpu.fleet import stage_serving_runtime

                self._sharded_runtime = stage_serving_runtime(
                    self.factors.user_factors,
                    self.factors.item_factors,
                    user_vocab=self.factors.user_vocab,
                    item_vocab=self.factors.item_vocab,
                    params=self.factors.params,
                    # the sharded tier honors the model's serve dtype
                    # (ISSUE 14): int8/bf16 slabs per shard
                    serve_dtype=self.serve_dtype,
                )
                if self._sharded_runtime is False:
                    return None
            return self._sharded_runtime

    def adopt_sharded(self, old_runtime, dirty_users=None, dirty_items=None):
        """Fold-in publish hook for the SHARDED tier (ISSUE 14,
        direction-1 item (c)): carry the predecessor's resident sharded
        state by publishing ONLY the tick's dirty rows through
        `ShardedRuntime.update_*_rows` — re-quantizing just those rows
        and donating the slab once in-flight readers drain — instead of
        re-staging f32 factor matrices per tick. Rows beyond the padded
        shard extent (vocab growth) leave the state unstaged; the next
        query rebuilds lazily (the amortized-growth contract)."""
        if old_runtime is None or old_runtime is False:
            return
        # validate BOTH sides BEFORE mutating either: the runtime is
        # shared in place with the still-serving predecessor, so a
        # user-side write followed by an item-side growth refusal would
        # leave the LIVE state half-updated with no rollback
        for side, dirty in (("user", dirty_users), ("item", dirty_items)):
            if dirty is not None and not old_runtime.rows_within_extent(
                side, dirty[0]
            ):
                return  # vocab grew past the padded extent: lazy restage
        try:
            if dirty_users is not None:
                ur, uv = dirty_users
                if len(ur):
                    old_runtime.update_user_rows(
                        ur, uv,
                        # within-pad growth must raise the live extent
                        # or the grown rows stay masked dead (the
                        # single-device publish's n_users/n_items twin)
                        n_users=self.factors.user_factors.shape[0],
                    )
            if dirty_items is not None:
                ir, iv = dirty_items
                if len(ir):
                    old_runtime.update_item_rows(
                        ir, iv,
                        n_items=self.factors.item_factors.shape[0],
                    )
            self._sharded_runtime = old_runtime
        except Exception:
            import logging as _logging

            _logging.getLogger(__name__).exception(
                "sharded dirty-row publish failed mid-carry; the "
                "runtime may be half-updated — dropping the carry so "
                "the next query restages from the folded factors"
            )
            self._sharded_runtime = None

    def sharded_info(self) -> Optional[dict]:
        """Shard layout for the server's fleet status (None when the
        sharded tier is not staged)."""
        srt = self._sharded_runtime
        return srt.info() if srt else None  # None or the False sentinel

    def resident_device_bytes(self) -> float:
        """Per-device HBM footprint for the tenant cache's budget
        (tenancy/cache.py walks to this hook): one SHARD when serving
        sharded — the whole point of the fleet tier is that no chip
        holds the catalog — else the factor matrices once (the staged
        device copies mirror the host arrays 1:1, so counting the
        host mirrors AND the copies would double-charge)."""
        srt = self._sharded_runtime
        if srt:
            return float(srt.device_bytes()["per_shard"])
        sv = self._serving_state
        if sv is not None:
            # the staged (possibly int8) state is the resident copy —
            # int8 serving genuinely halves the cache charge
            return sv.device_nbytes()
        return float(
            self.factors.user_factors.nbytes
            + self.factors.item_factors.nbytes
        )



class ALSAlgorithm(Algorithm):
    def __init__(self, params: ALSAlgorithmParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> ALSModel:
        from predictionio_tpu.workflow.checkpoint import (
            CheckpointManager,
            train_als_checkpointed,
        )

        als_params = als.ALSParams(
            rank=self.params.rank,
            iterations=self.params.num_iterations,
            lambda_=self.params.lambda_,
            alpha=self.params.alpha,
            implicit_prefs=self.params.implicit_prefs,
            cg_iterations=self.params.cg_iterations,
            seed=self.params.seed,
        )
        manager = None
        if (
            self.params.checkpoint_every > 0
            and ctx.storage is not None
            and ctx.instance_id
        ):
            manager = CheckpointManager(ctx.storage, ctx.instance_id)
        factors = train_als_checkpointed(
            pd.rows,
            pd.cols,
            pd.vals,
            pd.n_users,
            pd.n_items,
            als_params,
            manager,
            self.params.checkpoint_every,
            user_vocab=pd.user_vocab,
            item_vocab=pd.item_vocab,
            mesh=ctx.mesh,
            init_factors=self._warm_start_init(ctx, pd, als_params),
        )
        return ALSModel(
            factors,
            item_categories=pd.item_categories,
            serve_dtype=getattr(self.params, "serve_dtype", "f32"),
        )

    def _warm_start_init(self, ctx: RuntimeContext, pd: TrainingData,
                         als_params: als.ALSParams):
        """Parent-version factors mapped onto the new vocab (ISSUE 9):
        resolved through the registry lineage — the variant's live
        version is exactly the `parent_version` this train's new record
        will point at. Best-effort: any failure falls back to the cold
        random init."""
        if not self.params.warm_start or ctx.storage is None:
            return None
        try:
            if not ctx.instance_id:
                return None
            inst = ctx.storage.get_meta_data_engine_instances().get(
                ctx.instance_id
            )
            if inst is None:
                return None
            from predictionio_tpu.deploy.registry import ModelRegistry

            live = ModelRegistry(ctx.storage).live_version(
                inst.engine_id, inst.engine_variant
            )
            if live is None:
                return None
            blob = ctx.storage.get_model_data_models().get(live.instance_id)
            if blob is None:
                return None
            from predictionio_tpu.controller.persistent import (
                deserialize_models,
            )

            parent = next(
                (
                    m.factors for m in deserialize_models(blob.models)
                    if hasattr(m, "factors")
                ),
                None,
            )
            if parent is None or parent.params.rank != als_params.rank:
                return None
            import logging as _logging

            _logging.getLogger(__name__).info(
                "warm-starting train from live version %s", live.id
            )
            return als.warm_start_factors(
                parent, pd.user_vocab, pd.item_vocab, als_params
            )
        except Exception:
            import logging as _logging

            _logging.getLogger(__name__).warning(
                "warm start unavailable; using cold init", exc_info=True
            )
            return None

    def train_grid(
        self, ctx: RuntimeContext, pd: TrainingData, params_list
    ) -> list[ALSModel]:
        """A tuning grid trained as batched device programs sharing ONE
        staging (Engine.batch_eval's grid-batched path; VERDICT r3 #6).
        λ/α batch within a launch; rank/iterations/… group into
        per-shape launches over the same staged data (VERDICT r4 #7).
        The serial fallback only remains for eligibility edge cases."""
        als_list = [
            als.ALSParams(
                rank=p.rank,
                iterations=p.num_iterations,
                lambda_=p.lambda_,
                alpha=p.alpha,
                implicit_prefs=p.implicit_prefs,
                cg_iterations=p.cg_iterations,
                seed=p.seed,
            )
            for p in params_list
        ]
        try:
            grid = als.train_grid(
                pd.rows, pd.cols, pd.vals, pd.n_users, pd.n_items,
                als_list, user_vocab=pd.user_vocab, item_vocab=pd.item_vocab,
            )
        except ValueError:  # heterogeneous statics: train serially
            grid = [
                als.train(
                    pd.rows, pd.cols, pd.vals, pd.n_users, pd.n_items, p,
                    user_vocab=pd.user_vocab, item_vocab=pd.item_vocab,
                )
                for p in als_list
            ]
        return [
            ALSModel(
                f,
                item_categories=pd.item_categories,
                serve_dtype=getattr(self.params, "serve_dtype", "f32"),
            )
            for f in grid
        ]

    # -- serving -----------------------------------------------------------
    def warmup(self, model: ALSModel) -> None:
        """Pre-compile the serving programs + stage factors into HBM so the
        first live queries don't pay XLA compile (deploy server calls this
        at build_runtime; reference has no analogue — JVM serving had no
        compile step). Warms the single-query and micro-batch bucket
        shapes."""
        if model.factors.user_factors.shape[0] == 0:
            return
        vocab_ids = list(model.factors.user_vocab.to_dict())
        if not vocab_ids:
            return
        for batch in (1, 8, 64):  # the full serving bucket ladder
            # nomask program
            self._predict_batch(
                model, [Query(user=vocab_ids[0], num=10)] * batch
            )
            # masked program (filters allocate the exclusion-mask variant)
            self._predict_batch(
                model,
                [Query(user=vocab_ids[0], num=10, blacklist=["__warmup__"])]
                * batch,
            )

    def _exclusion_mask(
        self, model: ALSModel, queries: Sequence[Query]
    ) -> Optional[np.ndarray]:
        """Category/white/black-list filters → per-query item mask
        (True = exclude)."""
        if not any(q.whitelist or q.blacklist or q.categories for q in queries):
            return None
        vocab = model.factors.item_vocab
        n_items = model.factors.item_factors.shape[0]
        mask = np.zeros((len(queries), n_items), dtype=bool)
        for qi, q in enumerate(queries):
            # three independent exclusions, OR-ed (an item must pass ALL
            # configured filters, matching the reference variant semantics)
            if q.categories:
                if model.item_categories is None:
                    raise ValueError(
                        "query filters by categories but no item category "
                        "properties were found at train time"
                    )
                wanted = set(q.categories)
                no_overlap = np.fromiter(
                    (not (cats & wanted) for cats in model.item_categories),
                    dtype=bool,
                    count=n_items,
                )
                mask[qi] |= no_overlap
            if q.whitelist is not None:
                not_listed = np.ones(n_items, dtype=bool)
                for it in q.whitelist:
                    ix = vocab.get(it)
                    if ix is not None:
                        not_listed[ix] = False
                mask[qi] |= not_listed
            if q.blacklist:
                for it in q.blacklist:
                    ix = vocab.get(it)
                    if ix is not None:
                        mask[qi, ix] = True
        return mask

    def _exclusion_args(
        self, model: ALSModel, queries: Sequence[Query]
    ) -> tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        """(dense mask, row list) — exactly one is set when any filter
        applies. The common small-blacklist case ships a (B, E) int32
        ROW LIST (ISSUE 14): a handful of ids per query instead of an
        n_items-wide mask — the serving layer feeds it straight to the
        fused kernel's row-list input (or bit-packs it at 1/32 the f32
        bytes). Category/whitelist filters — which invert to most-of-
        the-catalog exclusions — keep the dense mask, packed downstream."""
        from predictionio_tpu.ops.recommend_pallas import (
            ROWLIST_MAX,
            rowlist_np,
        )

        if not any(
            q.whitelist or q.blacklist or q.categories for q in queries
        ):
            return None, None
        if any(q.whitelist is not None or q.categories for q in queries):
            return self._exclusion_mask(model, queries), None
        vocab = model.factors.item_vocab
        lists: list[list[int]] = []
        for q in queries:
            rows = [
                ix for it in (q.blacklist or [])
                if (ix := vocab.get(it)) is not None
            ]
            lists.append(rows)
        if max(len(r) for r in lists) > ROWLIST_MAX:
            return self._exclusion_mask(model, queries), None
        # the shared row-list wire convention (pow2 width, -1 pad)
        # lives in ops/recommend_pallas.py — one owner, no drift
        return None, rowlist_np(lists)

    def _predict_batch(
        self, model: ALSModel, queries: Sequence[Query]
    ) -> list[PredictedResult]:
        vocab = model.factors.user_vocab
        known = [(i, vocab.get(q.user)) for i, q in enumerate(queries)]
        known_ix = [(i, u) for i, u in known if u is not None]
        results: list[PredictedResult] = [PredictedResult() for _ in queries]
        if not known_ix:
            return results
        # fixed device-side k (pow2-bucketed above a floor) so q.num does
        # NOT create a new compiled program per distinct value — warmup can
        # actually cover live traffic; results are sliced to num on host
        n_items = model.factors.item_factors.shape[0]
        from predictionio_tpu.utils.bucket import batch_bucket, topk_bucket

        k_req = min(max(q.num for q in queries), n_items)
        k = topk_bucket(k_req, n_items)
        user_rows = np.array([u for _, u in known_ix], dtype=np.int64)
        full_mask, full_rows = self._exclusion_args(model, queries)
        keep = [i for i, _ in known_ix]
        sub_mask = full_mask[keep] if full_mask is not None else None
        sub_rows = full_rows[keep] if full_rows is not None else None
        n_real = len(user_rows)
        bucket = batch_bucket(n_real)
        if bucket != n_real:
            user_rows = np.concatenate(
                [user_rows, np.zeros(bucket - n_real, dtype=np.int64)]
            )
            if sub_mask is not None:
                sub_mask = np.concatenate(
                    [sub_mask, np.zeros((bucket - n_real, sub_mask.shape[1]), bool)]
                )
            if sub_rows is not None:
                sub_rows = np.concatenate([
                    sub_rows,
                    np.full(
                        (bucket - n_real, sub_rows.shape[1]), -1, np.int32
                    ),
                ])
        # padding-waste accounting (ISSUE 3) lives HERE, at the pad site:
        # this is the only place that knows both the live row count
        # (vocab-known users, not the micro-batch's group size) and the
        # bucket the device program actually ran at
        prof0 = _devprof.snapshot()
        srt = (
            model.sharded_runtime()
            if getattr(self.params, "shard_serving", False)
            else None
        )
        if srt is not None:
            # fleet sharded path (ISSUE 10): local top-k per shard +
            # global merge; factor state stays row-sharded in HBM
            scores, items = srt.recommend(
                user_rows, k, exclude_mask=sub_mask,
                exclude_rows=sub_rows,
            )
        else:
            # staged serving state (ISSUE 11/14): fused one-pass kernel
            # where the lowering runs, int8/bf16 when the params opt
            # in, exclusion as a row list or packed bit words — never
            # an f32 mask — and resident factor state either way
            scores, items = als.recommend_serving(
                model.serving_state(), user_rows, k,
                exclude_mask=sub_mask, exclude_rows=sub_rows,
            )
        _devprof.record_batch_padding(
            n_real, bucket, flops=_devprof.snapshot().flops - prof0.flops
        )
        scores, items = scores[:n_real], items[:n_real]
        inv = model.factors.item_vocab.inverse()
        from predictionio_tpu.ops.topk import NEG_INF

        for row, (qi, _u) in enumerate(known_ix):
            n = min(queries[qi].num, k)
            item_scores = [
                ItemScore(item=inv(int(ix)), score=float(s))
                for s, ix in zip(scores[row][:n], items[row][:n])
                if s > NEG_INF / 2
            ]
            results[qi] = PredictedResult(item_scores=item_scores)
        return results

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        return self._predict_batch(model, [query])[0]

    def batch_predict(self, ctx, model: ALSModel, queries):
        preds = self._predict_batch(model, [q for _, q in queries])
        return [(qx, p) for (qx, _q), p in zip(queries, preds)]


# -- evaluation -------------------------------------------------------------


class PrecisionAtK(OptionAverageMetric):
    """|top-k ∩ relevant| / k, averaged over users with relevant items
    (the standard tuning metric for the recommendation template)."""

    def __init__(self, k: int = 10):
        self.k = k

    def header(self) -> str:
        return f"Precision@{self.k}"

    def calculate_one(self, q: Query, p: PredictedResult, a: ActualResult):
        if not a.items:
            return None
        top = {s.item for s in p.item_scores[: self.k]}
        return len(top & set(a.items)) / self.k


# -- engine factory ---------------------------------------------------------


class RecommendationEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            RecommendationDataSource,
            IdentityPreparator,
            {"als": ALSAlgorithm},
            FirstServing,
        )


# ---------------------------------------------------------------------------
# Custom (foreign-store) data source — the DataSource SPI demo
# ---------------------------------------------------------------------------


@dataclass
class FileDataSourceParams:
    filepath: str
    delimiter: str = "::"  # MovieLens ratings.dat convention


class FileRatingsDataSource(DataSource):
    """The DataSource SPI against a FOREIGN store: `user::item::rating`
    lines from a delimited text file, no event store involved.

    Reference: examples/experimental/
    scala-parallel-recommendation-custom-datasource/DataSource.scala:24-33
    (sc.textFile + split, swapped into the stock recommendation engine) —
    the demo that the DASE contract only requires `read_training`, not
    the framework's own storage. The mongo-datasource experimental demo
    plays the same role against MongoDB; any `read_training` returning
    TrainingData slots into the engine identically."""

    def __init__(self, params: FileDataSourceParams):
        self.params = params

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        from predictionio_tpu.data.store.bimap import BiMap

        users: dict[str, int] = {}
        items: dict[str, int] = {}
        rows, cols, vals = [], [], []
        with open(self.params.filepath) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                parts = line.split(self.params.delimiter)
                if len(parts) < 3:
                    raise ValueError(
                        f"bad ratings line (want user{self.params.delimiter}"
                        f"item{self.params.delimiter}rating): {line!r}"
                    )
                u, i, r = parts[0], parts[1], float(parts[2])
                rows.append(users.setdefault(u, len(users)))
                cols.append(items.setdefault(i, len(items)))
                vals.append(r)
        return TrainingData(
            rows=np.asarray(rows, np.int32),
            cols=np.asarray(cols, np.int32),
            vals=np.asarray(vals, np.float32),
            n_users=len(users),
            n_items=len(items),
            user_vocab=BiMap(users),
            item_vocab=BiMap(items),
        )


class FileRecommendationEngine(EngineFactory):
    """The stock recommendation engine with the file-backed DataSource
    swapped in — everything downstream (ALS, serving, deploy) unchanged."""

    def apply(self) -> Engine:
        return Engine(
            FileRatingsDataSource,
            IdentityPreparator,
            {"als": ALSAlgorithm},
            FirstServing,
        )
