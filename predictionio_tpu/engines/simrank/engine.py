"""SimRank engine: graph-structural friend/node recommendation.

Reference: examples/experimental/scala-parallel-friend-recommendation —
SimRankAlgorithm.scala + DeltaSimRankRDD.scala compute SimRank over the
(subsampled, Sampling.scala) social graph and answer (user, user) /
top-similar queries. Here the graph comes from relation events between
entities of one type ("follow"/"friend"), the similarity matrix is the
dense MXU iteration in models/simrank.py, and serving reads rows of the
trained matrix.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.store.bimap import BiMap
from predictionio_tpu.data.store.event_store import EventStoreFacade
from predictionio_tpu.models import simrank


@dataclass
class Query:
    user: str
    user2: Optional[str] = None  # pair query: similarity of (user, user2)
    num: int = 10  # top-N query when user2 is absent


@dataclass
class UserScore:
    user: str
    score: float


@dataclass
class PredictedResult:
    user_scores: list[UserScore] = field(default_factory=list)
    similarity: Optional[float] = None  # set on pair queries


@dataclass
class DataSourceParams:
    app_name: str
    event_names: tuple[str, ...] = ("follow",)
    entity_type: str = "user"
    # dense SimRank is O(N²) memory; refuse graphs beyond this size the
    # same way the reference demo SUBSAMPLES its graph (Sampling.scala)
    max_nodes: int = 20_000


@dataclass
class TrainingData(SanityCheck):
    src: np.ndarray  # (E,) node idx
    dst: np.ndarray  # (E,)
    node_vocab: BiMap

    def sanity_check(self) -> None:
        if len(self.src) == 0:
            raise ValueError("no relation events found")


class SimRankDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        frame = EventStoreFacade(ctx.storage).find_frame(
            app_name=self.params.app_name,
            entity_type=self.params.entity_type,
            event_names=list(self.params.event_names),
        )
        mask = frame.target_idx >= 0
        # one shared node space: source entities and target entities are
        # both users — merge the two vocabularies
        vocab = dict(frame.entity_vocab.to_dict())
        for name, _ix in frame.target_vocab.to_dict().items():
            if name not in vocab:
                vocab[name] = len(vocab)
        if len(vocab) > self.params.max_nodes:
            raise ValueError(
                f"graph has {len(vocab)} nodes > max_nodes="
                f"{self.params.max_nodes}; dense SimRank is O(N²) — "
                "subsample upstream (reference Sampling.scala does the same)"
            )
        node_vocab = BiMap(vocab)
        inv_e = frame.entity_vocab.inverse()
        inv_t = frame.target_vocab.inverse()
        src = np.asarray(
            [vocab[inv_e(int(i))] for i in frame.entity_idx[mask]],
            dtype=np.int64,
        )
        dst = np.asarray(
            [vocab[inv_t(int(i))] for i in frame.target_idx[mask]],
            dtype=np.int64,
        )
        return TrainingData(src=src, dst=dst, node_vocab=node_vocab)


@dataclass
class SimRankAlgorithmParams:
    iterations: int = 5
    decay: float = 0.8  # DeltaSimRankRDD.scala:15 default


class SimRankAlgorithm(Algorithm):
    def __init__(self, params: SimRankAlgorithmParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> simrank.SimRankModel:
        return simrank.compute(
            pd.src, pd.dst, len(pd.node_vocab),
            iterations=self.params.iterations,
            decay=self.params.decay,
            node_vocab=pd.node_vocab,
        )

    def predict(
        self, model: simrank.SimRankModel, query: Query
    ) -> PredictedResult:
        ix = model.node_vocab.get(query.user)
        if ix is None:
            return PredictedResult()
        if query.user2 is not None:
            jx = model.node_vocab.get(query.user2)
            sim = float(model.scores[ix, jx]) if jx is not None else 0.0
            return PredictedResult(similarity=sim)
        vals, idx = model.top_k(int(ix), query.num)
        inv = model.node_vocab.inverse()
        return PredictedResult(
            user_scores=[
                UserScore(user=inv(int(j)), score=float(v))
                for v, j in zip(vals, idx)
                if v > 0.0
            ]
        )


class SimRankEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            SimRankDataSource,
            IdentityPreparator,
            {"simrank": SimRankAlgorithm},
            FirstServing,
        )
