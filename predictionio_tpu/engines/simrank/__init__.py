from predictionio_tpu.engines.simrank.engine import SimRankEngine

__all__ = ["SimRankEngine"]
