from predictionio_tpu.engines.ecommerce.engine import (
    ECommAlgorithm,
    ECommAlgorithmParams,
    ECommerceEngine,
    ECommerceDataSource,
    DataSourceParams,
    ItemScore,
    PredictedResult,
    Query,
)

__all__ = [
    "DataSourceParams",
    "ECommAlgorithm",
    "ECommAlgorithmParams",
    "ECommerceDataSource",
    "ECommerceEngine",
    "ItemScore",
    "PredictedResult",
    "Query",
]
