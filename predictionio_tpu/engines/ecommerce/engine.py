"""E-commerce recommendation template: ALS + serving-time business rules.

Reference: examples/scala-parallel-ecommercerecommendation
(train-with-rate-event, weighted-items variants) — ALS via P2LAlgorithm
with local factor maps; the serving path reads the event store LIVE:
`unseenOnly`/`seenEvents` filters out items the user already interacted
with, an "unavailableItems" constraint entity blocks out-of-stock items,
plus white/black lists (train-with-rate-event/src/main/scala/
ALSAlgorithm.scala:153-221). Unknown users fall back to recently-viewed
items' similarity (predictKnownUser vs predictSimilar paths).

TPU re-design: factors train on device (models/als.py); business-rule
masks are tiny host vectors folded into the masked top-k program."""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.store.event_store import EventStoreFacade
from predictionio_tpu.models import als, ranking

log = logging.getLogger(__name__)


@dataclass
class Query:
    user: str
    num: int = 10
    categories: Optional[list[str]] = None
    whitelist: Optional[list[str]] = None
    blacklist: Optional[list[str]] = None


@dataclass
class ItemScore:
    item: str
    score: float


@dataclass
class PredictedResult:
    item_scores: list[ItemScore] = field(default_factory=list)


@dataclass
class DataSourceParams:
    app_name: str
    # events used as training interactions, with per-event weights; the
    # rate event uses its "rating" property as weight (the
    # train-with-rate-event variant)
    event_names: tuple[str, ...] = ("view", "buy", "rate")
    rate_event: str = "rate"


@dataclass
class TrainingData(SanityCheck):
    rows: np.ndarray
    cols: np.ndarray
    vals: np.ndarray
    n_users: int
    n_items: int
    user_vocab: object
    item_vocab: object
    item_categories: Optional[list[frozenset]] = None

    def sanity_check(self) -> None:
        if len(self.rows) == 0:
            raise ValueError("no interaction events found")


class ECommerceDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        store = EventStoreFacade(ctx.storage)
        frame = store.find_frame(
            app_name=self.params.app_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(self.params.event_names),
            value_prop="rating",
            default_value=1.0,
        )
        import dataclasses as _dc

        rate_code = frame.event_vocab.get(self.params.rate_event, -2)
        frame = _dc.replace(
            frame,
            value=np.where(
                frame.event_code == rate_code, frame.value, 1.0
            ).astype(np.float32),
        )
        rows, cols, vals = frame.interactions(dedupe="sum")
        # item categories from $set properties for category filtering
        props = store.aggregate_properties(
            app_name=self.params.app_name, entity_type="item"
        )
        cats: Optional[list[frozenset]] = None
        if props:
            cats = [frozenset()] * frame.n_targets
            for item_id, pmap in props.items():
                row = frame.target_vocab.get(item_id)
                if row is not None:
                    cats[row] = frozenset(pmap.get_opt("categories", list) or [])
        return TrainingData(
            rows=rows, cols=cols, vals=vals,
            n_users=frame.n_entities, n_items=frame.n_targets,
            user_vocab=frame.entity_vocab, item_vocab=frame.target_vocab,
            item_categories=cats,
        )


# -- algorithm --------------------------------------------------------------


@dataclass
class ECommAlgorithmParams:
    app_name: str
    unseen_only: bool = False
    seen_events: tuple[str, ...] = ("view", "buy")
    similar_events: tuple[str, ...] = ("view",)  # unknown-user fallback basis
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int = 3


class ECommModel:
    def __init__(
        self,
        factors: als.ALSFactors,
        item_categories: Optional[list[frozenset]],
    ):
        self.factors = factors
        self.item_categories = item_categories
        self._normed = None

    def __getstate__(self):
        return {"factors": self.factors, "item_categories": self.item_categories}

    def __setstate__(self, state):
        self.factors = state["factors"]
        self.item_categories = state["item_categories"]
        self._normed = None

    def normed_item_factors(self) -> np.ndarray:
        if self._normed is None:
            self._normed = ranking.l2_normalize(self.factors.item_factors)
        return self._normed

    def category_index(self) -> dict:
        """category → sorted item-index array, built once per deploy —
        query-time category filtering is then a sparse candidate union
        instead of an O(I) per-query scan."""
        cached = getattr(self, "_cat_index", None)
        if cached is None:
            cached = {}
            for ix, cats in enumerate(self.item_categories or []):
                for c in cats:
                    cached.setdefault(c, []).append(ix)
            cached = {
                c: np.asarray(v, dtype=np.int64) for c, v in cached.items()
            }
            self._cat_index = cached
        return cached


class ECommAlgorithm(Algorithm):
    def __init__(self, params: ECommAlgorithmParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> ECommModel:
        factors = als.train(
            pd.rows, pd.cols, pd.vals, pd.n_users, pd.n_items,
            als.ALSParams(
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                lambda_=self.params.lambda_,
                alpha=self.params.alpha,
                implicit_prefs=True,
                seed=self.params.seed,
            ),
            user_vocab=pd.user_vocab,
            item_vocab=pd.item_vocab,
            mesh=ctx.mesh,
        )
        return ECommModel(factors, pd.item_categories)

    # -- serving-time event-store reads (reference ALSAlgorithm.scala:153) --
    def _seen_items(self, ctx: RuntimeContext, user: str) -> set[str]:
        store = EventStoreFacade(ctx.storage)
        try:
            events = store.find_by_entity(
                app_name=self.params.app_name,
                entity_type="user",
                entity_id=user,
                event_names=list(self.params.seen_events),
                target_entity_type="item",
                limit=None,
            )
            return {
                e.target_entity_id for e in events if e.target_entity_id
            }
        except Exception:
            log.exception("seen-items lookup failed; serving unfiltered")
            return set()

    def _unavailable_items(self, ctx: RuntimeContext) -> set[str]:
        """Constraint entity: $set of "items" on
        (entityType=constraint, entityId=unavailableItems) — reference
        ALSAlgorithm.scala reads the latest constraint at query time."""
        store = EventStoreFacade(ctx.storage)
        try:
            app_id, _ = store.app_name_to_id(self.params.app_name)
            pmap = ctx.storage.get_events().aggregate_properties_of_entity(
                app_id, "constraint", "unavailableItems"
            )
            if pmap is None:
                return set()
            return set(pmap.get_opt("items", list) or [])
        except Exception:
            log.exception("unavailable-items lookup failed; ignoring")
            return set()

    def _recent_item_rows(self, ctx: RuntimeContext, user: str, model) -> list[int]:
        """Unknown-user basis: their recent `similar_events` items
        (reference predictSimilar path)."""
        store = EventStoreFacade(ctx.storage)
        try:
            events = store.find_by_entity(
                app_name=self.params.app_name,
                entity_type="user",
                entity_id=user,
                event_names=list(self.params.similar_events),
                target_entity_type="item",
                limit=10,
                latest=True,
            )
            vocab = model.factors.item_vocab
            rows = []
            for e in events:
                ix = vocab.get(e.target_entity_id)
                if ix is not None:
                    rows.append(ix)
            return rows
        except Exception:
            return []

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        # live event-store filters use the injected serving context (the
        # deploy server sets it at build_runtime; tests set it directly)
        return self.predict_with_ctx(self.serving_context, model, query)

    def batch_predict(self, ctx: RuntimeContext, model: ECommModel, queries):
        # eval path: use the eval workflow's ctx so live-store filters are
        # measured the same way the deploy server applies them
        return [
            (qx, self.predict_with_ctx(ctx, model, q)) for qx, q in queries
        ]

    def predict_with_ctx(
        self, ctx: RuntimeContext, model: ECommModel, query: Query
    ) -> PredictedResult:
        vocab = model.factors.item_vocab

        # sparse business-rule filters: a candidate whitelist (categories /
        # explicit whitelist → index arrays) + an exclusion set (blacklist,
        # unavailable, seen, basis). Per-query memory stays
        # O(k + history + filters); no dense item-space mask is built.
        include = None
        if query.categories:
            if model.item_categories is None:
                # fail loudly instead of silently serving every category
                # (same contract as the recommendation template)
                raise ValueError(
                    "query filters by categories but no item category "
                    "properties were found at train time"
                )
            cat_index = model.category_index()
            arrs = [
                cat_index[c] for c in query.categories if c in cat_index
            ]
            include = (
                np.unique(np.concatenate(arrs))
                if arrs
                else np.empty(0, np.int64)
            )
        if query.whitelist is not None:
            wl = np.asarray(
                [
                    ix
                    for it in query.whitelist
                    if (ix := vocab.get(it)) is not None
                ],
                dtype=np.int64,
            )
            include = (
                wl if include is None
                else np.intersect1d(include, wl)
            )
        exclude: list[int] = []
        for it in query.blacklist or []:
            ix = vocab.get(it)
            if ix is not None:
                exclude.append(ix)
        if ctx.storage is not None:
            for it in self._unavailable_items(ctx):
                ix = vocab.get(it)
                if ix is not None:
                    exclude.append(ix)
            if self.params.unseen_only:
                for it in self._seen_items(ctx, query.user):
                    ix = vocab.get(it)
                    if ix is not None:
                        exclude.append(ix)

        user_row = model.factors.user_vocab.get(query.user)
        if user_row is not None:
            scores = model.factors.item_factors @ model.factors.user_factors[
                user_row
            ]
        else:
            # unknown user → similarity to recently-viewed items
            basis = (
                self._recent_item_rows(ctx, query.user, model)
                if ctx.storage is not None
                else []
            )
            if not basis:
                return PredictedResult()
            normed = model.normed_item_factors()
            scores = normed @ normed[basis].mean(axis=0)
            exclude.extend(basis)  # don't recommend the basis items

        inv = vocab.inverse()
        return PredictedResult(
            item_scores=[
                ItemScore(item=inv(int(ix)), score=float(scores[ix]))
                for ix in ranking.top_k_filtered(
                    scores, query.num,
                    exclude_idx=exclude, include_idx=include,
                )
            ]
        )


class ECommServing(FirstServing):
    pass


class ECommerceEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            ECommerceDataSource,
            IdentityPreparator,
            {"ecomm": ECommAlgorithm},
            ECommServing,
        )
