"""Item-similarity engine: exact column-cosine (the DIMSUM workload).

Reference: the experimental DIMSUM demo (examples/experimental/ — Spark
MLlib RowMatrix.columnSimilarities with sampling). On TPU the item-item
Gram matrix is one dense MXU matmul, so similarities are exact
(models/dimsum.py documents why sampling is obsolete here).

Shape: DataSource folds user→item interactions into a weighted indicator
matrix; the algorithm computes each item's top-N cosine-similar items
once at train time; serving sums similarity scores over the queried
items (multi-item queries rank by total similarity to the basket).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.store.bimap import BiMap
from predictionio_tpu.data.store.event_store import EventStoreFacade
from predictionio_tpu.models import dimsum


@dataclass
class Query:
    items: list[str] = field(default_factory=list)
    num: int = 10


@dataclass
class ItemScore:
    item: str
    score: float


@dataclass
class PredictedResult:
    item_scores: list[ItemScore] = field(default_factory=list)


@dataclass
class DataSourceParams:
    app_name: str
    event_names: tuple[str, ...] = ("view", "buy")
    entity_type: str = "user"


@dataclass
class TrainingData(SanityCheck):
    matrix: np.ndarray  # (U, I) weighted indicator
    item_vocab: BiMap

    def sanity_check(self) -> None:
        if self.matrix.size == 0 or not self.matrix.any():
            raise ValueError("no user→item interactions found")


class ItemSimDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        frame = EventStoreFacade(ctx.storage).find_frame(
            app_name=self.params.app_name,
            entity_type=self.params.entity_type,
            event_names=list(self.params.event_names),
        )
        mask = frame.target_idx >= 0
        users = frame.entity_idx[mask]
        items = frame.target_idx[mask]
        m = np.zeros((frame.n_entities, frame.n_targets), dtype=np.float32)
        np.add.at(m, (users, items), 1.0)
        return TrainingData(matrix=m, item_vocab=frame.target_vocab)


@dataclass
class ItemSimAlgorithmParams:
    top_n: int = 50  # similar items kept per item


@dataclass
class ItemSimModel:
    sim_scores: np.ndarray  # (I, top_n)
    sim_idx: np.ndarray  # (I, top_n), -1 padded
    item_vocab: BiMap


class ItemSimAlgorithm(Algorithm):
    def __init__(self, params: ItemSimAlgorithmParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> ItemSimModel:
        scores, idx = dimsum.column_cosine_topn(
            pd.matrix, top_n=self.params.top_n, mesh=ctx.mesh
        )
        return ItemSimModel(
            sim_scores=scores, sim_idx=idx, item_vocab=pd.item_vocab
        )

    def predict(self, model: ItemSimModel, query: Query) -> PredictedResult:
        n_items = len(model.item_vocab)
        known = [
            model.item_vocab.get(i)
            for i in query.items
            if model.item_vocab.get(i) is not None
        ]
        if not known:
            return PredictedResult()
        total = np.zeros(n_items, dtype=np.float32)
        for row in known:
            idx = model.sim_idx[row]
            ok = idx >= 0
            np.add.at(total, idx[ok], model.sim_scores[row][ok])
        total[known] = 0.0  # never recommend the queried items themselves
        top = np.argsort(-total)[: query.num]
        inv = model.item_vocab.inverse()
        return PredictedResult(
            item_scores=[
                ItemScore(item=inv(int(ix)), score=float(total[ix]))
                for ix in top
                if total[ix] > 0.0
            ]
        )


class ItemSimilarityEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            ItemSimDataSource,
            IdentityPreparator,
            {"dimsum": ItemSimAlgorithm},
            FirstServing,
        )
