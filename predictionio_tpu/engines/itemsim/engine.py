"""Item-similarity engine: exact column-cosine (the DIMSUM workload).

Reference: the experimental DIMSUM demo (examples/experimental/ — Spark
MLlib RowMatrix.columnSimilarities with sampling). On TPU the item-item
Gram matrix is one dense MXU matmul, so similarities are exact
(models/dimsum.py documents why sampling is obsolete here).

Shape: DataSource folds user→item interactions into a weighted indicator
matrix; the algorithm computes each item's top-N cosine-similar items
once at train time; serving sums similarity scores over the queried
items (multi-item queries rank by total similarity to the basket).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.store.bimap import BiMap
from predictionio_tpu.data.store.event_store import EventStoreFacade
from predictionio_tpu.models import dimsum


@dataclass
class Query:
    items: list[str] = field(default_factory=list)
    num: int = 10


@dataclass
class ItemScore:
    item: str
    score: float


@dataclass
class PredictedResult:
    item_scores: list[ItemScore] = field(default_factory=list)


@dataclass
class DataSourceParams:
    app_name: str
    event_names: tuple[str, ...] = ("view", "buy")
    entity_type: str = "user"


@dataclass
class TrainingData(SanityCheck):
    matrix: np.ndarray  # (U, I) weighted indicator
    item_vocab: BiMap

    def sanity_check(self) -> None:
        if self.matrix.size == 0 or not self.matrix.any():
            raise ValueError("no user→item interactions found")


class ItemSimDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        frame = EventStoreFacade(ctx.storage).find_frame(
            app_name=self.params.app_name,
            entity_type=self.params.entity_type,
            event_names=list(self.params.event_names),
        )
        mask = frame.target_idx >= 0
        users = frame.entity_idx[mask]
        items = frame.target_idx[mask]
        m = np.zeros((frame.n_entities, frame.n_targets), dtype=np.float32)
        np.add.at(m, (users, items), 1.0)
        return TrainingData(matrix=m, item_vocab=frame.target_vocab)


@dataclass
class ItemSimAlgorithmParams:
    top_n: int = 50  # similar items kept per item
    # sharded serving (ISSUE 11 satellite, carried fleet follow-up):
    # instead of the train-time O(I²) top-N precompute, keep the item
    # COLUMN vectors (the (I, U) transpose of the indicator matrix)
    # row-sharded across the serving mesh and compute each query item's
    # top-N cosine on the fly (fleet.ShardedRuntime.similar_items) —
    # the catalog (and the U-dim vectors) can exceed one chip's HBM,
    # and item-vocab growth needs no O(I²) recompute.
    shard_serving: bool = False
    # serving dtype for the on-the-fly cosine vectors (ISSUE 14):
    # "int8" per-row-quantizes the (I, U) column vectors (~1/4 the
    # resident bytes — the U dim is the expensive one here), "bf16"
    # halves them; cosine normalizes by the STAGED f32 norms either
    # way. Applies to both the single-device staged state and the
    # sharded tier.
    serve_dtype: str = "f32"


@dataclass
class ItemSimModel:
    sim_scores: np.ndarray  # (I, top_n) — empty when shard_serving
    sim_idx: np.ndarray  # (I, top_n), -1 padded
    item_vocab: BiMap
    top_n: int = 50
    # shard_serving: the raw (I, U) item column vectors; similarity is
    # computed on the fly from the sharded copies
    item_vectors: object = None  # Optional[np.ndarray]
    # serving dtype for the staged/sharded on-the-fly cosine (ISSUE 14)
    serve_dtype: str = "f32"

    def __post_init__(self):
        self._stage_lock = threading.Lock()

    def __getstate__(self):
        state = dict(self.__dict__)
        # serving state + lock are not part of the pickled model
        state.pop("_sharded_runtime", None)
        state.pop("_item_serving", None)
        state.pop("_stage_lock", None)
        return state

    def __setstate__(self, state):
        # models pickled BEFORE these fields existed must keep loading
        state.setdefault("top_n", 50)
        state.setdefault("item_vectors", None)
        state.setdefault("serve_dtype", "f32")
        self.__dict__.update(state)
        self._stage_lock = threading.Lock()

    def sharded_runtime(self):
        if self.item_vectors is None:
            return None
        # locked: concurrent pipelined batches must not double-stage
        # the sharded vector matrix (same discipline as ALSModel)
        with self._stage_lock:
            srt = getattr(self, "_sharded_runtime", None)
            if srt is False:
                return None
            if srt is None:
                from predictionio_tpu.fleet import stage_serving_runtime

                # no user side: the runtime only serves similar_items
                self._sharded_runtime = stage_serving_runtime(
                    np.zeros(
                        (0, self.item_vectors.shape[1]), np.float32
                    ),
                    self.item_vectors,
                    item_vocab=self.item_vocab,
                    serve_dtype=self.serve_dtype,
                )
                if self._sharded_runtime is False:
                    return None
                srt = self._sharded_runtime
            return srt

    def item_serving(self):
        """Single-device staged state for the on-the-fly cosine
        (ISSUE 14): the column vectors stage ONCE (quantized when
        serve_dtype opts in) and every query runs the fused
        score+top-k — the per-query numpy (Q, I) cosine matmul and its
        normalized matrix copy are gone."""
        if self.item_vectors is None:
            return None
        with self._stage_lock:
            sv = getattr(self, "_item_serving", None)
            if sv is None:
                from predictionio_tpu.models import als

                sv = self._item_serving = als.stage_item_serving(
                    self.item_vectors, serve_dtype=self.serve_dtype
                )
            return sv

    def sharded_info(self):
        srt = getattr(self, "_sharded_runtime", None)
        return srt.info() if srt else None


class ItemSimAlgorithm(Algorithm):
    def __init__(self, params: ItemSimAlgorithmParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> ItemSimModel:
        if self.params.shard_serving:
            # keep the column vectors; similarity is served on the fly
            # from the sharded copies — no O(I²) precompute
            empty = np.zeros((0, 0), np.float32)
            return ItemSimModel(
                sim_scores=empty,
                sim_idx=empty.astype(np.int64),
                item_vocab=pd.item_vocab,
                top_n=self.params.top_n,
                item_vectors=np.ascontiguousarray(
                    pd.matrix.T.astype(np.float32)
                ),
                serve_dtype=getattr(self.params, "serve_dtype", "f32"),
            )
        scores, idx = dimsum.column_cosine_topn(
            pd.matrix, top_n=self.params.top_n, mesh=ctx.mesh
        )
        return ItemSimModel(
            sim_scores=scores, sim_idx=idx, item_vocab=pd.item_vocab,
            top_n=self.params.top_n,
        )

    def _basket_rows(self, model: ItemSimModel, query: Query):
        return [
            model.item_vocab.get(i)
            for i in query.items
            if model.item_vocab.get(i) is not None
        ]

    def predict(self, model: ItemSimModel, query: Query) -> PredictedResult:
        n_items = len(model.item_vocab)
        known = self._basket_rows(model, query)
        if not known:
            return PredictedResult()
        total = np.zeros(n_items, dtype=np.float32)
        if model.item_vectors is not None:
            # on-the-fly similarity (shard_serving): sharded when > 1
            # device is visible, the STAGED fused cosine otherwise
            # (ISSUE 14 — als.similar_serving off the resident column
            # vectors; the per-query numpy cosine matmul is retired) —
            # both truncate to top_n per query item exactly like the
            # precomputed path
            srt = model.sharded_runtime()
            k = min(model.top_n, n_items)
            if srt is not None:
                vals, idx = srt.similar_items(
                    np.asarray(known, np.int64), k, exclude_self=True
                )
            else:
                from predictionio_tpu.models import als

                vals, idx = als.similar_serving(
                    model.item_serving(),
                    np.asarray(known, np.int64), k, exclude_self=True,
                )
            from predictionio_tpu.ops.topk import NEG_INF

            for r in range(len(known)):
                ok = vals[r] > NEG_INF / 2
                np.add.at(total, idx[r][ok], vals[r][ok])
        else:
            for row in known:
                idx = model.sim_idx[row]
                ok = idx >= 0
                np.add.at(total, idx[ok], model.sim_scores[row][ok])
        total[known] = 0.0  # never recommend the queried items themselves
        top = np.argsort(-total)[: query.num]
        inv = model.item_vocab.inverse()
        return PredictedResult(
            item_scores=[
                ItemScore(item=inv(int(ix)), score=float(total[ix]))
                for ix in top
                if total[ix] > 0.0
            ]
        )


class ItemSimilarityEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            ItemSimDataSource,
            IdentityPreparator,
            {"dimsum": ItemSimAlgorithm},
            FirstServing,
        )
