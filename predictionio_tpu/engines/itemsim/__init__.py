from predictionio_tpu.engines.itemsim.engine import (
    DataSourceParams,
    ItemScore,
    ItemSimAlgorithm,
    ItemSimAlgorithmParams,
    ItemSimDataSource,
    ItemSimilarityEngine,
    PredictedResult,
    Query,
)

__all__ = [
    "DataSourceParams",
    "ItemScore",
    "ItemSimAlgorithm",
    "ItemSimAlgorithmParams",
    "ItemSimDataSource",
    "ItemSimilarityEngine",
    "PredictedResult",
    "Query",
]
