from predictionio_tpu.engines.similarproduct.engine import (
    ALSSimilarAlgorithm,
    DataSourceParams,
    ItemScore,
    LikeAlgorithm,
    PredictedResult,
    Query,
    SimilarProductDataSource,
    SimilarProductEngine,
)

__all__ = [
    "ALSSimilarAlgorithm",
    "DataSourceParams",
    "ItemScore",
    "LikeAlgorithm",
    "PredictedResult",
    "Query",
    "SimilarProductDataSource",
    "SimilarProductEngine",
]
