"""Similar-product engine template: item-item similarity on ALS factors.

Reference: examples/scala-parallel-similarproduct (6 variants incl.
multi-algo) — DataSource reads "view" events; ALSAlgorithm trains implicit
ALS and keeps productFeatures; predict averages the query items' vectors
and returns cosine top-N excluding the query items; the `multi` variant
adds LikeAlgorithm (like/dislike events weighted ±1) and combines
predictions in Serving.

TPU re-design: one factor-training program shared with the recommendation
template (models/als.py); similarity serving is a cached-normalized
matmul + shared top-k ranking (models/ranking.py — host path; the
batched device path lives in models/als.similar_items)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
    Serving,
)
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.store.event_store import EventStoreFacade
from predictionio_tpu.models import als, ranking


@dataclass
class Query:
    items: list[str] = field(default_factory=list)
    num: int = 10
    whitelist: Optional[list[str]] = None
    blacklist: Optional[list[str]] = None


@dataclass
class ItemScore:
    item: str
    score: float


@dataclass
class PredictedResult:
    item_scores: list[ItemScore] = field(default_factory=list)


@dataclass
class DataSourceParams:
    app_name: str
    view_event: str = "view"
    like_event: str = "like"
    dislike_event: str = "dislike"


@dataclass
class TrainingData(SanityCheck):
    # view interactions
    rows: np.ndarray
    cols: np.ndarray
    vals: np.ndarray
    # like/dislike interactions (±1 weights) for LikeAlgorithm
    like_rows: np.ndarray
    like_cols: np.ndarray
    like_vals: np.ndarray
    n_users: int
    n_items: int
    user_vocab: object
    item_vocab: object

    def sanity_check(self) -> None:
        if len(self.rows) == 0 and len(self.like_rows) == 0:
            raise ValueError("no view or like/dislike events found")


class SimilarProductDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        store = EventStoreFacade(ctx.storage)
        frame = store.find_frame(
            app_name=self.params.app_name,
            entity_type="user",
            target_entity_type="item",
            event_names=[
                self.params.view_event,
                self.params.like_event,
                self.params.dislike_event,
            ],
        )
        views = frame.where_event(self.params.view_event)
        v_rows, v_cols, v_vals = views.interactions(dedupe="sum")

        likes = frame.where_event(
            self.params.like_event, self.params.dislike_event
        )
        like_code = frame.event_vocab.get(self.params.like_event, -2)
        # like=+1 / dislike=-1, latest event wins (reference LikeAlgorithm
        # keeps the most recent rating per pair)
        signed = np.where(likes.event_code == like_code, 1.0, -1.0).astype(
            np.float32
        )
        import dataclasses as _dc

        likes = _dc.replace(likes, value=signed)
        l_rows, l_cols, l_vals = likes.interactions(dedupe="last")

        return TrainingData(
            rows=v_rows, cols=v_cols, vals=v_vals,
            like_rows=l_rows, like_cols=l_cols, like_vals=l_vals,
            n_users=frame.n_entities, n_items=frame.n_targets,
            user_vocab=frame.entity_vocab, item_vocab=frame.target_vocab,
        )


# -- algorithms -------------------------------------------------------------


@dataclass
class ALSSimilarParams:
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int = 3
    # sharded serving (ISSUE 11 satellite, carried fleet follow-up):
    # with > 1 visible device, serve the basket cosine from
    # row-sharded item factors (fleet.ShardedRuntime.similar_vectors)
    # so the catalog can exceed one chip's HBM — the same wiring the
    # recommendation engine got in PR 10.
    shard_serving: bool = False
    # serving dtype for the basket cosine (ISSUE 14): "int8"/"bf16"
    # stage quantized item factors and run the fused scaled-dot cosine
    # (als.similar_vectors_serving); "f32" keeps the exact host path
    # on CPU and the fused kernel where the TPU lowering runs.
    serve_dtype: str = "f32"


class SimilarModel:
    """Item factors + vocab; normalized factors cached across queries."""

    def __init__(self, factors: als.ALSFactors, serve_dtype: str = "f32"):
        self.factors = factors
        self.serve_dtype = serve_dtype
        self._normed = None
        self._serving_state = None  # als.ServingFactors when staged
        self._sharded_runtime = None  # fleet.ShardedRuntime when active
        self._stage_lock = threading.Lock()

    # the cache is serving state, not part of the pickled model
    def __getstate__(self):
        return {
            "factors": self.factors,
            "serve_dtype": self.serve_dtype,
        }

    def __setstate__(self, state):
        # models pickled before serve_dtype existed must keep loading
        self.__init__(
            state["factors"], state.get("serve_dtype", "f32")
        )

    def normed_item_factors(self) -> np.ndarray:
        if self._normed is None:
            self._normed = ranking.l2_normalize(self.factors.item_factors)
        return self._normed

    def serving_state(self):
        """Staged item-side serving state for the fused basket cosine
        (ISSUE 14): quantized when serve_dtype opts in, resident
        across queries. Locked like every other staging."""
        with self._stage_lock:
            if self._serving_state is None:
                self._serving_state = als.stage_item_serving(
                    self.factors.item_factors,
                    serve_dtype=self.serve_dtype,
                )
            return self._serving_state

    def sharded_runtime(self):
        """Sharded serving state, staged lazily via the shared
        `fleet.stage_serving_runtime` helper (same contract as
        recommendation's ALSModel.sharded_runtime: needs > 1 visible
        device; PIO_SERVE_HBM_BYTES is the per-device budget; the
        single-device outcome caches as False). Locked: the pipelined
        dispatcher can run concurrent batches for one model, and
        double-staging would transiently double the sharded factor
        matrices' device footprint."""
        with self._stage_lock:
            if self._sharded_runtime is False:
                return None
            if self._sharded_runtime is None:
                from predictionio_tpu.fleet import stage_serving_runtime

                self._sharded_runtime = stage_serving_runtime(
                    self.factors.user_factors,
                    self.factors.item_factors,
                    item_vocab=self.factors.item_vocab,
                    serve_dtype=self.serve_dtype,
                )
                if self._sharded_runtime is False:
                    return None
            return self._sharded_runtime

    def sharded_info(self):
        srt = self._sharded_runtime
        return srt.info() if srt else None


class _SimilarBase(Algorithm):
    """Shared serving: average query item vectors → cosine top-N."""

    def _exclusion(self, model: SimilarModel, query: Query, known) -> np.ndarray:
        vocab = model.factors.item_vocab
        n = model.factors.item_factors.shape[0]
        excluded = np.zeros(n, dtype=bool)
        excluded[known] = True  # never recommend the query items
        if query.whitelist is not None:
            keep = np.zeros(n, dtype=bool)
            for it in query.whitelist:
                ix = vocab.get(it)
                if ix is not None:
                    keep[ix] = True
            excluded |= ~keep
        for it in query.blacklist or []:
            ix = vocab.get(it)
            if ix is not None:
                excluded[ix] = True
        return excluded

    def _predict(self, model: SimilarModel, query: Query) -> PredictedResult:
        vocab = model.factors.item_vocab
        known = [vocab.get(i) for i in query.items]
        known = [k for k in known if k is not None]
        if not known:
            return PredictedResult()
        excluded = self._exclusion(model, query, known)
        inv = vocab.inverse()
        srt = (
            model.sharded_runtime()
            if getattr(self.params, "shard_serving", False)
            else None
        )
        def basket_result(vals, idx, qnorm):
            # both device routes score the mean of NORMALIZED vectors
            # and divide by the query norm (cosine), so multiply it
            # back — the same query must yield the same SCORES as the
            # host path regardless of route/device count, not just the
            # same ranking (clients threshold on values). Filter masked
            # entries on the RAW value FIRST: a scale < 0.5 would
            # otherwise lift NEG_INF past the filter bound.
            from predictionio_tpu.ops.topk import NEG_INF

            return PredictedResult(
                item_scores=[
                    ItemScore(item=inv(int(ix)), score=float(s * qnorm))
                    for s, ix in zip(vals[0], idx[0])
                    if s > NEG_INF / 2
                ]
            )

        if srt is not None:
            # sharded basket cosine (ISSUE 11 satellite): the mean
            # query vector scores each shard's slab locally; only the
            # (1, k) candidates ride the ICI merge.
            q = model.normed_item_factors()[known].mean(axis=0)
            vals, idx = srt.similar_vectors(
                q[None, :], query.num, exclude_mask=excluded[None, :]
            )
            return basket_result(
                vals, idx, float(np.linalg.norm(q)) + 1e-9
            )
        serve_dtype = getattr(self.params, "serve_dtype", "f32")
        from predictionio_tpu.ops.recommend_pallas import resolve_mode

        if serve_dtype != "f32" or resolve_mode("auto") is not None:
            # staged fused basket cosine (ISSUE 14): quantized resident
            # item factors + one fused score+top-k dispatch; the host
            # path survives as the exact-f32 CPU default
            q = model.normed_item_factors()[known].mean(axis=0)
            vals, idx = als.similar_vectors_serving(
                model.serving_state(), q[None, :], query.num,
                exclude_mask=excluded[None, :],
            )
            return basket_result(
                vals, idx, float(np.linalg.norm(q)) + 1e-9
            )
        normed = model.normed_item_factors()
        scores = normed @ normed[known].mean(axis=0)
        scores = ranking.exclusion_scores(scores, excluded)
        return PredictedResult(
            item_scores=[
                ItemScore(item=inv(int(ix)), score=float(scores[ix]))
                for ix in ranking.top_k_indices(scores, query.num)
            ]
        )

    def predict(self, model: SimilarModel, query: Query) -> PredictedResult:
        return self._predict(model, query)


class ALSSimilarAlgorithm(_SimilarBase):
    """Implicit ALS on view events (reference ALSAlgorithm.scala of the
    similarproduct template)."""

    def __init__(self, params: ALSSimilarParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> SimilarModel:
        factors = als.train(
            pd.rows, pd.cols, pd.vals, pd.n_users, pd.n_items,
            als.ALSParams(
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                lambda_=self.params.lambda_,
                alpha=self.params.alpha,
                implicit_prefs=True,
                seed=self.params.seed,
            ),
            user_vocab=pd.user_vocab,
            item_vocab=pd.item_vocab,
            mesh=ctx.mesh,
        )
        return SimilarModel(
            factors, serve_dtype=getattr(self.params, "serve_dtype", "f32")
        )


class LikeAlgorithm(_SimilarBase):
    """Same factorization over like/dislike ±1 events (reference
    LikeAlgorithm.scala — the multi variant's second algorithm)."""

    def __init__(self, params: ALSSimilarParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> SimilarModel:
        if len(pd.like_rows) == 0:
            raise ValueError("LikeAlgorithm requires like/dislike events")
        factors = als.train(
            pd.like_rows, pd.like_cols, pd.like_vals, pd.n_users, pd.n_items,
            als.ALSParams(
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                lambda_=self.params.lambda_,
                alpha=self.params.alpha,
                implicit_prefs=True,
                seed=self.params.seed,
            ),
            user_vocab=pd.user_vocab,
            item_vocab=pd.item_vocab,
            mesh=ctx.mesh,
        )
        return SimilarModel(
            factors, serve_dtype=getattr(self.params, "serve_dtype", "f32")
        )


class SumScoreServing(Serving):
    """Multi-algo combination: sum per-item scores across algorithms
    (reference multi variant's Serving.scala)."""

    def serve(
        self, query: Query, predictions: Sequence[PredictedResult]
    ) -> PredictedResult:
        combined: dict[str, float] = {}
        for p in predictions:
            for s in p.item_scores:
                combined[s.item] = combined.get(s.item, 0.0) + s.score
        top = sorted(combined.items(), key=lambda kv: -kv[1])[: query.num]
        return PredictedResult(
            item_scores=[ItemScore(item=i, score=v) for i, v in top]
        )


class SimilarProductEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            SimilarProductDataSource,
            IdentityPreparator,
            {"als": ALSSimilarAlgorithm, "like": LikeAlgorithm},
            {"": FirstServing, "sum": SumScoreServing},
        )
