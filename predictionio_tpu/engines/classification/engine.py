"""Classification engine template: entity properties → label prediction.

Reference: examples/scala-parallel-classification (add-algorithm,
custom-attributes variants) — DataSource aggregates entity properties with
required attributes into LabeledPoints (add-algorithm/src/main/scala/
DataSource.scala:34-55), NaiveBayesAlgorithm.scala delegates to MLlib NB
(lambda param), add-algorithm shows a second algorithm selected via
engine.json; Query carries the attribute values, PredictedResult the label.

TPU re-design: the property aggregation produces one dense (N, D) feature
matrix staged to device; NB is a single segment-sum program and LR a
jitted GD loop (models/classify.py). Both algorithms batch-predict eval
queries in one device call."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.controller.metrics import AverageMetric
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.store.event_store import EventStoreFacade
from predictionio_tpu.e2.cross_validation import split_data
from predictionio_tpu.models import classify, forest


@dataclass
class Query:
    features: list[float] = field(default_factory=list)


@dataclass
class PredictedResult:
    label: str


@dataclass
class ActualResult:
    label: str


@dataclass
class DataSourceParams:
    app_name: str
    entity_type: str = "user"
    attrs: tuple[str, ...] = ("attr0", "attr1", "attr2")
    label_attr: str = "plan"
    eval_k: int = 0


@dataclass
class TrainingData(SanityCheck):
    features: np.ndarray  # (N, D) float32
    labels: np.ndarray  # (N,) int32
    label_vocab: tuple[str, ...]  # class index → label string

    def sanity_check(self) -> None:
        if len(self.features) == 0:
            raise ValueError("no labeled entities found")
        if len(self.label_vocab) < 2:
            raise ValueError(
                f"need ≥2 classes, found {list(self.label_vocab)}"
            )


@dataclass
class EvalInfo:
    fold: int


class ClassificationDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params

    def _read_all(self, ctx: RuntimeContext) -> TrainingData:
        store = EventStoreFacade(ctx.storage)
        props = store.aggregate_properties(
            app_name=self.params.app_name,
            entity_type=self.params.entity_type,
            required=[*self.params.attrs, self.params.label_attr],
        )
        rows = []
        labels = []
        for _entity, pmap in sorted(props.items()):
            rows.append(
                [float(pmap.get_opt(a, float) or 0.0) for a in self.params.attrs]
            )
            labels.append(str(pmap.get_opt(self.params.label_attr, str)))
        vocab = tuple(sorted(set(labels)))
        index = {lb: i for i, lb in enumerate(vocab)}
        return TrainingData(
            features=np.asarray(rows, dtype=np.float32),
            labels=np.asarray([index[lb] for lb in labels], dtype=np.int32),
            label_vocab=vocab,
        )

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        return self._read_all(ctx)

    def read_eval(self, ctx: RuntimeContext):
        if self.params.eval_k <= 0:
            raise ValueError("eval requires datasource params eval_k > 0")
        td = self._read_all(ctx)
        idx = list(range(len(td.labels)))
        out = []
        for fold, (train_ix, test_ix) in enumerate(
            split_data(self.params.eval_k, idx)
        ):
            tr = TrainingData(
                features=td.features[train_ix],
                labels=td.labels[train_ix],
                label_vocab=td.label_vocab,
            )
            qa = [
                (
                    Query(features=td.features[i].tolist()),
                    ActualResult(label=td.label_vocab[td.labels[i]]),
                )
                for i in test_ix
            ]
            out.append((tr, EvalInfo(fold=fold), qa))
        return out


# -- algorithms -------------------------------------------------------------


@dataclass
class NBModel:
    model: classify.NaiveBayesModel
    label_vocab: tuple[str, ...]


@dataclass
class NaiveBayesParams:
    lambda_: float = 1.0


class NaiveBayesAlgorithm(Algorithm):
    """Reference NaiveBayesAlgorithm.scala (MLlib NB, lambda smoothing)."""

    def __init__(self, params: NaiveBayesParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> NBModel:
        return NBModel(
            model=classify.train_naive_bayes(
                pd.features, pd.labels, len(pd.label_vocab),
                self.params.lambda_, mesh=ctx.mesh,
            ),
            label_vocab=pd.label_vocab,
        )

    def train_grid(
        self, ctx: RuntimeContext, pd: TrainingData, params_list
    ) -> list[NBModel]:
        """Whole smoothing grid in one device program (Engine.batch_eval's
        grid-batched tuning path, VERDICT r2 #9)."""
        models = classify.train_naive_bayes_grid(
            pd.features, pd.labels, len(pd.label_vocab),
            [p.lambda_ for p in params_list],
        )
        return [NBModel(model=m, label_vocab=pd.label_vocab) for m in models]

    def predict(self, model: NBModel, query: Query) -> PredictedResult:
        cls = int(model.model.predict(np.asarray(query.features))[0])
        return PredictedResult(label=model.label_vocab[cls])

    def batch_predict(self, ctx, model: NBModel, queries):
        x = np.asarray([q.features for _, q in queries], dtype=np.float32)
        classes = model.model.predict(x)
        return [
            (qx, PredictedResult(label=model.label_vocab[int(c)]))
            for (qx, _q), c in zip(queries, classes)
        ]


@dataclass
class LRModel:
    model: classify.LogisticRegressionModel
    label_vocab: tuple[str, ...]


@dataclass
class LogisticRegressionParams:
    iterations: int = 200
    lr: float = 0.5
    l2: float = 1e-4


class LogisticRegressionAlgorithm(Algorithm):
    """The template's second algorithm (the reference add-algorithm variant
    adds RandomForest; here the TPU-friendly second model is softmax LR)."""

    def __init__(self, params: LogisticRegressionParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> LRModel:
        return LRModel(
            model=classify.train_logistic_regression(
                pd.features,
                pd.labels,
                len(pd.label_vocab),
                iterations=self.params.iterations,
                lr=self.params.lr,
                l2=self.params.l2,
                mesh=ctx.mesh,
            ),
            label_vocab=pd.label_vocab,
        )

    def train_grid(
        self, ctx: RuntimeContext, pd: TrainingData, params_list
    ) -> list[LRModel]:
        """Whole (lr, l2) grid as one vmapped GD program — iterations must
        agree across points (it is a static loop bound); falls back to
        per-point training otherwise."""
        iterations = {p.iterations for p in params_list}
        if len(iterations) != 1:
            # type(self): a subclass's train() override must win here too
            return [type(self)(p).train(ctx, pd) for p in params_list]
        models = classify.train_logistic_regression_grid(
            pd.features, pd.labels, len(pd.label_vocab),
            [(p.lr, p.l2) for p in params_list],
            iterations=iterations.pop(),
        )
        return [LRModel(model=m, label_vocab=pd.label_vocab) for m in models]

    def predict(self, model: LRModel, query: Query) -> PredictedResult:
        cls = int(model.model.predict(np.asarray(query.features))[0])
        return PredictedResult(label=model.label_vocab[cls])

    def batch_predict(self, ctx, model: LRModel, queries):
        x = np.asarray([q.features for _, q in queries], dtype=np.float32)
        classes = model.model.predict(x)
        return [
            (qx, PredictedResult(label=model.label_vocab[int(c)]))
            for (qx, _q), c in zip(queries, classes)
        ]


@dataclass
class RFModel:
    model: forest.RandomForestModel
    label_vocab: tuple[str, ...]


@dataclass
class RandomForestParams:
    """Reference RandomForestAlgoParams (RandomForestAlgorithm.scala:17-24:
    numTrees/maxDepth/maxBins; featureSubsetStrategy="auto" →
    feature_fraction=None)."""

    num_trees: int = 20
    max_depth: int = 6
    max_bins: int = 32
    feature_fraction: Optional[float] = None
    seed: int = 42


class RandomForestAlgorithm(Algorithm):
    """Histogram random forest (models/forest.py) — the add-algorithm
    variant's second MLlib algorithm, rebuilt as an XLA program."""

    def __init__(self, params: RandomForestParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> RFModel:
        return RFModel(
            model=forest.train_random_forest(
                pd.features,
                pd.labels,
                len(pd.label_vocab),
                n_trees=self.params.num_trees,
                max_depth=self.params.max_depth,
                n_bins=self.params.max_bins,
                feature_fraction=self.params.feature_fraction,
                seed=self.params.seed,
                mesh=ctx.mesh,
            ),
            label_vocab=pd.label_vocab,
        )

    def predict(self, model: RFModel, query: Query) -> PredictedResult:
        cls = int(model.model.predict(np.asarray(query.features))[0])
        return PredictedResult(label=model.label_vocab[cls])

    def batch_predict(self, ctx, model: RFModel, queries):
        x = np.asarray([q.features for _, q in queries], dtype=np.float32)
        classes = model.model.predict(x)
        return [
            (qx, PredictedResult(label=model.label_vocab[int(c)]))
            for (qx, _q), c in zip(queries, classes)
        ]


# -- evaluation -------------------------------------------------------------


class Accuracy(AverageMetric):
    """Fraction of correct label predictions (the template's quickstart
    eval metric)."""

    def calculate_one(self, q: Query, p: PredictedResult, a: ActualResult):
        return 1.0 if p.label == a.label else 0.0


# -- engine factory ---------------------------------------------------------


class ClassificationEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            ClassificationDataSource,
            IdentityPreparator,
            {
                "naive": NaiveBayesAlgorithm,
                "logreg": LogisticRegressionAlgorithm,
                "randomforest": RandomForestAlgorithm,
            },
            FirstServing,
        )
