from predictionio_tpu.engines.classification.engine import (
    ClassificationDataSource,
    ClassificationEngine,
    DataSourceParams,
    LogisticRegressionAlgorithm,
    NaiveBayesAlgorithm,
    PredictedResult,
    Query,
)

__all__ = [
    "ClassificationDataSource",
    "ClassificationEngine",
    "DataSourceParams",
    "LogisticRegressionAlgorithm",
    "NaiveBayesAlgorithm",
    "PredictedResult",
    "Query",
]
