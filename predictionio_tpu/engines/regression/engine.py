"""Regression engine template: entity properties → numeric prediction.

Reference: the regression example family
(examples/experimental/scala-parallel-regression/Run.scala — MLlib
LinearRegressionWithSGD behind a P2LAlgorithm with k-fold eval, MSE
metric, LAverageServing over a params grid, and a custom VectorSerializer
for queries; also java-local-regression, scala-local-regression).

TPU re-design: entity $set properties aggregate into one dense (N, D)
matrix; ridge regression solves the normal equations with two MXU
contractions (models/linreg.py) instead of an SGD loop. The vector-query
serializer is reproduced through the Algorithm.query_serializer hook: a
bare JSON array `[x1, x2, ...]` is a valid query, and the response is the
bare predicted number."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    AverageServing,
    DataSource,
    Engine,
    EngineFactory,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.controller.metrics import AverageMetric
from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.store.event_store import EventStoreFacade
from predictionio_tpu.e2.cross_validation import split_data
from predictionio_tpu.models import linreg


@dataclass
class Query:
    features: list[float] = field(default_factory=list)


@dataclass
class PredictedResult:
    value: float


@dataclass
class ActualResult:
    value: float


class VectorQuerySerializer:
    """Reference VectorSerializer analogue: accepts `[1.0, 2.0]` (bare
    array) or `{"features": [...]}`; renders the bare predicted value."""

    def query_from_json(self, parsed) -> Query:
        if isinstance(parsed, list):
            return Query(features=[float(v) for v in parsed])
        if isinstance(parsed, dict) and "features" in parsed:
            return Query(features=[float(v) for v in parsed["features"]])
        raise ValueError(
            "regression query must be a JSON array or {'features': [...]}"
        )

    def result_to_json(self, prediction):
        if isinstance(prediction, PredictedResult):
            return prediction.value
        return prediction


@dataclass
class DataSourceParams:
    app_name: str
    entity_type: str = "point"
    attrs: tuple[str, ...] = ("x0", "x1", "x2")
    target_attr: str = "y"
    eval_k: int = 0


@dataclass
class TrainingData(SanityCheck):
    features: np.ndarray  # (N, D) float32
    targets: np.ndarray  # (N,) float32

    def sanity_check(self) -> None:
        if len(self.features) == 0:
            raise ValueError("no regression points found")


@dataclass
class EvalInfo:
    fold: int


class RegressionDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        self.params = params

    def _read_all(self, ctx: RuntimeContext) -> TrainingData:
        store = EventStoreFacade(ctx.storage)
        props = store.aggregate_properties(
            app_name=self.params.app_name,
            entity_type=self.params.entity_type,
            required=[*self.params.attrs, self.params.target_attr],
        )
        rows, targets = [], []
        for _entity, pmap in sorted(props.items()):
            rows.append(
                [
                    float(pmap.get_opt(a, float) or 0.0)
                    for a in self.params.attrs
                ]
            )
            targets.append(float(pmap.get_opt(self.params.target_attr, float)))
        return TrainingData(
            features=np.asarray(rows, dtype=np.float32),
            targets=np.asarray(targets, dtype=np.float32),
        )

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        return self._read_all(ctx)

    def read_eval(self, ctx: RuntimeContext):
        if self.params.eval_k <= 0:
            raise ValueError("eval requires datasource params eval_k > 0")
        td = self._read_all(ctx)
        idx = list(range(len(td.targets)))
        out = []
        for fold, (train_ix, test_ix) in enumerate(
            split_data(self.params.eval_k, idx)
        ):
            tr = TrainingData(
                features=td.features[train_ix], targets=td.targets[train_ix]
            )
            qa = [
                (
                    Query(features=td.features[i].tolist()),
                    ActualResult(value=float(td.targets[i])),
                )
                for i in test_ix
            ]
            out.append((tr, EvalInfo(fold=fold), qa))
        return out


@dataclass
class RidgeParams:
    l2: float = 1e-6
    fit_intercept: bool = True


@dataclass
class RidgeModel:
    model: linreg.LinearRegressionModel


class RidgeAlgorithm(Algorithm):
    """Closed-form ridge (replaces LinearRegressionWithSGD — same model
    family, exact solution)."""

    def __init__(self, params: RidgeParams):
        self.params = params

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> RidgeModel:
        return RidgeModel(
            model=linreg.train_linear_regression(
                pd.features,
                pd.targets,
                l2=self.params.l2,
                fit_intercept=self.params.fit_intercept,
                mesh=ctx.mesh,
            )
        )

    def train_grid(
        self, ctx: RuntimeContext, pd: TrainingData, params_list
    ) -> list[RidgeModel]:
        """Whole l2 grid from one sufficient-statistics pass; falls back
        per point when fit_intercept differs across the grid."""
        intercepts = {p.fit_intercept for p in params_list}
        if len(intercepts) != 1:
            # type(self): a subclass's train() override must win here too
            return [type(self)(p).train(ctx, pd) for p in params_list]
        models = linreg.train_linear_regression_grid(
            pd.features, pd.targets,
            [p.l2 for p in params_list],
            fit_intercept=intercepts.pop(),
        )
        return [RidgeModel(model=m) for m in models]

    def predict(self, model: RidgeModel, query: Query) -> PredictedResult:
        val = float(model.model.predict(np.asarray(query.features))[0])
        return PredictedResult(value=val)

    def batch_predict(self, ctx, model: RidgeModel, queries):
        x = np.asarray([q.features for _, q in queries], dtype=np.float32)
        vals = model.model.predict(x)
        return [
            (qx, PredictedResult(value=float(v)))
            for (qx, _q), v in zip(queries, vals)
        ]

    def query_serializer(self):
        return VectorQuerySerializer()


class RegressionAverageServing(AverageServing):
    """LAverageServing analogue: mean of the per-algorithm predictions
    (the reference serves the average of the SGD params grid)."""

    FIELD = "value"


class MeanSquareError(AverageMetric):
    """Reference controller MeanSquareError (used by the example's
    Workflow run)."""

    def calculate_one(self, q: Query, p: PredictedResult, a: ActualResult):
        return (p.value - a.value) ** 2

    def compare(self, a: float, b: float) -> int:
        # lower MSE is better
        return (a < b) - (a > b)


class RegressionEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            RegressionDataSource,
            IdentityPreparator,
            {"ridge": RidgeAlgorithm},
            RegressionAverageServing,
        )
