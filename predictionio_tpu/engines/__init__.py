"""L8 — engine templates (the product surface of reference examples/).

Each subpackage is a complete DASE engine a user can train/deploy/eval:
  recommendation     — ALS personal recommendations (scala-parallel-recommendation)
  similarproduct     — item-item similarity on ALS factors (scala-parallel-similarproduct)
  classification     — NaiveBayes / logistic regression (scala-parallel-classification)
  ecommerce          — ALS + serving-time business-rule filters
                       (scala-parallel-ecommercerecommendation)
"""
