"""Durable local write-ahead spill for accepted events (ISSUE 4).

When the event server's storage endpoint is unreachable (breaker open,
retries exhausted), accepted events land here instead of being dropped:
one JSON line per record in an append-only segment file, fsync'd before
the server acks 202. A background replayer drains segments **in arrival
order** once storage recovers.

Zero loss, zero duplicates:

- every record carries a `req_id` minted at spill time; the replayer
  hands it to the storage client, whose RPC-level dedupe (the existing
  req-id machinery in the storage daemon) makes a replayed insert
  idempotent even if the replayer crashed between applying the write
  and acking it locally;
- each successful replay appends the req_id to the segment's `.ack`
  sidecar (fsync'd), so a restart resumes exactly where it stopped
  instead of re-sending the whole segment;
- a fully-acked segment (and its sidecar) is deleted.

Layout under the WAL directory::

    wal-<epoch_ms>-<seq>-<pid>.jsonl      # records: {"req_id", "app_id",
                                          #   "channel_id", "event", "ts"}
    wal-<epoch_ms>-<seq>-<pid>.jsonl.ack  # one replayed req_id per line

Segment names lead with a fixed-width epoch-milliseconds stamp so the
lexicographic directory sort IS creation order — including across
process restarts, where a pid-first scheme would interleave old and new
segments by pid digit count.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Optional

from predictionio_tpu.analysis import tsan as _tsan


class EventWAL:
    def __init__(self, directory: str, fsync: bool = True):
        self.dir = directory
        self.fsync = fsync
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()  # append path + pending counter
        # sanitizer (ISSUE 15 satellite): the append lock is HELD
        # across the spill fsync by design — the fsync-before-ack
        # ordering and the pending counter are one critical section;
        # declaring it keeps the note_blocking hook below pointed at
        # OTHER locks callers might wrongly hold across a spill
        _tsan.allow_blocking_lock(self._lock)
        self._replay_lock = threading.Lock()  # one replayer at a time
        self._seq = 0
        self._current_path: Optional[str] = None
        self._current_file = None
        self._pending = self._scan_pending()

    # -- bookkeeping -------------------------------------------------------
    def _segments(self) -> list[str]:
        """Segment paths, oldest first: the fixed-width epoch-ms name
        prefix makes the lexicographic sort creation-ordered, across
        restarts and pids alike."""
        try:
            names = sorted(
                n for n in os.listdir(self.dir)
                if n.startswith("wal-") and n.endswith(".jsonl")
            )
        except FileNotFoundError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    @staticmethod
    def _read_records(path: str) -> list[dict[str, Any]]:
        records = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except json.JSONDecodeError:
                        # torn tail write from a crash mid-append: the
                        # record was never acked to the client, skip it
                        continue
        except FileNotFoundError:
            pass
        return records

    @staticmethod
    def _read_acks(path: str) -> set[str]:
        try:
            with open(path + ".ack") as f:
                return {line.strip() for line in f if line.strip()}
        except FileNotFoundError:
            return set()

    def _scan_pending(self) -> int:
        n = 0
        for seg in self._segments():
            acked = self._read_acks(seg)
            n += sum(
                1 for r in self._read_records(seg)
                if r.get("req_id") not in acked
            )
        return n

    def pending(self) -> int:
        with self._lock:
            return self._pending

    # -- spill -------------------------------------------------------------
    def append(
        self, event: Any, app_id: int, channel_id: Optional[int]
    ) -> str:
        """Spill one admitted event; returns its replay req_id. The
        record is flushed (and fsync'd) before return — the 202 ack the
        caller sends is a durability promise."""
        req_id = uuid.uuid4().hex
        # stamp the req_id as the event id when the client supplied none:
        # replayed inserts then dedupe at the STORE level too (same id →
        # overwrite, not a second row), which is what makes the batched
        # replay path idempotent even if a torn ack re-sends a suffix
        event_d = event.to_json_dict()
        event_d.setdefault("eventId", req_id)
        rec = {
            "req_id": req_id,
            "app_id": app_id,
            "channel_id": channel_id,
            "event": event_d,
            "ts": round(time.time(), 3),
        }
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            if self._current_file is None:
                self._seq += 1
                self._current_path = os.path.join(
                    self.dir,
                    f"wal-{int(time.time() * 1000):015d}"
                    f"-{self._seq:06d}-{os.getpid()}.jsonl",
                )
                self._current_file = open(self._current_path, "a")
            self._current_file.write(line)
            self._current_file.flush()
            if self.fsync:
                # blocking point (ISSUE 15 satellite): a lock held
                # across a WAL fsync serializes every waiter behind
                # one disk flush
                _tsan.note_blocking("wal.fsync")
                os.fsync(self._current_file.fileno())
            self._pending += 1
        return req_id

    def _rotate(self) -> None:
        with self._lock:
            if self._current_file is not None:
                self._current_file.close()
                self._current_file = None
                self._current_path = None

    # -- replay ------------------------------------------------------------
    def replay(
        self,
        insert_fn: Callable[[Any, int, Optional[int], str], Any],
        on_replayed: Optional[Callable[[dict], None]] = None,
    ) -> tuple[int, Optional[Exception]]:
        """Drain pending records in order through ``insert_fn(event,
        app_id, channel_id, req_id)``. Stops at the first failure (order
        preservation — later events must not leapfrog a stuck one) and
        returns ``(replayed_count, error_or_None)``."""
        from predictionio_tpu.data.event import Event

        if not self._replay_lock.acquire(blocking=False):
            return (0, None)  # another replay pass is already running
        try:
            self._rotate()  # appends move to a fresh segment
            replayed = 0
            for seg in self._segments():
                with self._lock:
                    if seg == self._current_path:
                        # re-opened by an append racing this replay pass:
                        # deleting a live segment would drop its events —
                        # the next pass picks it up after rotation
                        continue
                records = self._read_records(seg)
                acked = self._read_acks(seg)
                todo = [r for r in records if r["req_id"] not in acked]
                if todo:
                    ack_f = open(seg + ".ack", "a")
                    try:
                        for rec in todo:
                            event = Event.from_json_dict(rec["event"])
                            try:
                                insert_fn(
                                    event,
                                    rec["app_id"],
                                    rec.get("channel_id"),
                                    rec["req_id"],
                                )
                            except Exception as e:
                                return (replayed, e)
                            ack_f.write(rec["req_id"] + "\n")
                            ack_f.flush()
                            if self.fsync:
                                _tsan.note_blocking("wal.fsync")
                                os.fsync(ack_f.fileno())
                            with self._lock:
                                self._pending -= 1
                            replayed += 1
                            if on_replayed is not None:
                                try:
                                    on_replayed(rec)
                                except Exception:
                                    pass
                    finally:
                        ack_f.close()
                # fully acked: the segment is done, reclaim it
                for path in (seg, seg + ".ack"):
                    try:
                        os.remove(path)
                    except FileNotFoundError:
                        pass
            return (replayed, None)
        finally:
            self._replay_lock.release()

    def replay_batched(
        self,
        insert_batch_fn: Callable[[list, int, Optional[int], str], Any],
        max_batch: int = 50,
        on_replayed: Optional[Callable[[dict], None]] = None,
    ) -> tuple[int, Optional[Exception]]:
        """Ordered replay through a BULK insert seam (ISSUE 9 satellite):
        consecutive unacked records sharing one (app, channel) namespace
        group into ≤`max_batch` chunks and land as one
        ``insert_batch_fn(events, app_id, channel_id, batch_req_id)``
        call — one storage RPC per chunk instead of per event, which is
        what replay throughput needs once a consumer is tailing the
        store.

        Exactly-once contract: the batch req_id derives from the FIRST
        member's req_id, and batch composition is deterministic given the
        ack state (same prefix → same id), so a re-send after a lost
        response replays the daemon's recorded outcome; spill-time
        event-id stamping (see `append`) additionally makes any residual
        re-insert an overwrite, not a duplicate. Acks for the whole
        chunk land in one buffered write after the batch succeeds."""
        from predictionio_tpu.data.event import Event

        if not self._replay_lock.acquire(blocking=False):
            return (0, None)
        try:
            self._rotate()
            replayed = 0
            for seg in self._segments():
                with self._lock:
                    if seg == self._current_path:
                        continue
                records = self._read_records(seg)
                acked = self._read_acks(seg)
                todo = [r for r in records if r["req_id"] not in acked]
                if todo:
                    # consecutive same-namespace runs, order-preserving
                    chunks: list[list[dict]] = []
                    for rec in todo:
                        key = (rec["app_id"], rec.get("channel_id"))
                        if (
                            chunks
                            and len(chunks[-1]) < max_batch
                            and (
                                chunks[-1][0]["app_id"],
                                chunks[-1][0].get("channel_id"),
                            ) == key
                        ):
                            chunks[-1].append(rec)
                        else:
                            chunks.append([rec])
                    ack_f = open(seg + ".ack", "a")
                    try:
                        for chunk in chunks:
                            events = [
                                Event.from_json_dict(r["event"])
                                for r in chunk
                            ]
                            batch_req = f"walb-{chunk[0]['req_id']}"
                            try:
                                insert_batch_fn(
                                    events,
                                    chunk[0]["app_id"],
                                    chunk[0].get("channel_id"),
                                    batch_req,
                                )
                            except Exception as e:
                                return (replayed, e)
                            ack_f.write(
                                "".join(r["req_id"] + "\n" for r in chunk)
                            )
                            ack_f.flush()
                            if self.fsync:
                                _tsan.note_blocking("wal.fsync")
                                os.fsync(ack_f.fileno())
                            with self._lock:
                                self._pending -= len(chunk)
                            replayed += len(chunk)
                            if on_replayed is not None:
                                for r in chunk:
                                    try:
                                        on_replayed(r)
                                    except Exception:
                                        pass
                    finally:
                        ack_f.close()
                for path in (seg, seg + ".ack"):
                    try:
                        os.remove(path)
                    except FileNotFoundError:
                        pass
            return (replayed, None)
        finally:
            self._replay_lock.release()

    def close(self) -> None:
        self._rotate()
