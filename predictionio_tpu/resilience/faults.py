"""Deterministic fault-injection registry (ISSUE 4 tentpole backbone).

Named fault points sit at the boundaries the chaos tests need to break:

  ==================  =====================================================
  point               fires in
  ==================  =====================================================
  ``storage.rpc``     RemoteClient.call, before each network attempt
  ``event.insert``    event server, before the storage write
  ``dispatch.device`` micro-batch dispatcher, before batch_predict
  ``model.load``      deploy-server runtime build, before model rehydration
  ==================  =====================================================

Each point carries at most one :class:`FaultSpec` — mode ``error``
(raise :class:`FaultInjected`), ``delay`` (sleep ``param`` seconds, then
proceed), or ``corrupt`` (the call site substitutes a garbled result; a
site that cannot corrupt raises instead) — firing with ``probability``
decided by a **per-point seeded RNG**, so a chaos run replays the exact
same fault sequence for the same seed and call order.

Configure three ways:

- env at process start: ``PIO_FAULTS=storage.rpc:error:0.2`` (comma-
  separated specs, grammar ``point:mode:prob[:param]``; optional
  ``PIO_FAULTS_SEED=N`` for determinism across processes),
- the guarded ``POST /debug/faults`` admin endpoint on any server
  (requires ``PIO_FAULTS_ADMIN=1`` on the server process),
- `pio faults list|set|clear` from the console.

Inert by default: with no spec installed, :func:`fire` is one dict check
— the RPC hot path pays nothing (guarded by a CI latency check).
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

from predictionio_tpu.utils.env import env_raw, env_str

FAULT_POINTS = (
    "storage.rpc",
    "event.insert",
    "dispatch.device",
    "model.load",
    # online fold-in tick (ISSUE 9): "error" fails the tick (consumer
    # retries from its cursor), "corrupt" scrambles the solved factor
    # rows — the injected-drift chaos input the drift guard must catch
    "online.fold",
)

MODES = ("error", "delay", "corrupt")


class FaultInjected(Exception):
    """An injected failure (distinguishable from organic errors)."""


class FaultSpecError(ValueError):
    """A malformed fault spec string or field."""


@dataclass(frozen=True)
class FaultSpec:
    """One fault point's behavior. `param` is the sleep seconds for mode
    ``delay`` (ignored otherwise). `scope` narrows the spec to call sites
    that fire the point with a matching scope label (ISSUE 5: rollout
    needs `dispatch.device@candidate` to flip ONLY the canary variant bad
    while the live model keeps serving); a scope-less spec keeps the PR-4
    behavior of matching every fire of the point."""

    point: str
    mode: str
    probability: float
    param: float = 0.05
    seed: Optional[int] = None
    scope: Optional[str] = None

    def key(self) -> str:
        return self.point if self.scope is None else f"{self.point}@{self.scope}"

    def __post_init__(self):
        if self.point not in FAULT_POINTS:
            raise FaultSpecError(
                f"unknown fault point {self.point!r} "
                f"(known: {', '.join(FAULT_POINTS)})"
            )
        if self.mode not in MODES:
            raise FaultSpecError(
                f"unknown fault mode {self.mode!r} (known: {', '.join(MODES)})"
            )
        if not 0.0 <= self.probability <= 1.0:
            raise FaultSpecError(
                f"fault probability must be in [0, 1], got {self.probability}"
            )
        if self.param < 0:
            raise FaultSpecError("fault param must be >= 0")

    def to_dict(self) -> dict[str, Any]:
        return {
            "point": self.point,
            "mode": self.mode,
            "probability": self.probability,
            "param": self.param,
            "seed": self.seed,
            "scope": self.scope,
        }


def parse_spec(text: str, seed: Optional[int] = None) -> FaultSpec:
    """``point[@scope]:mode:prob[:param]`` → FaultSpec."""
    parts = text.strip().split(":")
    if len(parts) not in (3, 4):
        raise FaultSpecError(
            f"fault spec {text!r} is not point[@scope]:mode:prob[:param]"
        )
    try:
        prob = float(parts[2])
        param = float(parts[3]) if len(parts) == 4 else 0.05
    except ValueError as e:
        raise FaultSpecError(f"fault spec {text!r}: {e}")
    point, _, scope = parts[0].partition("@")
    return FaultSpec(point, parts[1], prob, param, seed, scope or None)


def parse_specs(text: str, seed: Optional[int] = None) -> list[FaultSpec]:
    """Comma-separated spec list (the ``PIO_FAULTS`` grammar)."""
    return [parse_spec(p, seed) for p in text.split(",") if p.strip()]


class FaultRegistry:
    """Thread-safe point → spec map with per-point deterministic RNGs.

    The specs dict is replaced wholesale on every mutation so `fire` can
    read it without taking the lock — the inert fast path is one
    attribute load + truthiness check."""

    def __init__(self):
        self._lock = threading.Lock()
        self._specs: dict[str, FaultSpec] = {}
        self._rngs: dict[str, random.Random] = {}

    def install(self, spec: FaultSpec) -> None:
        with self._lock:
            specs = dict(self._specs)
            specs[spec.key()] = spec
            self._rngs[spec.key()] = random.Random(spec.seed)
            self._specs = specs

    def clear(self, point: Optional[str] = None) -> None:
        """Clear one spec key (``point`` or ``point@scope``), or all."""
        with self._lock:
            if point is None:
                self._specs = {}
                self._rngs.clear()
            else:
                specs = dict(self._specs)
                specs.pop(point, None)
                self._rngs.pop(point, None)
                self._specs = specs

    def specs(self) -> list[dict[str, Any]]:
        return [s.to_dict() for s in self._specs.values()]

    def active(self) -> bool:
        return bool(self._specs)

    def fire(
        self, point: str, corruptable: bool = False,
        scope: Optional[str] = None, scoped_only: bool = False,
    ) -> Optional[str]:
        """Evaluate the fault point. Returns None (no fault), ``"delay"``
        (after sleeping), or ``"corrupt"`` (the caller substitutes a
        garbled result); raises :class:`FaultInjected` for mode ``error``
        — and for ``corrupt`` when the site can't corrupt its result.

        A `scope` label matches ``point@scope`` specs first, then falls
        through to the scope-less spec; `scoped_only=True` skips the
        fall-through — for call sites (the dispatcher's per-query
        fallback) that must keep their PR-4 behavior under scope-less
        specs but still honor a variant-targeted one."""
        specs = self._specs  # lock-free snapshot read; {} when inert
        if not specs:
            return None
        key = point
        spec = specs.get(f"{point}@{scope}") if scope is not None else None
        if spec is not None:
            key = spec.key()
        elif scoped_only:
            return None
        else:
            spec = specs.get(point)
        if spec is None:
            return None
        with self._lock:
            rng = self._rngs.get(key)
            roll = rng.random() if rng is not None else random.random()
        if roll >= spec.probability:
            return None
        self._count(point, spec.mode)
        if spec.mode == "delay":
            time.sleep(spec.param)
            return "delay"
        if spec.mode == "corrupt" and corruptable:
            return "corrupt"
        raise FaultInjected(f"injected {spec.mode} fault at {point}")

    @staticmethod
    def _count(point: str, mode: str) -> None:
        # lazy import: the registry must stay importable (and inert-fast)
        # without dragging obs into processes that never fault
        try:
            from predictionio_tpu.obs.registry import get_default_registry

            get_default_registry().counter(
                "faults_injected_total",
                "injected faults fired, by point and mode",
                # label-bound: registered fault points x literal modes
                ("point", "mode"),
            ).inc(point=point, mode=mode)
        except Exception:
            pass

    def configure_from_env(self, env: Optional[dict] = None) -> None:
        """Apply ``PIO_FAULTS`` / ``PIO_FAULTS_SEED`` from `env`.
        Raises FaultSpecError on a malformed grammar — explicit callers
        (tests, tools) want the loud failure; the import-time invocation
        below downgrades it to a warning so a typo'd env var cannot
        crash every server and the CLI alike."""
        env = env if env is not None else os.environ
        text = env_str("PIO_FAULTS", env=env)
        if not text:
            return
        seed_s = env_raw("PIO_FAULTS_SEED", env=env)
        try:
            seed = int(seed_s) if seed_s else None
        except ValueError:
            raise FaultSpecError(
                f"PIO_FAULTS_SEED must be an integer, got {seed_s!r}"
            )
        for spec in parse_specs(text, seed):
            self.install(spec)


_default = FaultRegistry()
try:
    _default.configure_from_env()
except FaultSpecError as _e:
    import logging as _logging

    _logging.getLogger(__name__).warning(
        "ignoring malformed PIO_FAULTS env (%s); fault registry stays "
        "inert — fix the spec and restart, or use `pio faults set`", _e,
    )


def registry() -> FaultRegistry:
    """The process-wide registry every fault point fires against."""
    return _default


def fire(
    point: str, corruptable: bool = False,
    scope: Optional[str] = None, scoped_only: bool = False,
) -> Optional[str]:
    return _default.fire(point, corruptable, scope, scoped_only)


def install(spec: FaultSpec) -> None:
    _default.install(spec)


def clear(point: Optional[str] = None) -> None:
    _default.clear(point)


def specs() -> list[dict[str, Any]]:
    return _default.specs()


def active() -> bool:
    return _default.active()
