"""End-to-end resilience: fault injection, retry, circuit breaking,
deadline propagation, and the event write-ahead spill (ISSUE 4).

The reference is a Lambda-architecture serving stack whose processes must
keep answering under partial failure; this package is the one place its
failure-handling policy lives, threaded through every network and device
boundary:

- `faults`   — deterministic fault-injection registry; the backbone that
               makes every other behavior here testable in-process.
- `retry`    — exponential backoff + jitter under a per-call deadline
               budget (replaces the old fixed one-retry in the storage
               client).
- `breaker`  — per-endpoint circuit breaker (closed/open/half-open with
               a recovery probe); state transitions emit metrics.
- `deadline` — `X-PIO-Deadline` header ⇄ ContextVar plumbing so a
               caller's remaining budget rides along every hop and
               expired work is shed before it wastes device time.
- `wal`      — durable local write-ahead log the event server spills
               accepted events into when storage is unreachable, with
               ordered replay and req-id dedupe (zero event loss).
"""

from predictionio_tpu.resilience.breaker import (
    CircuitBreaker,
    CircuitOpenError,
    get_breaker,
)
from predictionio_tpu.resilience.deadline import DeadlineExceeded
from predictionio_tpu.resilience.faults import FaultInjected, FaultSpec
from predictionio_tpu.resilience.retry import RetryPolicy
from predictionio_tpu.resilience.wal import EventWAL

__all__ = [
    "CircuitBreaker",
    "CircuitOpenError",
    "DeadlineExceeded",
    "EventWAL",
    "FaultInjected",
    "FaultSpec",
    "RetryPolicy",
    "get_breaker",
]
