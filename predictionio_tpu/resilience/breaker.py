"""Per-endpoint circuit breaker (closed → open → half-open) (ISSUE 4).

A breaker wraps one remote endpoint. While CLOSED every call passes;
`failure_threshold` consecutive failures trip it OPEN, after which calls
fail fast (no network, no retry budget burned) for `cooldown_s`. The
first call after the cooldown becomes the HALF-OPEN probe: its success
closes the breaker, its failure re-opens it for another cooldown. Only
one probe flies at a time — concurrent callers keep failing fast until
the probe reports.

Every state transition lands on the metrics registry:
`resilience_breaker_state{endpoint,dao}` (0 closed / 1 open / 2
half-open) and `resilience_breaker_transitions_total{endpoint,dao,state}`
— the acceptance surface `/metrics` scrapes. Call sites additionally
stamp the state onto their spans (`storage.rpc` carries
`breaker_state`).

Breakers key by endpoint **and DAO** (ISSUE 15 satellite, the carried
PR-4 follow-up): one storage daemon fronts several DAO tables, and an
events-table outage (a wedged events ingest path, a partial schema
migration) must fail fast ONLY the events path — the metadata DAO on
the same daemon keeps answering, so the query server can still resolve
tenants and models while ingestion is dark. Non-DAO breakers (the
gateway's per-replica ones) leave `dao` empty.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_VALUE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


class CircuitOpenError(Exception):
    """Fail-fast rejection: the endpoint's breaker is open."""


class CircuitBreaker:
    def __init__(
        self,
        name: str,
        failure_threshold: int = 5,
        cooldown_s: float = 10.0,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
        dao: str = "",
    ):
        self.name = name
        self.dao = dao
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probe_inflight = False
        if registry is None:
            from predictionio_tpu.obs.registry import get_default_registry

            registry = get_default_registry()
        self._state_gauge = registry.gauge(
            "resilience_breaker_state",
            "circuit breaker state (0 closed, 1 open, 2 half-open)",
            # label-bound: configured storage sources x fixed DAO set
            ("endpoint", "dao"),
        )
        self._transitions = registry.counter(
            "resilience_breaker_transitions_total",
            "circuit breaker state transitions, by destination state",
            # label-bound: configured storage sources x DAOs x states
            ("endpoint", "dao", "state"),
        )
        self._state_gauge.set(0.0, endpoint=name, dao=dao)

    @property
    def state(self) -> str:
        with self._lock:
            # surface the pending half-open without requiring an allow()
            if (
                self._state == OPEN
                and self._opened_at is not None
                and self._clock() - self._opened_at >= self.cooldown_s
            ):
                return HALF_OPEN
            return self._state

    def allow(self) -> bool:
        """True when a call may proceed (including as the recovery probe)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            now = self._clock()
            if self._state == OPEN:
                if (
                    self._opened_at is not None
                    and now - self._opened_at >= self.cooldown_s
                ):
                    self._transition(HALF_OPEN)
                    self._probe_inflight = True
                    return True
                return False
            # HALF_OPEN: exactly one probe at a time
            if not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def release_probe(self) -> None:
        """Abandon an allowed call WITHOUT an endpoint verdict — e.g. the
        caller's own deadline expired before any network I/O, or a local
        parse error aborted the attempt. Frees the half-open probe slot
        so recovery probing can continue; without this, an exception
        escaping between allow() and record_*() would latch the probe
        and wedge the breaker in fail-fast forever."""
        with self._lock:
            self._probe_inflight = False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_inflight = False
            if self._state == HALF_OPEN:
                self._transition(OPEN)  # failed probe: back to cooldown
                return
            self._failures += 1
            if self._state == CLOSED and self._failures >= self.failure_threshold:
                self._transition(OPEN)

    def _transition(self, to: str) -> None:
        # caller holds self._lock
        self._state = to
        if to == OPEN:
            self._opened_at = self._clock()
        elif to == CLOSED:
            self._opened_at = None
            self._failures = 0
        try:
            self._state_gauge.set(
                _STATE_VALUE[to], endpoint=self.name, dao=self.dao
            )
            self._transitions.inc(
                endpoint=self.name, dao=self.dao, state=to
            )
        except Exception:
            pass  # metrics hiccups must never break the call path

    def call(self, fn: Callable, *args, **kwargs):
        """Convenience wrapper: allow-gate, run, record the outcome."""
        if not self.allow():
            raise CircuitOpenError(f"circuit breaker {self.name} is open")
        try:
            result = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result


_breakers: dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def get_breaker(name: str, **kwargs) -> CircuitBreaker:
    """Process-global breaker per endpoint name: every client in the
    process shares one view of the endpoint's health (kwargs configure
    only the first construction)."""
    with _breakers_lock:
        b = _breakers.get(name)
        if b is None:
            b = _breakers[name] = CircuitBreaker(name, **kwargs)
        return b


def reset_breakers() -> None:
    """Drop all process-global breakers (tests)."""
    with _breakers_lock:
        _breakers.clear()
