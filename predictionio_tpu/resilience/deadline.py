"""Deadline propagation: `X-PIO-Deadline` header ⇄ per-request budget.

The header carries the caller's **remaining budget in milliseconds**
(like gRPC's ``grpc-timeout``) — never an absolute wall time, so clock
skew between hosts cannot corrupt it. On receipt, `JsonHandler` converts
it to an absolute ``time.monotonic()`` deadline in a ContextVar; every
downstream hop (the storage RPC client, the micro-batch dispatcher)
reads `remaining()` and:

- sheds work whose deadline already passed (503 + ``Retry-After``
  *before* the device or the network is touched),
- caps its own retry/backoff budget at the remaining time,
- re-stamps the shrunken budget onto the next hop's header.
"""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager
from typing import Iterator, Optional

HEADER = "X-PIO-Deadline"

_deadline: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "pio_deadline", default=None
)


class DeadlineExceeded(Exception):
    """The work's deadline passed before (or while) it ran."""


def current() -> Optional[float]:
    """Absolute ``time.monotonic()`` deadline, or None when unset."""
    return _deadline.get()


def set_deadline(at: Optional[float]) -> contextvars.Token:
    return _deadline.set(at)


def reset(token: contextvars.Token) -> None:
    _deadline.reset(token)


def remaining() -> Optional[float]:
    at = _deadline.get()
    return None if at is None else at - time.monotonic()


def expired() -> bool:
    rem = remaining()
    return rem is not None and rem <= 0


def from_budget(seconds: float) -> float:
    """Budget in seconds → absolute monotonic deadline."""
    return time.monotonic() + seconds


def parse_header(value: Optional[str]) -> Optional[float]:
    """Header string (remaining ms) → absolute monotonic deadline.
    Malformed or negative-beyond-reason values are ignored (None) —
    a bad client header must not 500 the request."""
    if not value:
        return None
    try:
        ms = float(value)
    except ValueError:
        return None
    if not -1e12 < ms < 1e12:  # reject inf/nan/absurd values
        return None
    return time.monotonic() + ms / 1000.0


def header_value(at: Optional[float] = None) -> Optional[str]:
    """Remaining budget as the header string (floored at 0 so an expired
    deadline propagates as expired, not as unset)."""
    at = at if at is not None else _deadline.get()
    if at is None:
        return None
    return str(max(0, int((at - time.monotonic()) * 1000)))


@contextmanager
def deadline_scope(at: Optional[float]) -> Iterator[None]:
    """Scope an absolute deadline over a block (no-op when None)."""
    if at is None:
        yield
        return
    token = _deadline.set(at)
    try:
        yield
    finally:
        _deadline.reset(token)
