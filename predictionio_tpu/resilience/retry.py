"""Retry policy: exponential backoff + jitter under a deadline budget.

Replaces the storage client's old fixed one-retry (ISSUE 4). The policy
is a value object — `delay(attempt)` exposes the schedule for tests and
`call(fn)` runs the loop: retry only the declared exception types, sleep
the (jittered) backoff between attempts, and stop early when the next
attempt could not complete before the deadline.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Tuple, Type


@dataclass
class RetryPolicy:
    """`max_attempts` total tries; attempt *i* (0-based) sleeps
    ``base_delay * multiplier**i`` capped at `max_delay` before attempt
    *i+1*, multiplied by a jitter factor uniform in
    ``[1 - jitter, 1 + jitter]``. `rng` is injectable so tests get a
    deterministic schedule."""

    max_attempts: int = 3
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    jitter: float = 0.5
    rng: random.Random = field(default_factory=random.Random, repr=False)

    def delay(self, attempt: int) -> float:
        d = min(self.base_delay * self.multiplier**attempt, self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self.rng.random() - 1.0)
        return max(0.0, d)

    def call(
        self,
        fn: Callable[[int], Any],
        retry_on: Tuple[Type[BaseException], ...],
        deadline: Optional[float] = None,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
    ) -> Any:
        """Run ``fn(attempt)`` until it returns, a non-retryable error
        escapes, attempts are exhausted, or `deadline` (absolute
        ``time.monotonic()`` seconds) passes — the per-call budget that
        keeps a retrying client inside its caller's deadline. The last
        retryable error re-raises."""
        last: Optional[BaseException] = None
        for attempt in range(max(1, self.max_attempts)):
            try:
                return fn(attempt)
            except retry_on as e:
                last = e
                if attempt + 1 >= max(1, self.max_attempts):
                    break
                pause = self.delay(attempt)
                if deadline is not None:
                    budget = deadline - time.monotonic()
                    if budget <= 0 or pause >= budget:
                        break  # the next attempt could not finish in time
                if on_retry is not None:
                    try:
                        on_retry(attempt, e)
                    except Exception:
                        pass
                if pause > 0:
                    time.sleep(pause)
        assert last is not None
        raise last
