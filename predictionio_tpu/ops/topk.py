"""Top-k scoring with exclusion masks — the serving-side ranking op.

Replaces the reference templates' host-side `.top(num)(Ordering)` over
score arrays (e.g. examples/.../ALSAlgorithm.scala predict top-N) with a
device `lax.top_k` over masked score vectors.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def masked_top_k(
    scores: jax.Array,  # (..., N)
    k: int,
    exclude_mask: Optional[jax.Array] = None,  # (..., N) bool — True = exclude
) -> tuple[jax.Array, jax.Array]:
    """Return (values, indices) of the top-k scores, with excluded positions
    pushed to -inf (they can still appear if fewer than k valid entries —
    callers filter on value > NEG_INF/2)."""
    if exclude_mask is not None:
        scores = jnp.where(exclude_mask, NEG_INF, scores)
    return jax.lax.top_k(scores, k)
