"""Segment reductions over interaction edge lists.

These are the workhorse ops of the sparse-factorization kernels (ALS, CCO):
training data is a COO edge list (src_idx, dst_idx, weight) and every
normal-equation product reduces per-edge contributions into per-row sums.
On TPU these lower to gathers + sorted segment scatter-adds that XLA fuses
with the surrounding elementwise work; the factor-matrix contractions stay
dense for the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def segment_sum(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    """Thin wrapper over jax.ops.segment_sum (kept as the single call site so
    a Pallas implementation can swap in without touching model code)."""
    return jax.ops.segment_sum(
        data,
        segment_ids,
        num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


def weighted_edge_sum(
    factors: jax.Array,  # (N_src, K)
    src_idx: jax.Array,  # (E,) int — rows of `factors` per edge
    dst_idx: jax.Array,  # (E,) int — output row per edge
    weights: jax.Array,  # (E,)
    num_dst: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    """out[d] = Σ_{edges e with dst_idx[e]==d} weights[e] * factors[src_idx[e]].

    The right-hand-side builder of the ALS normal equations: b_u = Σ c_ui y_i.
    """
    gathered = factors[src_idx] * weights[:, None]
    return segment_sum(gathered, dst_idx, num_dst, indices_are_sorted)


def edge_matvec(
    factors: jax.Array,  # (N_src, K) — the fixed side's factors (e.g. Y)
    v: jax.Array,  # (N_dst, K) — the vector being multiplied (per dst row)
    src_idx: jax.Array,  # (E,)
    dst_idx: jax.Array,  # (E,)
    weights: jax.Array,  # (E,) — per-edge scalar weight
    num_dst: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    """out[d] = Σ_e w_e * y_{src_e} (y_{src_e} · v_d)   for edges of d.

    The matrix-free normal-equation matvec: applies the per-row Gram
    correction Σ w y yᵀ without materializing any k×k matrices — per edge
    only a scalar inner product and a scaled gather, then a segment reduce.
    This keeps memory O(E·K) and lets CG solve all rows' systems batched.
    """
    y_e = factors[src_idx]  # (E, K)
    s = jnp.sum(y_e * v[dst_idx], axis=-1)  # (E,)
    return segment_sum(y_e * (weights * s)[:, None], dst_idx, num_dst, indices_are_sorted)


def chunked_weighted_edge_sum(
    factors: jax.Array,  # (N_src, K)
    src_idx: jax.Array,  # (E,) — sorted by dst
    dst_idx: jax.Array,  # (E,)
    weights: jax.Array,  # (E,)
    num_dst: int,
    n_chunks: int,
) -> jax.Array:
    """weighted_edge_sum with the edge axis processed in `n_chunks` scan
    steps, accumulating into the (num_dst, K) output.

    Bounds peak HBM: the (E, K) gather intermediate is lane-padded by XLA
    (K=10 → 128 lanes, a 12.8× expansion) and at MovieLens-20M scale a
    single-shot build OOMs a 16G chip; chunking caps the live intermediate
    at (E/n_chunks, K). E must divide evenly by n_chunks (pad upstream
    with weight-0 edges). Chunks are contiguous slices of the dst-sorted
    edge list, so the sorted segment fast path still applies per chunk."""
    if n_chunks <= 1:
        return weighted_edge_sum(
            factors, src_idx, dst_idx, weights, num_dst, True
        )
    chunks = (
        src_idx.reshape(n_chunks, -1),
        dst_idx.reshape(n_chunks, -1),
        weights.reshape(n_chunks, -1),
    )

    def body(acc, ch):
        s, d, w = ch
        acc = acc + segment_sum(factors[s] * w[:, None], d, num_dst, True)
        return acc, None

    acc0 = jnp.zeros((num_dst, factors.shape[1]), factors.dtype)
    acc, _ = jax.lax.scan(body, acc0, chunks)
    return acc


def chunked_edge_matvec(
    factors: jax.Array,  # (N_src, K)
    v: jax.Array,  # (N_dst, K)
    src_idx: jax.Array,  # (E,) — sorted by dst
    dst_idx: jax.Array,  # (E,)
    weights: jax.Array,  # (E,)
    num_dst: int,
    n_chunks: int,
) -> jax.Array:
    """edge_matvec with the edge axis scanned in chunks (see
    chunked_weighted_edge_sum for why)."""
    if n_chunks <= 1:
        return edge_matvec(
            factors, v, src_idx, dst_idx, weights, num_dst, True
        )
    chunks = (
        src_idx.reshape(n_chunks, -1),
        dst_idx.reshape(n_chunks, -1),
        weights.reshape(n_chunks, -1),
    )

    def body(acc, ch):
        s, d, w = ch
        y_e = factors[s]
        dot = jnp.sum(y_e * v[d], axis=-1)
        acc = acc + segment_sum(y_e * (w * dot)[:, None], d, num_dst, True)
        return acc, None

    acc0 = jnp.zeros((num_dst, factors.shape[1]), factors.dtype)
    acc, _ = jax.lax.scan(body, acc0, chunks)
    return acc


def chunked_gram_edge_sum(
    factors: jax.Array,  # (N_src, K)
    src_idx: jax.Array,  # (E,) — sorted by dst
    dst_idx: jax.Array,  # (E,)
    weights: jax.Array,  # (E,)
    num_dst: int,
    n_chunks: int,
) -> jax.Array:
    """A_flat[d] = Σ_{e: dst_e=d} w_e · (y_{src_e} ⊗ y_{src_e}), flattened
    to (num_dst, K²).

    The one-pass builder of per-row normal-equation operators: materializing
    the outer products FLATTENED keeps the minor dim at K² (≈128 lanes at
    rank ≤ 11 — near-zero padding) instead of two tiny trailing dims that
    TPU tiling would pad ~20×. One edge pass here replaces the
    2·cg_iterations matrix-free edge passes per half-step — the difference
    between HBM-bound and compute-bound ALS at MovieLens-20M scale."""
    k = factors.shape[1]

    def one(s, d, w):
        y = factors[s]
        outer = (y * w[:, None])[:, :, None] * y[:, None, :]
        return segment_sum(
            outer.reshape(y.shape[0], k * k), d, num_dst, True
        )

    if n_chunks <= 1:
        return one(src_idx, dst_idx, weights)
    chunks = (
        src_idx.reshape(n_chunks, -1),
        dst_idx.reshape(n_chunks, -1),
        weights.reshape(n_chunks, -1),
    )

    def body(acc, ch):
        return acc + one(*ch), None

    acc0 = jnp.zeros((num_dst, k * k), factors.dtype)
    acc, _ = jax.lax.scan(body, acc0, chunks)
    return acc


def f32_gram(a: jax.Array) -> jax.Array:
    """aᵀa at full float32 precision — CG needs exact Gram matrices; the
    TPU default (bf16 MXU passes) loses enough precision to stall
    convergence on ill-conditioned normal equations."""
    return jax.lax.dot_general(
        a, a,
        dimension_numbers=(((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )


def batched_cg(
    matvec,
    b: jax.Array,
    x0: jax.Array,
    iterations: int,
    eps: float = 1e-12,
) -> jax.Array:
    """Batched conjugate gradient: solves A_i x_i = b_i for every row i with
    a shared matvec that applies all A_i at once. Fixed iteration count —
    compiler-friendly (no data-dependent control flow under jit). Rows whose
    residual has reached float32 noise are frozen via `where` (iterating CG
    past convergence amplifies rounding error instead of reducing it).

    The iteration loop is PYTHON-UNROLLED, deliberately. A `lax.fori_loop`
    here miscompiles on TPU when the loop-invariant operators feeding
    `matvec` are large fused intermediates (observed at ML-20M shapes:
    the windowed edge pass + fori-CG in one jit returned garbage for
    every row — ~1000× off — while the identical math with the loop
    unrolled, or the same fori-CG with the operators passed in as jit
    arguments, is exact to f32). `iterations` is small and static (3 by
    default), so unrolling also lets XLA fuse across iterations."""
    r0 = b - matvec(x0)
    rs0 = jnp.sum(r0 * r0, axis=-1)
    tol = jnp.maximum(rs0, 1.0) * 1e-12  # relative f32 floor

    def body(state):
        x, r, p, rs = state
        live = rs > tol
        ap = matvec(p)
        alpha = jnp.where(live, rs / (jnp.sum(p * ap, axis=-1) + eps), 0.0)
        x = x + alpha[:, None] * p
        r = r - alpha[:, None] * ap
        rs_new = jnp.where(live, jnp.sum(r * r, axis=-1), rs)
        beta = jnp.where(live, rs_new / (rs + eps), 0.0)
        p = jnp.where(live[:, None], r + beta[:, None] * p, p)
        return x, r, p, rs_new

    state = (x0, r0, r0, rs0)
    for _ in range(iterations):
        state = body(state)
    return state[0]
