"""Segment reductions over interaction edge lists.

These are the workhorse ops of the sparse-factorization kernels (ALS, CCO):
training data is a COO edge list (src_idx, dst_idx, weight) and every
normal-equation product reduces per-edge contributions into per-row sums.
On TPU these lower to gathers + sorted segment scatter-adds that XLA fuses
with the surrounding elementwise work; the factor-matrix contractions stay
dense for the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def segment_sum(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    """Thin wrapper over jax.ops.segment_sum (kept as the single call site so
    a Pallas implementation can swap in without touching model code)."""
    return jax.ops.segment_sum(
        data,
        segment_ids,
        num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


def weighted_edge_sum(
    factors: jax.Array,  # (N_src, K)
    src_idx: jax.Array,  # (E,) int — rows of `factors` per edge
    dst_idx: jax.Array,  # (E,) int — output row per edge
    weights: jax.Array,  # (E,)
    num_dst: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    """out[d] = Σ_{edges e with dst_idx[e]==d} weights[e] * factors[src_idx[e]].

    The right-hand-side builder of the ALS normal equations: b_u = Σ c_ui y_i.
    """
    gathered = factors[src_idx] * weights[:, None]
    return segment_sum(gathered, dst_idx, num_dst, indices_are_sorted)


def edge_matvec(
    factors: jax.Array,  # (N_src, K) — the fixed side's factors (e.g. Y)
    v: jax.Array,  # (N_dst, K) — the vector being multiplied (per dst row)
    src_idx: jax.Array,  # (E,)
    dst_idx: jax.Array,  # (E,)
    weights: jax.Array,  # (E,) — per-edge scalar weight
    num_dst: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    """out[d] = Σ_e w_e * y_{src_e} (y_{src_e} · v_d)   for edges of d.

    The matrix-free normal-equation matvec: applies the per-row Gram
    correction Σ w y yᵀ without materializing any k×k matrices — per edge
    only a scalar inner product and a scaled gather, then a segment reduce.
    This keeps memory O(E·K) and lets CG solve all rows' systems batched.
    """
    y_e = factors[src_idx]  # (E, K)
    s = jnp.sum(y_e * v[dst_idx], axis=-1)  # (E,)
    return segment_sum(y_e * (weights * s)[:, None], dst_idx, num_dst, indices_are_sorted)


def f32_gram(a: jax.Array) -> jax.Array:
    """aᵀa at full float32 precision — CG needs exact Gram matrices; the
    TPU default (bf16 MXU passes) loses enough precision to stall
    convergence on ill-conditioned normal equations."""
    return jax.lax.dot_general(
        a, a,
        dimension_numbers=(((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )


def batched_cg(
    matvec,
    b: jax.Array,
    x0: jax.Array,
    iterations: int,
    eps: float = 1e-12,
) -> jax.Array:
    """Batched conjugate gradient: solves A_i x_i = b_i for every row i with
    a shared matvec that applies all A_i at once. Fixed iteration count —
    compiler-friendly (no data-dependent control flow under jit). Rows whose
    residual has reached float32 noise are frozen via `where` (iterating CG
    past convergence amplifies rounding error instead of reducing it)."""
    r0 = b - matvec(x0)
    rs0 = jnp.sum(r0 * r0, axis=-1)
    tol = jnp.maximum(rs0, 1.0) * 1e-12  # relative f32 floor

    def body(_, state):
        x, r, p, rs = state
        live = rs > tol
        ap = matvec(p)
        alpha = jnp.where(live, rs / (jnp.sum(p * ap, axis=-1) + eps), 0.0)
        x = x + alpha[:, None] * p
        r = r - alpha[:, None] * ap
        rs_new = jnp.where(live, jnp.sum(r * r, axis=-1), rs)
        beta = jnp.where(live, rs_new / (rs + eps), 0.0)
        p = jnp.where(live[:, None], r + beta[:, None] * p, p)
        return x, r, p, rs_new

    state = (x0, r0, r0, rs0)
    x, *_ = jax.lax.fori_loop(0, iterations, body, state)
    return x
