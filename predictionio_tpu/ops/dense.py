"""Dense-weight-matrix ALS edge pass — MXU matmuls instead of gathers.

At MovieLens-20M density (20M ratings over 138k×26.7k ≈ 0.54% filled),
the sparse edge pass is the wrong shape for a TPU: its per-edge factor
gather runs row-serial (~2.8 ns/row measured — 49% of round-4 train
time) and its one-hot segment reduction does 28 kFLOP/edge of synthetic
MXU work anyway. Below ~1% density the TPU-native move is to stop being
sparse: store the rating matrix DENSE in bf16 (138,624×26,880×2 B =
7.4 GB — it fits a 16 GB chip) and express each ALS half-step as two
plain dense matmuls over it:

    b     =  w1(R) @ Y         w1 = 1[r>0] + α·relu(r)   (implicit)
    gram  =  wg(R) @ Z         wg = α·|r|
         (explicit:  w1 = r, wg = 1[r≠0];  Z[i] = y_i ⊗ y_i flattened)

Zeros in R contribute exactly zero to every sum, so the dense contraction
computes the same per-row normal equations the windowed edge pass builds
— with no gather, no one-hot, no edge streams, at XLA's native dense
matmul efficiency. The weight matrices w1/wg are derived from R one row
-block at a time inside a scan, so they never materialize at full size
(deriving them whole would double peak HBM and invite XLA to hoist a
7.4 GB loop-invariant).

The half-step over R's ROWS (solving users) maps blocks to outputs; the
half-step over R's COLUMNS (solving items) contracts the same row blocks
against the matching user-factor blocks and accumulates — R is stored
once, row-major, and both directions stream it exactly once per pass.

Role in the reference: the MLlib-ALS hot loop
(examples/scala-parallel-recommendation/*/ALSAlgorithm.scala:50-57);
this is its below-1%-density dense reformulation, not a translation.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from predictionio_tpu.obs import devprof as _devprof

# rows of R processed per scan step; block weight derivations live in
# (ROW_BLOCK, n_cols) intermediates (~220 MB bf16 at ML-20M) instead of
# full-matrix ones
ROW_BLOCK = 2048
# lane quantum for the contraction axis
COL_PAD = 256


def _dt(dense_dtype: str):
    """Compute dtype of the weight tiles / matmul operands. int8 STORAGE
    still computes in bf16 — tiles dequantize block-by-block in VMEM-
    adjacent registers."""
    return jnp.float32 if dense_dtype == "f32" else jnp.bfloat16


#: bytes per dense-R cell, by storage mode — the single source the
#: staging gate and the bench's HBM model both read
BYTES_PER_CELL = {"f32": 4, "bf16": 2, "int8": 1}


def storage_dtype(dense_dtype: str):
    if dense_dtype == "int8":
        return jnp.int8
    return jnp.float32 if dense_dtype == "f32" else jnp.bfloat16


def int8_scale(vals) -> Optional[float]:
    """Smallest power-of-two (or decimal) scale making every rating an
    exact int8, or None. ML-style ratings (half-star steps ≤ 5) get
    s=2; integer counts ≤ 127 get s=1. Exactness is required — the
    dense path must train the SAME weights the sparse path would."""
    import numpy as np

    m = float(np.max(np.abs(vals))) if len(vals) else 0.0
    if m == 0.0:
        return 1.0
    for s in (1.0, 2.0, 4.0, 8.0, 10.0, 16.0, 20.0, 32.0, 50.0, 64.0, 100.0):
        scaled = np.asarray(vals, np.float64) * s
        if m * s <= 127.0 and np.all(scaled == np.round(scaled)):
            return s
    return None


def _precision(dense_dtype: str):
    # f32 mode exists for exactness (tests compare against the windowed
    # path); bf16 mode is the TPU throughput mode with f32 accumulation
    return (
        jax.lax.Precision.HIGHEST
        if dense_dtype == "f32"
        else jax.lax.Precision.DEFAULT
    )


def _weights(r_blk: jax.Array, implicit: bool, alpha, dt, inv_scale=None):
    """Per-block weight tiles derived in VMEM-adjacent registers — never
    materialized at matrix scale. int8-stored blocks dequantize here
    (r = q / scale), so HBM streams 1 byte per cell.

    implicit (Hu-Koren-Volinsky, signed feedback — matches
    models/als.py:_half_step_windowed):
      w1 = conf·pref = (1+α|r|)·1[r>0] = 1[r>0] + α·relu(r)
      wg = conf−1    = α·|r|
    explicit (ALS-WR):
      w1 = r, wg = 1[r≠0]  (staging rejects r==0 edges: a dense zero
      must mean "unobserved")
    """
    if r_blk.dtype == jnp.int8:
        r_blk = r_blk.astype(dt) * jnp.asarray(inv_scale, dt)
    if implicit:
        alpha = jnp.asarray(alpha, r_blk.dtype)
        w1 = (r_blk > 0).astype(r_blk.dtype) + alpha * jnp.maximum(
            r_blk, 0
        )
        wg = alpha * jnp.abs(r_blk)
    else:
        w1 = r_blk
        wg = (r_blk != 0).astype(r_blk.dtype)
    return w1.astype(dt), wg.astype(dt)


def _yz(fixed: jax.Array, dt):
    """Cast factor operands: Y (N, K) and flattened outer products
    Z (N, K²) — the K²-lane payload the gram matmul contracts."""
    n, k = fixed.shape
    y = fixed.astype(dt)
    z = (fixed[:, :, None] * fixed[:, None, :]).reshape(n, k * k).astype(dt)
    return y, z


@partial(
    jax.jit,
    static_argnames=("implicit", "dense_dtype", "row_block", "scale"),
)
def dense_row_pass(
    r: jax.Array,  # (n_rows_p, n_cols_p) storage-dtype rating matrix
    fixed: jax.Array,  # (n_cols_p, K) f32 — the fixed side's factors
    *,
    implicit: bool,
    alpha: float,
    dense_dtype: str = "bf16",
    row_block: int = ROW_BLOCK,
    scale: float = 1.0,
) -> tuple[jax.Array, jax.Array]:
    """(b (n_rows_p, K), gram_corr_flat (n_rows_p, K²)) for R's rows."""
    n_rows, n_cols = r.shape
    k = fixed.shape[1]
    dt = _dt(dense_dtype)
    prec = _precision(dense_dtype)
    y, z = _yz(fixed, dt)

    # Two dots, NOT one stacked dot: concatenating [w1; wg] into a
    # single (2·BR, n_cols) operand would stream R once instead of
    # twice, but XLA materializes the concatenated bf16 operand in HBM
    # (~write+read of 2× the R footprint per pass) — A/B-measured 2.5×
    # SLOWER at ML-20M (1.50 s vs 0.59 s per train). The two-dot form
    # fuses each weight derivation straight into its dot's operand
    # read, so the only HBM cost is reading int8 R twice.
    def blk(_, r_blk):  # (row_block, n_cols)
        w1, wg = _weights(r_blk, implicit, alpha, dt, 1.0 / scale)
        b = jax.lax.dot_general(
            w1, y, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        corr = jax.lax.dot_general(
            wg, z, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        return None, (b, corr)

    _, (b, corr) = jax.lax.scan(
        blk, None, r.reshape(n_rows // row_block, row_block, n_cols)
    )
    return b.reshape(n_rows, k), corr.reshape(n_rows, k * k)


# device profiling (ISSUE 3): top-level dispatches of these kernels (the
# alternating train loop traces THROUGH the wrappers — nested calls pass
# straight to the jit) land in the executable registry
dense_row_pass = _devprof.instrument("ops.dense_row_pass", dense_row_pass)


@partial(
    jax.jit,
    static_argnames=("implicit", "dense_dtype", "row_block", "scale"),
)
def dense_col_pass(
    r: jax.Array,  # (n_rows_p, n_cols_p) — SAME row-major storage
    fixed: jax.Array,  # (n_rows_p, K) f32 — factors of R's row side
    *,
    implicit: bool,
    alpha: float,
    dense_dtype: str = "bf16",
    row_block: int = ROW_BLOCK,
    scale: float = 1.0,
) -> tuple[jax.Array, jax.Array]:
    """(b (n_cols_p, K), gram_corr_flat (n_cols_p, K²)) for R's columns.

    Contracts the same row blocks the row pass streams (an Aᵀ·B GEMM per
    block — the MXU consumes either operand orientation natively, no
    materialized transpose of R)."""
    n_rows, n_cols = r.shape
    k = fixed.shape[1]
    dt = _dt(dense_dtype)
    prec = _precision(dense_dtype)
    y, z = _yz(fixed, dt)
    nb = n_rows // row_block
    xs = (
        r.reshape(nb, row_block, n_cols),
        y.reshape(nb, row_block, k),
        z.reshape(nb, row_block, k * k),
    )

    def blk(acc, ch):
        r_blk, y_blk, z_blk = ch
        w1, wg = _weights(r_blk, implicit, alpha, dt, 1.0 / scale)
        b_acc, c_acc = acc
        # two dots (see dense_row_pass: the stacked-operand fusion was
        # measured 2.5× slower — XLA materializes the concat)
        b_acc = b_acc + jax.lax.dot_general(
            w1, y_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        c_acc = c_acc + jax.lax.dot_general(
            wg, z_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        return (b_acc, c_acc), None

    acc0 = (
        jnp.zeros((n_cols, k), jnp.float32),
        jnp.zeros((n_cols, k * k), jnp.float32),
    )
    (b, corr), _ = jax.lax.scan(blk, acc0, xs)
    return b, corr


dense_col_pass = _devprof.instrument("ops.dense_col_pass", dense_col_pass)


@partial(jax.jit, static_argnames=("n_rows_p", "n_cols_p", "dense_dtype"))
def densify(
    rows: jax.Array,  # (E,) int32
    cols: jax.Array,  # (E,) int32
    vals: jax.Array,  # (E,) f32
    *,
    n_rows_p: int,
    n_cols_p: int,
    dense_dtype: str = "bf16",
    scale: float = 1.0,
) -> jax.Array:
    """Scatter the COO edge list into the dense padded rating matrix —
    ONCE per training set, on device (a 20M-edge scatter is ~180 ms; the
    matrix never crosses the host link). int8 mode stores round(r·scale)
    (exactness gated by int8_scale at staging). Requires unique (row,
    col) pairs — the staging gate checks."""
    st = storage_dtype(dense_dtype)
    r = jnp.zeros((n_rows_p, n_cols_p), st)
    if st == jnp.int8:
        q = jnp.round(vals * jnp.float32(scale)).astype(jnp.int8)
        return r.at[rows, cols].set(q)
    return r.at[rows, cols].set(vals.astype(st))


densify = _devprof.instrument("ops.densify", densify)
