"""Windowed (scatter-free) segment reduction for sorted edge lists.

The ALS normal-equation builders reduce 20M+ per-edge contributions into
per-row sums. XLA's scatter-add on TPU serializes per row (~9 ns/edge
measured on v5e — 174 ms for one 20M-edge scalar segment-sum), which made
the scatter-based gram/b builders the dominant cost of an ALS half-step
(~555 ms/pass at the ML-20M north star).

This module replaces the scatter with MXU matmuls (measured ~18× faster
at the same scale):

1. HOST PLAN (once per training set): cut the dst-sorted edge list into
   blocks of ≤ `block_edges` edges that never cross an `S`-row aligned
   output window. Blocks are padded to a fixed length; ≤ 3% inflation at
   MovieLens-20M degree distributions (one short block per non-empty
   window).
2. DEVICE PASS: for each block, build the (block_edges, S) one-hot of
   local row ids and contract it against the per-edge payload on the MXU
   — a batched (S × block_edges) @ (block_edges × D) matmul — giving
   per-block partial sums (n_blocks, S, D).
3. COMBINE: one segment-sum over the ~E/block_edges block rows (three
   orders of magnitude fewer scatter rows than edges).

The payload D packs the ALS b-vector (K lanes) and the flattened gram
correction (K² lanes) built from ONE factor gather, so a full implicit
half-step needs a single edge pass.

Role in the reference: this is the TPU replacement for MLlib ALS's
block-partitioned shuffle aggregation (org.apache.spark.mllib ALS used by
examples/scala-parallel-recommendation/*/ALSAlgorithm.scala:50-57).
"""

from __future__ import annotations
from predictionio_tpu.utils.env import env_str as _env_str

import os
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# Output window rows. 128 = one lane-width of rows; windows are aligned so
# every edge's local row id is dst % S with no per-edge host work.
WINDOW_ROWS = 128
# Max edges per block — the one-hot matmul's contraction length.
BLOCK_EDGES = 1024
# Blocks per scan step: bounds live intermediates to
# CHUNK_BLOCKS * BLOCK_EDGES * 128 lanes * 4 B ≈ 67 MB per materialized
# tensor (gather, one-hot, payload).
CHUNK_BLOCKS = 128


@dataclass(frozen=True)
class WindowPlan:
    """Host-side blocking of one dst-sorted edge list.

    The plan re-indexes every per-edge array through `edge_index` (padding
    slots point at edge 0 with valid=0), reshaped to (n_parts,
    chunks_per_part, chunk_blocks, block_edges). `n_parts` > 1 splits the
    block list into contiguous per-device groups for data-parallel
    training: axis 0 shards over the mesh's dp axis, and because blocks
    (hence output windows) are assigned to parts contiguously, the
    part-major global block order keeps window ids non-decreasing —
    padding blocks inside a part carry the part's LAST real window id
    (zero-weight, so they contribute nothing) to preserve sortedness.
    """

    edge_index: np.ndarray  # (E_p,) int — padded slot → original edge
    valid: np.ndarray  # (E_p,) float32 — 0.0 on padding slots
    local: np.ndarray  # (E_p,) int32 — dst % S per slot
    block_window: np.ndarray  # (n_blocks_p,) int32 — output window per block
    n_blocks: int  # real blocks (before padding)
    n_blocks_p: int  # padded blocks: n_parts * chunks_per_part * CB
    n_chunks: int  # n_parts * chunks_per_part
    n_windows: int  # output rows padded to n_windows * S
    n_rows: int  # true output row count
    n_parts: int = 1
    chunks_per_part: int = 1

    @property
    def n_rows_padded(self) -> int:
        return self.n_windows * WINDOW_ROWS

    def _shape4(self):
        return (self.n_parts, self.chunks_per_part, CHUNK_BLOCKS, BLOCK_EDGES)

    def take(self, per_edge: np.ndarray) -> np.ndarray:
        """Re-index a per-edge array into padded (P, L, CB, B_E) form.
        Float arrays are masked by `valid` so padding slots are inert."""
        if per_edge.size == 0:  # empty training set: all-padding plan
            per_edge = np.zeros(1, per_edge.dtype)
        out = per_edge[self.edge_index]
        if np.issubdtype(out.dtype, np.floating):
            out = out * self.valid
        return out.reshape(self._shape4())

    def chunked_local(self) -> np.ndarray:
        return self.local.reshape(self._shape4())

    def chunked_valid(self) -> np.ndarray:
        return self.valid.reshape(self._shape4())


def plan_windows(
    dst_sorted: np.ndarray, n_rows: int, n_parts: int = 1
) -> WindowPlan:
    """Build the block/window plan for a dst-sorted edge list. O(E) numpy.

    `n_parts` > 1 splits blocks into that many contiguous equal-size
    (padded) groups — one per data-parallel device."""
    S, B_E, CB = WINDOW_ROWS, BLOCK_EDGES, CHUNK_BLOCKS
    dst_sorted = np.asarray(dst_sorted)
    n_windows = max(1, -(-n_rows // S))
    if dst_sorted.size == 0:  # no edges: all-padding plan
        return WindowPlan(
            edge_index=np.zeros(n_parts * CB * B_E, np.int64),
            valid=np.zeros(n_parts * CB * B_E, np.float32),
            local=np.zeros(n_parts * CB * B_E, np.int32),
            block_window=np.zeros(n_parts * CB, np.int32),
            n_blocks=1,
            n_blocks_p=n_parts * CB,
            n_chunks=n_parts,
            n_windows=n_windows,
            n_rows=n_rows,
            n_parts=n_parts,
            chunks_per_part=1,
        )
    win = dst_sorted // S
    cnt = np.bincount(win, minlength=n_windows).astype(np.int64)
    nb_per_win = -(-cnt // B_E)
    nb_per_win[cnt == 0] = 0
    n_blocks = int(nb_per_win.sum())
    block_win = np.repeat(
        np.arange(n_windows, dtype=np.int32), nb_per_win
    )
    blk_in_win = np.concatenate(
        [np.arange(k, dtype=np.int64) for k in nb_per_win if k > 0]
    )
    rem = cnt[block_win] - blk_in_win * B_E
    block_len = np.clip(rem, 0, B_E).astype(np.int64)
    win_start = np.zeros(n_windows + 1, np.int64)
    np.cumsum(cnt, out=win_start[1:])
    block_start = win_start[block_win] + blk_in_win * B_E

    # contiguous equal-count split of real blocks over parts, each part
    # padded to a common chunk multiple (SPMD: every device scans the
    # same number of chunks)
    bounds = np.linspace(0, n_blocks, n_parts + 1).astype(np.int64)
    sizes = np.diff(bounds)
    L = max(1, int(-(-sizes.max() // CB)))
    bpp = L * CB  # padded blocks per part
    n_blocks_p = n_parts * bpp

    # padded-slot → real block id (-1 on padding blocks)
    part_block = np.full(n_blocks_p, -1, np.int64)
    pad_win = np.zeros(n_blocks_p, np.int32)
    last_win = np.int32(0)
    for d in range(n_parts):
        s, e = bounds[d], bounds[d + 1]
        lo = d * bpp
        part_block[lo : lo + (e - s)] = np.arange(s, e)
        if e > s:
            last_win = block_win[e - 1]
        pad_win[lo : lo + bpp] = last_win

    is_real = part_block >= 0
    safe = np.where(is_real, part_block, 0)
    b_len = np.where(is_real, block_len[safe], 0)
    b_start = np.where(is_real, block_start[safe], 0)
    b_win = np.where(is_real, block_win[safe], pad_win).astype(np.int32)

    off = np.tile(np.arange(B_E, dtype=np.int64), n_blocks_p)
    blk = np.repeat(np.arange(n_blocks_p, dtype=np.int64), B_E)
    valid = off < b_len[blk]
    edge_index = np.where(
        valid,
        b_start[blk] + np.minimum(off, np.maximum(b_len[blk] - 1, 0)),
        0,
    )
    local = (dst_sorted[edge_index] - b_win[blk] * S).astype(np.int32)

    return WindowPlan(
        edge_index=edge_index,
        valid=valid.astype(np.float32),
        local=local,
        block_window=b_win,
        n_blocks=n_blocks,
        n_blocks_p=n_blocks_p,
        n_chunks=n_parts * L,
        n_windows=n_windows,
        n_rows=n_rows,
        n_parts=n_parts,
        chunks_per_part=L,
    )


def resolve_pallas_mode(requested: str = "auto") -> Optional[str]:
    """Resolve the windowed-pass Pallas dispatch once, OUTSIDE any jit.

    Returns None (XLA scan path), "tpu" (compiled Pallas kernel) or
    "interpret" (Pallas interpreter — CPU equivalence tests). "auto"
    consults the PIO_PALLAS_WINDOWED env var: "0" forces XLA,
    "interpret" forces the interpreter, "1"/unset means Pallas whenever
    the default device is a TPU. Callers embedding the result in a jit
    must treat it as a static argument (stage_windowed does)."""
    from predictionio_tpu.ops import windowed_pallas

    if requested in (None, "off"):
        return None
    if requested == "interpret":
        return "interpret"
    if requested in ("tpu", "1"):
        return "tpu" if windowed_pallas.available() else None
    env = _env_str("PIO_PALLAS_WINDOWED").strip()
    if env == "0":
        return None
    if env == "interpret":
        return "interpret"
    return "tpu" if windowed_pallas.available() else None


def windowed_gram_b(
    factors: jax.Array,  # (N_src_padded, K)
    src: jax.Array,  # (P, L, CB, B_E) int32 — rows into `factors`
    w_b: jax.Array,  # (P, L, CB, B_E) — b-vector edge weights (0 on pads)
    w_g: jax.Array,  # (P, L, CB, B_E) — gram edge weights (0 on pads)
    local: jax.Array,  # (P, L, CB, B_E) int32 — dst % S
    block_window: jax.Array,  # (n_blocks_p,) int32, part-major, sorted
    n_windows: int,
    pallas: Optional[str] = None,  # resolved mode; None = XLA scan path
    mesh=None,  # required for the sharded pallas path (P > 1)
) -> tuple[jax.Array, jax.Array]:
    """One fused edge pass → (b (N_pad, K), gram_flat (N_pad, K²)).

    b[d]    = Σ_{e→d} w_b[e] · y[src[e]]
    gram[d] = Σ_{e→d} w_g[e] · y[src[e]] ⊗ y[src[e]]   (flattened K²)

    One gather of y per edge feeds both sums. Chunk arrays are 4D
    part-major (3D (L, CB, B_E) legacy inputs are treated as P=1): the
    part axis shards over the mesh's dp axis, the scan walks each part's
    chunks in SPMD lockstep, and GSPMD turns the final block-level
    segment-sum into per-device partial sums + one ICI all-reduce per
    half-step — the TPU-native analogue of MLlib ALS's block shuffle.

    The segment reduction is either the chunked XLA one-hot matmul below
    (pallas=None) or the fused VMEM kernel in ops/windowed_pallas.py
    (pallas="tpu" / "interpret"), which skips the HBM one-hot and
    payload entirely. pallas_call has no GSPMD partitioning rule, so
    P>1 runs the kernel under shard_map over dp instead (VERDICT r4
    #2): each device runs the single-part pallas scan on its own
    contiguous block group, segment-sums its local block partials into
    the full window space, and ONE psum over dp combines them — the
    same partial-sum + all-reduce shape GSPMD derives for the XLA path.
    Requires `mesh`; without it P>1 falls back to the XLA path.
    """
    k = factors.shape[1]
    if src.ndim == 3:  # legacy single-part layout
        src, w_b, w_g, local = (
            a[None] for a in (src, w_b, w_g, local)
        )
    p = src.shape[0]
    if p > 1 and pallas is not None and mesh is not None:
        from predictionio_tpu.parallel.mesh import DATA_AXIS

        none4 = jax.sharding.PartitionSpec(None, None, None, None)
        dp4 = jax.sharding.PartitionSpec(DATA_AXIS, None, None, None)

        def local_pass(f_l, src_l, wb_l, wg_l, lc_l, bwin_l):
            # each device: the single-part pallas path over ITS blocks
            # (window ids are global, so local sums land in full rows)
            b_l, g_l = windowed_gram_b(
                f_l, src_l, wb_l, wg_l, lc_l, bwin_l, n_windows,
                pallas=pallas,
            )
            return (
                jax.lax.psum(b_l, DATA_AXIS),
                jax.lax.psum(g_l, DATA_AXIS),
            )

        from predictionio_tpu.parallel.mesh import shard_map as _shard_map

        return _shard_map(
            local_pass,
            mesh=mesh,
            in_specs=(
                jax.sharding.PartitionSpec(None, None),  # factors (gathered)
                dp4, dp4, dp4, dp4,
                jax.sharding.PartitionSpec(DATA_AXIS),
            ),
            out_specs=(
                jax.sharding.PartitionSpec(None, None),
                jax.sharding.PartitionSpec(None, None),
            ),
            # pallas_call cannot annotate varying-mesh-axes on its
            # out_shapes; replication is established manually by the
            # psums above, so disable the checker rather than the kernel
            check=False,
        )(factors, src, w_b, w_g, local, block_window)
    if p > 1:
        pallas = None  # no mesh handle → XLA path (GSPMD shards it)
    d = k + k * k
    s_rows = WINDOW_ROWS
    # scan over each part's chunks in lockstep (axis 1 → leading)
    xs = tuple(jnp.swapaxes(a, 0, 1) for a in (src, w_b, w_g, local))

    if pallas is not None:
        from predictionio_tpu.ops import windowed_pallas

        factors_t = jnp.swapaxes(factors, 0, 1)  # (K, N) — tiny

        def body(_, ch):
            s, wb, wg, lc = ch  # (1, CB, B_E)
            cb, b_e = s.shape[1], s.shape[2]
            # transposed per-chunk gather (CB, K, B_E): the edge axis
            # stays in lanes, so the pallas boundary needs no 12.8×
            # lane-pad relayout, and the gather stays chunk-sized (a
            # whole-pass gather materialized GBs and measured slower)
            y_t = (
                factors_t[:, s.reshape(-1)]
                .reshape(k, cb, b_e)
                .transpose(1, 0, 2)
            )
            pb, pg = windowed_pallas.block_partials(
                y_t,
                wb.reshape(cb, b_e),
                wg.reshape(cb, b_e),
                lc.reshape(cb, b_e),
                s_rows=s_rows,
                interpret=(pallas == "interpret"),
            )
            return None, (pb, pg)

        _, (parts_b, parts_g) = jax.lax.scan(body, None, xs)
        out_b = jax.ops.segment_sum(
            parts_b.reshape(-1, s_rows * k), block_window,
            num_segments=n_windows + 1, indices_are_sorted=True,
        )[:n_windows].reshape(n_windows * s_rows, k)
        out_g = jax.ops.segment_sum(
            parts_g.reshape(-1, s_rows * k * k), block_window,
            num_segments=n_windows + 1, indices_are_sorted=True,
        )[:n_windows].reshape(n_windows * s_rows, k * k)
        return out_b, out_g

    def body(_, ch):
        s, wb, wg, lc = ch  # (P, CB, B_E)
        y = factors[s]  # (P, CB, B_E, K)
        outer = (y[..., :, None] * y[..., None, :]).reshape(
            *y.shape[:-1], k * k
        )
        payload = jnp.concatenate(
            [y * wb[..., None], outer * wg[..., None]], axis=-1
        )  # (P, CB, B_E, D)
        onehot = (
            lc[..., None] == jnp.arange(s_rows, dtype=jnp.int32)
        ).astype(jnp.float32)  # (P, CB, B_E, S)
        part = jnp.einsum(
            "pces,pced->pcsd", onehot, payload,
            precision=jax.lax.Precision.HIGHEST,
        )  # (P, CB, S, D)
        return None, part

    _, parts = jax.lax.scan(body, None, xs)  # (L, P, CB, S, D)
    # back to part-major global block order to match block_window
    parts = jnp.swapaxes(parts, 0, 1).reshape(-1, s_rows * d)
    out = jax.ops.segment_sum(
        parts, block_window, num_segments=n_windows + 1,
        indices_are_sorted=True,
    )[:n_windows].reshape(n_windows * s_rows, d)
    return out[:, :k], out[:, k:]


def flat_gram_matvec(a_flat: jax.Array, v: jax.Array) -> jax.Array:
    """Batched (K×K)·(K,) matvec with the operator kept FLAT (N, K²).

    Reshaping to (N, K, K) would tile both trailing dims on TPU (K=10 →
    8×128 tiles, a ~20× padding blowup that made the CG matvec ~10× slower
    than its data volume warrants). Instead: elementwise-multiply by the
    tiled vector, then contract groups of K lanes with a constant (K², K)
    selection matrix on the MXU.

    out[n, i] = Σ_j a_flat[n, i·K + j] · v[n, j]
    """
    n, k2 = a_flat.shape
    k = v.shape[1]
    vt = jnp.tile(v, (1, k))  # vt[n, m] = v[n, m % K]
    sel = jnp.repeat(jnp.eye(k, dtype=a_flat.dtype), k, axis=0)  # (K², K)
    return jax.lax.dot_general(
        a_flat * vt, sel,
        dimension_numbers=(((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )
