"""Windowed (scatter-free) segment reduction for sorted edge lists.

The ALS normal-equation builders reduce 20M+ per-edge contributions into
per-row sums. XLA's scatter-add on TPU serializes per row (~9 ns/edge
measured on v5e — 174 ms for one 20M-edge scalar segment-sum), which made
the scatter-based gram/b builders the dominant cost of an ALS half-step
(~555 ms/pass at the ML-20M north star).

This module replaces the scatter with MXU matmuls (measured ~18× faster
at the same scale):

1. HOST PLAN (once per training set): cut the dst-sorted edge list into
   blocks of ≤ `block_edges` edges that never cross an `S`-row aligned
   output window. Blocks are padded to a fixed length; ≤ 3% inflation at
   MovieLens-20M degree distributions (one short block per non-empty
   window).
2. DEVICE PASS: for each block, build the (block_edges, S) one-hot of
   local row ids and contract it against the per-edge payload on the MXU
   — a batched (S × block_edges) @ (block_edges × D) matmul — giving
   per-block partial sums (n_blocks, S, D).
3. COMBINE: one segment-sum over the ~E/block_edges block rows (three
   orders of magnitude fewer scatter rows than edges).

The payload D packs the ALS b-vector (K lanes) and the flattened gram
correction (K² lanes) built from ONE factor gather, so a full implicit
half-step needs a single edge pass.

Role in the reference: this is the TPU replacement for MLlib ALS's
block-partitioned shuffle aggregation (org.apache.spark.mllib ALS used by
examples/scala-parallel-recommendation/*/ALSAlgorithm.scala:50-57).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

# Output window rows. 128 = one lane-width of rows; windows are aligned so
# every edge's local row id is dst % S with no per-edge host work.
WINDOW_ROWS = 128
# Max edges per block — the one-hot matmul's contraction length.
BLOCK_EDGES = 1024
# Blocks per scan step: bounds live intermediates to
# CHUNK_BLOCKS * BLOCK_EDGES * 128 lanes * 4 B ≈ 67 MB per materialized
# tensor (gather, one-hot, payload).
CHUNK_BLOCKS = 128


@dataclass(frozen=True)
class WindowPlan:
    """Host-side blocking of one dst-sorted edge list.

    The plan re-indexes every per-edge array through `edge_index` (padding
    slots point at edge 0 with valid=0), reshaped to (n_chunks,
    chunk_blocks, block_edges) for a `lax.scan` over chunks.
    """

    edge_index: np.ndarray  # (E_p,) int — padded slot → original edge
    valid: np.ndarray  # (E_p,) float32 — 0.0 on padding slots
    local: np.ndarray  # (E_p,) int32 — dst % S per slot
    block_window: np.ndarray  # (n_blocks_p,) int32 — output window per block
    n_blocks: int  # real blocks (before chunk padding)
    n_blocks_p: int  # blocks padded to a chunk multiple
    n_chunks: int
    n_windows: int  # output rows padded to n_windows * S
    n_rows: int  # true output row count

    @property
    def n_rows_padded(self) -> int:
        return self.n_windows * WINDOW_ROWS

    def take(self, per_edge: np.ndarray) -> np.ndarray:
        """Re-index a per-edge array into padded (n_chunks, CB, B_E) form.
        Float arrays are masked by `valid` so padding slots are inert."""
        if per_edge.size == 0:  # empty training set: all-padding plan
            per_edge = np.zeros(1, per_edge.dtype)
        out = per_edge[self.edge_index]
        if np.issubdtype(out.dtype, np.floating):
            out = out * self.valid
        return out.reshape(self.n_chunks, CHUNK_BLOCKS, BLOCK_EDGES)

    def chunked_local(self) -> np.ndarray:
        return self.local.reshape(self.n_chunks, CHUNK_BLOCKS, BLOCK_EDGES)

    def chunked_valid(self) -> np.ndarray:
        return self.valid.reshape(self.n_chunks, CHUNK_BLOCKS, BLOCK_EDGES)


def plan_windows(dst_sorted: np.ndarray, n_rows: int) -> WindowPlan:
    """Build the block/window plan for a dst-sorted edge list. O(E) numpy."""
    S, B_E, CB = WINDOW_ROWS, BLOCK_EDGES, CHUNK_BLOCKS
    dst_sorted = np.asarray(dst_sorted)
    n_windows = max(1, -(-n_rows // S))
    if dst_sorted.size == 0:  # no edges: one all-padding chunk
        return WindowPlan(
            edge_index=np.zeros(CB * B_E, np.int64),
            valid=np.zeros(CB * B_E, np.float32),
            local=np.zeros(CB * B_E, np.int32),
            block_window=np.full(CB, n_windows, np.int32),
            n_blocks=1,
            n_blocks_p=CB,
            n_chunks=1,
            n_windows=n_windows,
            n_rows=n_rows,
        )
    win = dst_sorted // S
    cnt = np.bincount(win, minlength=n_windows).astype(np.int64)
    nb_per_win = -(-cnt // B_E)
    nb_per_win[cnt == 0] = 0
    n_blocks = int(nb_per_win.sum())
    block_win = np.repeat(
        np.arange(n_windows, dtype=np.int32), nb_per_win
    )
    blk_in_win = np.concatenate(
        [np.arange(k, dtype=np.int64) for k in nb_per_win if k > 0]
    )
    rem = cnt[block_win] - blk_in_win * B_E
    block_len = np.clip(rem, 0, B_E).astype(np.int64)
    win_start = np.zeros(n_windows + 1, np.int64)
    np.cumsum(cnt, out=win_start[1:])
    block_start = win_start[block_win] + blk_in_win * B_E

    E_p = n_blocks * B_E
    off = np.tile(np.arange(B_E, dtype=np.int64), n_blocks)
    blk = np.repeat(np.arange(n_blocks, dtype=np.int64), B_E)
    valid = off < block_len[blk]
    edge_index = np.where(
        valid, block_start[blk] + np.minimum(off, np.maximum(block_len[blk] - 1, 0)), 0
    )
    local = (dst_sorted[edge_index] - block_win[blk] * S).astype(np.int32)

    pad_blocks = (-n_blocks) % CB
    n_blocks_p = n_blocks + pad_blocks
    if pad_blocks:
        edge_index = np.concatenate(
            [edge_index, np.zeros(pad_blocks * B_E, np.int64)]
        )
        valid = np.concatenate([valid, np.zeros(pad_blocks * B_E, bool)])
        local = np.concatenate([local, np.zeros(pad_blocks * B_E, np.int32)])
        block_win = np.concatenate(
            [block_win, np.full(pad_blocks, n_windows, np.int32)]
        )
    return WindowPlan(
        edge_index=edge_index,
        valid=valid.astype(np.float32),
        local=local,
        block_window=block_win,
        n_blocks=n_blocks,
        n_blocks_p=n_blocks_p,
        n_chunks=n_blocks_p // CB,
        n_windows=n_windows,
        n_rows=n_rows,
    )


def windowed_gram_b(
    factors: jax.Array,  # (N_src_padded, K)
    src: jax.Array,  # (n_chunks, CB, B_E) int32 — rows into `factors`
    w_b: jax.Array,  # (n_chunks, CB, B_E) — b-vector edge weights (0 on pads)
    w_g: jax.Array,  # (n_chunks, CB, B_E) — gram edge weights (0 on pads)
    local: jax.Array,  # (n_chunks, CB, B_E) int32 — dst % S
    block_window: jax.Array,  # (n_blocks_p,) int32
    n_windows: int,
) -> tuple[jax.Array, jax.Array]:
    """One fused edge pass → (b (N_pad, K), gram_flat (N_pad, K²)).

    b[d]    = Σ_{e→d} w_b[e] · y[src[e]]
    gram[d] = Σ_{e→d} w_g[e] · y[src[e]] ⊗ y[src[e]]   (flattened K²)

    One gather of y per edge feeds both sums; the segment reduction is the
    windowed one-hot matmul described in the module docstring.
    """
    k = factors.shape[1]
    d = k + k * k
    s_rows = WINDOW_ROWS

    def body(_, ch):
        s, wb, wg, lc = ch  # (CB, B_E)
        y = factors[s]  # (CB, B_E, K)
        outer = (y[..., :, None] * y[..., None, :]).reshape(
            *y.shape[:-1], k * k
        )
        payload = jnp.concatenate(
            [y * wb[..., None], outer * wg[..., None]], axis=-1
        )  # (CB, B_E, D)
        onehot = (
            lc[..., None] == jnp.arange(s_rows, dtype=jnp.int32)
        ).astype(jnp.float32)  # (CB, B_E, S)
        part = jnp.einsum(
            "ces,ced->csd", onehot, payload,
            precision=jax.lax.Precision.HIGHEST,
        )  # (CB, S, D)
        return None, part

    _, parts = jax.lax.scan(body, None, (src, w_b, w_g, local))
    parts = parts.reshape(-1, s_rows * d)  # (n_blocks_p, S*D)
    out = jax.ops.segment_sum(
        parts, block_window, num_segments=n_windows + 1,
        indices_are_sorted=True,
    )[:n_windows].reshape(n_windows * s_rows, d)
    return out[:, :k], out[:, k:]


def flat_gram_matvec(a_flat: jax.Array, v: jax.Array) -> jax.Array:
    """Batched (K×K)·(K,) matvec with the operator kept FLAT (N, K²).

    Reshaping to (N, K, K) would tile both trailing dims on TPU (K=10 →
    8×128 tiles, a ~20× padding blowup that made the CG matvec ~10× slower
    than its data volume warrants). Instead: elementwise-multiply by the
    tiled vector, then contract groups of K lanes with a constant (K², K)
    selection matrix on the MXU.

    out[n, i] = Σ_j a_flat[n, i·K + j] · v[n, j]
    """
    n, k2 = a_flat.shape
    k = v.shape[1]
    vt = jnp.tile(v, (1, k))  # vt[n, m] = v[n, m % K]
    sel = jnp.repeat(jnp.eye(k, dtype=a_flat.dtype), k, axis=0)  # (K², K)
    return jax.lax.dot_general(
        a_flat * vt, sel,
        dimension_numbers=(((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )
