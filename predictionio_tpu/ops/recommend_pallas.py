"""Verb-agnostic fused score+top-k: score, mask, and select in ONE pass.

The serving hot path's XLA form is a two-step program —
``scores = q @ itf.T`` then ``lax.top_k`` (models/als.py's
`_recommend_jit[_nomask]`): XLA materializes the full (B, I) score
matrix in HBM between the matmul and the TopK custom call. At serving
rank (K ≈ 10) the score matrix IS the dominant HBM term: one write plus
one read of B·I·4 bytes against an item-factor stream of only I·K·4
(for B = 64 on the ML-20M catalog that's ~14 MB of score traffic vs
~1 MB of factors — >90 % of the pass).

This kernel never materializes the score matrix. The grid walks item
tiles; each step loads one (T, K) factor tile into VMEM, issues the
(B, T) MXU dot against the resident query block, applies the exclusion
mask and the dead-pad-column mask in registers, and merges the tile
into a RUNNING sorted top-k list held in VMEM scratch. Only the final
(B, k) values + global indices ever reach HBM.

ISSUE 14 generalizes the PR-11 recommend-only kernel into the ONE
fused selector every serving verb routes through:

- **scaled scoring** (the cosine/int8 unification): optional per-row
  (B, 1) query scales and (1, I_p) item scales multiply the dot in
  registers. int8 mode uses them as dequant scales; the cosine verbs
  (`als.similar`, itemsim's on-the-fly column cosine) pass INVERSE
  NORMS — cosine(q, x) = (q·x)·(1/|q|)·(1/|x|) — so the SAME resident
  factor slab serves both dot-product recommend and cosine similar
  with no normalized copy in HBM.
- **precomputed-score mode** (`fused_masked_topk`): the CCO/universal
  `batch_score_topk` accumulates its (B, I) LLR total by gather —
  there is no factor matmul to fuse — but its exclusion + top-k tail
  is this kernel's exact shape: stream the score tiles once, mask in
  registers, running top-k in VMEM. The XLA tail's masked score COPY
  (a second B·I write+read) and the (B, I) exclusion-mask
  materialization both disappear.
- **bit-packed masks**: the exclusion mask input is a little-endian
  bit-word column (`pack_mask_np`, (B, I_p/32) int32) — 1/32 the
  host→device and HBM mask bytes of the old f32 0/1 input — expanded
  to per-lane bits in registers.
- **exclusion ROW LISTS**: the common small-blacklist case (a few
  excluded items per query) ships a (B, E) int32 index list instead of
  any per-item mask; the kernel compares global column ids against the
  E resident entries per tile. E is static and small (row-list callers
  cap at `ROWLIST_MAX`); -1 and out-of-range entries are inert.

The merge is an iterative extraction with early exit: while any query
row's tile maximum still beats that row's current k-th value, extract
each such row's (max, lowest-index-of-max) and insert it into the
row's sorted list (count-position + lane shift — no sort primitive,
Mosaic has none on this jax). For random scores the expected number of
extractions across the WHOLE pass is k·(1 + ln n_tiles) — the early
exit makes later tiles nearly free — and the worst case terminates
(every iteration kills at least one element of some live row).

Tie-breaking matches `lax.top_k` exactly (stable: among equal values
the LOWEST index wins): tiles scan in index order, within a tile the
extraction takes the lowest index of the row max, and the insertion
position counts `>=` so a later tie lands after the resident equals.
tests/test_recommend_pallas.py + tests/test_fused_serving.py prove
parity against the XLA two-step in interpret mode (masked / unmasked /
k edge cases / crafted cross-tile ties / packed-vs-rowlist
equivalence).

dtype modes: f32 (exact), bf16 (bf16 storage + bf16×bf16→f32 MXU dot —
half the factor stream, scores within bf16 rounding), int8 (per-row
symmetric quantization, int8×int8→int32 dot, scale-product dequant in
registers — ~1/4 the factor stream).

Gating mirrors ops/windowed_pallas.py: `resolve_mode("auto")` returns
"tpu" only where the Mosaic lowering can actually run, "interpret"
under PIO_PALLAS_RECOMMEND=interpret (the CPU test path), else None —
callers then keep the XLA two-step (which still gets the int8/bf16,
packed-mask, and donation wins). This box is CPU-only, so the TPU
lowering is validated structurally (every primitive used has a Mosaic
rule on this jax: while/cond/concatenate/slice/iota/reduce_max/
select_n/dot_general/shift_right_logical/broadcast_in_dim); first TPU
deployment must re-run the parity suite in "tpu" mode.
"""

from __future__ import annotations
from predictionio_tpu.utils.env import env_str as _env_str

import functools

import jax
import jax.numpy as jnp

from predictionio_tpu.ops.topk import NEG_INF

#: item-tile ladder — first divisor of the padded item count wins; the
#: staging pad quantum (ITEM_PAD) guarantees at least one always does
ITEM_TILES = (2048, 1024, 512, 256, 128)
#: pad item rows to this multiple at staging so a tile always divides
#: (multiple of 32 so bit-packed mask words always cover whole tiles)
ITEM_PAD = 128

#: widest (B, E) exclusion row list the kernel unrolls per tile; longer
#: exclusion sets must ship as bit-packed mask words instead (the
#: unrolled compare chain would start to rival the score matmul's cost)
ROWLIST_MAX = 64

#: running-list sentinel: strictly below every representable score
#: INCLUDING the NEG_INF mask value, so dead pad columns and the
#: not-yet-filled tail never collide with legitimately masked entries
_SENTINEL = float(jnp.finfo(jnp.float32).min)


def pick_item_tile(n_items_padded: int) -> int:
    for t in ITEM_TILES:
        if n_items_padded % t == 0:
            return t
    return 0


def pad_items(n_items: int) -> int:
    """Padded item-row count the staging side must allocate."""
    return -(-max(n_items, 1) // ITEM_PAD) * ITEM_PAD


# ---------------------------------------------------------------------------
# bit-packed exclusion masks (ISSUE 14 tentpole part 3)
# ---------------------------------------------------------------------------


def pack_mask_np(mask, i_p: int):
    """Host-side pack of a bool (B, n) exclusion mask into little-endian
    32-bit words at the padded item width: word ``c // 32`` bit
    ``c % 32`` is column ``c``. (B, i_p/32) int32 — 1/32 the bytes of
    the f32 0/1 mask the kernel used to take (i_p is ITEM_PAD-aligned,
    so 32 always divides it)."""
    import numpy as np

    mask = np.asarray(mask, bool)
    b = mask.shape[0]
    out = np.zeros((b, i_p // 8), np.uint8)
    if mask.shape[1]:
        packed = np.packbits(mask, axis=1, bitorder="little")
        out[:, : packed.shape[1]] = packed[:, : i_p // 8]
    return np.ascontiguousarray(out).view("<u4").view("<i4")


def rowlist_np(lists):
    """Host-side (B, E) int32 -1-padded exclusion row list from
    per-query id lists, at the shared pow2-bucketed width (floor 8) —
    the ONE owner of the row-list wire convention (width bucketing +
    pad sentinel), so the engines and the serving layer can never
    drift. Returns None when every list is empty."""
    import numpy as np

    widest = max((len(r) for r in lists), default=0)
    if widest == 0:
        return None
    e_pad = max(8, 1 << (widest - 1).bit_length())
    ex = np.full((len(lists), e_pad), -1, np.int32)
    for b, row in enumerate(lists):
        ex[b, : len(row)] = row
    return ex


def unpack_mask_jnp(words: jax.Array, n_cols: int) -> jax.Array:
    """Traced unpack of packed mask words back to a bool (B, n_cols)
    mask — the XLA fallback's read side, so packed callers carry 1/32
    the mask traffic regardless of which kernel mode resolved."""
    b, w = words.shape
    bits = jnp.broadcast_to(words[:, :, None], (b, w, 32))
    shifts = jnp.arange(32, dtype=words.dtype)[None, None, :]
    return (
        jax.lax.shift_right_logical(bits, shifts) & 1
    ).reshape(b, w * 32)[:, :n_cols] != 0


def rowlist_mask_jnp(rows: jax.Array, n_cols) -> jax.Array:
    """Traced (B, E) exclusion row list → bool (B, n_cols) mask (the
    XLA fallback's scatter; -1/-out-of-range entries inert)."""
    b = rows.shape[0]
    safe = jnp.where(
        (rows >= 0) & (rows < n_cols), rows, n_cols
    )
    m = jnp.zeros((b, n_cols + 1), bool)
    m = m.at[jnp.arange(b)[:, None], safe].set(True)
    return m[:, :n_cols]


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


def _shift_right(x: jax.Array) -> jax.Array:
    """Lane shift by one: out[:, j] = x[:, j-1] (lane 0 duplicated —
    always overwritten by the insert select)."""
    return jnp.concatenate([x[:, :1], x[:, :-1]], axis=1)


def _make_kernel(
    *, k: int, tile: int, mask_kind, n_excl: int, scaled: bool,
    int8: bool, precomputed: bool, n_tiles: int,
):
    from jax.experimental import pallas as pl

    def kernel(*refs):
        it = iter(refs)
        n_ref = next(it)  # (1,) i32 SMEM — live item count (TRACED:
        # vocab growth within the pad must not recompile the program)
        if precomputed:
            sc_ref = next(it)  # (B, tile) f32 score tile
            q_ref = itf_ref = None
        else:
            q_ref = next(it)
            itf_ref = next(it)
        qs_ref = next(it) if scaled else None
        isc_ref = next(it) if scaled else None
        mask_ref = next(it) if mask_kind is not None else None
        vals_ref = next(it)
        idx_ref = next(it)
        rv_ref = next(it)  # (B, k) f32 running values, sorted desc
        ri_ref = next(it)  # (B, k) i32 running global indices

        j = pl.program_id(0)

        @pl.when(j == 0)
        def _init():
            rv_ref[...] = jnp.full(rv_ref.shape, _SENTINEL, jnp.float32)
            ri_ref[...] = jnp.zeros(ri_ref.shape, jnp.int32)

        # -- score tile — the only read of this factor/score tile ------
        if precomputed:
            s = sc_ref[...]
        elif int8:
            s = jax.lax.dot_general(
                q_ref[...], itf_ref[...], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            ).astype(jnp.float32)
        else:
            # f32 or bf16 storage; the MXU accumulates in f32 either way
            s = jax.lax.dot_general(
                q_ref[...], itf_ref[...], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        if scaled:
            # dequant (int8) or inverse-norm (cosine) scale product —
            # the (B,1)·(1,T) outer product applies in registers
            s = s * qs_ref[...] * isc_ref[...]
        b = s.shape[0]
        col = jax.lax.broadcasted_iota(jnp.int32, (b, tile), 1)
        gcol0 = j * tile
        if mask_kind == "bits":
            # packed words (B, tile/32): expand each word over its 32
            # lanes and shift the lane's bit down — no f32 mask column
            w = mask_ref[...]
            bits = jnp.broadcast_to(
                w.reshape(b, tile // 32, 1), (b, tile // 32, 32)
            ).reshape(b, tile)
            bit = jax.lax.shift_right_logical(bits, col % 32) & 1
            s = jnp.where(bit != 0, NEG_INF, s)
        elif mask_kind == "rows":
            # (B, E) exclusion row list, resident: compare global column
            # ids per tile; -1 / out-of-range entries never match
            ex = mask_ref[...]
            gc = gcol0 + col
            hit = gc == ex[:, 0:1]
            for e in range(1, n_excl):
                hit = hit | (gc == ex[:, e : e + 1])
            s = jnp.where(hit, NEG_INF, s)
        # dead pad columns sink BELOW the mask value: they must lose to
        # legitimately masked real items when the list drains that deep
        s = jnp.where(gcol0 + col >= n_ref[0], _SENTINEL, s)

        lane = jax.lax.broadcasted_iota(jnp.int32, (b, k), 1)

        def body(carry):
            s, rv, ri, _ = carry
            m = jnp.max(s, axis=1, keepdims=True)  # (B, 1)
            # lowest column index attaining the row max (argmax is not
            # a Mosaic primitive; two reductions are)
            am = jnp.min(
                jnp.where(s == m, col, jnp.int32(2**30)),
                axis=1, keepdims=True,
            )
            live = m > rv[:, k - 1 : k]  # (B, 1) rows still inserting
            # sorted insert: position counts >= so ties land AFTER the
            # resident equals (earlier tiles = lower indices = stable)
            pos = jnp.sum(
                (rv >= m).astype(jnp.int32), axis=1, keepdims=True
            )
            nv = jnp.where(
                lane < pos, rv,
                jnp.where(lane == pos, m, _shift_right(rv)),
            )
            ni = jnp.where(
                lane < pos, ri,
                jnp.where(lane == pos, am + gcol0, _shift_right(ri)),
            )
            rv = jnp.where(live, nv, rv)
            ri = jnp.where(live, ni, ri)
            # kill the extracted element so the next max is fresh
            s = jnp.where((col == am) & live, _SENTINEL, s)
            cont = jnp.max(
                jnp.max(s, axis=1, keepdims=True) - rv[:, k - 1 : k]
            )
            return s, rv, ri, cont

        rv0, ri0 = rv_ref[...], ri_ref[...]
        cont0 = jnp.max(
            jnp.max(s, axis=1, keepdims=True) - rv0[:, k - 1 : k]
        )
        _, rv1, ri1, _ = jax.lax.while_loop(
            lambda c: c[3] > 0.0, body, (s, rv0, ri0, cont0)
        )
        rv_ref[...] = rv1
        ri_ref[...] = ri1

        @pl.when(j == n_tiles - 1)
        def _emit():
            vals_ref[...] = rv_ref[...]
            idx_ref[...] = ri_ref[...]

    return kernel


def _fused_call(
    *, b: int, kdim: int, n_items_p: int, k: int, item_tile: int,
    interpret: bool, precomputed: bool, scaled: bool, int8: bool,
    mask_kind, n_excl: int, n_items, main_args: list, main_specs: list,
    scale_args: list, mask_arg,
):
    """Shared pallas_call assembly for the q·itf and precomputed-score
    entry points — one place owns specs, scratch, and grid."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tile = item_tile or pick_item_tile(n_items_p)
    if tile <= 0:
        raise ValueError(
            f"padded item count {n_items_p} has no tile divisor — stage "
            f"with recommend_pallas.pad_items"
        )
    if not 0 < k <= n_items_p:
        raise ValueError(f"need 0 < k ({k}) <= padded {n_items_p}")
    n_tiles = n_items_p // tile

    n_arr = jnp.asarray(n_items, jnp.int32).reshape(1)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + main_specs(tile)
    args = [n_arr] + main_args
    if scaled:
        in_specs.append(pl.BlockSpec((b, 1), lambda j: (0, 0)))
        in_specs.append(pl.BlockSpec((1, tile), lambda j: (0, j)))
        args.extend(scale_args)
    if mask_kind == "bits":
        in_specs.append(pl.BlockSpec((b, tile // 32), lambda j: (0, j)))
        args.append(mask_arg)
    elif mask_kind == "rows":
        in_specs.append(pl.BlockSpec((b, n_excl), lambda j: (0, 0)))
        args.append(mask_arg)

    kernel = _make_kernel(
        k=k, tile=tile, mask_kind=mask_kind, n_excl=n_excl,
        scaled=scaled, int8=int8, precomputed=precomputed,
        n_tiles=n_tiles,
    )
    # jax renamed TPUCompilerParams -> CompilerParams across 0.4/0.5
    cp = getattr(
        pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
    )(dimension_semantics=("arbitrary",))
    return pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((b, k), lambda j: (0, 0)),
            pl.BlockSpec((b, k), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, k), jnp.float32),
            jax.ShapeDtypeStruct((b, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, k), jnp.float32),
            pltpu.VMEM((b, k), jnp.int32),
        ],
        compiler_params=cp,
        interpret=interpret,
    )(*args)


def _mask_kind(mask_bits, exclude_rows):
    if mask_bits is not None and exclude_rows is not None:
        raise ValueError(
            "pass either packed mask words or an exclusion row list, "
            "not both — callers compose exclusions into one form"
        )
    if mask_bits is not None:
        return "bits"
    if exclude_rows is not None:
        if exclude_rows.shape[1] == 0:
            # a (B, 0) list excludes nothing — the kernel's compare
            # chain cannot broadcast against a zero width
            return None
        if exclude_rows.shape[1] > ROWLIST_MAX:
            raise ValueError(
                f"exclusion row list width {exclude_rows.shape[1]} > "
                f"ROWLIST_MAX ({ROWLIST_MAX}) — pack to mask words"
            )
        return "rows"
    return None


@functools.partial(
    jax.jit,
    static_argnames=("k", "interpret", "item_tile"),
)
def fused_recommend_topk(  # lint: disable=jit-boundary — inner
    # boundary: invoked inside als.recommend_serving/similar_serving or
    # the sharded local(), all instrumented; this jit inlines into
    # their traces
    q: jax.Array,  # (B, K) f32 | bf16 | int8 — matches itf's dtype
    itf: jax.Array,  # (I_p, K) f32 | bf16 | int8
    q_scale=None,  # (B, 1) f32 per-row scales (int8 dequant / cosine 1/|q|)
    item_scale=None,  # (1, I_p) f32 per-row scales
    mask_bits=None,  # (B, I_p/32) int32 packed exclusion words
    exclude_rows=None,  # (B, E) int32 exclusion row list, -1 padded
    *,
    k: int,
    n_items,  # TRACED live item count (int or () int32 array)
    interpret: bool = False,
    item_tile: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """One-pass fused score+top-k over a padded item-factor matrix.

    Returns (values (B, k) f32, global indices (B, k) int32) with
    `lax.top_k` semantics (descending, ties to the lowest index).
    Requires k <= n_items (callers cap — models/als.py does) and
    itf.shape[0] % tile == 0 (stage with `pad_items`). `n_items` rides
    as a TRACED SMEM scalar so online vocab growth within the pad
    reuses the compiled program instead of retracing per tick.

    With `q_scale`/`item_scale` set the dot is multiplied by their
    outer product in registers: int8 dequantization and cosine inverse
    norms are the same operation, so every verb (dot recommend, cosine
    similar) and every dtype (f32/bf16/int8) is this one kernel."""
    b, kdim = q.shape
    n_items_p = itf.shape[0]
    int8 = itf.dtype == jnp.int8
    scaled = q_scale is not None
    if int8 and not scaled:
        raise ValueError("int8 factors require dequant scales")
    kind = _mask_kind(mask_bits, exclude_rows)
    return _fused_call(
        b=b, kdim=kdim, n_items_p=n_items_p, k=k, item_tile=item_tile,
        interpret=interpret, precomputed=False, scaled=scaled, int8=int8,
        mask_kind=kind,
        n_excl=0 if exclude_rows is None else exclude_rows.shape[1],
        n_items=n_items,
        main_args=[q, itf],
        main_specs=lambda tile: [
            _bspec((b, kdim), lambda j: (0, 0)),
            _bspec((tile, kdim), lambda j: (j, 0)),
        ],
        scale_args=[q_scale, item_scale],
        mask_arg=mask_bits if kind == "bits" else exclude_rows,
    )


@functools.partial(
    jax.jit,
    static_argnames=("k", "interpret", "item_tile"),
)
def fused_masked_topk(  # lint: disable=jit-boundary — inner boundary:
    # invoked inside cco.batch_score_topk, which is instrumented; this
    # jit inlines into its trace
    scores: jax.Array,  # (B, I_p) f32 — precomputed score matrix
    mask_bits=None,
    exclude_rows=None,
    *,
    k: int,
    n_items,
    interpret: bool = False,
    item_tile: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """Fused exclusion + top-k over a PRECOMPUTED score matrix — the
    CCO/universal `batch_score_topk` tail (its scores accumulate by
    gather, so there is no factor matmul to fuse, but the masked-copy
    write + top-k re-read and the (B, I) exclusion-mask
    materialization both disappear: scores stream through once,
    exclusion applies in registers off the packed words / row list)."""
    b, n_items_p = scores.shape
    kind = _mask_kind(mask_bits, exclude_rows)
    return _fused_call(
        b=b, kdim=0, n_items_p=n_items_p, k=k, item_tile=item_tile,
        interpret=interpret, precomputed=True, scaled=False, int8=False,
        mask_kind=kind,
        n_excl=0 if exclude_rows is None else exclude_rows.shape[1],
        n_items=n_items,
        main_args=[scores],
        main_specs=lambda tile: [_bspec((b, tile), lambda j: (0, j))],
        scale_args=[],
        mask_arg=mask_bits if kind == "bits" else exclude_rows,
    )


def _bspec(shape, index_map):
    from jax.experimental import pallas as pl

    return pl.BlockSpec(shape, index_map)


def xla_scores(q, items, qs, isc):
    """The XLA fallback's score semantics, shared by EVERY serving verb
    on every tier so a mode change can never change scores: int8
    accumulates in int32 and dequantizes by the scale product; bf16
    accumulates in f32; caller-supplied scales (cosine inverse norms)
    multiply the same way the kernel's register pass does.

    The f32/bf16 dot is spelled `q @ items.T`, NOT dot_general with a
    (1,)/(1,) contraction: measured on this jax's CPU backend the
    transposed-contraction form picks a GEMM whose last-ulp rounding
    varies with the BATCH size, and the shadow-rollout agreement
    window compares a B=1 mirror against B=n live answers — identical
    models must serialize identical floats regardless of batching
    (regression: tests/test_fused_serving.py batch-size invariance)."""
    if items.dtype == jnp.int8:
        s = jax.lax.dot_general(
            q, items, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
    elif items.dtype == jnp.bfloat16:
        s = jnp.matmul(q, items.T, preferred_element_type=jnp.float32)
    else:
        s = q @ items.T
    if qs is not None:
        s = s * qs * isc
    return s


def fused_or_xla_topk(
    q, items, qs, isc, mask_bits, excl_rows, n_items, *, k, mode
):
    """One dispatch seam for every serving verb on every tier: the
    fused one-pass kernel where a mode resolved, else the XLA two-step
    with IDENTICAL scoring + exclusion semantics (packed words / row
    lists unpack in-jit, so the 1/32 mask-traffic win holds on both
    paths). `n_items` may be traced (the sharded tier passes per-shard
    live counts); dead pad columns sink strictly below NEG_INF."""
    if mode is not None:
        return fused_recommend_topk(
            q, items, qs, isc, mask_bits, excl_rows,
            k=k, n_items=n_items, interpret=(mode == "interpret"),
        )
    s = xla_scores(q, items, qs, isc)
    i_p = int(items.shape[0])
    if mask_bits is not None:
        s = jnp.where(unpack_mask_jnp(mask_bits, i_p), NEG_INF, s)
    elif excl_rows is not None and excl_rows.shape[1]:
        s = jnp.where(rowlist_mask_jnp(excl_rows, i_p), NEG_INF, s)
    col = jnp.arange(i_p, dtype=jnp.int32)
    s = jnp.where(
        (col >= n_items)[None, :], jnp.finfo(jnp.float32).min, s
    )
    return jax.lax.top_k(s, k)


# ---------------------------------------------------------------------------
# int8 quantization (per-row symmetric)
# ---------------------------------------------------------------------------


def quantize_rows_np(arr) -> tuple:
    """Host-side per-row symmetric int8 quantization:
    scale_r = max|row| / 127 (1.0 for all-zero rows so dequant is
    exact zero), q = round(row / scale) in [-127, 127]. Returns
    (int8 (N, K), f32 scales (N,))."""
    import numpy as np

    arr = np.asarray(arr, np.float32)
    amax = np.max(np.abs(arr), axis=1) if arr.size else np.zeros(
        arr.shape[0], np.float32
    )
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(
        np.round(arr / scale[:, None]), -127, 127
    ).astype(np.int8)
    return q, scale


def quantize_rows_jnp(arr: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Traced twin of `quantize_rows_np` for in-jit query-row
    quantization (the gather side of int8 serving)."""
    amax = jnp.max(jnp.abs(arr), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(arr / scale), -127, 127).astype(jnp.int8)
    return q, scale


def inv_norms_np(arr, pad_to: int = 0):
    """Per-row inverse L2 norms 1/(|row|+1e-9) as a (1, N_p) f32 row —
    the cosine verbs' item-side scale, computed ONCE at stage time from
    the f32 factors (pad rows get 0.0: their scores are dead either
    way, and 0 keeps them finite)."""
    import numpy as np

    arr = np.asarray(arr, np.float32)
    n = arr.shape[0]
    out = np.zeros((1, max(pad_to, n)), np.float32)
    if n:
        out[0, :n] = 1.0 / (np.linalg.norm(arr, axis=1) + 1e-9)
    return out


# ---------------------------------------------------------------------------
# gating
# ---------------------------------------------------------------------------


def available() -> bool:
    try:
        if jax.devices()[0].platform != "tpu":
            return False
        from jax.experimental.pallas import tpu as _  # noqa: F401

        return True
    except Exception:
        return False


def resolve_mode(requested: str = "auto"):
    """None (XLA two-step), "tpu", or "interpret" — resolved OUTSIDE
    the jit so trace caches key on it (windowed_pallas precedent).

    Default: ON where the TPU lowering can run (the score-matrix HBM
    round-trip it removes dominates the pass at serving rank), off
    elsewhere. PIO_PALLAS_RECOMMEND=0 forces the XLA path, =interpret
    runs the kernel through the Pallas interpreter (the CPU test
    path)."""
    if requested in (None, "off"):
        return None
    if requested == "interpret":
        return "interpret"
    env = _env_str("PIO_PALLAS_RECOMMEND").strip()
    if env == "0":
        return None
    if env == "interpret":
        return "interpret"
    if env == "1":
        return "tpu" if available() else None
    return "tpu" if available() else None
