"""Fused Pallas recommend+top-k: score, mask, and select in ONE pass.

The serving hot path's XLA form is a two-step program —
``scores = q @ itf.T`` then ``lax.top_k`` (models/als.py's
`_recommend_jit[_nomask]`): XLA materializes the full (B, I) score
matrix in HBM between the matmul and the TopK custom call. At serving
rank (K ≈ 10) the score matrix IS the dominant HBM term: one write plus
one read of B·I·4 bytes against an item-factor stream of only I·K·4
(for B = 64 on the ML-20M catalog that's ~14 MB of score traffic vs
~1 MB of factors — >90 % of the pass).

This kernel never materializes the score matrix. The grid walks item
tiles; each step loads one (T, K) factor tile into VMEM, issues the
(B, T) MXU dot against the resident query block, applies the exclusion
mask and the dead-pad-column mask in registers, and merges the tile
into a RUNNING sorted top-k list held in VMEM scratch. Only the final
(B, k) values + global indices ever reach HBM.

The merge is an iterative extraction with early exit: while any query
row's tile maximum still beats that row's current k-th value, extract
each such row's (max, lowest-index-of-max) and insert it into the
row's sorted list (count-position + lane shift — no sort primitive,
Mosaic has none on this jax). For random scores the expected number of
extractions across the WHOLE pass is k·(1 + ln n_tiles) — the early
exit makes later tiles nearly free — and the worst case terminates
(every iteration kills at least one element of some live row).

Tie-breaking matches `lax.top_k` exactly (stable: among equal values
the LOWEST index wins): tiles scan in index order, within a tile the
extraction takes the lowest index of the row max, and the insertion
position counts `>=` so a later tie lands after the resident equals.
tests/test_recommend_pallas.py proves parity against
`ops.topk.masked_top_k` in interpret mode (masked / unmasked / k edge
cases / crafted ties).

int8 mode (ISSUE 11 tentpole part 2): both factor matrices quantized
per-row to int8 (symmetric, scale = max|row|/127); the kernel's dot is
int8×int8→int32 (MXU-native on generations that support it; emulated
elsewhere) and the (B, 1)·(1, T) scale outer product dequantizes the
score tile in registers — the factor stream halves and no dequantized
copy ever exists in HBM.

Gating mirrors ops/windowed_pallas.py: `resolve_mode("auto")` returns
"tpu" only where the Mosaic lowering can actually run, "interpret"
under PIO_PALLAS_RECOMMEND=interpret (the CPU test path), else None —
callers then keep the XLA two-step (which still gets the int8 and
donation wins). This box is CPU-only, so the TPU lowering is validated
structurally (every primitive used has a Mosaic rule on this jax:
while/cond/concatenate/slice/iota/reduce_max/select_n/dot_general);
first TPU deployment must re-run the parity suite in "tpu" mode.
"""

from __future__ import annotations
from predictionio_tpu.utils.env import env_str as _env_str

import functools
import os

import jax
import jax.numpy as jnp

from predictionio_tpu.ops.topk import NEG_INF

#: item-tile ladder — first divisor of the padded item count wins; the
#: staging pad quantum (ITEM_PAD) guarantees at least one always does
ITEM_TILES = (2048, 1024, 512, 256, 128)
#: pad item rows to this multiple at staging so a tile always divides
ITEM_PAD = 128

#: running-list sentinel: strictly below every representable score
#: INCLUDING the NEG_INF mask value, so dead pad columns and the
#: not-yet-filled tail never collide with legitimately masked entries
_SENTINEL = float(jnp.finfo(jnp.float32).min)


def pick_item_tile(n_items_padded: int) -> int:
    for t in ITEM_TILES:
        if n_items_padded % t == 0:
            return t
    return 0


def pad_items(n_items: int) -> int:
    """Padded item-row count the staging side must allocate."""
    return -(-max(n_items, 1) // ITEM_PAD) * ITEM_PAD


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


def _shift_right(x: jax.Array) -> jax.Array:
    """Lane shift by one: out[:, j] = x[:, j-1] (lane 0 duplicated —
    always overwritten by the insert select)."""
    return jnp.concatenate([x[:, :1], x[:, :-1]], axis=1)


def _make_kernel(
    *, k: int, tile: int, masked: bool, quantized: bool, n_tiles: int,
):
    from jax.experimental import pallas as pl

    def kernel(*refs):
        it = iter(refs)
        n_ref = next(it)  # (1,) i32 SMEM — live item count (TRACED:
        # vocab growth within the pad must not recompile the program)
        q_ref = next(it)
        itf_ref = next(it)
        qs_ref = next(it) if quantized else None
        isc_ref = next(it) if quantized else None
        mask_ref = next(it) if masked else None
        vals_ref = next(it)
        idx_ref = next(it)
        rv_ref = next(it)  # (B, k) f32 running values, sorted desc
        ri_ref = next(it)  # (B, k) i32 running global indices

        j = pl.program_id(0)

        @pl.when(j == 0)
        def _init():
            rv_ref[...] = jnp.full(rv_ref.shape, _SENTINEL, jnp.float32)
            ri_ref[...] = jnp.zeros(ri_ref.shape, jnp.int32)

        # -- score tile (MXU) — the only read of this factor tile ------
        if quantized:
            s32 = jax.lax.dot_general(
                q_ref[...], itf_ref[...], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            s = s32.astype(jnp.float32) * qs_ref[...] * isc_ref[...]
        else:
            s = jax.lax.dot_general(
                q_ref[...], itf_ref[...], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        b = s.shape[0]
        col = jax.lax.broadcasted_iota(jnp.int32, (b, tile), 1)
        if masked:
            # f32 0/1 mask: Mosaic vector compare lowers for f32 only
            s = jnp.where(mask_ref[...] > 0.0, NEG_INF, s)
        # dead pad columns sink BELOW the mask value: they must lose to
        # legitimately masked real items when the list drains that deep
        gcol0 = j * tile
        s = jnp.where(gcol0 + col >= n_ref[0], _SENTINEL, s)

        lane = jax.lax.broadcasted_iota(jnp.int32, (b, k), 1)

        def body(carry):
            s, rv, ri, _ = carry
            m = jnp.max(s, axis=1, keepdims=True)  # (B, 1)
            # lowest column index attaining the row max (argmax is not
            # a Mosaic primitive; two reductions are)
            am = jnp.min(
                jnp.where(s == m, col, jnp.int32(2**30)),
                axis=1, keepdims=True,
            )
            live = m > rv[:, k - 1 : k]  # (B, 1) rows still inserting
            # sorted insert: position counts >= so ties land AFTER the
            # resident equals (earlier tiles = lower indices = stable)
            pos = jnp.sum(
                (rv >= m).astype(jnp.int32), axis=1, keepdims=True
            )
            nv = jnp.where(
                lane < pos, rv,
                jnp.where(lane == pos, m, _shift_right(rv)),
            )
            ni = jnp.where(
                lane < pos, ri,
                jnp.where(lane == pos, am + gcol0, _shift_right(ri)),
            )
            rv = jnp.where(live, nv, rv)
            ri = jnp.where(live, ni, ri)
            # kill the extracted element so the next max is fresh
            s = jnp.where((col == am) & live, _SENTINEL, s)
            cont = jnp.max(
                jnp.max(s, axis=1, keepdims=True) - rv[:, k - 1 : k]
            )
            return s, rv, ri, cont

        rv0, ri0 = rv_ref[...], ri_ref[...]
        cont0 = jnp.max(
            jnp.max(s, axis=1, keepdims=True) - rv0[:, k - 1 : k]
        )
        _, rv1, ri1, _ = jax.lax.while_loop(
            lambda c: c[3] > 0.0, body, (s, rv0, ri0, cont0)
        )
        rv_ref[...] = rv1
        ri_ref[...] = ri1

        @pl.when(j == n_tiles - 1)
        def _emit():
            vals_ref[...] = rv_ref[...]
            idx_ref[...] = ri_ref[...]

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("k", "interpret", "item_tile"),
)
def fused_recommend_topk(  # lint: disable=jit-boundary — inner
    # boundary: invoked inside als.recommend_serving / the sharded
    # local(), both instrumented; this jit inlines into their traces
    q: jax.Array,  # (B, K) f32 — or int8 when quantized
    itf: jax.Array,  # (I_p, K) f32 — or int8 when quantized
    q_scale=None,  # (B, 1) f32 per-row dequant scales (int8 mode)
    item_scale=None,  # (1, I_p) f32 per-row scales (int8 mode)
    mask=None,  # (B, I_p) f32 0/1 — 1 = exclude (None = unmasked)
    *,
    k: int,
    n_items,  # TRACED live item count (int or () int32 array)
    interpret: bool = False,
    item_tile: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """One-pass fused recommend+top-k over a padded item-factor matrix.

    Returns (values (B, k) f32, global indices (B, k) int32) with
    `lax.top_k` semantics (descending, ties to the lowest index).
    Requires k <= n_items (callers cap — models/als.py does) and
    itf.shape[0] % tile == 0 (stage with `pad_items`). `n_items` rides
    as a TRACED SMEM scalar so online vocab growth within the pad
    reuses the compiled program instead of retracing per tick."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, kdim = q.shape
    n_items_p = itf.shape[0]
    tile = item_tile or pick_item_tile(n_items_p)
    if tile <= 0:
        raise ValueError(
            f"padded item count {n_items_p} has no tile divisor — stage "
            f"with recommend_pallas.pad_items"
        )
    if not 0 < k <= n_items_p:
        raise ValueError(f"need 0 < k ({k}) <= padded {n_items_p}")
    n_tiles = n_items_p // tile
    quantized = itf.dtype == jnp.int8
    masked = mask is not None

    n_arr = jnp.asarray(n_items, jnp.int32).reshape(1)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # live item count
        pl.BlockSpec((b, kdim), lambda j: (0, 0)),  # q: resident
        pl.BlockSpec((tile, kdim), lambda j: (j, 0)),  # factor tile
    ]
    args = [n_arr, q, itf]
    if quantized:
        in_specs.append(pl.BlockSpec((b, 1), lambda j: (0, 0)))
        in_specs.append(pl.BlockSpec((1, tile), lambda j: (0, j)))
        args.extend([q_scale, item_scale])
    if masked:
        in_specs.append(pl.BlockSpec((b, tile), lambda j: (0, j)))
        args.append(mask)

    kernel = _make_kernel(
        k=k, tile=tile, masked=masked, quantized=quantized,
        n_tiles=n_tiles,
    )
    # jax renamed TPUCompilerParams -> CompilerParams across 0.4/0.5
    cp = getattr(
        pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
    )(dimension_semantics=("arbitrary",))
    return pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((b, k), lambda j: (0, 0)),
            pl.BlockSpec((b, k), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, k), jnp.float32),
            jax.ShapeDtypeStruct((b, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, k), jnp.float32),
            pltpu.VMEM((b, k), jnp.int32),
        ],
        compiler_params=cp,
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# int8 quantization (per-row symmetric)
# ---------------------------------------------------------------------------


def quantize_rows_np(arr) -> tuple:
    """Host-side per-row symmetric int8 quantization:
    scale_r = max|row| / 127 (1.0 for all-zero rows so dequant is
    exact zero), q = round(row / scale) in [-127, 127]. Returns
    (int8 (N, K), f32 scales (N,))."""
    import numpy as np

    arr = np.asarray(arr, np.float32)
    amax = np.max(np.abs(arr), axis=1) if arr.size else np.zeros(
        arr.shape[0], np.float32
    )
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(
        np.round(arr / scale[:, None]), -127, 127
    ).astype(np.int8)
    return q, scale


def quantize_rows_jnp(arr: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Traced twin of `quantize_rows_np` for in-jit query-row
    quantization (the gather side of int8 serving)."""
    amax = jnp.max(jnp.abs(arr), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(arr / scale), -127, 127).astype(jnp.int8)
    return q, scale


# ---------------------------------------------------------------------------
# gating
# ---------------------------------------------------------------------------


def available() -> bool:
    try:
        if jax.devices()[0].platform != "tpu":
            return False
        from jax.experimental.pallas import tpu as _  # noqa: F401

        return True
    except Exception:
        return False


def resolve_mode(requested: str = "auto"):
    """None (XLA two-step), "tpu", or "interpret" — resolved OUTSIDE
    the jit so trace caches key on it (windowed_pallas precedent).

    Default: ON where the TPU lowering can run (the score-matrix HBM
    round-trip it removes dominates the pass at serving rank), off
    elsewhere. PIO_PALLAS_RECOMMEND=0 forces the XLA path, =interpret
    runs the kernel through the Pallas interpreter (the CPU test
    path)."""
    if requested in (None, "off"):
        return None
    if requested == "interpret":
        return "interpret"
    env = _env_str("PIO_PALLAS_RECOMMEND").strip()
    if env == "0":
        return None
    if env == "interpret":
        return "interpret"
    if env == "1":
        return "tpu" if available() else None
    return "tpu" if available() else None
