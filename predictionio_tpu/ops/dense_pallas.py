"""Pallas TPU kernel for the dense-W ALS half-step: ONE R read per pass.

The XLA dense path (ops/dense.py) is R-bandwidth-bound: its two
dot_generals each fuse their own weight-tile derivation, so the int8
rating matrix streams from HBM TWICE per half-step (measured ~62% of
the HBM roof at ML-20M; the single-stacked-dot alternative is 2.5×
slower because XLA materializes the concatenated operand — see
dense_row_pass). This kernel loads each R tile into VMEM once, derives
BOTH weight tiles in registers, and issues both MXU dots against the
resident factor slices — halving the dominant HBM term.

Layout: grid (row_tiles, col_tiles) with the column axis innermost; the
two outputs (b (BR, K), corr (BR, K²)) revisit the same block across
the inner axis and accumulate (zeroed at j == 0). The implicit-ALS
weights fold the confidence scale into the dequant:

    w1 = 1[q > 0] + (α/s)·relu(q)        wg = (α/s)·|q|
    (explicit:  w1 = q/s,  wg = 1[q != 0])

`alpha/s` arrives as an SMEM scalar so a traced α never forces a
retrace. int8 storage only — the f32/bf16 modes keep the XLA path.

Gated by PIO_PALLAS_DENSE and DEFAULT-OFF — measured SLOWER than the
XLA two-dot path at ML-20M (see resolve_mode for the arithmetic of the
negative result); kept correct + opt-in for future chip generations.
Interpret mode backs the CPU equivalence tests.
"""

from __future__ import annotations
from predictionio_tpu.utils.env import env_str as _env_str

import functools

import jax
import jax.numpy as jnp

ROW_TILE = 1024
COL_TILE = 1280


def _make_row_kernel(implicit: bool):
    from jax.experimental import pallas as pl

    def kernel(ascale_ref, r_ref, y_ref, z_ref, b_ref, c_ref):
        # f32 derivation: Mosaic vector compare exists ONLY for f32 on
        # this target (int8 and bf16 cmp both fail to lower)
        qf = r_ref[...].astype(jnp.float32)  # (BR, BC)
        a = ascale_ref[0]
        if implicit:
            w1 = (qf > 0).astype(jnp.float32) + a * jnp.maximum(qf, 0.0)
            wg = a * jnp.abs(qf)
        else:
            w1 = a * qf
            wg = (qf != 0).astype(jnp.float32)
        w1 = w1.astype(jnp.bfloat16)
        wg = wg.astype(jnp.bfloat16)
        b = jax.lax.dot_general(
            w1, y_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        c = jax.lax.dot_general(
            wg, z_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        @pl.when(pl.program_id(1) == 0)
        def _init():
            b_ref[...] = jnp.zeros_like(b_ref)
            c_ref[...] = jnp.zeros_like(c_ref)

        b_ref[...] += b
        c_ref[...] += c

    return kernel


def _make_col_kernel(implicit: bool):
    from jax.experimental import pallas as pl

    def kernel(ascale_ref, r_ref, x_ref, zx_ref, b_ref, c_ref):
        # f32 derivation (see row kernel: only f32 cmp lowers)
        qf = r_ref[...].astype(jnp.float32)  # (BR, BC); rows contract
        a = ascale_ref[0]
        if implicit:
            w1 = (qf > 0).astype(jnp.float32) + a * jnp.maximum(qf, 0.0)
            wg = a * jnp.abs(qf)
        else:
            w1 = a * qf
            wg = (qf != 0).astype(jnp.float32)
        w1 = w1.astype(jnp.bfloat16)
        wg = wg.astype(jnp.bfloat16)
        b = jax.lax.dot_general(
            w1, x_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BC, K)
        c = jax.lax.dot_general(
            wg, zx_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BC, K²)

        @pl.when(pl.program_id(1) == 0)
        def _init():
            b_ref[...] = jnp.zeros_like(b_ref)
            c_ref[...] = jnp.zeros_like(c_ref)

        b_ref[...] += b
        c_ref[...] += c

    return kernel


def _tiles(n: int, t: int) -> int:
    if n % t:
        raise ValueError(f"dim {n} not divisible by tile {t}")
    return n // t


@functools.partial(
    jax.jit,
    static_argnames=("implicit", "interpret", "row_tile", "col_tile"),
)
def fused_row_pass(  # lint: disable=jit-boundary — inner boundary:
    # only invoked inside the instrumented als train jits, where this
    # jit inlines into the trace; instrumenting would record nothing
    r: jax.Array,  # (n_rows_p, n_cols_p) int8
    y: jax.Array,  # (n_cols_p, K) f32
    z: jax.Array,  # (n_cols_p, K²) f32
    ascale: jax.Array,  # (1,) f32 — α/s (implicit) or 1/s (explicit)
    *,
    implicit: bool,
    interpret: bool = False,
    row_tile: int = ROW_TILE,
    col_tile: int = COL_TILE,
) -> tuple[jax.Array, jax.Array]:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_rows, n_cols = r.shape
    k = y.shape[1]
    gi, gj = _tiles(n_rows, row_tile), _tiles(n_cols, col_tile)
    y16 = y.astype(jnp.bfloat16)
    z16 = z.astype(jnp.bfloat16)
    return pl.pallas_call(
        _make_row_kernel(implicit),
        grid=(gi, gj),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((row_tile, col_tile), lambda i, j: (i, j)),
            pl.BlockSpec((col_tile, k), lambda i, j: (j, 0)),
            pl.BlockSpec((col_tile, k * k), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((row_tile, k), lambda i, j: (i, 0)),
            pl.BlockSpec((row_tile, k * k), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_rows, k), jnp.float32),
            jax.ShapeDtypeStruct((n_rows, k * k), jnp.float32),
        ],
        # jax renamed TPUCompilerParams -> CompilerParams across 0.4/0.5
        compiler_params=getattr(
            pltpu, "CompilerParams",
            getattr(pltpu, "TPUCompilerParams", None),
        )(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(ascale, r, y16, z16)


@functools.partial(
    jax.jit,
    static_argnames=("implicit", "interpret", "row_tile", "col_tile"),
)
def fused_col_pass(  # lint: disable=jit-boundary — inner boundary:
    # only invoked inside the instrumented als train jits, where this
    # jit inlines into the trace; instrumenting would record nothing
    r: jax.Array,  # (n_rows_p, n_cols_p) int8
    x: jax.Array,  # (n_rows_p, K) f32 — row-side factors
    zx: jax.Array,  # (n_rows_p, K²) f32
    ascale: jax.Array,  # (1,) f32
    *,
    implicit: bool,
    interpret: bool = False,
    row_tile: int = ROW_TILE,
    col_tile: int = COL_TILE,
) -> tuple[jax.Array, jax.Array]:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_rows, n_cols = r.shape
    k = x.shape[1]
    gi, gj = _tiles(n_cols, col_tile), _tiles(n_rows, row_tile)
    x16 = x.astype(jnp.bfloat16)
    zx16 = zx.astype(jnp.bfloat16)
    return pl.pallas_call(
        _make_col_kernel(implicit),
        grid=(gi, gj),  # outer: output col tile; inner: row accumulate
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((row_tile, col_tile), lambda i, j: (j, i)),
            pl.BlockSpec((row_tile, k), lambda i, j: (j, 0)),
            pl.BlockSpec((row_tile, k * k), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((col_tile, k), lambda i, j: (i, 0)),
            pl.BlockSpec((col_tile, k * k), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_cols, k), jnp.float32),
            jax.ShapeDtypeStruct((n_cols, k * k), jnp.float32),
        ],
        # jax renamed TPUCompilerParams -> CompilerParams across 0.4/0.5
        compiler_params=getattr(
            pltpu, "CompilerParams",
            getattr(pltpu, "TPUCompilerParams", None),
        )(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(ascale, r, x16, zx16)


def pick_tiles(n_rows_p: int, n_cols_p: int) -> tuple[int, int]:
    """Preferred tile sizes dividing the padded dims (static)."""
    row_tile = next(
        (t for t in (1024, 512, 256) if n_rows_p % t == 0), 0
    )
    col_tile = next(
        (
            t
            for t in (1280, 1024, 1536, 768, 640, 512, 384, 256)
            if n_cols_p % t == 0
        ),
        0,
    )
    return row_tile, col_tile


def resolve_mode(requested: str = "auto"):
    """None (XLA dense path — the DEFAULT), "tpu", or "interpret".

    Default OFF by measurement: at ML-20M the kernel runs 0.70 s per
    train vs the XLA path's 0.60 s. The hypothesis (halving the
    dominant HBM term by reading R once) holds on bytes, but the
    in-kernel weight derivation must run in f32 (Mosaic lowers vector
    compares for f32 only) and its VPU cost on every (1024×1280) tile
    exceeds the saved int8 re-read, which XLA's two-dot form overlaps
    with MXU work anyway. Kept in-tree with interpret-mode equivalence
    tests: PIO_PALLAS_DENSE=1 opts in (e.g. for re-measurement on a
    chip generation with cheaper VPU compares or costlier HBM)."""
    import os

    if requested in (None, "off"):
        return None
    if requested == "interpret":
        return "interpret"
    env = _env_str("PIO_PALLAS_DENSE").strip()
    if env == "1":
        return "tpu" if available() else None
    if env == "interpret":
        return "interpret"
    return None


def available() -> bool:
    try:
        if jax.devices()[0].platform != "tpu":
            return False
        from jax.experimental.pallas import tpu as _  # noqa: F401

        return True
    except Exception:
        return False
