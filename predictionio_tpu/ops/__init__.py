"""Low-level XLA/Pallas ops shared by model kernels."""

from predictionio_tpu.ops.segment import edge_matvec, segment_sum, weighted_edge_sum
from predictionio_tpu.ops.topk import masked_top_k

__all__ = ["edge_matvec", "segment_sum", "weighted_edge_sum", "masked_top_k"]
