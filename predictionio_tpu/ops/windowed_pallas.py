"""Pallas TPU kernel for the fused windowed ALS edge pass.

Replaces the device half of ops/windowed.windowed_gram_b (the XLA scan
path) with one kernel that keeps every per-edge intermediate in VMEM:

- the (B_E, S) one-hot is built from an iota compare and never touches
  HBM (the XLA path materializes it per chunk: write + read ≈
  2·E_p·S·4 B ≈ 21 GB per ML-20M edge pass);
- the (B_E, K²) outer-product payload is built in-register from the
  gathered factor rows and never touches HBM either (the XLA path
  materializes the concatenated (B_E, K+K²) payload per chunk ≈ another
  18 GB per pass);
- per-window output tiles accumulate in VMEM across consecutive blocks
  (the grid walks blocks in non-decreasing window order, so the output
  index map revisits the same tile until the window changes — the
  standard TPU reduction idiom), eliminating the (n_blocks, S, D)
  partials array and the final segment-sum combine.

Remaining HBM traffic per pass ≈ one read of the gathered factor rows
(E_p·K·4 B), the edge weights, and one write of the (n_windows·S, K+K²)
output — an order of magnitude below the XLA path at ML-20M shapes.

Weights are folded into the ONE-HOT (not the payload): b uses
onehot·w_b, gram uses onehot·w_g, so the kernel needs no (B_E, 1)
transposes and emits b and the flat gram correction as two outputs.

Integration: ops/windowed.windowed_gram_b dispatches here when
`PIO_PALLAS_WINDOWED` allows it (default: on when the default device is
a TPU; `0` forces the XLA path; `interpret` runs this kernel through the
Pallas interpreter on CPU — how tests/test_windowed_pallas.py checks
bit-level agreement with the XLA path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _kernel(bw_ref, yt_ref, wb_ref, wg_ref, local_ref, b_ref, g_ref):
    """One grid step = one edge block.

    b_window    += (onehot·w_b) @ yᵀ
    gram_window += (onehot·w_g) @ [yᵀ_i·yᵀ_j for (i,j) in K×K]ᵀ

    Everything edge-indexed keeps the 1024-wide edge axis in LANES
    (factor rows arrive transposed (K, B_E)): the (K², B_E) outer
    product is a sublane concat of full-lane pieces, so VMEM holds no
    lane-padded narrow arrays, and both contractions run edge-axis
    against edge-axis on the MXU with no in-kernel transposes.
    """
    from jax.experimental import pallas as pl

    step = pl.program_id(0)
    prev = bw_ref[jnp.maximum(step - 1, 0)]
    new_window = (step == 0) | (prev != bw_ref[step])

    @pl.when(new_window)
    def _zero():
        b_ref[...] = jnp.zeros_like(b_ref)
        g_ref[...] = jnp.zeros_like(g_ref)

    yt = yt_ref[0]  # (K, B_E) f32 — gathered fixed-side factor rows, transposed
    k = yt.shape[0]
    lid = local_ref[0]  # (1, B_E) int32; padding slots carry w_b=w_g=0
    s_rows = b_ref.shape[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, (s_rows, lid.shape[1]), 0)
    onehot = (rows == lid).astype(jnp.float32)  # (S, B_E) — VMEM only

    dot_e = functools.partial(
        jax.lax.dot_general,  # contract both operands on their edge axis
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        # HIGHEST: CG consumes these sums; one bf16 MXU pass loses ~2^-8
        precision=jax.lax.Precision.HIGHEST,
    )
    b_ref[...] += dot_e(onehot * wb_ref[0], yt)
    # outer_t[i*K+j, e] = y[e,i]·y[e,j] — K sublane-stacked (K, B_E) pieces
    outer_t = jnp.concatenate(
        [yt * yt[i : i + 1, :] for i in range(k)], axis=0
    )  # (K², B_E)
    g_ref[...] += dot_e(onehot * wg_ref[0], outer_t)


@functools.partial(
    jax.jit, static_argnames=("n_windows", "s_rows", "interpret")
)
def windowed_pass(
    y_t: jax.Array,  # (n_blocks_p, K, B_E) f32 — factors[src] per block,
    # TRANSPOSED so the wide edge axis sits in lanes (the (·, K) layout
    # would cost a 12.8× lane-padding relayout at the pallas boundary)
    w_b: jax.Array,  # (n_blocks_p, B_E) f32 — b-vector edge weights (0 on pads)
    w_g: jax.Array,  # (n_blocks_p, B_E) f32 — gram edge weights (0 on pads)
    local: jax.Array,  # (n_blocks_p, B_E) int32 — dst % s_rows (arbitrary
    # values outside [0, s_rows) on padding slots never match a row)
    block_window: jax.Array,  # (n_blocks_p,) int32, NON-DECREASING
    *,
    n_windows: int,
    s_rows: int = 128,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Fused edge pass → (b ((n_windows+1)·S, K), gram ((n_windows+1)·S, K²)).

    b[w·S + r]    = Σ_{blocks b of w} Σ_{e: local=r} w_b[e] · y[e]
    gram[w·S + r] = Σ_{blocks b of w} Σ_{e: local=r} w_g[e] · y[e] ⊗ y[e]

    The output is over-allocated by one window and callers trim to
    n_windows·S rows; tiles of windows NO block maps to (including that
    spare window) are never written and hold garbage — the caller masks
    them (windowed.windowed_gram_b's covered-mask). plan_windows gives
    padding blocks the window id of their part's last real block (zero
    weights, zero contribution), keeping block_window non-decreasing —
    the invariant that makes the VMEM window accumulation exact.
    """
    # lazy: pallas.tpu cannot always import in a CPU-only process (tests
    # force a CPU platform and strip the TPU plugin)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_blocks, k, b_e = y_t.shape
    # Mosaic requires the last two block dims to divide (8, 128) or equal
    # the array dims — a singleton middle axis makes (1, 1, B_E) legal.
    w_b = w_b.reshape(n_blocks, 1, b_e)
    w_g = w_g.reshape(n_blocks, 1, b_e)
    local = local.reshape(n_blocks, 1, b_e)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((1, k, b_e), lambda i, bw: (i, 0, 0)),
            pl.BlockSpec((1, 1, b_e), lambda i, bw: (i, 0, 0)),
            pl.BlockSpec((1, 1, b_e), lambda i, bw: (i, 0, 0)),
            pl.BlockSpec((1, 1, b_e), lambda i, bw: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((s_rows, k), lambda i, bw: (bw[i], 0)),
            pl.BlockSpec((s_rows, k * k), lambda i, bw: (bw[i], 0)),
        ],
    )
    return pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(((n_windows + 1) * s_rows, k), jnp.float32),
            jax.ShapeDtypeStruct(
                ((n_windows + 1) * s_rows, k * k), jnp.float32
            ),
        ],
        interpret=interpret,
    )(block_window, y_t, w_b, w_g, local)


def available() -> bool:
    """True when the TPU Pallas lowering can run here."""
    try:
        if jax.devices()[0].platform != "tpu":
            return False
        from jax.experimental.pallas import tpu as _  # noqa: F401

        return True
    except Exception:
        return False
