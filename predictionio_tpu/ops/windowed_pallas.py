"""Pallas TPU kernel for the windowed segment reduction.

Fuses the one-hot build into the block matmul of ops/windowed.py's
reduction: the XLA path materializes each block's (B_E, S) one-hot in HBM
(write + read ≈ 2×E_p×S×4 bytes — ~21 GB per ML-20M edge pass, ~35% of
the pass's traffic); here the one-hot lives only in VMEM, built from an
iota compare, and the per-block partial accumulates directly into the
output window tile.

Accumulation pattern: the grid walks blocks in order; consecutive blocks
sharing an output window map to the SAME output block (index_map reads
the scalar-prefetched window ids), so Pallas keeps the (S, D) tile in
VMEM across those steps and flushes it to HBM only when the window
changes — the standard TPU reduction idiom (matmul k-loop). The host plan
guarantees window ids are non-decreasing, which makes this exact.

Used behind ops/windowed.windowed_gram_b on TPU (PIO_PALLAS_WINDOWED=0
forces the XLA path); CPU tests run the kernel in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _kernel(bw_ref, local_ref, payload_ref, out_ref):
    """One grid step = one edge block: out_window += onehotᵀ @ payload."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    s_rows = out_ref.shape[0]
    prev = bw_ref[jnp.maximum(i - 1, 0)]
    new_window = (i == 0) | (prev != bw_ref[i])

    @pl.when(new_window)
    def _zero():
        out_ref[...] = jnp.zeros_like(out_ref)

    lid = local_ref[...]  # (B_E,) int32; -1 padding never matches a row
    rows = jax.lax.broadcasted_iota(jnp.int32, (s_rows, lid.shape[0]), 0)
    onehot = (rows == lid[None, :]).astype(jnp.float32)  # (S, B_E), VMEM-only
    out_ref[...] += jax.lax.dot_general(
        onehot, payload_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        # HIGHEST: CG consumes these sums; one bf16 MXU pass loses ~2^-8
        precision=jax.lax.Precision.HIGHEST,
    )


@functools.partial(
    jax.jit, static_argnames=("n_windows", "s_rows", "interpret")
)
def windowed_segment_matmul(
    payload: jax.Array,  # (n_blocks_p * B_E, D_pad) f32; D_pad % 128 == 0
    local: jax.Array,  # (n_blocks_p, B_E) int32, -1 padded
    block_window: jax.Array,  # (n_blocks_p,) int32, NON-DECREASING
    *,
    n_windows: int,
    s_rows: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """out[w*S + r, :] = Σ_{blocks b of window w} Σ_{e: local=r} payload_e.

    Returns ((n_windows + 1) * s_rows, D_pad); the +1 window absorbs
    chunk-padding blocks (their block_window is n_windows)."""
    # lazy: pallas.tpu cannot import in a CPU-only process (tests force a
    # CPU platform and strip the TPU plugin)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_blocks, b_e = local.shape
    d_pad = payload.shape[1]
    local_flat = local.reshape(n_blocks * b_e)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((b_e,), lambda i, bw: (i,)),
            pl.BlockSpec((b_e, d_pad), lambda i, bw: (i, 0)),
        ],
        out_specs=pl.BlockSpec((s_rows, d_pad), lambda i, bw: (bw[i], 0)),
    )
    return pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            ((n_windows + 1) * s_rows, d_pad), jnp.float32
        ),
        interpret=interpret,
    )(block_window, local_flat, payload)


def available() -> bool:
    """True when the TPU Pallas lowering can run here."""
    try:
        if jax.devices()[0].platform != "tpu":
            return False
        from jax.experimental.pallas import tpu as _  # noqa: F401

        return True
    except Exception:
        return False
