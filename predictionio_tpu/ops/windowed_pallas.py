"""Pallas TPU kernel for the windowed ALS edge pass (per-chunk).

Replaces the one-hot contraction inside ops/windowed.windowed_gram_b's
chunk scan: the XLA path materializes, per chunk, the (CB, B_E, S)
one-hot and the (CB, B_E, K+K²) outer-product payload in HBM (together
~40 GB of write+read traffic per ML-20M edge pass); this kernel builds
both in VMEM and emits only the per-block (S, K) / (S, K²) partial sums
— the same partials the XLA path produces — so the existing block-level
segment-sum combine is unchanged.

The kernel stays INSIDE the scan (one pallas_call per chunk, grid = one
step per block) rather than spanning the whole edge list: a whole-pass
kernel needs the gathered factor rows for every edge materialized at
once (~GBs, plus a relayout at the pallas boundary), which measured
SLOWER than the XLA path at ML-20M; per chunk the gather stays small
and overlaps the kernel through XLA's scheduler.

Everything edge-indexed keeps the 1024-wide edge axis in LANES (factor
rows arrive transposed (K, B_E)): the (K², B_E) outer product is a
sublane concat of full-lane pieces, so VMEM holds no lane-padded narrow
arrays, and both contractions run edge-axis against edge-axis on the
MXU with no in-kernel transposes.

Integration: ops/windowed.windowed_gram_b dispatches here when
`PIO_PALLAS_WINDOWED` allows it (default: on when the default device is
a TPU; `0` forces the XLA path; `interpret` runs this kernel through the
Pallas interpreter on CPU — how tests/test_windowed_pallas.py checks
agreement with the XLA path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _kernel(yt_ref, wb_ref, wg_ref, local_ref, b_ref, g_ref):
    """One grid step = one edge block.

    b_partial    = (onehot·w_b) @ yᵀ          (S, K)
    gram_partial = (onehot·w_g) @ outer(y)ᵀ   (S, K²)
    """
    yt = yt_ref[0]  # (K, B_E) f32 — gathered fixed-side rows, transposed
    k = yt.shape[0]
    lid = local_ref[0]  # (1, B_E) int32; padding slots carry w_b=w_g=0
    s_rows = b_ref.shape[1]
    rows = jax.lax.broadcasted_iota(jnp.int32, (s_rows, lid.shape[1]), 0)
    onehot = (rows == lid).astype(jnp.float32)  # (S, B_E) — VMEM only

    dot_e = functools.partial(
        jax.lax.dot_general,  # contract both operands on their edge axis
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        # HIGHEST: CG consumes these sums; one bf16 MXU pass loses ~2^-8
        precision=jax.lax.Precision.HIGHEST,
    )
    b_ref[0] = dot_e(onehot * wb_ref[0], yt)
    # outer_t[i*K+j, e] = y[e,i]·y[e,j] — K sublane-stacked (K, B_E) pieces
    outer_t = jnp.concatenate(
        [yt * yt[i : i + 1, :] for i in range(k)], axis=0
    )  # (K², B_E)
    g_ref[0] = dot_e(onehot * wg_ref[0], outer_t)


@functools.partial(jax.jit, static_argnames=("s_rows", "interpret"))
def block_partials(
    y_t: jax.Array,  # (CB, K, B_E) f32 — factors[src] per block, TRANSPOSED
    # so the wide edge axis sits in lanes (a (·, B_E, K) layout would cost
    # a 12.8× lane-pad relayout at the pallas boundary)
    w_b: jax.Array,  # (CB, B_E) f32 — b-vector edge weights (0 on pads)
    w_g: jax.Array,  # (CB, B_E) f32 — gram edge weights (0 on pads)
    local: jax.Array,  # (CB, B_E) int32 — dst % s_rows (arbitrary values
    # outside [0, s_rows) on padding slots never match a one-hot row)
    *,
    s_rows: int = 128,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """One chunk's per-block partial sums → ((CB, S, K), (CB, S, K²)).

    partial_b[c, r]    = Σ_{e in block c: local=r} w_b[e] · y[e]
    partial_gram[c, r] = Σ_{e in block c: local=r} w_g[e] · y[e] ⊗ y[e]

    Callers (windowed_gram_b) segment-sum the block partials into window
    rows exactly as they do for the XLA einsum path.
    """
    # lazy: pallas.tpu cannot always import in a CPU-only process (tests
    # force a CPU platform and strip the TPU plugin)
    from jax.experimental import pallas as pl

    n_blocks, k, b_e = y_t.shape
    # Mosaic requires the last two block dims to divide (8, 128) or equal
    # the array dims — a singleton middle axis makes (1, 1, B_E) legal.
    w_b = w_b.reshape(n_blocks, 1, b_e)
    w_g = w_g.reshape(n_blocks, 1, b_e)
    local = local.reshape(n_blocks, 1, b_e)
    return pl.pallas_call(
        _kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((1, k, b_e), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, b_e), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, b_e), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, b_e), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, s_rows, k), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, s_rows, k * k), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_blocks, s_rows, k), jnp.float32),
            jax.ShapeDtypeStruct((n_blocks, s_rows, k * k), jnp.float32),
        ],
        interpret=interpret,
    )(y_t, w_b, w_g, local)


# device profiling (ISSUE 3): only top-level dispatches record (the train
# loop traces through); cost_analysis of a pallas_call may legitimately
# report 0 flops — the registry then shows invocations/seconds only
from predictionio_tpu.obs import devprof as _devprof  # noqa: E402

block_partials = _devprof.instrument(
    "ops.windowed_block_partials", block_partials
)


def available() -> bool:
    """True when the TPU Pallas lowering can run here."""
    try:
        if jax.devices()[0].platform != "tpu":
            return False
        from jax.experimental.pallas import tpu as _  # noqa: F401

        return True
    except Exception:
        return False
