"""CAS leader election on the lifecycle record layer (ISSUE 19).

The train scheduler's claim protocol (PR 10, deploy/scheduler.py) showed
that an append-only event fold gives a correct compare-and-swap without
any backend growing a CAS primitive: every candidate appends a BID
record carrying (generation, claim_token), and the winner is the FIRST
bid of that generation in the record layer's total event order — an
order every reader computes identically once the bids are visible. This
module lifts that protocol out of the scheduler into a reusable
`CasElection` so replicated-store failover (data/storage/replication.py)
elects its primary with the same fencing:

- the **generation** is monotone and never reused (each claim bids
  generation = settled + 1), so it doubles as the replication *epoch*
  stamped into shipped WAL frames — a zombie primary still holding the
  old generation produces frames every follower rejects;
- the **claim_token** makes a candidate's own bid distinguishable from
  another candidate's bid for the same generation, so losing a race is
  detected locally, not by side effect.

No jax anywhere on this path — elections run inside storage daemons.
"""

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger(__name__)

ELECTION_ENTITY = "pio_election"
ELECTION_BID_ENTITY = "pio_election_bid"


@dataclass(frozen=True)
class ElectionState:
    """Settled view of one election group."""

    leader: Optional[str]
    generation: int
    claim_token: Optional[str]
    claimed_at: float


class CasElection:
    """Fenced leader election for one named group.

    Usage::

        el = CasElection(records, group="events-primary")
        gen = el.claim("replica-a1b2", settle_s=0.2)
        if gen is not None:
            ...   # this candidate is leader at generation/epoch `gen`

    `claim` returns the won generation (the new epoch) or None when
    another candidate won the race or the settled generation moved on
    while we were bidding. Claims are *advisory* leadership — fencing is
    the consumer's job: stamp the generation into every side effect and
    reject effects carrying an older one.
    """

    def __init__(
        self,
        records,
        group: str,
        entity: str = ELECTION_ENTITY,
        bid_entity: str = ELECTION_BID_ENTITY,
    ):
        self._records = records
        self.group = group
        self._entity = entity
        self._bid_entity = bid_entity

    # -- reads -------------------------------------------------------------
    def state(self) -> ElectionState:
        d = self._records.fold(self._entity, self.group).get(self.group, {})
        return ElectionState(
            leader=d.get("leader"),
            generation=int(d.get("generation", 0)),
            claim_token=d.get("claim_token"),
            claimed_at=float(d.get("claimed_at", 0.0)),
        )

    # -- claim -------------------------------------------------------------
    def claim(
        self,
        candidate: str,
        settle_s: float = 0.0,
        generation: Optional[int] = None,
    ) -> Optional[int]:
        """Bid for leadership. Returns the won generation or None.

        The bid generation defaults to settled + 1; passing an explicit
        `generation` lets a coordinator drive a specific epoch bump. The
        optional settle window gives racing candidates time to land
        their bids before resolution — resolution itself needs no
        window for correctness (the total order is deterministic), the
        window only reduces the chance a *later-visible* earlier bid
        flips the outcome between a winner's check and its announce."""
        cur = self.state()
        gen = int(generation) if generation is not None else cur.generation + 1
        if gen <= cur.generation:
            return None
        token = uuid.uuid4().hex
        self._records.append(
            self._bid_entity, self.group,
            {
                "generation": gen,
                "claim_token": token,
                "candidate": candidate,
                "bid_at": time.time(),
            },
        )
        if settle_s > 0:
            time.sleep(settle_s)
        winner = self._winning_bid(gen)
        if winner is None or winner.get("claim_token") != token:
            return None
        # the settled record may have moved past our generation while we
        # slept (another group of candidates ran a later election) — a
        # stale announce would roll the epoch BACK, so re-check first
        if self.state().generation >= gen:
            return None
        self._records.append(
            self._entity, self.group,
            {
                "leader": candidate,
                "generation": gen,
                "claim_token": token,
                "claimed_at": time.time(),
            },
        )
        return gen

    def _winning_bid(self, generation: int) -> Optional[dict]:
        """First bid of `generation` in the record layer's total event
        order — the same resolution rule as the scheduler's job claims."""
        for ev in self._records.events(self._bid_entity, self.group):
            props = ev.properties.to_dict()
            if int(props.get("generation", -1)) == generation:
                return props
        return None

    # -- hygiene -----------------------------------------------------------
    def gc_bids(self) -> int:
        """Delete bids whose generation is at or below the settled one
        (they can never win again); keeps the bid record O(contenders)."""
        settled = self.state().generation
        removed = 0
        for ev in self._records.events(self._bid_entity, self.group):
            props = ev.properties.to_dict()
            if int(props.get("generation", 0)) <= settled and ev.event_id:
                self._records.discard(ev.event_id)
                removed += 1
        return removed
