"""Training worker fleet: N cooperating schedulers on shared storage
(ISSUE 10 tentpole part 3).

PR 5's `TrainScheduler` was one process supervising one queue. A fleet
member wraps that scheduler with the two things N-worker operation
needs:

- **worker records**: each member registers a heartbeating
  ``pio_fleet_worker`` record in the lifecycle record store, so every
  member (and `pio fleet status`) sees who is alive. The scheduler's
  ``peer_probe`` reads this — claims pay the CAS settle window only
  when live peers could actually be bidding (deploy/scheduler.py),
- **multi-host wiring**: an optional `DistributedConfig` is exported to
  every train subprocess via the env contract (distributed.py), so an
  N-host fleet's trains form one jax.distributed mesh; the single-host
  fallback keeps laptops and tests config-free.

There is deliberately NO elected coordinator process: the queue itself
(compare-and-set job claims, fenced heartbeats, CAS stale-steal) is the
coordination point, the same way the reference's HBase-backed metadata
let any host run `pio train`. Any member can die at any time; its jobs
go stale and the survivors steal them.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.deploy.registry import LifecycleRecordStore
from predictionio_tpu.deploy.scheduler import (
    JobQueue,
    SchedulerConfig,
    TrainScheduler,
)
from predictionio_tpu.fleet.distributed import DistributedConfig
from predictionio_tpu.utils.env import env_float, env_str

log = logging.getLogger(__name__)

WORKER_ENTITY = "pio_fleet_worker"


def _utcnow_iso() -> str:
    import datetime as _dt

    return _dt.datetime.now(_dt.timezone.utc).isoformat()


@dataclass
class WorkerInfo:
    """One fleet member's heartbeating presence record."""

    id: str
    host: str = ""
    pid: int = 0
    started_at: str = ""
    heartbeat_at: float = 0.0
    running_jobs: int = 0
    capacity: int = 1
    process_id: int = 0
    num_processes: int = 1
    devices: int = 0
    # advertised /metrics URL (PIO_WORKER_METRICS_URL): lets
    # `pio fleet status` scrape live device gauges off each worker
    metrics_url: str = ""

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id, "host": self.host, "pid": self.pid,
            "started_at": self.started_at,
            "heartbeat_at": self.heartbeat_at,
            "running_jobs": self.running_jobs, "capacity": self.capacity,
            "process_id": self.process_id,
            "num_processes": self.num_processes, "devices": self.devices,
            "metrics_url": self.metrics_url,
        }

    @staticmethod
    def from_dict(d: dict) -> "WorkerInfo":
        w = WorkerInfo(id=d.get("id", ""))
        for k in (
            "host", "pid", "started_at", "heartbeat_at", "running_jobs",
            "capacity", "process_id", "num_processes", "devices",
            "metrics_url",
        ):
            if d.get(k) is not None:
                setattr(w, k, d[k])
        return w


@dataclass
class FleetConfig:
    """Fleet-member knobs on top of the scheduler's own config."""

    # worker-record heartbeat cadence and liveness horizon
    heartbeat_interval_s: float = 2.0
    worker_stale_after_s: float = 10.0
    # multi-host process topology exported to train children
    distributed: DistributedConfig = field(
        default_factory=DistributedConfig
    )
    # adapt the CAS claim settle window from measured storage
    # write-visibility skew at start() (ISSUE 20); PIO_CAS_SETTLE_S
    # pins it instead when set
    adaptive_settle: bool = True


# safety factor on the measured same-process visibility latency: cross-
# worker skew (other host's clock + commit pipeline) is what the settle
# window really waits out, and we can only probe our own round trip
SETTLE_SKEW_FACTOR = 4.0


def measure_write_visibility_skew(
    storage: Storage, probes: int = 3, timeout_s: float = 2.0
) -> float:
    """Worst observed append→visible latency of the record store,
    measured with throwaway probe records (purged afterwards). This is
    the floor of the skew a CAS claimant must out-wait before reading
    the bid order; the settle window derives from it instead of a
    guessed constant."""
    store = LifecycleRecordStore(storage)
    entity = f"probe-{uuid.uuid4().hex[:8]}"
    worst = 0.0
    try:
        for i in range(max(1, probes)):
            t0 = time.monotonic()
            store.append("pio_settle_probe", entity, {"i": i})
            while True:
                if len(store.events("pio_settle_probe", entity)) > i:
                    break
                if time.monotonic() - t0 >= timeout_s:
                    break
                time.sleep(0.001)
            worst = max(worst, time.monotonic() - t0)
    finally:
        try:
            store.purge("pio_settle_probe", entity)
        except Exception:
            log.debug("settle probe cleanup failed", exc_info=True)
    return worst


class WorkerRegistry:
    """CRUD + liveness over worker records (shared record layer)."""

    def __init__(self, storage: Storage):
        self._store = LifecycleRecordStore(storage)

    def upsert(self, info: WorkerInfo) -> None:
        self._store.append(WORKER_ENTITY, info.id, info.to_dict())

    def heartbeat(
        self, worker_id: str, prev_event_id: Optional[str],
        running_jobs: int,
    ) -> str:
        """Heartbeat with compaction (same discipline as job
        heartbeats: one live beat event per worker, not one per tick).
        The beat carries `id` too: a record a peer GC'd away during a
        connectivity gap is otherwise resurrected identity-less, and an
        id-"" phantom would count as a live peer of everyone forever."""
        eid = self._store.append(WORKER_ENTITY, worker_id, {
            "id": worker_id,
            "heartbeat_at": time.time(), "running_jobs": running_jobs,
        })
        if prev_event_id:
            self._store.discard(prev_event_id)
        return eid

    def remove(self, worker_id: str) -> None:
        self._store.purge(WORKER_ENTITY, worker_id)

    def list(self) -> list[WorkerInfo]:
        return [
            WorkerInfo.from_dict(d)
            for d in self._store.fold(WORKER_ENTITY).values()
        ]

    def live(self, stale_after_s: float = 10.0) -> list[WorkerInfo]:
        cutoff = time.time() - stale_after_s
        return [w for w in self.list() if w.heartbeat_at >= cutoff]

    def gc(self, stale_after_s: float = 60.0) -> list[str]:
        """Purge records of workers dead for much longer than the
        liveness horizon (a crashed member can't deregister itself)."""
        cutoff = time.time() - stale_after_s
        doomed = [w.id for w in self.list() if w.heartbeat_at < cutoff]
        for wid in doomed:
            self.remove(wid)
        return doomed


class FleetMember:
    """One worker of the training fleet: a TrainScheduler + a
    heartbeating worker record + the peer probe that arms the CAS
    settle window only under real contention."""

    def __init__(
        self,
        storage: Storage,
        scheduler_config: Optional[SchedulerConfig] = None,
        fleet_config: Optional[FleetConfig] = None,
    ):
        self.storage = storage
        self.config = fleet_config or FleetConfig()
        sched_cfg = scheduler_config or SchedulerConfig()
        # export the process topology to every train child (single-host
        # fallback exports nothing)
        sched_cfg.child_env = dict(
            sched_cfg.child_env, **self.config.distributed.child_env()
        )
        self.scheduler = TrainScheduler(storage, sched_cfg)
        self.scheduler.peer_probe = self.live_peer_count
        self.registry = WorkerRegistry(storage)
        self.worker_id = self.scheduler.worker_id
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_event: Optional[str] = None
        # liveness reads hit storage; cache them for a heartbeat period
        # so every claim doesn't pay a worker-record fold
        self._peer_cache: tuple[float, int] = (0.0, 0)
        self._peer_lock = threading.Lock()
        self._shipper = None  # push telemetry (ISSUE 17), armed in start()

    # -- liveness ----------------------------------------------------------
    def live_peer_count(self) -> int:
        """Live workers OTHER than this one (the scheduler's settle
        gate). Cached for one heartbeat interval."""
        now = time.monotonic()
        with self._peer_lock:
            ts, n = self._peer_cache
            if now - ts < self.config.heartbeat_interval_s:
                return n
        try:
            peers = [
                w for w in self.registry.live(
                    self.config.worker_stale_after_s
                )
                if w.id != self.worker_id
            ]
            n = len(peers)
        except Exception:
            n = 1  # storage hiccup: assume contention, pay the wait
        with self._peer_lock:
            self._peer_cache = (now, n)
        return n

    def peers(self) -> list[WorkerInfo]:
        return [
            w for w in self.registry.live(self.config.worker_stale_after_s)
            if w.id != self.worker_id
        ]

    # -- lifecycle ---------------------------------------------------------
    def _device_count(self) -> int:
        # jax only if someone already paid for it — the fleet member
        # itself must stay importable on jax-free control planes
        import sys

        if "jax" not in sys.modules:
            return 0
        try:
            return len(sys.modules["jax"].devices())
        except Exception:
            return 0

    def start(self) -> None:
        dist = self.config.distributed
        self.registry.upsert(WorkerInfo(
            id=self.worker_id,
            host=socket.gethostname(),
            pid=os.getpid(),
            started_at=_utcnow_iso(),
            heartbeat_at=time.time(),
            capacity=max(1, int(self.scheduler.config.max_concurrent)),
            process_id=dist.process_id,
            num_processes=dist.num_processes,
            devices=self._device_count(),
            metrics_url=env_str("PIO_WORKER_METRICS_URL").strip(),
        ))
        self._stop.clear()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, name="fleet-worker-heartbeat",
            daemon=True,
        )
        self._hb_thread.start()
        # push telemetry (ISSUE 17): fleet workers are often behind NAT
        # or firewalls where the monitor can't scrape them — ship this
        # process's series/spans out instead. No-op unless PIO_PUSH_URL
        # or PIO_PUSH_SPOOL is set.
        try:
            from predictionio_tpu.obs.monitor.push import TelemetryShipper

            self._shipper = TelemetryShipper.from_env(
                instance=f"fleet-{self.worker_id}"
            )
            if self._shipper is not None:
                self._shipper.start()
        except Exception:
            log.debug("telemetry shipper unavailable", exc_info=True)
        self._adapt_claim_settle()
        self.scheduler.resume_orphans()
        self.scheduler.start()

    def _adapt_claim_settle(self) -> None:
        """Derive the CAS claim settle window from MEASURED storage
        write-visibility skew instead of a fixed constant (ISSUE 20):
        eval fan-out multiplies concurrent claims, and a settle window
        tuned for sqlite-on-localhost is wrong for a remote store. An
        operator-pinned PIO_CAS_SETTLE_S wins; failures keep the
        configured default (adaptation must never block a start)."""
        pinned = env_str("PIO_CAS_SETTLE_S").strip()
        if pinned:
            try:
                self.scheduler.config.claim_settle_s = float(pinned)
                log.info("claim settle pinned: %.3fs (PIO_CAS_SETTLE_S)",
                         self.scheduler.config.claim_settle_s)
            except ValueError:
                log.warning("PIO_CAS_SETTLE_S=%r is not a number; keeping "
                            "%.3fs", pinned,
                            self.scheduler.config.claim_settle_s)
            return
        if not self.config.adaptive_settle:
            return
        try:
            skew = measure_write_visibility_skew(self.storage)
        except Exception:
            log.debug("settle skew probe failed; keeping %.3fs",
                      self.scheduler.config.claim_settle_s, exc_info=True)
            return
        lo = env_float("PIO_CAS_SETTLE_MIN_S")
        hi = env_float("PIO_CAS_SETTLE_MAX_S")
        settle = min(max(SETTLE_SKEW_FACTOR * skew, lo), max(lo, hi))
        log.info(
            "claim settle adapted: measured visibility skew %.4fs -> "
            "settle %.3fs (was %.3fs)", skew, settle,
            self.scheduler.config.claim_settle_s,
        )
        self.scheduler.config.claim_settle_s = settle

    def stop(self, kill_child: bool = False) -> None:
        if self._shipper is not None:
            try:
                self._shipper.stop()  # joins + final flush
            except Exception:
                log.debug("telemetry shipper stop failed", exc_info=True)
            self._shipper = None
        self.scheduler.stop(kill_child=kill_child)
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join()
            self._hb_thread = None
        if kill_child:
            # crash simulation: leave the worker record to go stale so
            # peers observe the death the way they would a real one
            return
        try:
            self.registry.remove(self.worker_id)
        except Exception:
            log.debug("worker deregister failed (non-fatal)", exc_info=True)

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.config.heartbeat_interval_s):
            try:
                running = len(self.scheduler._running_ids)
                self._hb_event = self.registry.heartbeat(
                    self.worker_id, self._hb_event, running
                )
                self.registry.gc(
                    stale_after_s=6 * self.config.worker_stale_after_s
                )
            except Exception:
                log.warning(
                    "worker heartbeat failed (storage down?); continuing",
                    exc_info=True,
                )


#: device-relevant gauge families `pio fleet status` pulls off each
#: worker's /metrics (jaxmon.py exports; everything else is noise here)
_DEVICE_FAMILIES = (
    "jax_jit_compile_count",
    "jax_jit_compile_seconds_total",
    "jax_live_buffer_count",
    "jax_live_buffer_bytes",
)


def worker_device_info(
    metrics_url: str, timeout_s: float = 2.0
) -> Optional[dict[str, float]]:
    """Scrape one worker's advertised /metrics for its live device
    gauges (ISSUE 16); None when unreachable or nothing exported."""
    import urllib.request

    from predictionio_tpu.obs.monitor.scrape import parse_prometheus_text

    try:
        with urllib.request.urlopen(metrics_url, timeout=timeout_s) as r:
            body = r.read().decode(errors="replace")
    except Exception as e:
        log.debug("worker metrics scrape %s failed: %s", metrics_url, e)
        return None
    out: dict[str, float] = {}
    for name, _labels, value in parse_prometheus_text(body):
        if name in _DEVICE_FAMILIES:
            out[name] = out.get(name, 0.0) + value
    return out or None


def fleet_status(
    storage: Storage, stale_after_s: float = 10.0,
    probe_devices: bool = True,
) -> dict[str, Any]:
    """Operator view of the fleet: live/stale workers + queue depth
    (the `pio fleet status` payload). Live workers that advertise a
    metrics URL (PIO_WORKER_METRICS_URL) additionally get a
    ``device_info`` dict scraped off their /metrics."""
    registry = WorkerRegistry(storage)
    queue = JobQueue(storage)
    workers = registry.list()
    cutoff = time.time() - stale_after_s
    jobs = queue.list()
    by_status: dict[str, int] = {}
    for j in jobs:
        by_status[j.status] = by_status.get(j.status, 0) + 1

    def _row(w: WorkerInfo) -> dict[str, Any]:
        live = w.heartbeat_at >= cutoff
        row = dict(
            w.to_dict(),
            live=live,
            heartbeat_age_s=round(
                max(0.0, time.time() - w.heartbeat_at), 1
            ),
        )
        if probe_devices and live and w.metrics_url:
            info = worker_device_info(w.metrics_url)
            if info is not None:
                row["device_info"] = info
        return row

    return {
        "workers": [
            _row(w) for w in sorted(workers, key=lambda w: w.id)
        ],
        "live_workers": sum(
            1 for w in workers if w.heartbeat_at >= cutoff
        ),
        "jobs": by_status,
        "claimable": len(queue.claimable()),
        "running": [
            {
                "id": j.id, "worker_id": j.worker_id,
                "generation": j.generation,
                "heartbeat_age_s": round(
                    max(0.0, time.time() - j.heartbeat_at), 1
                ),
            }
            for j in jobs if j.status == "running"
        ],
    }
