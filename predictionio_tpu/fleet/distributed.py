"""Multi-host process wiring for the training/serving fleet (ISSUE 10).

The reference system scaled its training shuffle by handing partitions
to Spark executors over a cluster manager; the jax_graft analogue is
`jax.distributed`: N processes, each bound to its local chips, agree on
a coordinator and form ONE device mesh spanning all of them (the
tests/test_multihost.py topology, productized). ``DistributedConfig``
carries the three coordinates every runtime needs — coordinator
address, process id, process count — with a **single-host fallback**:
`num_processes <= 1` makes `initialize()` a no-op, so every code path
(tests, laptops, single-chip deployments) runs the same code with zero
distributed setup.

Import discipline: this module sits on control paths (scheduler worker
spawn, console) — jax is imported lazily inside `initialize()` only.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger(__name__)

# env contract: the worker fleet exports these to train subprocesses so
# an N-host train forms its mesh without per-job plumbing
ENV_COORDINATOR = "PIO_FLEET_COORDINATOR"
ENV_NUM_PROCESSES = "PIO_FLEET_NUM_PROCESSES"
ENV_PROCESS_ID = "PIO_FLEET_PROCESS_ID"


@dataclass(frozen=True)
class DistributedConfig:
    """jax.distributed-style multi-host init coordinates.

    `coordinator_address` is host:port of process 0's coordinator
    service; `process_id` ∈ [0, num_processes). With the default
    `num_processes=1` everything degrades to single-host: no
    coordinator, no collective init, tests run anywhere."""

    coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0

    def __post_init__(self):
        if self.num_processes < 1:
            raise ValueError(
                f"num_processes must be >= 1, got {self.num_processes}"
            )
        if not (0 <= self.process_id < self.num_processes):
            raise ValueError(
                f"process_id {self.process_id} outside "
                f"[0, {self.num_processes})"
            )
        if self.num_processes > 1 and not self.coordinator_address:
            raise ValueError(
                "multi-process fleet needs a coordinator_address"
            )

    @property
    def multi_host(self) -> bool:
        return self.num_processes > 1

    @staticmethod
    def from_env(env: Optional[dict] = None) -> "DistributedConfig":
        """The worker-side read of the env contract (missing → the
        single-host fallback)."""
        env = os.environ if env is None else env
        return DistributedConfig(
            coordinator_address=env.get(ENV_COORDINATOR) or None,
            num_processes=int(env.get(ENV_NUM_PROCESSES, "1") or 1),
            process_id=int(env.get(ENV_PROCESS_ID, "0") or 0),
        )

    @staticmethod
    def from_json(obj: Optional[dict]) -> "DistributedConfig":
        """Engine-variant / fleet-config JSON → config (the `fleet` key
        next to `mesh` in engine.json)."""
        obj = obj or {}
        return DistributedConfig(
            coordinator_address=obj.get("coordinator") or None,
            num_processes=int(obj.get("num_processes", 1) or 1),
            process_id=int(obj.get("process_id", 0) or 0),
        )

    def child_env(self) -> dict[str, str]:
        """Env to export to a train subprocess so it re-forms the same
        process topology (empty for single-host — the child must not
        try to reach a coordinator that isn't there)."""
        if not self.multi_host:
            return {}
        return {
            ENV_COORDINATOR: str(self.coordinator_address),
            ENV_NUM_PROCESSES: str(self.num_processes),
            ENV_PROCESS_ID: str(self.process_id),
        }

    def initialize(self) -> bool:
        """Join the multi-host collective (idempotent); returns whether
        a distributed init actually ran. Single-host: no-op, False.

        jax.distributed.initialize must run BEFORE any backend is
        created — callers invoke this first thing in a worker process,
        like tests/test_multihost.py's child does."""
        if not self.multi_host:
            return False
        import jax

        try:
            jax.distributed.initialize(
                coordinator_address=self.coordinator_address,
                num_processes=self.num_processes,
                process_id=self.process_id,
            )
        except RuntimeError as e:
            # already initialized (idempotent re-entry) is fine; a real
            # topology error is not
            if "already" in str(e).lower():
                log.debug("jax.distributed already initialized")
                return True
            raise
        log.info(
            "joined fleet collective: process %d/%d via %s",
            self.process_id, self.num_processes, self.coordinator_address,
        )
        return True
