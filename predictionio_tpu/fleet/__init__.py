"""Fleet subsystem (ISSUE 10): multi-chip sharded serving from
TPU-resident factor state + a multi-worker distributed training tier.

Three planes:

- **coordinator.py** — N `TrainScheduler` workers cooperating on ONE
  shared-storage job queue via compare-and-set claims (fenced
  claim_token + generation, CAS stale-heartbeat steal), with
  heartbeating worker records and `pio fleet status`,
- **distributed.py** — jax.distributed-style multi-host init config
  (coordinator address, process id/count) with a single-host fallback
  so every test and laptop runs the same code,
- **runtime.py** — `ShardedRuntime`: factor state row-sharded across a
  serving mesh, recommend/similar/fold_in lowered as sharded
  executables (local top-k per shard + global merge), so one model
  serves a catalog larger than a single chip's HBM.

Import discipline: this package sits on server/console control paths —
it must not import jax. `runtime` (which does) loads lazily through
module __getattr__.
"""

from predictionio_tpu.fleet.coordinator import (
    WORKER_ENTITY,
    FleetConfig,
    FleetMember,
    WorkerInfo,
    WorkerRegistry,
    fleet_status,
)
from predictionio_tpu.fleet.distributed import DistributedConfig

_LAZY_RUNTIME = (
    "ShardedRuntime",
    "OversizedModelError",
    "factor_state_bytes",
    "check_single_device_budget",
)

__all__ = [
    "DistributedConfig",
    "FleetConfig",
    "FleetMember",
    "WORKER_ENTITY",
    "WorkerInfo",
    "WorkerRegistry",
    "fleet_status",
    *_LAZY_RUNTIME,
]


def stage_serving_runtime(user_factors, item_factors, **kwargs):
    """Shared lazy staging for the engines' `shard_serving` knobs
    (recommendation / similarproduct / itemsim): returns a
    `ShardedRuntime` over the visible devices honoring the
    PIO_SERVE_HBM_BYTES per-device budget, or ``False`` when fewer
    than two devices are visible — the sentinel the engine models
    cache so the serving hot path never re-probes jax.devices().
    jax imports HERE, never at module import (data-plane discipline)."""
    import os

    import jax

    if len(jax.devices()) < 2:
        return False
    from predictionio_tpu.fleet import runtime as _runtime

    from predictionio_tpu.utils.env import env_opt_float

    return _runtime.ShardedRuntime(
        user_factors,
        item_factors,
        device_budget_bytes=env_opt_float("PIO_SERVE_HBM_BYTES"),
        **kwargs,
    )


__all__.append("stage_serving_runtime")


def __getattr__(name):
    if name in _LAZY_RUNTIME:
        from predictionio_tpu.fleet import runtime as _runtime

        return getattr(_runtime, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
