"""Sharded serving runtime: TPU-resident, row-sharded factor state
(ISSUE 10 tentpole part 2).

A single-chip serving tier caps the catalog at one HBM's worth of
factor rows. `ShardedRuntime` keeps BOTH factor matrices row-sharded
over a 1-D device mesh (parallel/mesh.py:serving_mesh) and lowers the
three serving verbs as sharded executables, so one model serves a
catalog larger than any single chip can load:

- **recommend**: each shard assembles the query block from the rows it
  owns (masked gather + psum — the all-reduce half of the classic
  gather), scores against ITS item slab, takes a LOCAL top-k, and an
  all-gather + second top-k merges the per-shard candidates into the
  global answer. Score traffic never leaves the shard; only (B, k)
  candidates ride the ICI.
- **similar**: same shape over L2-normalized item factors (cosine).
- **fold_in**: the single-side normal-equation solve against the FIXED
  opposite matrix — each shard contributes the partial Gram/b terms of
  the edges it owns, one psum assembles the K×K systems, every shard
  solves them redundantly (they are tiny), matching
  models/als.py:_fold_in_jit numerics.

Padding rows are exactly zero and masked out of every top-k by the
global-index pad mask, the same inertness discipline the train paths
use. This module imports jax at module level — reach it via
``predictionio_tpu.fleet``'s lazy attribute, never from a data-plane
import path.
"""

from __future__ import annotations

import logging
import threading
from functools import partial
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from predictionio_tpu.obs import devprof as _devprof
from predictionio_tpu.ops.segment import batched_cg, f32_gram
from predictionio_tpu.ops.topk import NEG_INF
from predictionio_tpu.parallel.mesh import (
    MODEL_AXIS,
    pad_rows_to_shards,
    serving_mesh,
    shard_map,
    shard_rows,
)

log = logging.getLogger(__name__)


class OversizedModelError(RuntimeError):
    """The factor state does not fit the given per-device HBM budget."""


def factor_state_bytes(
    n_users: int, n_items: int, rank: int, dtype_bytes: int = 4
) -> int:
    """Resident bytes of the full (unsharded) factor state — what a
    single-device runtime must fit in one HBM."""
    return (n_users + n_items) * rank * dtype_bytes


def check_single_device_budget(
    n_users: int, n_items: int, rank: int, budget_bytes: float
) -> None:
    """Raise when a SINGLE-device runtime cannot hold this factor
    state — the gate the sharded tier exists to pass (bench's
    oversized-catalog proof calls this for the refusal side)."""
    need = factor_state_bytes(n_users, n_items, rank)
    if need > budget_bytes:
        raise OversizedModelError(
            f"factor state needs {need / 1e9:.2f} GB resident but the "
            f"single-device budget is {budget_bytes / 1e9:.2f} GB — "
            "serve it sharded (fleet.ShardedRuntime)"
        )


# ---------------------------------------------------------------------------
# sharded executables
# ---------------------------------------------------------------------------


def _owned_rows(rows: jax.Array, table: jax.Array, n_local: int):
    """Shard-local gather of `table[rows]` contributions: rows this
    shard owns yield their slab row, others yield zero — a psum over
    the shard axis completes the distributed gather."""
    idx = jax.lax.axis_index(MODEL_AXIS)
    loc = rows - idx * n_local
    own = (loc >= 0) & (loc < n_local)
    safe = jnp.clip(loc, 0, n_local - 1)
    return jnp.where(own[..., None], table[safe], 0.0)


def _merge_topk(v: jax.Array, ix: jax.Array, k: int):
    """Local (B, k_l) candidates → global (B, k) top-k: all-gather the
    per-shard candidates along the score axis, then one more top_k."""
    vs = jax.lax.all_gather(v, MODEL_AXIS, axis=1, tiled=True)
    ixs = jax.lax.all_gather(ix, MODEL_AXIS, axis=1, tiled=True)
    vv, sel = jax.lax.top_k(vs, k)
    return vv, jnp.take_along_axis(ixs, sel, axis=1)


@partial(
    jax.jit, static_argnames=("k", "n_items", "mesh", "masked", "mode")
)
def _sharded_recommend(
    rows: jax.Array,  # (B,) int32, replicated
    uf: jax.Array,  # (U_p, K) row-sharded over mp
    itf: jax.Array,  # (I_p, K) row-sharded over mp
    mask: Optional[jax.Array],  # (B, I_p) bool col-sharded / None
    *,
    k: int,
    n_items: int,
    mesh: jax.sharding.Mesh,
    masked: bool,
    mode: Optional[str] = None,
):
    """Sharded recommend. With `mode` set (ISSUE 11), the shard-local
    score+select runs the fused Pallas recommend+top-k kernel
    (ops/recommend_pallas.py) — the same one-HBM-pass fusion as the
    single-device path, amortized here by the existing local-top-k +
    all-gather merge: each shard never materializes even its local
    (B, i_local) score slab. Requires the item rows padded so every
    shard's slab is tile-divisible (ShardedRuntime pre-pads when a mode
    resolves); dead pad/foreign columns ride the kernel's mask input."""
    n_shards = int(mesh.shape[MODEL_AXIS])
    u_local = uf.shape[0] // n_shards
    i_local = itf.shape[0] // n_shards
    k_l = min(k, i_local)

    def local(rows_l, uf_l, itf_l, mask_l):
        idx = jax.lax.axis_index(MODEL_AXIS)
        q = jax.lax.psum(
            _owned_rows(rows_l, uf_l, u_local), MODEL_AXIS
        )  # (B, K) — every shard now holds the full query block
        gcol = idx * i_local + jnp.arange(i_local)
        dead = (gcol >= n_items)[None, :]
        if masked:
            dead = dead | mask_l
        if mode is not None:
            from predictionio_tpu.ops.recommend_pallas import (
                fused_recommend_topk,
            )

            b = q.shape[0]
            dead_f = jnp.broadcast_to(
                dead.astype(jnp.float32), (b, i_local)
            )
            v, ix = fused_recommend_topk(
                q, itf_l, None, None, dead_f,
                k=k_l, n_items=i_local,
                interpret=(mode == "interpret"),
            )
        else:
            scores = q @ itf_l.T  # (B, i_local): the local slab only
            scores = jnp.where(dead, NEG_INF, scores)
            v, ix = jax.lax.top_k(scores, k_l)
        return _merge_topk(v, ix + idx * i_local, k)

    sh = P(MODEL_AXIS, None)
    if masked:
        fn, args = local, (rows, uf, itf, mask)
        in_specs = (P(), sh, sh, P(None, MODEL_AXIS))
    else:
        fn = lambda r, u, i: local(r, u, i, None)
        args = (rows, uf, itf)
        in_specs = (P(), sh, sh)
    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=(P(), P()),
        check=False,
    )(*args)


@partial(
    jax.jit, static_argnames=("k", "n_items", "mesh", "exclude_self")
)
def _sharded_similar(
    rows: jax.Array,  # (B,) int32 item rows, replicated
    itf: jax.Array,  # (I_p, K) row-sharded
    *,
    k: int,
    n_items: int,
    mesh: jax.sharding.Mesh,
    exclude_self: bool,
):
    n_shards = int(mesh.shape[MODEL_AXIS])
    i_local = itf.shape[0] // n_shards
    k_l = min(k, i_local)

    def local(rows_l, itf_l):
        idx = jax.lax.axis_index(MODEL_AXIS)
        q = jax.lax.psum(_owned_rows(rows_l, itf_l, i_local), MODEL_AXIS)
        qn = q / (jnp.linalg.norm(q, axis=-1, keepdims=True) + 1e-9)
        fn_ = itf_l / (
            jnp.linalg.norm(itf_l, axis=-1, keepdims=True) + 1e-9
        )
        scores = qn @ fn_.T  # (B, i_local)
        gcol = idx * i_local + jnp.arange(i_local)
        dead = (gcol >= n_items)[None, :]
        if exclude_self:
            dead = dead | (gcol[None, :] == rows_l[:, None])
        scores = jnp.where(dead, NEG_INF, scores)
        v, ix = jax.lax.top_k(scores, k_l)
        return _merge_topk(v, ix + idx * i_local, k)

    return shard_map(
        local, mesh=mesh, in_specs=(P(), P(MODEL_AXIS, None)),
        out_specs=(P(), P()), check=False,
    )(rows, itf)


@partial(
    jax.jit, static_argnames=("k", "n_items", "mesh", "masked")
)
def _sharded_similar_vecs(
    vecs: jax.Array,  # (B, K) f32 query vectors, replicated
    itf: jax.Array,  # (I_p, K) row-sharded
    mask: Optional[jax.Array],  # (B, I_p) bool col-sharded / None
    *,
    k: int,
    n_items: int,
    mesh: jax.sharding.Mesh,
    masked: bool,
):
    """Cosine top-k against ARBITRARY query vectors (the
    similarproduct/itemsim basket query: mean of the query items'
    vectors; ISSUE 11 satellite). Same local-top-k + all-gather merge
    as `_sharded_similar`, without the owned-rows gather — the caller
    already holds the query vectors."""
    n_shards = int(mesh.shape[MODEL_AXIS])
    i_local = itf.shape[0] // n_shards
    k_l = min(k, i_local)

    def local(vecs_l, itf_l, mask_l):
        idx = jax.lax.axis_index(MODEL_AXIS)
        qn = vecs_l / (
            jnp.linalg.norm(vecs_l, axis=-1, keepdims=True) + 1e-9
        )
        fn_ = itf_l / (
            jnp.linalg.norm(itf_l, axis=-1, keepdims=True) + 1e-9
        )
        scores = qn @ fn_.T  # (B, i_local)
        gcol = idx * i_local + jnp.arange(i_local)
        dead = (gcol >= n_items)[None, :]
        if masked:
            dead = dead | mask_l
        scores = jnp.where(dead, NEG_INF, scores)
        v, ix = jax.lax.top_k(scores, k_l)
        return _merge_topk(v, ix + idx * i_local, k)

    sh = P(MODEL_AXIS, None)
    if masked:
        fn, args = local, (vecs, itf, mask)
        in_specs = (P(), sh, P(None, MODEL_AXIS))
    else:
        fn = lambda v, i: local(v, i, None)
        args = (vecs, itf)
        in_specs = (P(), sh)
    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=(P(), P()),
        check=False,
    )(*args)


@partial(jax.jit, static_argnames=("implicit", "cg_iterations", "mesh"))
def _sharded_fold_in(
    fixed: jax.Array,  # (N_p, K) row-sharded — the FIXED opposite side
    edge_idx: jax.Array,  # (R, E) int32 rows into `fixed` (replicated)
    edge_val: jax.Array,  # (R, E)
    edge_ok: jax.Array,  # (R, E) 1.0 real / 0.0 pad
    lam: jax.Array,  # () f32
    alpha: jax.Array,  # () f32
    *,
    implicit: bool,
    cg_iterations: int,
    mesh: jax.sharding.Mesh,
):
    """Sharded single-side fold-in solve: identical operator assembly to
    models/als.py:_fold_in_jit, with the edge gather distributed — each
    shard contributes the terms of the fixed rows it owns and ONE psum
    assembles the (R, K, K) systems everywhere."""
    n_shards = int(mesh.shape[MODEL_AXIS])
    n_local = fixed.shape[0] // n_shards
    k = fixed.shape[1]

    def local(fixed_l, edge_idx, edge_val, edge_ok):
        idx = jax.lax.axis_index(MODEL_AXIS)
        loc = edge_idx - idx * n_local
        own = (
            ((loc >= 0) & (loc < n_local)).astype(jnp.float32) * edge_ok
        )
        safe = jnp.clip(loc, 0, n_local - 1)
        y = fixed_l[safe] * own[..., None]  # (R, E, K) — owner-masked
        eye = jnp.eye(k, dtype=jnp.float32)
        if implicit:
            conf = 1.0 + alpha * jnp.abs(edge_val)
            pref = (edge_val > 0).astype(jnp.float32)
            w_b = conf * pref * own
            w_g = (conf - 1.0) * own
            gram = jax.lax.psum(f32_gram(fixed_l), MODEL_AXIS)
            b = jax.lax.psum(
                jnp.einsum("re,rek->rk", w_b, y), MODEL_AXIS
            )
            a = (
                jax.lax.psum(
                    jnp.einsum("re,rek,rel->rkl", w_g, y, y), MODEL_AXIS
                )
                + gram[None, :, :]
                + lam * eye
            )
        else:
            b = jax.lax.psum(
                jnp.einsum("re,rek->rk", edge_val * own, y), MODEL_AXIS
            )
            deg = jnp.sum(edge_ok, axis=1)  # edge_ok is replicated
            reg = lam * jnp.maximum(deg, 1.0)
            a = (
                jax.lax.psum(
                    jnp.einsum("re,rek,rel->rkl", own, y, y), MODEL_AXIS
                )
                + reg[:, None, None] * eye
            )

        def matvec(v):
            return jnp.einsum("rkl,rl->rk", a, v)

        return batched_cg(matvec, b, jnp.zeros_like(b), cg_iterations)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(MODEL_AXIS, None), P(), P(), P()),
        out_specs=P(), check=False,
    )(fixed, edge_idx, edge_val, edge_ok)


@partial(jax.jit, static_argnames=("mesh",))
def _scatter_rows(
    table: jax.Array, rows: jax.Array, values: jax.Array, *, mesh
):
    """Functional row update that PRESERVES the row sharding (the
    fold-in publish path: solved rows land in the resident state
    without a host round-trip or a resharding copy). Deliberately NOT
    donated: the pipelined dispatcher serves queries concurrently with
    fold-in publishes, and a reader that captured the old table
    reference must keep a live buffer (copy-on-write, like the dense
    publish path) — the transient 2× is the price of zero-drop."""
    out = table.at[rows].set(values)
    return jax.lax.with_sharding_constraint(
        out, NamedSharding(mesh, P(MODEL_AXIS, None))
    )


# serving executables opt into memory analysis like the dense serving
# kernels: the per-signature AOT compile lands in warmup, and the
# temp/output bytes feed the tenant cache's transient accounting
_scatter_rows = _devprof.instrument("fleet.scatter_rows", _scatter_rows)
_sharded_recommend = _devprof.instrument(
    "fleet.recommend_sharded", _sharded_recommend, memory=True
)
_sharded_similar = _devprof.instrument(
    "fleet.similar_sharded", _sharded_similar, memory=True
)
_sharded_similar_vecs = _devprof.instrument(
    "fleet.similar_vecs_sharded", _sharded_similar_vecs, memory=True
)
_sharded_fold_in = _devprof.instrument(
    "fleet.fold_in_sharded", _sharded_fold_in, memory=True
)


# ---------------------------------------------------------------------------
# the runtime
# ---------------------------------------------------------------------------


class ShardedRuntime:
    """Row-sharded, device-resident ALS factor state + the sharded
    serving verbs. Swapped atomically like any other runtime: the query
    server's runtime-swap lock and the tenant model cache treat it as
    opaque model state (tenancy/cache.py's device-bytes walk counts
    only the per-device addressable shard)."""

    def __init__(
        self,
        user_factors: np.ndarray,  # (U, K) f32
        item_factors: np.ndarray,  # (I, K) f32
        user_vocab: Optional[Any] = None,
        item_vocab: Optional[Any] = None,
        params: Optional[Any] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        device_budget_bytes: Optional[float] = None,
        serve_mode: str = "auto",
    ):
        from predictionio_tpu.ops import recommend_pallas as _rp

        if mesh is None:
            mesh = serving_mesh()
        if MODEL_AXIS not in mesh.shape or len(mesh.shape) != 1:
            raise ValueError(
                "ShardedRuntime needs a 1-D serving mesh "
                f"(parallel.mesh.serving_mesh); got axes {dict(mesh.shape)}"
            )
        self.mesh = mesh
        self.n_shards = int(mesh.shape[MODEL_AXIS])
        # fused local score+select (ISSUE 11): the sharded twin of the
        # one-pass recommend+top-k kernel — resolved once here so every
        # serving call traces against a fixed mode
        self.serve_mode = _rp.resolve_mode(serve_mode)
        uf = np.asarray(user_factors, np.float32)
        itf = np.asarray(item_factors, np.float32)
        if self.serve_mode is not None:
            # the kernel needs each shard's item slab tile-divisible:
            # pad item rows to shards × ITEM_PAD (pad rows are zero and
            # ride the dead-column mask, same inertness discipline)
            quantum = self.n_shards * _rp.ITEM_PAD
            i_p = -(-max(itf.shape[0], 1) // quantum) * quantum
            if i_p != itf.shape[0]:
                itf = np.concatenate([
                    itf,
                    np.zeros(
                        (i_p - itf.shape[0], itf.shape[1]), itf.dtype
                    ),
                ])
        self.n_users, self.rank = uf.shape
        self.n_items = int(np.asarray(item_factors).shape[0])
        if device_budget_bytes is not None:
            per_shard = self._padded_bytes(uf, itf) / self.n_shards
            if per_shard > device_budget_bytes:
                raise OversizedModelError(
                    f"factor state needs {per_shard / 1e9:.2f} GB per "
                    f"shard over {self.n_shards} shard(s) but the "
                    f"per-device budget is "
                    f"{device_budget_bytes / 1e9:.2f} GB"
                )
        self.user_vocab = user_vocab
        self.item_vocab = item_vocab
        self.params = params
        self._lock = threading.Lock()
        # ONE staging each: the sharded arrays stay HBM-resident across
        # queries, folds, and swaps (CreateServer-style resident state)
        self._uf = shard_rows(mesh, uf)
        self._itf = shard_rows(mesh, itf)

    def _padded_bytes(self, uf: np.ndarray, itf: np.ndarray) -> int:
        u_p = pad_rows_to_shards(uf.shape[0], self.n_shards)
        i_p = pad_rows_to_shards(itf.shape[0], self.n_shards)
        return (u_p + i_p) * self.rank * 4

    @classmethod
    def from_factors(
        cls,
        factors: Any,  # models.als.ALSFactors
        mesh: Optional[jax.sharding.Mesh] = None,
        device_budget_bytes: Optional[float] = None,
    ) -> "ShardedRuntime":
        return cls(
            factors.user_factors,
            factors.item_factors,
            user_vocab=factors.user_vocab,
            item_vocab=factors.item_vocab,
            params=factors.params,
            mesh=mesh,
            device_budget_bytes=device_budget_bytes,
        )

    # -- serving -----------------------------------------------------------
    def recommend(
        self,
        user_indices: np.ndarray,
        k: int,
        exclude_mask: Optional[np.ndarray] = None,  # (B, n_items) bool
    ) -> tuple[np.ndarray, np.ndarray]:
        """Global top-k items per user from the sharded state; same
        contract as models.als.recommend (scores, item_indices)."""
        k = min(int(k), self.n_items)
        rows = jnp.asarray(np.asarray(user_indices, np.int32))
        if exclude_mask is None:
            vals, idx = _sharded_recommend(
                rows, self._uf, self._itf, None,
                k=k, n_items=self.n_items, mesh=self.mesh, masked=False,
                mode=self.serve_mode,
            )
        else:
            vals, idx = _sharded_recommend(
                rows, self._uf, self._itf,
                jnp.asarray(self._pad_mask(exclude_mask)),
                k=k, n_items=self.n_items, mesh=self.mesh, masked=True,
                mode=self.serve_mode,
            )
        return np.asarray(vals), np.asarray(idx)

    def _pad_mask(self, exclude_mask) -> np.ndarray:
        """Pad mask columns to the sharded item width."""
        mask = np.asarray(exclude_mask, bool)
        i_p = int(self._itf.shape[0])
        if mask.shape[1] != i_p:
            mask = np.concatenate([
                mask,
                np.zeros((mask.shape[0], i_p - mask.shape[1]), bool),
            ], axis=1)
        return mask

    def similar_vectors(
        self,
        vectors: np.ndarray,  # (B, K) query vectors (e.g. basket means)
        k: int,
        exclude_mask: Optional[np.ndarray] = None,  # (B, n_items) bool
    ) -> tuple[np.ndarray, np.ndarray]:
        """Cosine top-k against arbitrary query vectors — the
        similarproduct/itemsim basket query served from the sharded
        state (ISSUE 11 satellite)."""
        k = min(int(k), self.n_items)
        vecs = jnp.asarray(np.asarray(vectors, np.float32))
        if exclude_mask is None:
            vals, idx = _sharded_similar_vecs(
                vecs, self._itf, None,
                k=k, n_items=self.n_items, mesh=self.mesh, masked=False,
            )
        else:
            vals, idx = _sharded_similar_vecs(
                vecs, self._itf,
                jnp.asarray(self._pad_mask(exclude_mask)),
                k=k, n_items=self.n_items, mesh=self.mesh, masked=True,
            )
        return np.asarray(vals), np.asarray(idx)

    def similar_items(
        self,
        item_indices: np.ndarray,
        k: int,
        exclude_self: bool = True,
    ) -> tuple[np.ndarray, np.ndarray]:
        k = min(int(k), self.n_items)
        rows = jnp.asarray(np.asarray(item_indices, np.int32))
        vals, idx = _sharded_similar(
            rows, self._itf,
            k=k, n_items=self.n_items, mesh=self.mesh,
            exclude_self=exclude_self,
        )
        return np.asarray(vals), np.asarray(idx)

    def fold_in_rows(
        self,
        edges: Sequence[Sequence[tuple[int, float]]],
        params: Any,  # models.als.ALSParams
        side: str = "user",
    ) -> np.ndarray:
        """Sharded single-side fold-in (the online consumer's solve):
        per dirty row, solve its system against the FIXED opposite
        sharded matrix; returns the (R, K) solved factors. Bucketing
        mirrors models.als.fold_in_rows so streaming ticks reuse a
        handful of compiled programs."""
        from predictionio_tpu.models.als import _fold_edge_bucket
        from predictionio_tpu.utils.bucket import batch_bucket

        if not edges:
            return np.zeros((0, self.rank), np.float32)
        fixed = self._itf if side == "user" else self._uf
        r_real = len(edges)
        r_pad = batch_bucket(r_real)
        e_pad = _fold_edge_bucket(max(len(e) for e in edges))
        idx = np.zeros((r_pad, e_pad), np.int32)
        val = np.zeros((r_pad, e_pad), np.float32)
        ok = np.zeros((r_pad, e_pad), np.float32)
        for r, row in enumerate(edges):
            for e, (j, v) in enumerate(row):
                idx[r, e] = j
                val[r, e] = v
                ok[r, e] = 1.0
        solved = _sharded_fold_in(
            fixed, jnp.asarray(idx), jnp.asarray(val), jnp.asarray(ok),
            jnp.float32(params.lambda_), jnp.float32(params.alpha),
            implicit=params.implicit_prefs,
            cg_iterations=params.cg_iterations,
            mesh=self.mesh,
        )
        return np.asarray(solved)[:r_real]

    # -- state updates -----------------------------------------------------
    def update_user_rows(
        self, rows: np.ndarray, values: np.ndarray
    ) -> None:
        self._update("_uf", rows, values)

    def update_item_rows(
        self, rows: np.ndarray, values: np.ndarray
    ) -> None:
        self._update("_itf", rows, values)

    def _update(self, attr: str, rows, values) -> None:
        rows = np.asarray(rows, np.int32)
        table = getattr(self, attr)
        if rows.size and int(rows.max()) >= int(table.shape[0]):
            raise ValueError(
                "row update beyond the padded shard extent — vocab "
                "growth needs a rebuild (amortized like the online "
                "fold-in's factor growth), not an in-place set"
            )
        with self._lock:
            setattr(self, attr, _scatter_rows(
                getattr(self, attr), jnp.asarray(rows),
                jnp.asarray(np.asarray(values, np.float32)),
                mesh=self.mesh,
            ))

    # -- accounting --------------------------------------------------------
    def device_bytes(self) -> dict[str, float]:
        total = float(self._uf.nbytes + self._itf.nbytes)
        return {
            "total": total,
            "per_shard": total / self.n_shards,
            "shards": float(self.n_shards),
        }

    def info(self) -> dict[str, Any]:
        b = self.device_bytes()
        return {
            "shards": self.n_shards,
            "devices": [
                str(d) for d in self.mesh.devices.reshape(-1)
            ],
            "n_users": self.n_users,
            "n_items": self.n_items,
            "rank": self.rank,
            "resident_bytes_total": b["total"],
            "resident_bytes_per_shard": b["per_shard"],
        }

    # the tenant cache's device-bytes walk finds these via __dict__:
    # jax arrays report addressable-shard bytes there, so a cached
    # sharded runtime is charged one SHARD, not the whole catalog
    @property
    def models(self):  # EngineRuntime-walk compatibility
        return (self._uf, self._itf)
