"""Sharded serving runtime: TPU-resident, row-sharded factor state
(ISSUE 10 tentpole part 2; ISSUE 14 brings it to dtype/kernel parity
with the single-device tier).

A single-chip serving tier caps the catalog at one HBM's worth of
factor rows. `ShardedRuntime` keeps BOTH factor matrices row-sharded
over a 1-D device mesh (parallel/mesh.py:serving_mesh) and lowers the
serving verbs as sharded executables, so one model serves a catalog
larger than any single chip can load:

- **recommend**: each shard assembles the query block from the rows it
  owns (masked gather + psum — the all-reduce half of the classic
  gather), scores against ITS item slab, takes a LOCAL top-k, and an
  all-gather + second top-k merges the per-shard candidates into the
  global answer. Score traffic never leaves the shard; only (B, k)
  candidates ride the ICI.
- **similar** / **similar_vectors**: the same shape over cosine scores
  — computed as the scaled dot (inverse norms ride the kernel's scale
  inputs, models/als.py discipline), so the ONE resident slab serves
  both verbs with no normalized copy.
- **fold_in**: the single-side normal-equation solve against the FIXED
  opposite matrix — each shard contributes the partial Gram/b terms of
  the edges it owns (dequantized in registers when the slab is
  int8/bf16), one psum assembles the K×K systems, every shard solves
  them redundantly (they are tiny).

ISSUE 14 additions:

- **serve_dtype** ("f32" | "bf16" | "int8"): int8 stages per-row
  symmetric-quantized slabs + scale vectors (~1/3 the resident HBM of
  f32 once scales and inverse norms ride along); bf16 halves it. The
  local score pass matches the single-device semantics exactly —
  int8×int8→int32 with scale-product dequant — on both the fused
  kernel and the XLA fallback.
- **fused local pass for every verb**: with a resolved serve_mode the
  shard-local score+select runs ops/recommend_pallas.py's one-pass
  kernel (per-shard live counts ride its traced SMEM scalar; item rows
  pre-pad to shards × ITEM_PAD so every slab is tile-divisible).
- **bit-packed exclusion masks**: the (B, I) bool mask input is gone —
  exclusion ships as (B, I_p/32) packed words column-sharded over the
  mesh (1/32 the f32-equivalent bytes), expanded in registers by the
  kernel or unpacked in-jit by the XLA fallback.
- **donated dirty-row publish** (direction-1 item (c)): `update_*_rows`
  re-quantizes ONLY the dirty rows and, once in-flight readers drain
  (a short writer-priority window on the reader lease), DONATES the
  resident slab into the row write — the publish costs the dirty rows,
  not a slab copy and never a host restage. Readers that cannot drain
  in time fall back to the copy-on-write scatter (zero-drop either
  way).

Padding rows are exactly zero and masked out of every top-k by the
live-count/pad discipline the train paths use. This module imports jax
at module level — reach it via ``predictionio_tpu.fleet``'s lazy
attribute, never from a data-plane import path.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from functools import partial
from typing import Any, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from predictionio_tpu.obs import devprof as _devprof
from predictionio_tpu.ops.segment import batched_cg, f32_gram
from predictionio_tpu.ops.topk import NEG_INF
from predictionio_tpu.parallel.mesh import (
    MODEL_AXIS,
    pad_rows_to_shards,
    serving_mesh,
    shard_map,
    shard_rows,
)

log = logging.getLogger(__name__)

#: how long a donated publish waits for in-flight readers to drain
#: before falling back to the copy-on-write scatter
_DONATE_DRAIN_S = 2.0


class OversizedModelError(RuntimeError):
    """The factor state does not fit the given per-device HBM budget."""


def factor_state_bytes(
    n_users: int, n_items: int, rank: int, dtype_bytes: int = 4
) -> int:
    """Resident bytes of the full (unsharded) factor state — what a
    single-device runtime must fit in one HBM."""
    return (n_users + n_items) * rank * dtype_bytes


def check_single_device_budget(
    n_users: int, n_items: int, rank: int, budget_bytes: float
) -> None:
    """Raise when a SINGLE-device runtime cannot hold this factor
    state — the gate the sharded tier exists to pass (bench's
    oversized-catalog proof calls this for the refusal side)."""
    need = factor_state_bytes(n_users, n_items, rank)
    if need > budget_bytes:
        raise OversizedModelError(
            f"factor state needs {need / 1e9:.2f} GB resident but the "
            f"single-device budget is {budget_bytes / 1e9:.2f} GB — "
            "serve it sharded (fleet.ShardedRuntime)"
        )


# ---------------------------------------------------------------------------
# sharded executables
# ---------------------------------------------------------------------------


def _owned_rows(rows: jax.Array, table: jax.Array, n_local: int):
    """Shard-local gather of `table[rows]` contributions: rows this
    shard owns yield their slab row, others yield zero — a psum over
    the shard axis completes the distributed gather. int8/bf16 tables
    contribute as exact f32 (small integers / bf16 values are exact in
    f32, and one shard owns each row, so the psum reconstructs the
    stored row bit-for-bit)."""
    idx = jax.lax.axis_index(MODEL_AXIS)
    loc = rows - idx * n_local
    own = (loc >= 0) & (loc < n_local)
    safe = jnp.clip(loc, 0, n_local - 1)
    vals = table[safe].astype(jnp.float32)
    return jnp.where(own[..., None], vals, 0.0)


def _owned_vec(rows: jax.Array, vec: jax.Array, n_local: int):
    """Owned gather of a (1, i_local) per-row vector (scales, inverse
    norms) at global `rows` → (B, 1) after the caller's psum."""
    idx = jax.lax.axis_index(MODEL_AXIS)
    loc = rows - idx * n_local
    own = (loc >= 0) & (loc < n_local)
    safe = jnp.clip(loc, 0, n_local - 1)
    return jnp.where(own, vec[0, safe], 0.0)[:, None]


def _merge_topk(v: jax.Array, ix: jax.Array, k: int):
    """Local (B, k_l) candidates → global (B, k) top-k: all-gather the
    per-shard candidates along the score axis, then one more top_k."""
    vs = jax.lax.all_gather(v, MODEL_AXIS, axis=1, tiled=True)
    ixs = jax.lax.all_gather(ix, MODEL_AXIS, axis=1, tiled=True)
    vv, sel = jax.lax.top_k(vs, k)
    return vv, jnp.take_along_axis(ixs, sel, axis=1)


def _sharded_call(mesh, local, *, required, optional):
    """ONE shard_map assembler for every serving verb's optional-input
    plumbing: `required`/`optional` are [(array_or_None, spec), ...];
    absent optionals are excluded from the traced inputs (shard_map
    cannot spec None leaves) and re-inflated as None positionals onto
    `local`, whose signature is required-args-first then the optionals
    in declaration order."""
    args = [a for a, _ in required]
    in_specs = [s for _, s in required]
    present = []
    for a, spec in optional:
        present.append(a is not None)
        if a is not None:
            args.append(a)
            in_specs.append(spec)
    n_req = len(required)

    def fn(*xs):
        it = iter(xs[n_req:])
        filled = [next(it) if p else None for p in present]
        return local(*xs[:n_req], *filled)

    return shard_map(
        fn, mesh=mesh, in_specs=tuple(in_specs), out_specs=(P(), P()),
        check=False,
    )(*args)


def _local_score_topk(
    q, itf_l, qs, isc_l, mask_bits_l, excl_local, live_l, *, k_l, mode
):
    """The shard-local score+mask+select every verb shares — the SAME
    seam the single-device tier serves through
    (ops/recommend_pallas.py:fused_or_xla_topk): the fused one-pass
    kernel when a mode resolved (the per-shard live count rides the
    traced SMEM scalar; packed words / local-id row lists apply in
    registers), else the XLA two-step with identical semantics
    (including the batch-size-stable dot spelling its docstring
    records)."""
    from predictionio_tpu.ops.recommend_pallas import fused_or_xla_topk

    return fused_or_xla_topk(
        q, itf_l, qs, isc_l, mask_bits_l, excl_local, live_l,
        k=k_l, mode=mode,
    )


@partial(
    jax.jit, static_argnames=("k", "n_items", "mesh", "mode")
)
def _sharded_recommend(
    rows: jax.Array,  # (B,) int32, replicated
    uf: jax.Array,  # (U_p, K) row-sharded over mp — f32 | bf16 | int8
    itf: jax.Array,  # (I_p, K) row-sharded over mp
    uscale: Optional[jax.Array],  # (U_p, 1) f32 row-sharded (int8)
    iscale: Optional[jax.Array],  # (1, I_p) f32 col-sharded (int8)
    mask_bits: Optional[jax.Array],  # (B, I_p/32) int32 col-sharded
    *,
    k: int,
    n_items: int,
    mesh: jax.sharding.Mesh,
    mode: Optional[str] = None,
):
    """Sharded recommend: the shard-local score+select is the SAME
    verb-agnostic fused pass as the single-device path (ISSUE 14),
    amortized by the local-top-k + all-gather merge — each shard never
    materializes even its local (B, i_local) score slab."""
    n_shards = int(mesh.shape[MODEL_AXIS])
    u_local = uf.shape[0] // n_shards
    i_local = itf.shape[0] // n_shards
    k_l = min(k, i_local)
    int8 = uf.dtype == jnp.int8

    def local(rows_l, uf_l, itf_l, uscale_l, iscale_l, mask_l):
        idx = jax.lax.axis_index(MODEL_AXIS)
        qf = jax.lax.psum(
            _owned_rows(rows_l, uf_l, u_local), MODEL_AXIS
        )  # (B, K) f32 — every shard now holds the full query block
        if int8:
            # the stored per-row quantization carries over exactly:
            # values are the resident int8 rows, scale their vector
            q = qf.astype(jnp.int8)
            qs = jax.lax.psum(
                _owned_vec(
                    rows_l, jnp.swapaxes(uscale_l, 0, 1), u_local
                ),
                MODEL_AXIS,
            )
            isc_l_ = iscale_l
        else:
            q = qf.astype(itf_l.dtype)
            qs = isc_l_ = None
        # per-shard live column count: global vocab clipped to my slab
        live_l = jnp.clip(n_items - idx * i_local, 0, i_local)
        v, ix = _local_score_topk(
            q, itf_l, qs, isc_l_, mask_l, None, live_l,
            k_l=k_l, mode=mode,
        )
        return _merge_topk(v, ix + idx * i_local, k)

    sh = P(MODEL_AXIS, None)
    col_sh = P(None, MODEL_AXIS)
    return _sharded_call(
        mesh, local,
        required=[(rows, P()), (uf, sh), (itf, sh)],
        optional=[(uscale, sh), (iscale, col_sh), (mask_bits, col_sh)],
    )


@partial(
    jax.jit,
    static_argnames=("k", "n_items", "mesh", "exclude_self", "mode"),
)
def _sharded_similar(
    rows: jax.Array,  # (B,) int32 item rows, replicated
    itf: jax.Array,  # (I_p, K) row-sharded
    iscale: Optional[jax.Array],  # (1, I_p) f32 col-sharded (int8)
    iinv: jax.Array,  # (1, I_p) f32 col-sharded inverse norms
    mask_bits: Optional[jax.Array],
    *,
    k: int,
    n_items: int,
    mesh: jax.sharding.Mesh,
    exclude_self: bool,
    mode: Optional[str] = None,
):
    """Sharded cosine similar off the SAME resident slab as recommend:
    cosine = (q·x)·(1/|q|)·(1/|x|), the inverse norms riding the
    fused kernel's scale inputs. exclude_self translates the query's
    GLOBAL row ids into shard-local ids and rides the kernel's
    row-list input — entries outside the shard never match."""
    n_shards = int(mesh.shape[MODEL_AXIS])
    i_local = itf.shape[0] // n_shards
    k_l = min(k, i_local)
    int8 = itf.dtype == jnp.int8

    def local(rows_l, itf_l, iinv_l, iscale_l, mask_l):
        idx = jax.lax.axis_index(MODEL_AXIS)
        qf = jax.lax.psum(
            _owned_rows(rows_l, itf_l, i_local), MODEL_AXIS
        )
        inv_q = jax.lax.psum(
            _owned_vec(rows_l, iinv_l, i_local), MODEL_AXIS
        )  # (B, 1) — the query rows' staged inverse norms
        if int8:
            q = qf.astype(jnp.int8)
            qscale = jax.lax.psum(
                _owned_vec(rows_l, iscale_l, i_local), MODEL_AXIS
            )
            qs = qscale * inv_q
            isc_l_ = iscale_l * iinv_l
        else:
            q = qf.astype(itf_l.dtype)
            qs = inv_q
            isc_l_ = iinv_l
        live_l = jnp.clip(n_items - idx * i_local, 0, i_local)
        excl_local = (
            (rows_l - idx * i_local)[:, None] if exclude_self else None
        )
        v, ix = _local_score_topk(
            q, itf_l, qs, isc_l_, mask_l, excl_local, live_l,
            k_l=k_l, mode=mode,
        )
        return _merge_topk(v, ix + idx * i_local, k)

    sh = P(MODEL_AXIS, None)
    col_sh = P(None, MODEL_AXIS)
    return _sharded_call(
        mesh, local,
        required=[(rows, P()), (itf, sh), (iinv, col_sh)],
        optional=[(iscale, col_sh), (mask_bits, col_sh)],
    )


@partial(
    jax.jit, static_argnames=("k", "n_items", "mesh", "mode")
)
def _sharded_similar_vecs(
    vecs: jax.Array,  # (B, K) f32 query vectors, replicated
    itf: jax.Array,  # (I_p, K) row-sharded
    iscale: Optional[jax.Array],
    iinv: jax.Array,
    mask_bits: Optional[jax.Array],
    *,
    k: int,
    n_items: int,
    mesh: jax.sharding.Mesh,
    mode: Optional[str] = None,
):
    """Cosine top-k against ARBITRARY query vectors (the
    similarproduct/itemsim basket query) from the sharded state. The
    query side quantizes in-jit for int8 slabs — replicated compute,
    so the answer is device-count invariant."""
    from predictionio_tpu.ops.recommend_pallas import quantize_rows_jnp

    n_shards = int(mesh.shape[MODEL_AXIS])
    i_local = itf.shape[0] // n_shards
    k_l = min(k, i_local)
    int8 = itf.dtype == jnp.int8

    def local(vecs_l, itf_l, iinv_l, iscale_l, mask_l):
        idx = jax.lax.axis_index(MODEL_AXIS)
        inv_q = 1.0 / (
            jnp.linalg.norm(vecs_l, axis=-1, keepdims=True) + 1e-9
        )
        if int8:
            q, qscale = quantize_rows_jnp(vecs_l)
            qs = qscale * inv_q
            isc_l_ = iscale_l * iinv_l
        else:
            q = vecs_l.astype(itf_l.dtype)
            qs = inv_q
            isc_l_ = iinv_l
        live_l = jnp.clip(n_items - idx * i_local, 0, i_local)
        v, ix = _local_score_topk(
            q, itf_l, qs, isc_l_, mask_l, None, live_l,
            k_l=k_l, mode=mode,
        )
        return _merge_topk(v, ix + idx * i_local, k)

    sh = P(MODEL_AXIS, None)
    col_sh = P(None, MODEL_AXIS)
    return _sharded_call(
        mesh, local,
        required=[(vecs, P()), (itf, sh), (iinv, col_sh)],
        optional=[(iscale, col_sh), (mask_bits, col_sh)],
    )


@partial(
    jax.jit,
    static_argnames=("implicit", "cg_iterations", "mesh", "scale_cols"),
)
def _sharded_fold_in(
    fixed: jax.Array,  # (N_p, K) row-sharded — the FIXED opposite side
    fixed_scale: Optional[jax.Array],  # dequant scales (int8 slabs)
    edge_idx: jax.Array,  # (R, E) int32 rows into `fixed` (replicated)
    edge_val: jax.Array,  # (R, E)
    edge_ok: jax.Array,  # (R, E) 1.0 real / 0.0 pad
    lam: jax.Array,  # () f32
    alpha: jax.Array,  # () f32
    *,
    implicit: bool,
    cg_iterations: int,
    mesh: jax.sharding.Mesh,
    scale_cols: bool = False,  # scale layout: (1, N_p) cols vs (N_p, 1)
):
    """Sharded single-side fold-in solve: identical operator assembly to
    models/als.py:_fold_in_jit, with the edge gather distributed — each
    shard contributes the terms of the fixed rows it owns and ONE psum
    assembles the (R, K, K) systems everywhere. Quantized slabs
    dequantize in registers at the gather (the solve itself is f32)."""
    n_shards = int(mesh.shape[MODEL_AXIS])
    n_local = fixed.shape[0] // n_shards
    k = fixed.shape[1]

    def local(fixed_l, fixed_scale_l, edge_idx, edge_val, edge_ok):
        idx = jax.lax.axis_index(MODEL_AXIS)
        loc = edge_idx - idx * n_local
        own = (
            ((loc >= 0) & (loc < n_local)).astype(jnp.float32) * edge_ok
        )
        safe = jnp.clip(loc, 0, n_local - 1)
        fl = fixed_l.astype(jnp.float32)
        if fixed_scale_l is not None:
            row_scale = (
                jnp.swapaxes(fixed_scale_l, 0, 1)
                if scale_cols else fixed_scale_l
            )  # (n_local, 1) either way
            fl = fl * row_scale
        y = fl[safe] * own[..., None]  # (R, E, K) — owner-masked
        eye = jnp.eye(k, dtype=jnp.float32)
        if implicit:
            conf = 1.0 + alpha * jnp.abs(edge_val)
            pref = (edge_val > 0).astype(jnp.float32)
            w_b = conf * pref * own
            w_g = (conf - 1.0) * own
            gram = jax.lax.psum(f32_gram(fl), MODEL_AXIS)
            b = jax.lax.psum(
                jnp.einsum("re,rek->rk", w_b, y), MODEL_AXIS
            )
            a = (
                jax.lax.psum(
                    jnp.einsum("re,rek,rel->rkl", w_g, y, y), MODEL_AXIS
                )
                + gram[None, :, :]
                + lam * eye
            )
        else:
            b = jax.lax.psum(
                jnp.einsum("re,rek->rk", edge_val * own, y), MODEL_AXIS
            )
            deg = jnp.sum(edge_ok, axis=1)  # edge_ok is replicated
            reg = lam * jnp.maximum(deg, 1.0)
            a = (
                jax.lax.psum(
                    jnp.einsum("re,rek,rel->rkl", own, y, y), MODEL_AXIS
                )
                + reg[:, None, None] * eye
            )

        def matvec(v):
            return jnp.einsum("rkl,rl->rk", a, v)

        return batched_cg(matvec, b, jnp.zeros_like(b), cg_iterations)

    sh = P(MODEL_AXIS, None)
    if fixed_scale is not None:
        scale_spec = P(None, MODEL_AXIS) if scale_cols else sh
        return shard_map(
            local, mesh=mesh,
            in_specs=(sh, scale_spec, P(), P(), P()),
            out_specs=P(), check=False,
        )(fixed, fixed_scale, edge_idx, edge_val, edge_ok)
    return shard_map(
        lambda f, ei, ev, eo: local(f, None, ei, ev, eo),
        mesh=mesh,
        in_specs=(sh, P(), P(), P()),
        out_specs=P(), check=False,
    )(fixed, edge_idx, edge_val, edge_ok)


def _make_scatter_rows(donate: bool):
    def scatter(table, rows, values, *, mesh):
        out = table.at[rows].set(values.astype(table.dtype))
        return jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P(MODEL_AXIS, None))
        )

    return (
        jax.jit(scatter, static_argnames=("mesh",), donate_argnums=(0,))
        if donate
        else jax.jit(scatter, static_argnames=("mesh",))
    )


def _make_scatter_cols(donate: bool):
    def scatter(vec, cols, values, *, mesh):
        out = vec.at[0, cols].set(values.astype(vec.dtype))
        return jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P(None, MODEL_AXIS))
        )

    return (
        jax.jit(scatter, static_argnames=("mesh",), donate_argnums=(0,))
        if donate
        else jax.jit(scatter, static_argnames=("mesh",))
    )


#: COW row update: preserves sharding AND in-flight readers — a reader
#: that captured the old table reference keeps a live buffer (the
#: zero-drop fallback when the donated path cannot drain readers)
_scatter_rows = _make_scatter_rows(donate=False)
#: donated row update (ISSUE 14, direction-1 item (c)): aliases the
#: resident slab into the row write — ONLY safe once `_publish` has
#: drained every in-flight reader lease; the publish then costs the
#: dirty rows, not a slab copy
_scatter_rows_donated = _make_scatter_rows(donate=True)
_scatter_cols = _make_scatter_cols(donate=False)
_scatter_cols_donated = _make_scatter_cols(donate=True)


# serving executables opt into memory analysis like the dense serving
# kernels: the per-signature AOT compile lands in warmup, and the
# temp/output bytes feed the tenant cache's transient accounting.
# dtype_of: the resident item slab's dtype IS the MXU dtype (ISSUE 14)
def _fleet_dtype_of(ix: int):
    def pick(args, kwargs):
        dt = str(getattr(args[ix], "dtype", ""))
        return "int8" if dt == "int8" else (
            "bf16" if dt == "bfloat16" else "f32"
        )

    return pick


_scatter_rows = _devprof.instrument("fleet.scatter_rows", _scatter_rows)
_scatter_rows_donated = _devprof.instrument(
    "fleet.scatter_rows_donated", _scatter_rows_donated
)
_scatter_cols = _devprof.instrument("fleet.scatter_cols", _scatter_cols)
_scatter_cols_donated = _devprof.instrument(
    "fleet.scatter_cols_donated", _scatter_cols_donated
)
_sharded_recommend = _devprof.instrument(
    "fleet.recommend_sharded", _sharded_recommend, memory=True,
    dtype_of=_fleet_dtype_of(2),
)
_sharded_similar = _devprof.instrument(
    "fleet.similar_sharded", _sharded_similar, memory=True,
    dtype_of=_fleet_dtype_of(1),
)
_sharded_similar_vecs = _devprof.instrument(
    "fleet.similar_vecs_sharded", _sharded_similar_vecs, memory=True,
    dtype_of=_fleet_dtype_of(1),
)
# no dtype_of on fold_in: its slab may STORE int8/bf16 but the solve
# dequantizes at the gather and runs entirely in f32 — declaring the
# storage dtype would roofline f32 FLOPs against the int8 peak (dtype
# is compute, never inferred from storage; the PR-11 discipline)
_sharded_fold_in = _devprof.instrument(
    "fleet.fold_in_sharded", _sharded_fold_in, memory=True,
)


#: XLA's CPU collectives run every per-device program on one shared
#: inter-op pool and rendezvous ALL participants before any may finish.
#: Two multi-device executables in flight at once can split the pool's
#: threads across their rendezvous sets on small hosts and starve both
#: forever (observed: concurrent recommend() readers under the 8-way
#: virtual test mesh on 1-2 vCPUs wedge in AllReduce with every thread
#: asleep). Collective dispatch on the cpu platform therefore
#: serializes through one process-wide lock — held only around the
#: launch+block, never while waiting on reader leases, so it is always
#: the innermost lock. Real accelerator streams don't share a host
#: thread pool and skip the lock entirely.
_CPU_COLLECTIVE_LOCK = threading.Lock()


def _collective_guard(mesh):
    devs = mesh.devices
    if devs.size > 1 and devs.flat[0].platform == "cpu":
        return _CPU_COLLECTIVE_LOCK
    return contextlib.nullcontext()


# ---------------------------------------------------------------------------
# the runtime
# ---------------------------------------------------------------------------


class _ShardState(NamedTuple):
    """ONE immutable snapshot of the resident sharded arrays. Readers
    take the whole tuple in one atomic attribute read inside their
    lease and publishes swap it in one assignment — a quantized (int8)
    publish can therefore never be observed with new rows but old
    scales/inverse norms (the torn-pair hazard the per-attribute
    layout had on the COW fallback path)."""

    uf: jax.Array  # (U_p, K) f32 | bf16 | int8, row-sharded
    itf: jax.Array  # (I_p, K), row-sharded
    uscale: Optional[jax.Array]  # (U_p, 1) f32 (int8 only)
    iscale: Optional[jax.Array]  # (1, I_p) f32 (int8 only)
    iinv: jax.Array  # (1, I_p) f32 inverse norms


class ShardedRuntime:
    """Row-sharded, device-resident ALS factor state + the sharded
    serving verbs. Swapped atomically like any other runtime: the query
    server's runtime-swap lock and the tenant model cache treat it as
    opaque model state (tenancy/cache.py's device-bytes walk counts
    only the per-device addressable shard)."""

    SERVE_DTYPES = ("f32", "bf16", "int8")

    def __init__(
        self,
        user_factors: np.ndarray,  # (U, K) f32
        item_factors: np.ndarray,  # (I, K) f32
        user_vocab: Optional[Any] = None,
        item_vocab: Optional[Any] = None,
        params: Optional[Any] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        device_budget_bytes: Optional[float] = None,
        serve_mode: str = "auto",
        serve_dtype: str = "f32",
    ):
        from predictionio_tpu.ops import recommend_pallas as _rp

        if mesh is None:
            mesh = serving_mesh()
        if MODEL_AXIS not in mesh.shape or len(mesh.shape) != 1:
            raise ValueError(
                "ShardedRuntime needs a 1-D serving mesh "
                f"(parallel.mesh.serving_mesh); got axes {dict(mesh.shape)}"
            )
        if serve_dtype not in self.SERVE_DTYPES:
            raise ValueError(
                f"serve_dtype must be one of {self.SERVE_DTYPES}, got "
                f"{serve_dtype!r}"
            )
        self.mesh = mesh
        self.n_shards = int(mesh.shape[MODEL_AXIS])
        # fused local score+select (ISSUE 11/14): the sharded twin of
        # the one-pass kernel — resolved once here so every serving
        # call traces against a fixed mode
        self.serve_mode = _rp.resolve_mode(serve_mode)
        self.serve_dtype = serve_dtype
        uf = np.asarray(user_factors, np.float32)
        itf = np.asarray(item_factors, np.float32)
        # item rows pad so every shard's slab is tile-divisible for the
        # fused kernel (ITEM_PAD per shard) — or, on the XLA path, at
        # least 32-divisible so the packed-mask words column-shard
        # cleanly (pad rows are zero and die under the per-shard live
        # count — the usual inertness discipline)
        quantum = self.n_shards * (
            _rp.ITEM_PAD if self.serve_mode is not None else 32
        )
        i_p = -(-max(itf.shape[0], 1) // quantum) * quantum
        if i_p != itf.shape[0]:
            itf = np.concatenate([
                itf,
                np.zeros((i_p - itf.shape[0], itf.shape[1]), itf.dtype),
            ])
        self.n_users, self.rank = uf.shape
        self.n_items = int(np.asarray(item_factors).shape[0])
        if device_budget_bytes is not None:
            per_shard = self._staged_bytes_estimate(uf, itf) / self.n_shards
            if per_shard > device_budget_bytes:
                raise OversizedModelError(
                    f"factor state needs {per_shard / 1e9:.2f} GB per "
                    f"shard over {self.n_shards} shard(s) but the "
                    f"per-device budget is "
                    f"{device_budget_bytes / 1e9:.2f} GB"
                )
        self.user_vocab = user_vocab
        self.item_vocab = item_vocab
        self.params = params
        self._lock = threading.Lock()
        # reader-lease state for the donated publish (ISSUE 14): verbs
        # hold a lease while their arrays are in flight; update_*_rows
        # briefly gates new leases, drains the in-flight ones, and
        # donates — or falls back to COW if the drain times out
        self._readers = 0  # guarded-by: _reader_cv
        self._writer_waiting = False  # guarded-by: _reader_cv
        self._poisoned = False  # set by a failed DONATED publish
        self._reader_cv = threading.Condition()
        # ONE staging each: the sharded arrays stay HBM-resident across
        # queries, folds, and swaps (CreateServer-style resident state);
        # they live in ONE immutable _ShardState tuple that readers
        # snapshot atomically and publishes swap atomically
        uscale = iscale = None
        if serve_dtype == "int8":
            uq, us = _rp.quantize_rows_np(uf)
            iq, isc = _rp.quantize_rows_np(itf)
            uf_dev = shard_rows(mesh, uq)
            itf_dev = shard_rows(mesh, iq)
            uscale = shard_rows(mesh, us[:, None])
            iscale = self._put_cols(np.ascontiguousarray(isc[None, :]))
        else:
            uf_dev = shard_rows(mesh, uf)
            itf_dev = shard_rows(mesh, itf)
            if serve_dtype == "bf16":
                uf_dev = uf_dev.astype(jnp.bfloat16)
                itf_dev = itf_dev.astype(jnp.bfloat16)
        # inverse norms (from the f32 rows) serve the cosine verbs off
        # the same slab; i_p is col-shardable by construction
        self._state = _ShardState(
            uf=uf_dev, itf=itf_dev, uscale=uscale, iscale=iscale,
            iinv=self._put_cols(_rp.inv_norms_np(itf, i_p)),
        )

    def _put_cols(self, arr: np.ndarray):
        return jax.device_put(
            arr, NamedSharding(self.mesh, P(None, MODEL_AXIS))
        )

    def _staged_bytes_estimate(self, uf: np.ndarray, itf: np.ndarray) -> int:
        """LOGICAL staged bytes for the budget gate: dtype cells plus
        scale/inverse-norm vectors, excluding the tile-pad quantum —
        pad waste is bounded by shards × ITEM_PAD rows (noise at the
        catalog scales the budget gate exists for) and must not refuse
        a tiny catalog that plainly fits."""
        u_p = pad_rows_to_shards(self.n_users, self.n_shards)
        i_p = pad_rows_to_shards(self.n_items, self.n_shards)
        cell = {"f32": 4, "bf16": 2, "int8": 1}[self.serve_dtype]
        total = (u_p + i_p) * self.rank * cell + i_p * 4  # + inv norms
        if self.serve_dtype == "int8":
            total += (u_p + i_p) * 4  # scale vectors
        return total

    @classmethod
    def from_factors(
        cls,
        factors: Any,  # models.als.ALSFactors
        mesh: Optional[jax.sharding.Mesh] = None,
        device_budget_bytes: Optional[float] = None,
        serve_dtype: str = "f32",
        serve_mode: str = "auto",
    ) -> "ShardedRuntime":
        return cls(
            factors.user_factors,
            factors.item_factors,
            user_vocab=factors.user_vocab,
            item_vocab=factors.item_vocab,
            params=factors.params,
            mesh=mesh,
            device_budget_bytes=device_budget_bytes,
            serve_dtype=serve_dtype,
            serve_mode=serve_mode,
        )

    # -- reader leases -----------------------------------------------------
    @contextlib.contextmanager
    def _lease(self):
        """Read lease around a serving dispatch, yielding ONE atomic
        snapshot of the resident state (value/scale/norm arrays can
        never tear). The donated publish drains leases before aliasing
        the resident slabs. Writer priority: new leases wait out a
        pending donate (one scatter dispatch — microseconds) so the
        drain always terminates."""
        with self._reader_cv:
            while self._writer_waiting:
                self._reader_cv.wait(timeout=0.1)
            if self._poisoned:
                raise RuntimeError(
                    "sharded runtime poisoned by a failed donated "
                    "publish — restage (ShardedRuntime.from_factors)"
                )
            self._readers += 1
            st = self._state
        try:
            yield st
        finally:
            with self._reader_cv:
                self._readers -= 1
                self._reader_cv.notify_all()

    # -- serving -----------------------------------------------------------
    def recommend(
        self,
        user_indices: np.ndarray,
        k: int,
        exclude_mask: Optional[np.ndarray] = None,  # (B, n_items) bool
        exclude_rows: Optional[np.ndarray] = None,  # (B, E) int, -1 pad
    ) -> tuple[np.ndarray, np.ndarray]:
        """Global top-k items per user from the sharded state; same
        contract as models.als.recommend (scores, item_indices).
        `exclude_rows` (the small-blacklist row-list form) scatters
        into packed words host-side — the sharded tier always ships
        bit-packed exclusion (1/32 the f32-equivalent bytes)."""
        k = min(int(k), self.n_items)
        rows = jnp.asarray(np.asarray(user_indices, np.int32))
        if exclude_rows is not None and exclude_mask is None:
            bits = self._pack_rows(exclude_rows)
        else:
            bits = self._pack_mask(exclude_mask)
        with self._lease() as st, _collective_guard(self.mesh):
            vals, idx = jax.block_until_ready(_sharded_recommend(
                rows, st.uf, st.itf, st.uscale, st.iscale, bits,
                k=k, n_items=self.n_items, mesh=self.mesh,
                mode=self.serve_mode,
            ))
        return np.asarray(vals), np.asarray(idx)

    def _pack_rows(self, exclude_rows) -> Optional[jax.Array]:
        """Exclusion ROW LISTS (the small-blacklist form) scatter their
        ids straight into packed words — never a dense (B, n_items)
        intermediate, which at the catalog scales this tier exists for
        would dwarf the blacklist itself."""
        ex = np.asarray(exclude_rows, np.int64)
        i_p = int(self._state.itf.shape[0])
        words = np.zeros((ex.shape[0], i_p // 32), np.uint32)
        b_idx, e_idx = np.nonzero((ex >= 0) & (ex < self.n_items))
        if len(b_idx):
            ids = ex[b_idx, e_idx]
            np.bitwise_or.at(
                words, (b_idx, ids >> 5),
                np.uint32(1) << (ids & 31).astype(np.uint32),
            )
        return self._put_cols(words.view(np.int32))

    def _pack_mask(self, exclude_mask) -> Optional[jax.Array]:
        """Bool exclusion mask → bit-packed words at the sharded item
        width, column-sharded over the mesh — 1/32 the f32-equivalent
        mask bytes on the wire and in HBM (ISSUE 14)."""
        if exclude_mask is None:
            return None
        from predictionio_tpu.ops.recommend_pallas import pack_mask_np

        i_p = int(self._state.itf.shape[0])
        return self._put_cols(
            pack_mask_np(np.asarray(exclude_mask, bool), i_p)
        )

    def similar_vectors(
        self,
        vectors: np.ndarray,  # (B, K) query vectors (e.g. basket means)
        k: int,
        exclude_mask: Optional[np.ndarray] = None,  # (B, n_items) bool
    ) -> tuple[np.ndarray, np.ndarray]:
        """Cosine top-k against arbitrary query vectors — the
        similarproduct/itemsim basket query served from the sharded
        state (ISSUE 11 satellite)."""
        k = min(int(k), self.n_items)
        vecs = jnp.asarray(np.asarray(vectors, np.float32))
        bits = self._pack_mask(exclude_mask)
        with self._lease() as st, _collective_guard(self.mesh):
            vals, idx = jax.block_until_ready(_sharded_similar_vecs(
                vecs, st.itf, st.iscale, st.iinv, bits,
                k=k, n_items=self.n_items, mesh=self.mesh,
                mode=self.serve_mode,
            ))
        return np.asarray(vals), np.asarray(idx)

    def similar_items(
        self,
        item_indices: np.ndarray,
        k: int,
        exclude_self: bool = True,
    ) -> tuple[np.ndarray, np.ndarray]:
        k = min(int(k), self.n_items)
        rows = jnp.asarray(np.asarray(item_indices, np.int32))
        with self._lease() as st, _collective_guard(self.mesh):
            vals, idx = jax.block_until_ready(_sharded_similar(
                rows, st.itf, st.iscale, st.iinv, None,
                k=k, n_items=self.n_items, mesh=self.mesh,
                exclude_self=exclude_self, mode=self.serve_mode,
            ))
        return np.asarray(vals), np.asarray(idx)

    def fold_in_rows(
        self,
        edges: Sequence[Sequence[tuple[int, float]]],
        params: Any,  # models.als.ALSParams
        side: str = "user",
    ) -> np.ndarray:
        """Sharded single-side fold-in (the online consumer's solve):
        per dirty row, solve its system against the FIXED opposite
        sharded matrix; returns the (R, K) solved factors. Bucketing
        mirrors models.als.fold_in_rows so streaming ticks reuse a
        handful of compiled programs."""
        from predictionio_tpu.models.als import _fold_edge_bucket
        from predictionio_tpu.utils.bucket import batch_bucket

        if not edges:
            return np.zeros((0, self.rank), np.float32)
        r_real = len(edges)
        r_pad = batch_bucket(r_real)
        e_pad = _fold_edge_bucket(max(len(e) for e in edges))
        idx = np.zeros((r_pad, e_pad), np.int32)
        val = np.zeros((r_pad, e_pad), np.float32)
        ok = np.zeros((r_pad, e_pad), np.float32)
        for r, row in enumerate(edges):
            for e, (j, v) in enumerate(row):
                idx[r, e] = j
                val[r, e] = v
                ok[r, e] = 1.0
        with self._lease() as st:
            if side == "user":
                fixed, scale, scale_cols = st.itf, st.iscale, True
            else:
                fixed, scale, scale_cols = st.uf, st.uscale, False
            with _collective_guard(self.mesh):
                solved = jax.block_until_ready(_sharded_fold_in(
                    fixed, scale,
                    jnp.asarray(idx), jnp.asarray(val), jnp.asarray(ok),
                    jnp.float32(params.lambda_), jnp.float32(params.alpha),
                    implicit=params.implicit_prefs,
                    cg_iterations=params.cg_iterations,
                    mesh=self.mesh,
                    scale_cols=scale_cols,
                ))
        return np.asarray(solved)[:r_real]

    # -- state updates -----------------------------------------------------
    def update_user_rows(
        self, rows: np.ndarray, values: np.ndarray,
        n_users: Optional[int] = None,
    ) -> None:
        """Publish dirty user rows (f32 values) into the resident
        sharded slab: re-quantizes ONLY these rows for int8 slabs and
        donates the slab into the row write once in-flight readers
        drain — no full restage, no host round-trip (ISSUE 14,
        direction-1 item (c)). `n_users`/`n_items` carry the fold's
        new LIVE vocab extent: within-pad growth must raise the live
        count, or the grown rows stay masked dead under every verb's
        live-count gate (the count is a static jit arg on this tier,
        so a growth tick retraces — amortized like the pad itself)."""
        self._publish("user", rows, values, new_count=n_users)

    def update_item_rows(
        self, rows: np.ndarray, values: np.ndarray,
        n_items: Optional[int] = None,
    ) -> None:
        self._publish("item", rows, values, new_count=n_items)

    def rows_within_extent(self, side: str, rows) -> bool:
        """True when a dirty-row publish for `side` fits the padded
        shard extent — the pre-check a fold-in carry runs on BOTH
        sides BEFORE mutating either, so a grown side can never leave
        the live runtime half-updated (ALSModel.adopt_sharded)."""
        rows = np.asarray(rows, np.int64)
        st = self._state
        table = st.uf if side == "user" else st.itf
        return not rows.size or int(rows.max()) < int(table.shape[0])

    def _publish(self, side: str, rows, values, new_count=None) -> None:
        from predictionio_tpu.ops import recommend_pallas as _rp

        rows = np.asarray(rows, np.int32)
        values = np.asarray(values, np.float32)
        if not self.rows_within_extent(side, rows):
            raise ValueError(
                "row update beyond the padded shard extent — vocab "
                "growth needs a rebuild (amortized like the online "
                "fold-in's factor growth), not an in-place set"
            )
        if not rows.size:
            return
        # host prep: quantize/norm ONLY the dirty rows
        if self.serve_dtype == "int8":
            q, s = _rp.quantize_rows_np(values)
            vals_dev = jnp.asarray(q)
            scale_dev = jnp.asarray(s)
        else:
            vals_dev = jnp.asarray(values)
            scale_dev = None
        inv_dev = (
            jnp.asarray(_rp.inv_norms_np(values)[0])
            if side == "item" else None
        )
        rows_dev = jnp.asarray(rows)
        with self._lock:  # one publisher at a time
            st = self._state
            donate = self._drain_readers()
            try:
                srows = (
                    _scatter_rows_donated if donate else _scatter_rows
                )
                scols = (
                    _scatter_cols_donated if donate else _scatter_cols
                )
                # the guard also covers the COW fallback: its scatters
                # run WHILE readers keep serving, and an unserialized
                # overlap of two cpu collectives is exactly the pool-
                # starvation wedge the lock exists for. block before
                # releasing so no scatter is still in flight when the
                # next reader launches.
                with _collective_guard(self.mesh):
                    if side == "user":
                        uf = srows(
                            st.uf, rows_dev, vals_dev, mesh=self.mesh
                        )
                        uscale = st.uscale
                        if scale_dev is not None:
                            uscale = srows(
                                st.uscale, rows_dev, scale_dev[:, None],
                                mesh=self.mesh,
                            )
                        new = st._replace(uf=uf, uscale=uscale)
                    else:
                        itf = srows(
                            st.itf, rows_dev, vals_dev, mesh=self.mesh
                        )
                        iscale = st.iscale
                        if scale_dev is not None:
                            iscale = scols(
                                st.iscale, rows_dev, scale_dev,
                                mesh=self.mesh,
                            )
                        iinv = scols(
                            st.iinv, rows_dev, inv_dev, mesh=self.mesh
                        )
                        new = st._replace(
                            itf=itf, iscale=iscale, iinv=iinv
                        )
                    new = jax.block_until_ready(new)
                # ONE atomic swap: readers see either the old or the
                # new state tuple, never a torn value/scale pair (the
                # COW fallback admits readers during these scatters)
                self._state = new
                if new_count is not None:
                    # within-pad vocab growth: raise the LIVE extent or
                    # the grown rows stay dead under the verbs' live-
                    # count gates (a growth tick retraces the static-
                    # count jits — amortized like the pad headroom)
                    if side == "user":
                        self.n_users = max(self.n_users, int(new_count))
                    else:
                        self.n_items = max(self.n_items, int(new_count))
            except BaseException:
                if donate:
                    # the donated scatters may have consumed buffers the
                    # un-swapped state still references — every further
                    # dispatch against them would crash with an opaque
                    # XLA error. Poison the runtime so leases fail FAST
                    # and callers restage (adopt_sharded drops the
                    # carry; the predecessor is mid-replacement anyway).
                    self._poisoned = True
                    log.exception(
                        "donated sharded publish failed mid-write — "
                        "runtime poisoned; callers must restage"
                    )
                raise
            finally:
                if donate:
                    with self._reader_cv:
                        self._writer_waiting = False
                        self._reader_cv.notify_all()

    def _drain_readers(self) -> bool:
        """Gate new leases and wait for in-flight ones; True = drained
        (donation safe), False = timed out (caller must COW). Always
        leaves `_writer_waiting` True on success — the caller clears it
        after the donated writes land."""
        import time as _time

        deadline = _time.monotonic() + _DONATE_DRAIN_S
        with self._reader_cv:
            self._writer_waiting = True
            while self._readers > 0:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    self._writer_waiting = False
                    self._reader_cv.notify_all()
                    log.warning(
                        "sharded publish: readers did not drain in "
                        "%.1fs — falling back to copy-on-write",
                        _DONATE_DRAIN_S,
                    )
                    return False
                self._reader_cv.wait(timeout=remaining)
            return True

    # -- accounting --------------------------------------------------------
    def device_bytes(self) -> dict[str, float]:
        st = self._state
        total = float(st.uf.nbytes + st.itf.nbytes + st.iinv.nbytes)
        if st.uscale is not None:
            total += float(st.uscale.nbytes + st.iscale.nbytes)
        return {
            "total": total,
            "per_shard": total / self.n_shards,
            "shards": float(self.n_shards),
        }

    def info(self) -> dict[str, Any]:
        b = self.device_bytes()
        return {
            "shards": self.n_shards,
            "devices": [
                str(d) for d in self.mesh.devices.reshape(-1)
            ],
            "n_users": self.n_users,
            "n_items": self.n_items,
            "rank": self.rank,
            "serve_dtype": self.serve_dtype,
            "serve_mode": self.serve_mode or "xla",
            "resident_bytes_total": b["total"],
            "resident_bytes_per_shard": b["per_shard"],
        }

    # the tenant cache's device-bytes walk finds the state tuple via
    # __dict__: jax arrays report addressable-shard bytes there, so a
    # cached sharded runtime is charged one SHARD, not the catalog
    @property
    def models(self):  # EngineRuntime-walk compatibility
        st = self._state
        return (st.uf, st.itf)
