"""predictionio_tpu — a TPU-native machine learning server framework.

A from-scratch re-design of the capabilities of PredictionIO 0.9.7-aml
(reference: Scala/Spark/MLlib) for TPU hardware: JAX/XLA/pjit for compute,
columnar host data plane, and a storage-mediated multi-process topology
(event server / training workflow / deploy server / evaluation).

Layer map (mirrors reference SURVEY.md §1, re-architected):
  L0/L1  predictionio_tpu.data          event model + storage backends
  L2     predictionio_tpu.data.api      event ingestion HTTP server
  L3     predictionio_tpu.controller    DASE user-facing SDK
  L4     predictionio_tpu.core          typeless runtime base
  L5     predictionio_tpu.workflow      train / eval / deploy drivers
  L6     predictionio_tpu.tools         CLI + ops
  L7     predictionio_tpu.e2           reusable algorithm/eval library
         predictionio_tpu.models       TPU model kernels (ALS, NB, LR, CCO…)
         predictionio_tpu.ops          low-level XLA/Pallas ops
         predictionio_tpu.parallel     mesh/sharding utilities
"""

__version__ = "0.1.0"
