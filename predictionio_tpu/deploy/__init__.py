"""Model lifecycle subsystem (ISSUE 5): version registry, background
training scheduler, and canary rollout with automatic rollback.

The reference PredictionIO ties a deployment to a single EngineInstance
blob — `pio train` blocks a console and `pio deploy` loads whatever is
newest. This package is the piece between training and serving:

- registry.py  — versioned, immutable model records layered on the
  existing storage backends, with lineage queries and retention GC
- scheduler.py — persistent job queue + supervised subprocess worker
  (heartbeats, per-job logs, timeout, retry-with-backoff, periodic
  retrain); jobs survive restarts by re-reading the queue from storage
- worker.py    — the train-job subprocess entry point
- rollout.py   — canary traffic splitting + verdict loop that promotes
  or rolls back a candidate model on measured serve metrics

Import discipline: like obs/ and resilience/, nothing here may import
jax at module import time — the scheduler and control-plane endpoints
run inside data-plane server processes.
"""

from predictionio_tpu.deploy.registry import (
    LIFECYCLE_APP_ID,
    ModelRegistry,
    ModelVersion,
    VERSION_STATUSES,
)
from predictionio_tpu.deploy.rollout import (
    RolloutConfig,
    RolloutController,
    VariantWindow,
    verdict,
)
from predictionio_tpu.deploy.scheduler import (
    JobQueue,
    SchedulerConfig,
    TrainJob,
    TrainScheduler,
)

__all__ = [
    "LIFECYCLE_APP_ID",
    "JobQueue",
    "ModelRegistry",
    "ModelVersion",
    "RolloutConfig",
    "RolloutController",
    "SchedulerConfig",
    "TrainJob",
    "TrainScheduler",
    "VERSION_STATUSES",
    "VariantWindow",
    "verdict",
]
