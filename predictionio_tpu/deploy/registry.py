"""Model version registry: versioned, immutable model records layered on
the existing storage backends (ISSUE 5 tentpole part 1).

No backend grows a new DAO: a record is the fold of ``$set`` events in a
reserved event-store namespace (`LIFECYCLE_APP_ID`), so every backend
that can store events — memory, sqlite, parquetfs, remote, sharded —
already persists the registry, and the event WAL / breaker / retry
machinery from PR 4 protects registry writes for free. Status changes
append a new ``$set``; the full event stream of a record is its audit
trail, and a record fold never mutates an existing event (immutability).

Records carry: id, parent engine instance, params hash, train metrics,
devprof snapshot, status (``trained|canary|live|rolled_back|archived``),
and the previous-live lineage pointer. Retention GC keeps live/canary
records unconditionally and the newest N others per engine variant.
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import itertools
import json
import logging
import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from predictionio_tpu.data.event import SET_EVENT, Event
from predictionio_tpu.data.storage.base import EngineInstance, EventQuery
from predictionio_tpu.data.storage.registry import Storage

log = logging.getLogger(__name__)

# Reserved event-store namespace for lifecycle records. Positive and far
# above any auto-assigned app id (sqlite table names cannot carry a
# minus sign, and verify_all_data_objects probes/wipes app 0).
LIFECYCLE_APP_ID = 2_000_000_000

VERSION_ENTITY = "pio_model_version"

# rollout-state records (rollout.py owns the logic; the name lives here
# so registry-side compaction can reach it without an import cycle)
ROLLOUT_ENTITY = "pio_rollout"

VERSION_STATUSES = ("trained", "canary", "live", "rolled_back", "archived")

# process-monotonic tie-breaker: two record updates can land in the same
# event_time microsecond; the fold orders by (event_time, seq)
_seq = itertools.count()
_seq_lock = threading.Lock()


def _next_seq() -> int:
    with _seq_lock:
        return next(_seq)


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


class LifecycleRecordStore:
    """Shared record layer: entity → last-write-wins field fold over the
    reserved namespace. ModelRegistry and JobQueue both build on it."""

    def __init__(self, storage: Storage):
        self.storage = storage
        self._initialized = False

    def _events(self):
        store = self.storage.get_events()
        if not self._initialized:
            store.init_app(LIFECYCLE_APP_ID)
            self._initialized = True
        return store

    def append(self, entity_type: str, entity_id: str, props: dict) -> str:
        """Append one field-update record (``$set`` event); returns the
        event id so high-frequency writers (scheduler heartbeats) can
        compact their previous update away."""
        return self._events().insert(
            Event(
                event=SET_EVENT,
                entity_type=entity_type,
                entity_id=entity_id,
                properties=dict(props, _seq=_next_seq()),
            ),
            LIFECYCLE_APP_ID,
        )

    def discard(self, event_id: str) -> None:
        """Best-effort delete of one earlier update event (compaction);
        a failure just leaves an extra event in the fold."""
        try:
            self._events().delete(event_id, LIFECYCLE_APP_ID)
        except Exception:
            log.debug("record compaction delete failed", exc_info=True)

    def fold(self, entity_type: str, entity_id: Optional[str] = None) -> dict:
        """entity_id → merged field dict (newest write per field wins)."""
        evs = list(self._events().find(EventQuery(
            app_id=LIFECYCLE_APP_ID,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=[SET_EVENT],
        )))
        evs.sort(key=lambda e: (
            e.event_time, e.properties.get_or_else("_seq", 0)
        ))
        out: dict[str, dict] = {}
        for e in evs:
            d = out.setdefault(e.entity_id, {})
            d.update(e.properties.to_dict())
        for d in out.values():
            d.pop("_seq", None)
        return out

    def events(self, entity_type: str, entity_id: str) -> list:
        """One record's raw update events in fold order — the CAS-claim
        bid resolution read (deploy/scheduler.py): a claim's winner is
        the FIRST bid in this total order, which every reader computes
        identically once the bids are visible, unlike the LWW fold where
        the LAST write wins. The (event_time, _seq, event_id) key makes
        the order total even across processes whose clocks collide at
        microsecond granularity."""
        evs = list(self._events().find(EventQuery(
            app_id=LIFECYCLE_APP_ID,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=[SET_EVENT],
        )))
        evs.sort(key=lambda e: (
            e.event_time, e.properties.get_or_else("_seq", 0),
            e.event_id or "",
        ))
        return evs

    def compact(
        self, entity_type: str, entity_id: str, min_events: int = 2,
        min_age_s: float = 60.0,
    ) -> int:
        """Fold one record's update events into a single snapshot event
        (fold → snapshot), deleting the older ones. Every reader of the
        record layer re-folds history — `/models`, the queue poll, the
        mux's tenant refresh — so long-lived records must stay O(1)
        events, not O(updates). Returns how many events were removed.

        Crash-safe ordering: the snapshot (which carries every folded
        field, so it wins last-write-wins on all of them) is appended
        BEFORE the old events are deleted — a crash in between leaves
        redundant events whose fold is unchanged.

        Concurrent-writer guard: only QUIESCENT records compact — a
        record updated within `min_age_s` is skipped, because a write
        landing between this fold read and the snapshot append would be
        outranked by the snapshot and silently reverted (e.g. a job's
        `completed` flip racing the scheduler's retention sweep would
        resurrect it as `running`). Active records are exactly the ones
        still being written; the sweep gets them on a later pass."""
        store = self._events()
        evs = list(store.find(EventQuery(
            app_id=LIFECYCLE_APP_ID,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=[SET_EVENT],
        )))
        if len(evs) < max(2, min_events):
            return 0
        if min_age_s > 0:
            newest = max(e.event_time for e in evs)
            age = (_utcnow() - newest).total_seconds()
            if age < min_age_s:
                return 0
        evs.sort(key=lambda e: (
            e.event_time, e.properties.get_or_else("_seq", 0)
        ))
        merged: dict[str, Any] = {}
        for e in evs:
            merged.update(e.properties.to_dict())
        merged.pop("_seq", None)
        self.append(entity_type, entity_id, merged)
        ids = [e.event_id for e in evs if e.event_id]
        if ids:
            store.delete_batch(ids, LIFECYCLE_APP_ID)
        return len(ids)

    def compact_all(
        self, entity_type: str, min_events: int = 8,
        min_age_s: float = 60.0,
    ) -> int:
        """Compact every QUIESCENT record of `entity_type` whose fold
        spans at least `min_events` events (see `compact` for the
        concurrent-writer guard). Returns total events removed."""
        counts: dict[str, int] = {}
        for e in self._events().find(EventQuery(
            app_id=LIFECYCLE_APP_ID,
            entity_type=entity_type,
            event_names=[SET_EVENT],
        )):
            counts[e.entity_id] = counts.get(e.entity_id, 0) + 1
        removed = 0
        for entity_id, n in counts.items():
            if n >= max(2, min_events):
                try:
                    removed += self.compact(
                        entity_type, entity_id, min_events=min_events,
                        min_age_s=min_age_s,
                    )
                except Exception:
                    log.exception(
                        "compaction of %s/%s failed (non-fatal)",
                        entity_type, entity_id,
                    )
        return removed

    def purge(self, entity_type: str, entity_id: str) -> int:
        """Delete every event of one record; returns how many existed."""
        store = self._events()
        ids = [
            e.event_id for e in store.find(EventQuery(
                app_id=LIFECYCLE_APP_ID,
                entity_type=entity_type,
                entity_id=entity_id,
            ))
            if e.event_id
        ]
        if not ids:
            return 0
        return store.delete_batch(ids, LIFECYCLE_APP_ID)


@dataclass
class ModelVersion:
    """One immutable trained-model record."""

    id: str
    engine_id: str
    engine_version: str
    engine_variant: str
    instance_id: str  # parent EngineInstance (and MODELDATA blob key)
    params_hash: str
    status: str = "trained"
    created_at: str = ""
    updated_at: str = ""
    parent_version: Optional[str] = None  # live version at registration
    train_metrics: dict[str, Any] = field(default_factory=dict)
    devprof: dict[str, Any] = field(default_factory=dict)
    reason: Optional[str] = None  # why rolled_back/archived
    # submitting train-job id (ISSUE 9 satellite): a RETRIED job finds
    # its already-registered version by this stamp and adopts it instead
    # of retraining (the scheduler's infra-retry after a crash between
    # register and the result receipt)
    job_id: Optional[str] = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "engine_id": self.engine_id,
            "engine_version": self.engine_version,
            "engine_variant": self.engine_variant,
            "instance_id": self.instance_id,
            "params_hash": self.params_hash,
            "status": self.status,
            "created_at": self.created_at,
            "updated_at": self.updated_at,
            "parent_version": self.parent_version,
            "train_metrics": self.train_metrics,
            "devprof": self.devprof,
            "reason": self.reason,
            "job_id": self.job_id,
        }

    @staticmethod
    def from_dict(d: dict) -> "ModelVersion":
        return ModelVersion(**{
            k: d.get(
                k,
                None if k in ("parent_version", "reason", "job_id") else "",
            )
            for k in (
                "id", "engine_id", "engine_version", "engine_variant",
                "instance_id", "params_hash", "status", "created_at",
                "updated_at", "parent_version", "reason", "job_id",
            )
        } | {
            "train_metrics": d.get("train_metrics") or {},
            "devprof": d.get("devprof") or {},
        })


def params_hash(instance: EngineInstance) -> str:
    """Stable hash of the full DASE parameterization — two versions with
    the same hash were trained with identical stage params."""
    payload = json.dumps(
        [
            instance.engine_factory,
            instance.data_source_params,
            instance.preparator_params,
            instance.algorithms_params,
            instance.serving_params,
        ],
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


class ModelRegistry:
    """CRUD + lineage + retention GC over ModelVersion records."""

    def __init__(self, storage: Storage):
        self.storage = storage
        self._store = LifecycleRecordStore(storage)

    # -- writes -----------------------------------------------------------
    def register(
        self,
        instance: EngineInstance,
        train_metrics: Optional[dict] = None,
        devprof: Optional[dict] = None,
        job_id: Optional[str] = None,
    ) -> ModelVersion:
        """Record a COMPLETED train run as a new ``trained`` version.
        Lineage: `parent_version` points at the variant's live version at
        registration time (None for the first)."""
        if instance.status != "COMPLETED":
            raise ValueError(
                f"only COMPLETED instances register; {instance.id} is "
                f"{instance.status}"
            )
        live = self.live_version(
            instance.engine_id, instance.engine_variant
        )
        now = _utcnow().isoformat()
        metrics = dict(train_metrics or {})
        if not metrics and instance.env.get("stage_timings"):
            try:
                metrics["stage_timings"] = json.loads(
                    instance.env["stage_timings"]
                )
            except (ValueError, TypeError):
                pass
        version = ModelVersion(
            id=f"mv-{uuid.uuid4().hex[:12]}",
            engine_id=instance.engine_id,
            engine_version=instance.engine_version,
            engine_variant=instance.engine_variant,
            instance_id=instance.id,
            params_hash=params_hash(instance),
            status="trained",
            created_at=now,
            updated_at=now,
            parent_version=live.id if live else None,
            train_metrics=metrics,
            devprof=dict(devprof or {}),
            job_id=job_id,
        )
        self._store.append(VERSION_ENTITY, version.id, version.to_dict())
        return version

    def find_by_job(self, job_id: str) -> Optional[ModelVersion]:
        """The version a train job already registered, if any — the
        retried-job adoption read (newest wins if a bug ever stamped
        two)."""
        if not job_id:
            return None
        hits = [v for v in self.list() if v.job_id == job_id]
        return hits[0] if hits else None

    def set_status(
        self, version_id: str, status: str, reason: Optional[str] = None
    ) -> ModelVersion:
        if status not in VERSION_STATUSES:
            raise ValueError(
                f"unknown version status {status!r} "
                f"(known: {', '.join(VERSION_STATUSES)})"
            )
        v = self.get(version_id)
        if v is None:
            raise KeyError(f"no model version {version_id}")
        self._store.append(VERSION_ENTITY, version_id, {
            "status": status,
            "updated_at": _utcnow().isoformat(),
            "reason": reason,
        })
        v.status, v.reason = status, reason
        return v

    def promote(self, version_id: str) -> ModelVersion:
        """Make `version_id` the variant's live version; the previous
        live one is archived (still servable, still in lineage)."""
        v = self.get(version_id)
        if v is None:
            raise KeyError(f"no model version {version_id}")
        prev = self.live_version(v.engine_id, v.engine_variant)
        if prev is not None and prev.id != v.id:
            self.set_status(prev.id, "archived", reason=f"superseded by {v.id}")
        return self.set_status(version_id, "live")

    def rollback(self, version_id: str, reason: str) -> ModelVersion:
        return self.set_status(version_id, "rolled_back", reason=reason)

    # -- reads ------------------------------------------------------------
    def get(self, version_id: str) -> Optional[ModelVersion]:
        folded = self._store.fold(VERSION_ENTITY, version_id)
        d = folded.get(version_id)
        return ModelVersion.from_dict(d) if d else None

    def list(
        self,
        engine_id: Optional[str] = None,
        engine_variant: Optional[str] = None,
        status: Optional[str] = None,
    ) -> list[ModelVersion]:
        """Newest-first version listing with optional filters."""
        out = [
            ModelVersion.from_dict(d)
            for d in self._store.fold(VERSION_ENTITY).values()
        ]
        if engine_id is not None:
            out = [v for v in out if v.engine_id == engine_id]
        if engine_variant is not None:
            out = [v for v in out if v.engine_variant == engine_variant]
        if status is not None:
            out = [v for v in out if v.status == status]
        out.sort(key=lambda v: v.created_at, reverse=True)
        return out

    def live_version(
        self, engine_id: str, engine_variant: str
    ) -> Optional[ModelVersion]:
        live = self.list(engine_id, engine_variant, status="live")
        return live[0] if live else None

    def lineage(self, version_id: str) -> list[ModelVersion]:
        """The ancestry chain, newest first: this version, then the live
        version it superseded, and so on (cycle-guarded)."""
        chain: list[ModelVersion] = []
        seen: set[str] = set()
        cur = self.get(version_id)
        while cur is not None and cur.id not in seen:
            chain.append(cur)
            seen.add(cur.id)
            cur = self.get(cur.parent_version) if cur.parent_version else None
        return chain

    def compact(
        self, min_events: int = 8, min_age_s: float = 60.0
    ) -> int:
        """Registry-fold compaction (fold → snapshot event): bound the
        event count behind `/models`, the mux's prefetch reads, and the
        rollout resume pre-checks as tenant count × version × rollout
        history grows. Returns events removed."""
        removed = self._store.compact_all(
            VERSION_ENTITY, min_events=min_events, min_age_s=min_age_s
        )
        # rollout-state records accumulate 2-3 events per canary per
        # scope forever — every QueryServer.start and mux sync re-folds
        # them, so they need the same retention discipline
        removed += self._store.compact_all(
            ROLLOUT_ENTITY, min_events=min_events, min_age_s=min_age_s
        )
        return removed

    # -- retention GC -----------------------------------------------------
    def gc(
        self, keep: int = 5, delete_blobs: bool = False
    ) -> list[ModelVersion]:
        """Drop all but the newest `keep` non-serving versions per
        (engine_id, engine_variant). ``live`` and ``canary`` versions are
        never collected. With `delete_blobs`, MODELDATA blobs whose
        instance is referenced by no surviving version are deleted too.
        Returns the collected versions."""
        if keep < 0:
            raise ValueError("keep must be >= 0")
        by_variant: dict[tuple[str, str], list[ModelVersion]] = {}
        for v in self.list():
            by_variant.setdefault(
                (v.engine_id, v.engine_variant), []
            ).append(v)
        collected: list[ModelVersion] = []
        survivors: list[ModelVersion] = []
        for versions in by_variant.values():
            disposable = [
                v for v in versions if v.status not in ("live", "canary")
            ]
            survivors.extend(
                v for v in versions if v.status in ("live", "canary")
            )
            survivors.extend(disposable[:keep])  # list() is newest-first
            collected.extend(disposable[keep:])
        kept_instances = {v.instance_id for v in survivors}
        models = self.storage.get_model_data_models()
        for v in collected:
            self._store.purge(VERSION_ENTITY, v.id)
            if delete_blobs and v.instance_id not in kept_instances:
                try:
                    models.delete(v.instance_id)
                except Exception:
                    log.exception(
                        "model blob delete failed for %s (non-fatal)",
                        v.instance_id,
                    )
        # retention + compaction together keep the fold bounded in both
        # dimensions: record COUNT (gc) and events PER record (snapshot)
        self.compact()
        return collected
