"""Train-job subprocess entry point (`python -m predictionio_tpu.deploy.worker`).

The scheduler writes a spec file (storage wiring + variant + result
path), spawns this module, and supervises from outside. In here the job
is plain: open the same stores, run the full `run_train` data path,
register the COMPLETED instance as a model version, write the result
receipt, exit 0.

Exit codes are the scheduler's retry contract:
- 0                  — trained + registered
- EXIT_TRAIN_FAILED  — the train itself raised / did not complete
                       (deterministic; the scheduler fails the job fast)
- anything else      — infra trouble (storage down, import error, OOM
                       kill); the scheduler re-queues with backoff
"""

from __future__ import annotations

import json
import logging
import sys
import traceback


def main(argv: list[str]) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    if len(argv) != 2:
        print("usage: python -m predictionio_tpu.deploy.worker <spec.json>",
              file=sys.stderr)
        return 2
    from predictionio_tpu.data.storage.base import StorageError
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.deploy.registry import ModelRegistry
    from predictionio_tpu.deploy.scheduler import (
        EXIT_INFRA_FAILED,
        EXIT_TRAIN_FAILED,
        storage_config_from_json,
    )
    from predictionio_tpu.workflow.core import run_train

    with open(argv[1]) as f:
        spec = json.load(f)
    storage = Storage(storage_config_from_json(spec["storage"]))

    # push telemetry (ISSUE 17): this process usually dies before any
    # scraper gets a chance to poll it, so its train spans / stage
    # metrics / devprof report ship OUT instead — spooled durably every
    # interval, flushed on exit (atexit covers clean exits AND the
    # uncaught-exception path; kill -9 leaves the spool for the
    # supervisor to ship). No-op unless PIO_PUSH_URL/PIO_PUSH_SPOOL set.
    shipper = None
    try:
        from predictionio_tpu.obs.monitor.push import TelemetryShipper

        shipper = TelemetryShipper.from_env(job_id=spec.get("job_id"))
        if shipper is not None:
            shipper.start()
            import atexit

            atexit.register(shipper.stop)
    except Exception:
        logging.getLogger(__name__).debug(
            "telemetry shipper unavailable", exc_info=True
        )

    # retried-job adoption (ISSUE 9 satellite): if a previous attempt of
    # THIS job already trained and registered a version — and only the
    # result receipt / bookkeeping was lost — adopt it instead of paying
    # a full duplicate train. The job id is stamped on every version
    # this worker registers (below), so the check is one registry fold.
    job_id = spec.get("job_id")
    if job_id:
        try:
            existing = ModelRegistry(storage).find_by_job(job_id)
        except Exception:
            existing = None  # storage hiccup: fall through to training
        if existing is not None and existing.status not in (
            "rolled_back", "archived"
        ):
            with open(spec["result_path"], "w") as f:
                json.dump({
                    "instance_id": existing.instance_id,
                    "model_version": existing.id,
                }, f)
            print(
                f"job {job_id}: adopting already-registered version "
                f"{existing.id} (instance {existing.instance_id}); "
                f"skipping retrain"
            )
            return 0
    try:
        instance = run_train(
            storage, spec["variant"], engine_id=spec.get("engine_id")
        )
    except StorageError:
        traceback.print_exc()
        return EXIT_INFRA_FAILED
    except Exception:
        traceback.print_exc()
        return EXIT_TRAIN_FAILED
    if instance.status != "COMPLETED":
        print(f"train ended {instance.status}, not COMPLETED",
              file=sys.stderr)
        return EXIT_TRAIN_FAILED

    devprof_snapshot: dict = {}
    try:
        from predictionio_tpu.obs import devprof as _devprof

        report = _devprof.report()
        if report.get("executables"):
            devprof_snapshot = report
    except Exception:
        pass  # profiling is best-effort; the version record stays valid

    version = ModelRegistry(storage).register(
        instance, devprof=devprof_snapshot, job_id=job_id,
    )
    with open(spec["result_path"], "w") as f:
        json.dump(
            {"instance_id": instance.id, "model_version": version.id}, f
        )
    print(f"trained instance {instance.id} → model version {version.id}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
