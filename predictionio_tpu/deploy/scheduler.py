"""Background training scheduler (ISSUE 5 tentpole part 2).

`pio train` blocked a console in the reference; here trains are jobs in
a persistent queue (same record layer as the model registry, so the
queue survives server restarts — a new worker re-reads it from storage)
executed by a supervising worker:

- each job runs ``run_train`` in a **subprocess** (worker.py) so an
  OOM/segfault in engine code cannot take the scheduler down,
- the parent heartbeats the job record while the child lives; a worker
  crash leaves a ``running`` job with a stale heartbeat, and the next
  scheduler start re-queues it (crash-resume),
- per-job stdout/stderr land in a log file (`pio jobs logs <id>`),
- a wall-clock timeout kills runaway trains,
- infra-class failures (killed child, storage down — exit code ≠ the
  train-failure code) re-queue with ``resilience.retry`` exponential
  backoff until `max_attempts`; deterministic train failures fail fast,
- `period_s` gives cron-style periodic retrain per engine: completion
  (or final failure) of a periodic job enqueues the next run.

Fleet-safe claims (ISSUE 10): ownership transitions are **compare-and-
set** on a fenced ``claim_token`` + monotonically increasing
``generation``, so N workers (predictionio_tpu/fleet/coordinator.py)
can poll ONE queue and two of them can never supervise the same job:

- a claim is a **bid** appended to the job's claim record
  (``pio_job_claim``, one entity per job); the winner of generation g
  is the FIRST bid for g in the storage's total event order — every
  reader computes the same winner once the bids are visible,
- bidders with known live peers wait ``claim_settle_s`` (covering
  write-visibility skew) before resolving; a lone worker skips the
  wait, so single-worker deployments keep the old latency,
- every queued↔running transition bumps ``generation`` — a claim's bid
  generation is therefore never reused, and an owner's terminal
  bookkeeping is **fenced**: it re-reads the record and abandons if its
  (token, generation) was superseded,
- the stale-heartbeat steal rides the SAME CAS: re-queuing an orphan is
  a bid for the next generation, so two resuming schedulers cannot
  both requeue (double-incrementing attempts) — and a wedged worker
  that wakes up after being stolen sees the fence on its next
  heartbeat, kills its child, and abandons.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.deploy.registry import LifecycleRecordStore
from predictionio_tpu.obs import get_default_registry
from predictionio_tpu.resilience.retry import RetryPolicy
from predictionio_tpu.utils.env import env_str

log = logging.getLogger(__name__)

JOB_ENTITY = "pio_train_job"

# claim-bid records (ISSUE 10): one entity per job accumulates every
# worker's claim bids; the winner of a generation is the first bid for
# it in the record store's total event order (registry.py:events)
CLAIM_ENTITY = "pio_job_claim"

JOB_STATUSES = ("queued", "running", "completed", "failed")

# worker.py exit codes: train failures are deterministic (retry would
# reproduce them), anything else is infra and worth a backoff retry
EXIT_TRAIN_FAILED = 3
EXIT_INFRA_FAILED = 4

# job kind → subprocess entry module (ISSUE 20): eval shards ride the
# same queue/claim/heartbeat machinery but run a different workload
WORKER_MODULES = {
    "train": "predictionio_tpu.deploy.worker",
    "eval": "predictionio_tpu.evalfleet.worker",
}


def storage_config_to_json(config: StorageConfig) -> dict:
    """StorageConfig → JSON round-trip so the train subprocess opens the
    SAME stores as the scheduler (the reference shipped env vars to the
    spark-submit child; this is the explicit version)."""
    return {
        "sources": {
            name: {"type": s.type, "settings": dict(s.settings)}
            for name, s in config.sources.items()
        },
        "repositories": dict(config.repositories),
    }


def storage_config_from_json(obj: dict) -> StorageConfig:
    return StorageConfig(
        sources={
            name: SourceConfig(name, s["type"], dict(s.get("settings", {})))
            for name, s in obj.get("sources", {}).items()
        },
        repositories=dict(obj.get("repositories", {})),
    )


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def _now_iso() -> str:
    return _utcnow().isoformat()


@dataclass
class TrainJob:
    """One queued/running/finished train-job record."""

    id: str
    variant: dict[str, Any]
    engine_id: str
    status: str = "queued"
    created_at: str = ""
    not_before: float = 0.0  # epoch seconds; backoff/periodic gate
    started_at: Optional[str] = None
    finished_at: Optional[str] = None
    heartbeat_at: float = 0.0  # epoch seconds; parent liveness signal
    attempt: int = 0
    max_attempts: int = 3
    timeout_s: Optional[float] = None
    period_s: Optional[float] = None  # periodic retrain interval
    last_error: Optional[str] = None
    instance_id: Optional[str] = None
    model_version: Optional[str] = None
    log_path: Optional[str] = None
    worker_id: Optional[str] = None
    # fenced-claim state (ISSUE 10): `generation` increments on every
    # queued↔running transition; `claim_token` identifies the current
    # owner's claim and fences its heartbeats/terminal writes
    generation: int = 0
    claim_token: Optional[str] = None
    # job kind (ISSUE 20): "train" jobs keep the per-engine serialization
    # and spawn deploy/worker; "eval" shards parallelize freely and spawn
    # evalfleet/worker. `tenant` scopes periodic-retrain preset lookups.
    kind: str = "train"
    tenant: Optional[str] = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id, "variant": self.variant,
            "engine_id": self.engine_id, "status": self.status,
            "created_at": self.created_at, "not_before": self.not_before,
            "started_at": self.started_at, "finished_at": self.finished_at,
            "heartbeat_at": self.heartbeat_at, "attempt": self.attempt,
            "max_attempts": self.max_attempts, "timeout_s": self.timeout_s,
            "period_s": self.period_s, "last_error": self.last_error,
            "instance_id": self.instance_id,
            "model_version": self.model_version,
            "log_path": self.log_path, "worker_id": self.worker_id,
            "generation": self.generation,
            "claim_token": self.claim_token,
            "kind": self.kind, "tenant": self.tenant,
        }

    @staticmethod
    def from_dict(d: dict) -> "TrainJob":
        job = TrainJob(
            id=d["id"], variant=dict(d.get("variant") or {}),
            engine_id=d.get("engine_id", ""),
        )
        for k in (
            "status", "created_at", "not_before", "started_at",
            "finished_at", "heartbeat_at", "attempt", "max_attempts",
            "timeout_s", "period_s", "last_error", "instance_id",
            "model_version", "log_path", "worker_id", "generation",
            "claim_token", "kind", "tenant",
        ):
            if d.get(k) is not None:
                setattr(job, k, d[k])
        return job


class JobQueue:
    """Storage-backed job records — shared by the console, the admin
    server, and the scheduler worker, so a `pio jobs submit` from any
    host lands in the queue every worker polls."""

    def __init__(self, storage: Storage):
        self.storage = storage
        self._store = LifecycleRecordStore(storage)

    def submit(
        self,
        variant: dict,
        engine_id: Optional[str] = None,
        timeout_s: Optional[float] = None,
        period_s: Optional[float] = None,
        max_attempts: int = 3,
        not_before: float = 0.0,
        attempt: int = 0,
        kind: str = "train",
        tenant: Optional[str] = None,
    ) -> TrainJob:
        for key in ("id", "engineFactory"):
            if key not in variant:
                raise ValueError(f"engine variant is missing {key!r}")
        if kind not in WORKER_MODULES:
            raise ValueError(
                f"unknown job kind {kind!r} (known: {sorted(WORKER_MODULES)})"
            )

        # validate numerics AT SUBMIT: a string timeout_s stored raw
        # would 201 now and wedge the job at claim time (TypeError mid-
        # supervision leaves it `running` until a scheduler restart)
        def _num(name: str, val: Any) -> Optional[float]:
            if val is None:
                return None
            try:
                out = float(val)
            except (TypeError, ValueError):
                raise ValueError(f"{name} must be a number, got {val!r}")
            if out <= 0:
                raise ValueError(f"{name} must be positive, got {out}")
            return out

        job = TrainJob(
            id=f"job-{uuid.uuid4().hex[:12]}",
            variant=dict(variant),
            engine_id=engine_id or variant["id"],
            created_at=_now_iso(),
            not_before=not_before,
            timeout_s=_num("timeout_s", timeout_s),
            period_s=_num("period_s", period_s),
            max_attempts=max(1, int(max_attempts)),
            attempt=attempt,
            kind=kind,
            tenant=tenant,
        )
        self._store.append(JOB_ENTITY, job.id, job.to_dict())
        return job

    def update(self, job_id: str, **fields: Any) -> str:
        return self._store.append(JOB_ENTITY, job_id, fields)

    def heartbeat(self, job_id: str, prev_event_id: Optional[str]) -> str:
        """Heartbeat with compaction: append the new beat, then delete
        the previous one — a 1-hour train leaves ONE heartbeat event in
        the job's fold, not 3600 (the fold is re-read by every queue
        poll, so unbounded growth there is quadratic storage work)."""
        eid = self.update(job_id, heartbeat_at=time.time())
        if prev_event_id:
            self._store.discard(prev_event_id)
        return eid

    def heartbeat_fenced(
        self, job_id: str, prev_event_id: Optional[str], claim_token: str,
    ) -> tuple[Optional[str], bool]:
        """Heartbeat ONLY while `claim_token` still owns the job.
        Returns (event_id, owned). A stolen job (another worker CAS-won
        the next generation off our stale heartbeat) must not be
        refreshed — the beat would make the re-queued record look
        supervised — and the caller must kill its child and abandon."""
        job = self.get(job_id)
        if job is None or job.claim_token != claim_token:
            return None, False
        return self.heartbeat(job_id, prev_event_id), True

    # -- compare-and-set claims (ISSUE 10) --------------------------------
    def claim_bid(
        self, job_id: str, generation: int
    ) -> Optional[dict]:
        """The winning bid's properties for `generation`: the FIRST bid
        for it in the claim record's total event order (None when
        nobody bid). Deterministic for every reader once the bids are
        visible."""
        for e in self._store.events(CLAIM_ENTITY, f"{job_id}#claim"):
            props = e.properties.to_dict()
            if int(props.get("generation") or 0) == generation:
                return props
        return None

    def claim_winner(self, job_id: str, generation: int) -> Optional[str]:
        bid = self.claim_bid(job_id, generation)
        return bid.get("claim_token") if bid else None

    def highest_bid(self, job_id: str) -> tuple[int, Optional[dict]]:
        """(generation, winning-bid props) of the HIGHEST generation any
        bid names — the unwedge pass must bid past this, not past the
        job record's generation (dead unwedge bids stack above it)."""
        best_gen, best = 0, None
        for e in self._store.events(CLAIM_ENTITY, f"{job_id}#claim"):
            props = e.properties.to_dict()
            gen = int(props.get("generation") or 0)
            if gen > best_gen:
                best_gen, best = gen, props
        return best_gen, best

    def claim(
        self,
        job: TrainJob,
        worker_id: str,
        settle_s: float = 0.0,
        intent: str = "run",
        generation: Optional[int] = None,
        fields: Optional[dict] = None,
    ) -> Optional[str]:
        """CAS-acquire the job's next ownership transition.

        Appends a bid and resolves the winner from the claim record's
        total order; returns this worker's claim token when it won, None
        when another worker's bid sorted first (or the job's generation
        already moved past the observed one — the record was re-read
        stale). `settle_s` > 0 waits out write-visibility skew before
        resolving, which multi-worker fleets need (coordinator.py wires
        it from the live-peer probe); a lone worker resolves
        immediately.

        `fields` is the winner's post-transition job-record write
        (status/worker_id/...), performed HERE — immediately after the
        final re-check — so the window in which a crashed winner leaves
        a won-but-unwritten bid is a few storage calls, not a caller's
        arbitrary code path. Such a wedge is still possible (a worker
        can die on any instruction) and is recovered by
        `resume_orphans`'s stale-bid unwedge pass, which bids PAST the
        dead generation. `generation` overrides the default
        job.generation+1 for exactly that unwedge."""
        gen = generation if generation is not None else job.generation + 1
        token = uuid.uuid4().hex
        self._store.append(CLAIM_ENTITY, f"{job.id}#claim", {
            "job_id": job.id,
            "generation": gen,
            "claim_token": token,
            "worker_id": worker_id,
            "intent": intent,
            "bid_at": time.time(),
        })
        if settle_s > 0:
            time.sleep(settle_s)
        if self.claim_winner(job.id, gen) != token:
            return None
        cur = self.get(job.id)
        if cur is None or cur.generation >= gen:
            # the observed snapshot was stale: the transition we bid for
            # already happened (or the job was purged) — a "win" here
            # would supervise on top of the real generation's owner
            return None
        if fields is not None:
            # fields may override claim_token (a steal/unwedge ends
            # UNOWNED: status=queued, claim_token=None)
            self.update(job.id, **{
                "generation": gen, "claim_token": token, **fields,
            })
        return token

    def is_owner(self, job: TrainJob) -> bool:
        """Fencing read: does `job`'s recorded (claim_token, generation)
        still match the caller's copy? Terminal bookkeeping checks this
        right before writing; a steal that lands in the tiny window
        after the check is bounded by the staleness the steal itself
        required (an actively-writing owner is never stale)."""
        cur = self.get(job.id)
        return (
            cur is not None
            and cur.claim_token == job.claim_token
            and cur.generation == job.generation
        )

    def get(self, job_id: str) -> Optional[TrainJob]:
        d = self._store.fold(JOB_ENTITY, job_id).get(job_id)
        return TrainJob.from_dict(d) if d else None

    def list(self, status: Optional[str] = None) -> list[TrainJob]:
        jobs = [
            TrainJob.from_dict(d)
            for d in self._store.fold(JOB_ENTITY).values()
        ]
        if status is not None:
            jobs = [j for j in jobs if j.status == status]
        jobs.sort(key=lambda j: j.created_at)
        return jobs

    def purge(self, job_id: str) -> int:
        n = self._store.purge(JOB_ENTITY, job_id)
        # claim-bid records live and die with their job
        n += self._store.purge(CLAIM_ENTITY, f"{job_id}#claim")
        return n

    def gc(self, keep: int = 200) -> list[str]:
        """Purge terminal (completed/failed) job records beyond the
        newest `keep`. Every queue poll re-folds the full job history,
        so without retention a periodic retrain (24 jobs/day) grows the
        scheduler's hot loop without bound. Returns purged ids."""
        if keep < 0:
            raise ValueError("keep must be >= 0")
        terminal = [
            j for j in self.list()  # oldest-first by created_at
            if j.status in ("completed", "failed")
        ]
        doomed = terminal[: len(terminal) - keep] if keep else terminal
        for j in doomed:
            self.purge(j.id)  # job record + its claim-bid record
        # compact the survivors: status transitions accumulate ~5 events
        # per job, and every queue poll re-folds the whole history
        self._store.compact_all(JOB_ENTITY)
        return [j.id for j in doomed]

    def claimable(self, now_epoch: Optional[float] = None) -> list[TrainJob]:
        now_epoch = time.time() if now_epoch is None else now_epoch
        return [
            j for j in self.list(status="queued")
            if j.not_before <= now_epoch
        ]


@dataclass
class SchedulerConfig:
    poll_interval_s: float = 0.5
    heartbeat_interval_s: float = 1.0
    # concurrency knob (ISSUE 6 satellite, PR-5 follow-up): N train
    # subprocesses in flight at once, so many tenants' periodic retrains
    # don't serialize behind one worker. Jobs for the SAME engine stay
    # serialized — two concurrent trains of one engine would race the
    # latest-COMPLETED pointer their deploys read.
    max_concurrent: int = 1
    # a `running` job whose heartbeat is older than this is an orphan of
    # a crashed worker and gets re-queued on scheduler start
    stale_after_s: float = 15.0
    # claim-bid settle window (ISSUE 10): with live fleet peers, a
    # bidder waits this long before resolving its claim so concurrent
    # bids become visible and every worker computes the same winner.
    # Must exceed the storage's write-visibility skew (embedded stores:
    # ~0; cross-host daemons: replication lag + clock skew). A worker
    # with NO live peers skips the wait entirely.
    claim_settle_s: float = 0.25
    default_timeout_s: float = 3600.0
    # terminal job records kept by the periodic retention sweep (the
    # queue poll re-folds the whole job history, so it must stay bounded)
    job_retention: int = 200
    log_dir: Optional[str] = None
    # infra-failure re-queue backoff (reusing resilience.retry so the
    # schedule matches the storage client's semantics)
    retry: RetryPolicy = field(
        default_factory=lambda: RetryPolicy(
            max_attempts=3, base_delay=1.0, multiplier=4.0, max_delay=60.0
        )
    )
    # extra env for the child (tests add PYTHONPATH for their engines)
    child_env: dict[str, str] = field(default_factory=dict)


class TrainScheduler:
    """The worker: claims queued jobs and supervises their subprocesses.

    Claims are compare-and-set on a fenced claim_token + generation
    (ISSUE 10), so N schedulers over shared storage cooperate as a
    worker fleet (fleet/coordinator.py) — two workers can never
    supervise one job. A lone scheduler pays no settle wait and behaves
    exactly like the PR-5 single-worker shape."""

    def __init__(
        self, storage: Storage, config: Optional[SchedulerConfig] = None
    ):
        self.storage = storage
        self.config = config or SchedulerConfig()
        self.queue = JobQueue(storage)
        self.worker_id = f"worker-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        # live-peer probe (fleet/coordinator.py wires this to the worker
        # records): > 0 live peers → claims wait out the settle window;
        # None/0 → lone worker, resolve immediately
        self.peer_probe: Optional[Any] = None
        self._stop = threading.Event()
        self._abandon = False  # crash simulation: die without bookkeeping
        self._thread: Optional[threading.Thread] = None
        # per-job children + claim bookkeeping: with max_concurrent > 1
        # several supervisions run at once on a worker pool
        self._children: dict[str, subprocess.Popen] = {}
        self._child_lock = threading.Lock()
        self._pool: Optional[Any] = None
        self._claim_lock = threading.Lock()
        self._running_ids: set[str] = set()
        self._running_engines: set[str] = set()
        self._log_dir = self.config.log_dir or os.path.join(
            tempfile.gettempdir(), "pio_train_jobs"
        )
        self._jobs_counter = get_default_registry().counter(
            "train_jobs_total", "scheduler job outcomes",
            ("outcome",),  # label-bound: literal outcome set
        )

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        from concurrent.futures import ThreadPoolExecutor

        self._stop.clear()
        self._abandon = False
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=max(1, int(self.config.max_concurrent)),
                thread_name_prefix="train-supervise",
            )
        self._thread = threading.Thread(
            target=self._loop, name="train-scheduler", daemon=True
        )
        self._thread.start()

    def stop(self, kill_child: bool = False) -> None:
        """Stop polling. `kill_child=True` hard-kills every in-flight
        train subprocess AND abandons their records unchanged — the
        chaos-test stand-in for a worker crash (jobs stay `running` with
        going-stale heartbeats until the next scheduler start resumes
        them); a plain stop BLOCKS until in-flight trains finish and
        are bookkept — returning early would let the interpreter exit
        kill the daemon supervisor mid-train, orphaning children whose
        stale heartbeats then get the jobs trained a second time. The
        wait is bounded by each job's own timeout enforcement."""
        self._stop.set()
        if kill_child:
            self._abandon = True
            with self._child_lock:
                children = list(self._children.values())
            for child in children:
                if child.poll() is None:
                    child.kill()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._pool is not None:
            # abandoned supervisions return fast (their children are
            # dead and bookkeeping is skipped); clean ones block here
            # until the in-flight trains are bookkept
            self._pool.shutdown(wait=True)
            self._pool = None

    def _claim_settle(self) -> float:
        """Settle wait for claim bids: only paid when live fleet peers
        could be bidding concurrently (coordinator.py wires the probe);
        a lone worker resolves immediately — single-worker deployments
        keep PR-5 claim latency."""
        try:
            peers = int(self.peer_probe()) if self.peer_probe else 0
        except Exception:
            peers = 1  # probe broken: assume contention, pay the wait
        return self.config.claim_settle_s if peers > 0 else 0.0

    # -- crash resume -----------------------------------------------------
    def resume_orphans(self) -> list[str]:
        """Re-queue `running` jobs whose heartbeat went stale (their
        worker died mid-train). Returns the re-queued job ids.

        The steal is the SAME CAS as a run claim (ISSUE 10): the
        requeue/fail transition is bid for generation+1, so two
        schedulers resuming the same orphan can't both write it (a
        double requeue would double-increment the attempt budget on the
        next claim, and a requeue racing a fail would resurrect a dead
        job). The bumped generation also fences the crashed owner if it
        was merely wedged: its next heartbeat sees the token mismatch,
        kills its child, and abandons."""
        cutoff = time.time() - self.config.stale_after_s
        requeued = []
        for job in self.queue.list(status="running"):
            if job.heartbeat_at >= cutoff:
                continue
            if job.attempt >= job.max_attempts:
                # a train that keeps killing its worker must not
                # crash-loop forever: the attempt budget covers orphan
                # resumes too, not just supervised infra failures
                token = self.queue.claim(
                    job, self.worker_id, settle_s=self._claim_settle(),
                    intent="steal",
                    fields=dict(
                        status="failed", finished_at=_now_iso(),
                        claim_token=None, worker_id=None,
                        last_error="worker crashed mid-train; attempts "
                                   "exhausted",
                    ),
                )
                if token is None:
                    continue  # another scheduler's steal won
                log.warning(
                    "job %s orphaned on final attempt %d/%d; failing",
                    job.id, job.attempt, job.max_attempts,
                )
                self._jobs_counter.inc(outcome="failed_infra")
                # a periodic retrain chain must survive one exhausted
                # run — the supervised failure path schedules the next
                # period, and the orphan path owes the same
                self._schedule_next_period(job)
                continue
            token = self.queue.claim(
                job, self.worker_id, settle_s=self._claim_settle(),
                intent="steal",
                fields=dict(
                    status="queued", worker_id=None, claim_token=None,
                    last_error="worker crashed mid-train; re-queued",
                ),
            )
            if token is None:
                continue  # another scheduler's steal won — its write
            log.warning(
                "job %s orphaned (heartbeat %.1fs stale); re-queuing",
                job.id, time.time() - job.heartbeat_at,
            )
            self._jobs_counter.inc(outcome="requeued_orphan")
            requeued.append(job.id)
        # un-wedge QUEUED jobs whose next generation was won by a bid
        # that never became a record write (the bidder died between
        # winning and writing): every later claim of that generation
        # loses to the dead bid forever. A stale winning bid on a job
        # whose record never advanced is exactly that wedge — bid PAST
        # the HIGHEST bid generation on record (not a fixed +1: a died
        # unwedge stacks another dead bid above the first) so the next
        # claim starts on a fresh generation.
        for job in self.queue.list(status="queued"):
            if self.queue.claim_bid(job.id, job.generation + 1) is None:
                continue  # no bid above the record: not wedged
            top_gen, top = self.queue.highest_bid(job.id)
            if top is None or top_gen <= job.generation:
                continue
            if time.time() - float(top.get("bid_at") or 0) < \
                    self.config.stale_after_s:
                continue  # a live claimant is mid-protocol; leave it
            token = self.queue.claim(
                job, self.worker_id, settle_s=self._claim_settle(),
                intent="unwedge", generation=top_gen + 1,
                fields=dict(
                    status="queued", worker_id=None, claim_token=None,
                    last_error="claim wedged by a dead bid; generation "
                               "bumped",
                ),
            )
            if token is not None:
                log.warning(
                    "job %s: dead claim bid at generation %d; un-wedged",
                    job.id, job.generation + 1,
                )
                self._jobs_counter.inc(outcome="unwedged")
        # orphaned push spools (ISSUE 17): a kill -9'd worker never ran
        # its exit flush — its durably-spooled telemetry batches are
        # still sitting under log_dir. Ship them now so the dead job's
        # spans / stage metrics / devprof land without a single poll.
        try:
            self.ship_orphan_spools()
        except Exception:
            log.debug("orphan spool sweep failed", exc_info=True)
        return requeued

    # -- push-telemetry spool handling (ISSUE 17) --------------------------
    def _push_spool_dir(
        self, job_id: str, env: dict[str, str]
    ) -> Optional[str]:
        """Per-job spool dir for the worker's TelemetryShipper, or None
        when push shipping isn't configured. An operator-pinned
        PIO_PUSH_SPOOL is respected (shared spool — the workers own it,
        the supervisor stays out)."""
        if not env_str("PIO_PUSH_URL", env=env).strip():
            return None
        if env_str("PIO_PUSH_SPOOL", env=env).strip():
            return None
        return os.path.join(self._log_dir, f"{job_id}.spool")

    def _ship_spool_residue(self, spool_dir: str, url: str) -> int:
        """Best-effort ship of everything left in `spool_dir`, removing
        the dir once empty. Never raises — a dead ingest endpoint keeps
        the files for the next sweep."""
        from predictionio_tpu.obs.monitor import push as _push

        if not url:
            return 0
        try:
            shipped = _push.ship_spool(spool_dir, url)
        except Exception:
            log.debug("spool ship failed: %s", spool_dir, exc_info=True)
            return 0
        try:
            os.rmdir(spool_dir)  # only succeeds once fully drained
        except OSError:
            pass
        return shipped

    def ship_orphan_spools(self) -> int:
        """Ship every `<log_dir>/<job>.spool` left by a dead worker
        (skipping jobs whose child is still alive under THIS scheduler —
        a live worker ships its own spool). Returns batches shipped."""
        env = dict(os.environ, **self.config.child_env)
        url = env_str("PIO_PUSH_URL", env=env).strip()
        if not url:
            return 0
        try:
            entries = sorted(os.listdir(self._log_dir))
        except OSError:
            return 0
        shipped = 0
        for entry in entries:
            if not entry.endswith(".spool"):
                continue
            with self._child_lock:
                live = entry[: -len(".spool")] in self._children
            if live:
                continue
            shipped += self._ship_spool_residue(
                os.path.join(self._log_dir, entry), url
            )
        return shipped

    # -- main loop --------------------------------------------------------
    def _loop(self) -> None:
        last_resume = 0.0
        while not self._stop.is_set():
            # orphan resume runs on start AND periodically: a job whose
            # post-claim bookkeeping failed on THIS worker (storage
            # blip) wedges in `running` and must be resumed without
            # waiting for a process restart
            if time.monotonic() - last_resume >= self.config.stale_after_s:
                last_resume = time.monotonic()
                try:
                    self.resume_orphans()
                    self.queue.gc(keep=self.config.job_retention)
                except Exception:
                    log.exception("orphan resume/gc failed; continuing")
            try:
                ready = self.queue.claimable()
            except Exception:
                log.exception("job poll failed (storage down?); retrying")
                ready = []
            dispatched = False
            for job in ready:
                if self._stop.is_set():
                    break
                if self._dispatch(job):
                    dispatched = True
            if not dispatched:
                self._stop.wait(self.config.poll_interval_s)

    def _dispatch(self, job: TrainJob) -> bool:
        """Claim `job` onto the supervision pool if capacity and the
        per-engine serialization allow it. Claims are capped at
        max_concurrent so a burst of submissions doesn't pile jobs into
        a `running`-but-not-started limbo behind the pool queue."""
        # eval shards (ISSUE 20) skip the per-engine serialization — the
        # whole point of the fan-out is same-engine shards in parallel
        engine_key = job.engine_id if job.kind == "train" else None
        with self._claim_lock:
            if (
                len(self._running_ids) >= max(
                    1, int(self.config.max_concurrent)
                )
                or job.id in self._running_ids
                or (engine_key is not None
                    and engine_key in self._running_engines)
            ):
                return False
            self._running_ids.add(job.id)
            if engine_key is not None:
                self._running_engines.add(engine_key)

        def run() -> None:
            try:
                self._run_job(job)
            except Exception:
                # a storage/filesystem error mid-supervision must not
                # kill the worker — the job's stale heartbeat makes it
                # an orphan the next resume pass re-queues
                log.exception("job %s supervision failed", job.id)
            finally:
                with self._claim_lock:
                    self._running_ids.discard(job.id)
                    if engine_key is not None:
                        self._running_engines.discard(engine_key)

        pool = self._pool
        if pool is None:
            # no pool (synchronous path): run inline
            run()
            return True
        try:
            pool.submit(run)
        except RuntimeError:  # pool already shut down (stop raced)
            with self._claim_lock:
                self._running_ids.discard(job.id)
                if engine_key is not None:
                    self._running_engines.discard(engine_key)
            return False
        return True

    def run_pending_once(self) -> int:
        """Drain currently-claimable jobs synchronously (tests and
        `pio jobs worker --once`). Returns how many ran."""
        self.resume_orphans()
        ready = self.queue.claimable()
        for job in ready:
            self._run_job(job)
        return len(ready)

    # -- job execution ----------------------------------------------------
    def _run_job(self, job: TrainJob) -> None:
        # fleet-wide engine-serialization PRE-check (cheap, bid-free):
        # while a same-engine job trains on any worker, don't even bid —
        # a bid per poll cycle would grow the claim record by thousands
        # of dead bids over a long rival train, and bids are
        # uncompactable (first-bid-wins reads them all). The post-claim
        # seniority check below still closes the claim/claim race this
        # read can't see.
        try:
            if job.kind == "train" and any(
                j.engine_id == job.engine_id and j.id != job.id
                and j.kind == "train"
                for j in self.queue.list(status="running")
            ):
                return  # re-polled next cycle; nothing written
        except Exception:
            pass  # storage blip: the post-claim check still guards
        # CAS-claim the queued→running transition (ISSUE 10): only the
        # bid winner supervises; losers walk away without having touched
        # the job record. The running-record write happens INSIDE
        # claim(), right after the win — see claim()'s wedge note.
        os.makedirs(self._log_dir, mode=0o700, exist_ok=True)
        log_path = os.path.join(self._log_dir, f"{job.id}.log")
        token = self.queue.claim(
            job, self.worker_id, settle_s=self._claim_settle(),
            fields=dict(
                status="running", worker_id=self.worker_id,
                started_at=_now_iso(), heartbeat_at=time.time(),
                log_path=log_path, attempt=job.attempt + 1,
            ),
        )
        if token is None:
            self._jobs_counter.inc(outcome="claim_lost")
            log.debug("job %s: claim lost to another worker", job.id)
            return
        job.claim_token = token
        job.generation += 1
        job.attempt += 1
        # fleet-wide per-engine serialization: the in-process
        # _running_engines set only guards ONE worker — two fleet
        # members claiming two different jobs of the same engine would
        # race the latest-COMPLETED pointer their deploys read. After
        # the claim record lands (and a settle window when live peers
        # exist, so concurrent claimants see each other), the SENIOR
        # running job of the engine (earliest started_at, id
        # tie-break — recorded strings, so every reader agrees)
        # proceeds; juniors yield back to the queue without consuming
        # their attempt.
        settle = self._claim_settle()
        if settle:
            time.sleep(settle)
        try:
            rivals = [
                j for j in self.queue.list(status="running")
                if j.engine_id == job.engine_id and j.id != job.id
                and j.kind == "train"
            ] if job.kind == "train" else []
        except Exception:
            rivals = []  # storage blip: the in-process guard still holds
        if rivals:
            mine = self.queue.get(job.id)
            key = lambda j: (j.started_at or "", j.id)
            if mine is not None and min(
                rivals + [mine], key=key
            ).id != job.id:
                self.queue.update(
                    job.id, status="queued", worker_id=None,
                    claim_token=None, generation=job.generation + 1,
                    attempt=job.attempt - 1,
                    not_before=time.time() + self.config.poll_interval_s,
                    last_error=None,
                )
                self._jobs_counter.inc(outcome="engine_yield")
                log.info(
                    "job %s: engine %s already training on another "
                    "worker; yielded", job.id, job.engine_id,
                )
                return
        spec_path = os.path.join(self._log_dir, f"{job.id}.spec.json")
        result_path = os.path.join(self._log_dir, f"{job.id}.result.json")
        # the spec carries the storage wiring VERBATIM — including any
        # source passwords — so it is owner-only and deleted after the
        # run (the default tempdir log_dir is shared on multi-user hosts)
        fd = os.open(spec_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump({
                "job_id": job.id,
                "storage": storage_config_to_json(self.storage.config),
                "variant": job.variant,
                "engine_id": job.engine_id,
                "result_path": result_path,
            }, f)
        try:
            self._supervise(job, spec_path, result_path, log_path)
        finally:
            for p in (spec_path, result_path):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def _supervise(
        self, job: TrainJob, spec_path: str, result_path: str, log_path: str
    ) -> None:
        env = dict(os.environ, **self.config.child_env)
        # push telemetry (ISSUE 17): give each worker its OWN spool dir
        # under log_dir (unless the operator pinned one) so a kill -9'd
        # child's unsent batches survive as files THIS supervisor can
        # ship — see the post-exit residue ship below and
        # ship_orphan_spools()
        spool_dir = self._push_spool_dir(job.id, env)
        if spool_dir is not None:
            env["PIO_PUSH_SPOOL"] = spool_dir
        # push auth (ISSUE 18): hand the worker the shared push secret
        # explicitly — its shipper mints the per-instance wire token
        # (HMAC(secret, instance)) from it, so the receiver 403s any
        # pusher that can't prove it, and a captured token can't write
        # series under another instance's label
        push_secret = env_str("PIO_PUSH_TOKEN", env=env).strip()
        if push_secret:
            env["PIO_PUSH_TOKEN"] = push_secret
        timeout_s = job.timeout_s or self.config.default_timeout_s
        deadline = time.monotonic() + timeout_s
        timed_out = False
        try:
            with open(log_path, "ab") as logf:
                logf.write(
                    f"--- attempt {job.attempt} ({_now_iso()}) ---\n".encode()
                )
                logf.flush()
                worker_module = WORKER_MODULES.get(
                    job.kind, WORKER_MODULES["train"]
                )
                child = subprocess.Popen(
                    [sys.executable, "-m", worker_module, spec_path],
                    stdout=logf, stderr=subprocess.STDOUT, env=env,
                )
            with self._child_lock:
                self._children[job.id] = child
                if self._abandon and child.poll() is None:
                    # stop(kill_child=True) raced the spawn: this child
                    # must die too, or it finishes unsupervised
                    child.kill()
            # heartbeat while the child lives: liveness for crash
            # detection AND the timeout enforcement point. A clean
            # stop() does NOT break out — the supervisor keeps
            # heartbeating (so a restarted scheduler can't mistake this
            # still-running job for an orphan and train it twice) and
            # keeps enforcing the timeout until the child exits;
            # stop(kill_child=True) is the crash path.
            hb_event: Optional[str] = None
            try:
                while True:
                    try:
                        rc = child.wait(
                            timeout=self.config.heartbeat_interval_s
                        )
                        break
                    except subprocess.TimeoutExpired:
                        if self._abandon:
                            return  # crashed worker: no bookkeeping at all
                        try:
                            hb_event, owned = self.queue.heartbeat_fenced(
                                job.id, hb_event, job.claim_token or ""
                            )
                            if not owned:
                                # stolen: our heartbeat went stale long
                                # enough for another scheduler to CAS the
                                # next generation — kill the child NOW so
                                # the job is never trained twice, and
                                # drop all bookkeeping (the thief owns
                                # the record)
                                log.warning(
                                    "job %s: claim fenced (stolen by "
                                    "another worker); killing child and "
                                    "abandoning", job.id,
                                )
                                self._jobs_counter.inc(outcome="fenced")
                                child.kill()
                                child.wait()
                                return
                        except Exception:
                            # transient storage outage must not abort
                            # supervision of a healthy train — keep
                            # enforcing the timeout; the beat resumes
                            # when storage answers again
                            log.warning(
                                "job %s heartbeat write failed (storage "
                                "down?); supervision continues", job.id,
                                exc_info=True,
                            )
                        if time.monotonic() >= deadline:
                            timed_out = True
                            child.kill()
                            rc = child.wait()
                            break
            except BaseException:
                # supervision is dying for real: never leave the child
                # running unsupervised (it would finish on its own and
                # the orphan resume would then train the job a 2nd time)
                if child.poll() is None:
                    child.kill()
                    child.wait()
                raise
        except FileNotFoundError as e:  # interpreter/module missing
            self._finish_infra(job, f"could not spawn train worker: {e}")
            return
        finally:
            with self._child_lock:
                self._children.pop(job.id, None)
        if self._abandon:
            return  # crashed worker: the record keeps its stale heartbeat
        if spool_dir is not None:
            # the worker's exit flush usually leaves the spool empty; a
            # SIGKILLed / OOM-killed child cannot flush, so whatever
            # batches it durably spooled ship from HERE (best-effort,
            # zero polls of the dead process)
            self._ship_spool_residue(
                spool_dir, env_str("PIO_PUSH_URL", env=env)
            )
        if not self.queue.is_owner(job):
            # fenced between the last heartbeat and child exit: the
            # thief's record wins, our outcome is dropped (the retrain
            # the steal implies is by design — our heartbeats were stale)
            log.warning(
                "job %s: claim superseded before bookkeeping; dropping "
                "outcome", job.id,
            )
            self._jobs_counter.inc(outcome="fenced")
            return
        if timed_out:
            self._finish_infra(
                job, f"train exceeded timeout ({timeout_s:.0f}s); killed"
            )
            return
        if rc == 0:
            try:
                with open(result_path) as f:
                    result = json.load(f)
            except (OSError, ValueError) as e:
                self._finish_infra(job, f"train result unreadable: {e}")
                return
            self.queue.update(
                job.id, status="completed", finished_at=_now_iso(),
                instance_id=result.get("instance_id"),
                model_version=result.get("model_version"),
                last_error=None, claim_token=None,
            )
            self._jobs_counter.inc(outcome="completed")
            self._link_eval_run(job, result)
            self._schedule_next_period(job)
        elif rc == EXIT_TRAIN_FAILED:
            # deterministic failure: retrying reproduces it — fail fast
            self.queue.update(
                job.id, status="failed", finished_at=_now_iso(),
                last_error=f"train failed (see {log_path})",
                claim_token=None,
            )
            self._jobs_counter.inc(outcome="failed_train")
            self._schedule_next_period(job)
        else:
            self._finish_infra(
                job, f"train worker exited {rc} (see {log_path})"
            )

    def _finish_infra(self, job: TrainJob, error: str) -> None:
        """Infra-class failure: re-queue with backoff, or give up after
        max_attempts. Fenced like every terminal write (the spawn-failed
        path reaches here without the supervise-side check)."""
        if job.claim_token is not None and not self.queue.is_owner(job):
            self._jobs_counter.inc(outcome="fenced")
            return
        if job.attempt >= job.max_attempts:
            self.queue.update(
                job.id, status="failed", finished_at=_now_iso(),
                last_error=f"{error} (attempts exhausted)",
                claim_token=None,
            )
            self._jobs_counter.inc(outcome="failed_infra")
            self._schedule_next_period(job)
            return
        backoff = self.config.retry.delay(job.attempt - 1)
        # the running→queued transition bumps generation so the next
        # claim's bid can never collide with this round's resolved bids
        self.queue.update(
            job.id, status="queued", last_error=error,
            not_before=time.time() + backoff, worker_id=None,
            generation=job.generation + 1, claim_token=None,
        )
        self._jobs_counter.inc(outcome="retried")
        log.warning(
            "job %s infra failure (%s); retry %d/%d in %.1fs",
            job.id, error, job.attempt, job.max_attempts, backoff,
        )

    def _link_eval_run(self, job: TrainJob, result: dict) -> None:
        """Lineage stamp (ISSUE 20): a completed retrain whose variant
        carries an `evalRun` marker (the tuning loop's preset merge put
        it there) links the trained ModelVersion back onto the eval run
        — the winning params now point at the model they produced.
        Best-effort: lineage must never fail a completed train."""
        run_id = (job.variant or {}).get("evalRun")
        version = result.get("model_version")
        if not run_id or not version:
            return
        try:
            from predictionio_tpu.evalfleet.records import EvalRecordStore

            EvalRecordStore(self.storage).link_model_version(
                run_id, version, job_id=job.id
            )
            log.info("job %s: linked model version %s to eval run %s",
                     job.id, version, run_id)
        except Exception:
            log.debug("eval-run lineage stamp failed", exc_info=True)

    def _schedule_next_period(self, job: TrainJob) -> None:
        """Cron-style periodic retrain: a finished periodic job enqueues
        its next run (fixed-delay schedule — the next run starts
        `period_s` after this one ENDED, so a slow train can't stack)."""
        if not job.period_s:
            return
        variant = job.variant
        if job.kind == "train":
            # tuning loop (ISSUE 20): overlay the parked eval winner (the
            # job's tenant-scoped preset wins over the global one) so the
            # NEXT scheduled retrain trains the winning params
            try:
                from predictionio_tpu.evalfleet.tuning import apply_preset

                variant = apply_preset(
                    self.storage, variant, job.engine_id, tenant=job.tenant
                )
            except Exception:
                log.debug("retrain preset lookup failed", exc_info=True)
        nxt = self.queue.submit(
            variant, engine_id=job.engine_id,
            timeout_s=job.timeout_s, period_s=job.period_s,
            max_attempts=job.max_attempts,
            not_before=time.time() + job.period_s,
            kind=job.kind, tenant=job.tenant,
        )
        log.info(
            "periodic retrain: job %s scheduled %.0fs after %s finished",
            nxt.id, job.period_s, job.id,
        )
