"""Canary rollout with automatic rollback (ISSUE 5 tentpole part 3).

The query server holds up to two `EngineRuntime`s — live + candidate —
and routes a sticky hash-of-request traffic fraction to the candidate.
Per-variant serve/error histograms land in the server registry under a
``variant`` label, and a verdict loop compares candidate vs live over a
sliding window:

- error-rate delta above `max_error_delta`      → roll back
- candidate p99 / live p99 above `max_p99_ratio` → roll back
- optional shadow mode: candidate answers a mirrored copy of live
  traffic off the response path; result disagreement above
  `1 - min_agreement` → roll back
- healthy through `bake_s` of traffic            → promote

Promote is an atomic reference hot-swap under the server's runtime-swap
lock; the old runtime is drained, not dropped — in-flight queries hold
their runtime snapshot (the dispatcher groups by runtime), so zero
queries are dropped during either swap. Rollback simply detaches the
candidate and marks the version ``rolled_back``.

Every knob has a ``PIO_ROLLOUT_*`` env default so operators tune the
verdict without redeploying.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Optional, TYPE_CHECKING

from predictionio_tpu.utils.env import env_bool, env_float, env_int
from predictionio_tpu.deploy.registry import (
    ROLLOUT_ENTITY,
    LifecycleRecordStore,
    ModelRegistry,
    ModelVersion,
)

if TYPE_CHECKING:  # avoid the runtime import cycle with workflow.server
    from predictionio_tpu.workflow.server import EngineRuntime, QueryServer

log = logging.getLogger(__name__)

VARIANT_LIVE = "live"
VARIANT_CANDIDATE = "candidate"

# persisted rollout state (ISSUE 6 satellite, PR-5 follow-up): one
# ROLLOUT_ENTITY record per rollout scope on the shared record layer, so
# a query-server restart mid-canary re-adopts the bake instead of
# silently dropping it


@dataclass
class RolloutConfig:
    """Verdict knobs. `from_env` reads ``PIO_ROLLOUT_*`` so a deployment
    sets policy once; per-rollout overrides ride the start request."""

    fraction: float = 0.1          # candidate traffic share (0..1]
    window_s: float = 30.0         # sliding comparison window
    interval_s: float = 1.0        # verdict loop cadence
    min_requests: int = 20         # candidate samples before judging
    max_error_delta: float = 0.05  # cand err-rate − live err-rate bound
    max_p99_ratio: float = 3.0     # cand p99 / live p99 bound
    bake_s: float = 60.0           # healthy-for-this-long → promote
    shadow: bool = False           # mirror mode instead of live traffic
    min_agreement: float = 0.9     # shadow result-agreement floor

    @staticmethod
    def from_env(
        env: Optional[dict] = None, **overrides: Any
    ) -> "RolloutConfig":
        env = dict(os.environ if env is None else env)
        cfg = RolloutConfig(
            fraction=env_float("PIO_ROLLOUT_FRACTION", env=env),
            window_s=env_float("PIO_ROLLOUT_WINDOW_S", env=env),
            interval_s=env_float("PIO_ROLLOUT_INTERVAL_S", env=env),
            min_requests=env_int("PIO_ROLLOUT_MIN_REQUESTS", env=env),
            max_error_delta=env_float(
                "PIO_ROLLOUT_MAX_ERROR_DELTA", env=env
            ),
            max_p99_ratio=env_float("PIO_ROLLOUT_MAX_P99_RATIO", env=env),
            bake_s=env_float("PIO_ROLLOUT_BAKE_S", env=env),
            shadow=env_bool("PIO_ROLLOUT_SHADOW", env=env),
            min_agreement=env_float("PIO_ROLLOUT_MIN_AGREEMENT", env=env),
        )
        for k, v in overrides.items():
            if v is None:
                continue
            cur = getattr(cfg, k)
            if isinstance(cur, bool):
                # bool("false") is True — parse string spellings so a
                # shell-templated {"shadow": "false"} cannot silently
                # turn a live canary into a shadow one
                if isinstance(v, str):
                    v = v.strip().lower() in ("1", "true", "yes", "on")
                else:
                    v = bool(v)
                setattr(cfg, k, v)
            else:
                setattr(cfg, k, type(cur)(v))
        if not 0.0 < cfg.fraction <= 1.0:
            raise ValueError(f"fraction must be in (0, 1], got {cfg.fraction}")
        return cfg

    def to_dict(self) -> dict[str, Any]:
        return {
            "fraction": self.fraction, "window_s": self.window_s,
            "interval_s": self.interval_s,
            "min_requests": self.min_requests,
            "max_error_delta": self.max_error_delta,
            "max_p99_ratio": self.max_p99_ratio, "bake_s": self.bake_s,
            "shadow": self.shadow, "min_agreement": self.min_agreement,
        }


def route_bucket(raw_request: bytes) -> int:
    """The sticky routing bucket of one request body: crc32 % 10000.
    Computed ONCE per request — at the gateway when one fronts the
    replica tier (forwarded as X-PIO-Route-Hash so every replica agrees
    on the canary fraction end-to-end), else at the replica itself."""
    return zlib.crc32(raw_request) % 10_000


def sticky_candidate(
    raw_request: bytes, fraction: float, bucket: Optional[int] = None
) -> bool:
    """Hash-of-request routing: the same request body always lands on the
    same variant (sticky), and the candidate share tracks `fraction`.
    `bucket` (ISSUE 15) overrides the locally-computed hash with the
    gateway's — a replica behind the gateway must make the same canary
    decision the gateway's hash implies, or a hedged/failed-over retry
    could flip variants mid-request."""
    if bucket is None:
        bucket = route_bucket(raw_request)
    return bucket < fraction * 10_000


class VariantWindow:
    """Thread-safe sliding window of (wall time, duration, error) serve
    samples for one variant, plus shadow agree/disagree counts."""

    def __init__(self, window_s: float):
        self.window_s = window_s
        self._lock = threading.Lock()
        self._samples: collections.deque = collections.deque()
        self._agree: collections.deque = collections.deque()

    def add(self, duration_s: float, error: bool,
            trace_id: Optional[str] = None) -> None:
        with self._lock:
            self._samples.append(
                (time.monotonic(), duration_s, error, trace_id)
            )
            self._trim()

    def add_agreement(self, agree: bool) -> None:
        with self._lock:
            self._agree.append((time.monotonic(), agree))
            self._trim()

    def _trim(self) -> None:
        cutoff = time.monotonic() - self.window_s
        for dq in (self._samples, self._agree):
            while dq and dq[0][0] < cutoff:
                dq.popleft()

    def stats(self) -> dict[str, Any]:
        with self._lock:
            self._trim()
            samples = list(self._samples)
            agree = list(self._agree)
        n = len(samples)
        errors = sum(1 for _, _, e, _tid in samples if e)
        durations = sorted(d for _, d, _, _tid in samples)
        p99 = durations[min(n - 1, int(0.99 * n))] if n else 0.0
        out = {
            "count": n,
            "errors": errors,
            "error_rate": errors / n if n else 0.0,
            "p50_ms": (
                durations[n // 2] * 1000.0 if n else 0.0
            ),
            "p99_ms": p99 * 1000.0,
        }
        # worst-sample exemplar (ISSUE 16): a p99-ratio rollback verdict
        # should hand the operator the trace behind its slowest sample
        if n:
            _t, worst_d, _e, worst_tid = max(
                samples, key=lambda s: s[1]
            )
            if worst_tid:
                out["worst_trace_id"] = worst_tid
                out["worst_ms"] = worst_d * 1000.0
        if agree:
            out["agreement"] = sum(1 for _, a in agree if a) / len(agree)
            out["shadow_count"] = len(agree)
        return out


def verdict(
    live: dict[str, Any], cand: dict[str, Any], cfg: RolloutConfig,
    elapsed_s: float,
) -> tuple[str, str]:
    """Pure verdict math over two window-stat dicts → (action, reason)
    with action in {"wait", "promote", "rollback"}. Separated from the
    controller so the promote/rollback boundaries unit-test without a
    server."""
    n = cand.get("shadow_count", 0) if cfg.shadow else cand["count"]
    if n < cfg.min_requests:
        return "wait", f"candidate has {n}/{cfg.min_requests} samples"
    if not cfg.shadow:
        delta = cand["error_rate"] - live["error_rate"]
        if delta > cfg.max_error_delta:
            return "rollback", (
                f"error-rate delta {delta:.3f} > {cfg.max_error_delta} "
                f"(candidate {cand['error_rate']:.3f} vs live "
                f"{live['error_rate']:.3f})"
            )
        if live["p99_ms"] > 0 and cand["p99_ms"] > 0:
            ratio = cand["p99_ms"] / live["p99_ms"]
            if ratio > cfg.max_p99_ratio:
                return "rollback", (
                    f"p99 ratio {ratio:.2f} > {cfg.max_p99_ratio} "
                    f"(candidate {cand['p99_ms']:.1f}ms vs live "
                    f"{live['p99_ms']:.1f}ms)"
                )
    else:
        agreement = cand.get("agreement")
        if agreement is not None and agreement < cfg.min_agreement:
            return "rollback", (
                f"shadow agreement {agreement:.3f} < {cfg.min_agreement}"
            )
    if elapsed_s >= cfg.bake_s:
        return "promote", f"healthy through {cfg.bake_s:.0f}s bake"
    return "wait", f"baking ({elapsed_s:.0f}/{cfg.bake_s:.0f}s)"


@dataclass
class RolloutState:
    version: ModelVersion
    config: RolloutConfig
    state: str = "starting"  # canary|promoted|rolled_back|aborted|failed
    started_at: float = field(default_factory=time.monotonic)
    started_wall: float = 0.0  # epoch seconds; survives restarts
    verdict_reason: str = ""
    last_action: str = "wait"


class RolloutController:
    """Owns one canary's life: build → route → judge → swap or detach."""

    def __init__(
        self,
        server: "QueryServer",
        version: ModelVersion,
        config: Optional[RolloutConfig] = None,
        scope: Optional[str] = None,
    ):
        self.server = server
        self.registry = ModelRegistry(server.storage)
        self.config = config or RolloutConfig.from_env()
        self.st = RolloutState(version, self.config)
        # persistence scope: one active rollout per scope. The default
        # is the engine variant (a query server serves one); tenant
        # rollouts pass "tenant/<id>" so they persist independently
        self.scope = scope or f"{version.engine_id}/{version.engine_variant}"
        self._records = LifecycleRecordStore(server.storage)
        self.windows = {
            VARIANT_LIVE: VariantWindow(self.config.window_s),
            VARIANT_CANDIDATE: VariantWindow(self.config.window_s),
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._shadow_inflight = threading.Semaphore(8)
        # persistent mirror pool (shadow mode only): per-request thread
        # spawn at serving QPS would churn a thread per mirror
        self._shadow_pool = (
            ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="rollout-shadow"
            )
            if self.config.shadow else None
        )
        # fallback mirrors spawned after the pool closed mid-request:
        # tracked so stop() joins them (ISSUE 12 thread-lifecycle —
        # the old fire-and-forget spawn outlived the controller)
        self._stray_lock = threading.Lock()
        self._stray_shadows: list[threading.Thread] = []  # guarded-by: _stray_lock

    # -- persistence ------------------------------------------------------
    def _persist(self, **fields: Any) -> None:
        """Best-effort rollout-state write: a storage blip must not
        fail the rollout itself — at worst a restart misses one
        transition and the resume path re-checks the registry anyway."""
        try:
            self._records.append(ROLLOUT_ENTITY, self.scope, fields)
        except Exception:
            log.warning(
                "rollout state persist failed for scope %s (storage "
                "down?); restart re-adoption may miss this transition",
                self.scope, exc_info=True,
            )

    # -- lifecycle --------------------------------------------------------
    def start(self, resume_started_wall: Optional[float] = None) -> None:
        """Build the candidate runtime and attach it to the server. A
        build failure (model.load fault, bad blob) leaves the live
        runtime untouched — the canary never starts.

        `resume_started_wall` re-adopts a persisted mid-canary rollout
        after a restart: bake progress is credited from the original
        wall-clock start, so a canary 50s into a 60s bake doesn't
        restart its bake from zero (it DOES need fresh verdict-window
        samples — the windows are in-memory by design)."""
        from predictionio_tpu.workflow.server import (
            RolloutConflict,
            build_runtime,
        )

        # cheap conflict pre-check BEFORE the expensive model build —
        # attach_rollout re-verifies under the swap lock; this just
        # avoids deserializing a runtime onto the device only to 409
        active = self.server.rollout
        if active is not None and active is not self and active.st.state in (
            "starting", "canary"
        ):
            raise RolloutConflict(
                f"rollout of {active.st.version.id} is already active"
            )
        instance = (
            self.server.storage.get_meta_data_engine_instances()
            .get(self.st.version.instance_id)
        )
        if instance is None:
            self.st.state = "failed"
            raise RuntimeError(
                f"model version {self.st.version.id} references missing "
                f"instance {self.st.version.instance_id}"
            )
        try:
            candidate = build_runtime(self.server.storage, instance)
        except Exception as e:
            self.st.state = "failed"
            self.st.verdict_reason = f"candidate build failed: {e}"
            raise
        # attach BEFORE the registry status flip: a conflicting active
        # rollout must abort this start without marking the version.
        # If the flip (a storage write) then fails, DETACH — otherwise
        # the server routes traffic to a candidate no verdict loop is
        # judging, and neither abort nor a new start can clear it.
        self.server.attach_rollout(self, candidate)
        try:
            # TENANT scopes only: a version another tenant already
            # promoted stays "live" — tenants of one engine canary the
            # same trained version by default, and flipping the shared
            # record back to "canary" would erase the variant's live
            # pointer out from under the tenants serving it. The
            # default scope still always flips: its resume path is
            # strict (status must be "canary"), so skipping the flip
            # there would make a server-scope bake unresumable.
            cur = (
                self.registry.get(self.st.version.id)
                if self.scope.startswith("tenant/") else None
            )
            if cur is None or cur.status != "live":
                self.registry.set_status(self.st.version.id, "canary")
        except Exception:
            self.st.state = "failed"
            self.server.complete_rollout(self, promote=False)
            raise
        self.st.state = "canary"
        # offline prior (ISSUE 20): when both candidate and live carry
        # lineage-linked eval-run scores and the candidate's OFFLINE
        # metric is worse, stretch the bake window — the online verdict
        # gets more evidence before promoting a model the fleet eval
        # already ranked below live. Never blocking, never a veto.
        try:
            from predictionio_tpu.evalfleet.tuning import (
                offline_prior_multiplier,
            )

            live = self.registry.live_version(
                self.st.version.engine_id, self.st.version.engine_variant
            )
            mult, why = offline_prior_multiplier(
                self.server.storage, self.st.version.engine_id,
                self.st.version.id, live.id if live is not None else None,
            )
            if mult > 1.0:
                self.config.bake_s *= mult
                log.info("%s; bake now %.0fs", why, self.config.bake_s)
        except Exception:
            log.debug("offline prior unavailable", exc_info=True)
        now_wall = time.time()
        if (
            resume_started_wall is not None
            and 0 < resume_started_wall <= now_wall
        ):
            self.st.started_at = time.monotonic() - (
                now_wall - resume_started_wall
            )
            self.st.started_wall = resume_started_wall
        else:
            self.st.started_at = time.monotonic()
            self.st.started_wall = now_wall
        self._persist(
            state="canary",
            version_id=self.st.version.id,
            config=self.config.to_dict(),
            started_wall=self.st.started_wall,
        )
        self._thread = threading.Thread(
            target=self._loop, name="rollout-verdict", daemon=True
        )
        self._thread.start()
        log.info(
            "canary started: version %s at %.0f%% traffic%s",
            self.st.version.id, self.config.fraction * 100,
            " (shadow)" if self.config.shadow else "",
        )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._shadow_pool is not None:
            self._shadow_pool.shutdown(wait=False)
        with self._stray_lock:
            strays = list(self._stray_shadows)
        for t in strays:
            t.join(timeout=2)
        with self._stray_lock:
            self._stray_shadows[:] = [
                s for s in self._stray_shadows if s.is_alive()
            ]

    # -- serving-path hooks ----------------------------------------------
    def record(self, variant: str, duration_s: float, error: bool) -> None:
        w = self.windows.get(variant)
        if w is not None:
            from predictionio_tpu.obs.tracing import current_trace_id

            # the serving path calls this on the handler thread, where
            # the request's trace id is ambient — it becomes the
            # window's worst-sample exemplar (ISSUE 16)
            w.add(duration_s, error, trace_id=current_trace_id())

    def record_agreement(self, agree: bool) -> None:
        self.windows[VARIANT_CANDIDATE].add_agreement(agree)

    def try_shadow(self) -> bool:
        """Bounded-concurrency gate for shadow mirrors (a slow candidate
        must not pile mirror threads up behind it)."""
        return self._shadow_inflight.acquire(blocking=False)

    def shadow_done(self) -> None:
        self._shadow_inflight.release()

    def run_shadow(self, fn) -> None:
        """Run a mirror off the response path on the persistent pool
        (per-request thread spawn would churn at serving QPS); falls
        back to a one-off thread if the pool closed mid-request so the
        caller's semaphore slot is always released by `fn`."""
        if self._shadow_pool is not None:
            try:
                self._shadow_pool.submit(fn)
                return
            except RuntimeError:
                pass  # pool shut down: the rollout just ended
        t = threading.Thread(target=fn, name="rollout-shadow", daemon=True)
        with self._stray_lock:
            self._stray_shadows[:] = [
                s for s in self._stray_shadows if s.is_alive()
            ]
            self._stray_shadows.append(t)
        t.start()

    # -- verdict loop -----------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                action, reason = self._tick()
            except Exception:
                log.exception("rollout verdict tick failed; retrying")
                continue
            if action != "wait":
                return

    def _tick(self) -> tuple[str, str]:
        live = self.windows[VARIANT_LIVE].stats()
        cand = self.windows[VARIANT_CANDIDATE].stats()
        elapsed = time.monotonic() - self.st.started_at
        action, reason = verdict(live, cand, self.config, elapsed)
        self.st.last_action, self.st.verdict_reason = action, reason
        if action == "promote":
            self.promote(reason)
        elif action == "rollback":
            self.rollback(reason)
        return action, reason

    # -- transitions ------------------------------------------------------
    def promote(self, reason: str = "operator promote") -> None:
        """Atomic hot-swap: candidate becomes live under the server's
        swap lock; the old runtime drains (in-flight queries keep their
        snapshot) rather than being dropped.

        The serving swap is the source of truth: once it lands, the
        controller state reflects it even if the registry write fails
        (a wedged 'canary' state would block every future rollout and
        invite an abort that marks the NOW-SERVING version rolled_back;
        `pio models promote` repairs a missed registry flip)."""
        self._stop.set()
        self.server.complete_rollout(self, promote=True)
        self.st.state = "promoted"
        self.st.verdict_reason = reason
        try:
            self.registry.promote(self.st.version.id)
        except Exception:
            self.st.verdict_reason = (
                f"{reason} — REGISTRY UPDATE FAILED; run "
                f"`pio models promote {self.st.version.id}`"
            )
            log.exception(
                "canary %s promoted in serving, but the registry status "
                "write failed", self.st.version.id,
            )
        self._persist(state="promoted", verdict_reason=reason)
        log.info("canary promoted: %s (%s)", self.st.version.id, reason)

    def rollback(self, reason: str) -> None:
        self._stop.set()
        self.server.complete_rollout(self, promote=False)
        self.st.state = "rolled_back"
        self.st.verdict_reason = reason
        try:
            self.registry.rollback(self.st.version.id, reason)
        except Exception:
            self.st.verdict_reason = (
                f"{reason} — REGISTRY UPDATE FAILED; run "
                f"`pio models rollback {self.st.version.id}`"
            )
            log.exception(
                "canary %s detached from serving, but the registry "
                "status write failed", self.st.version.id,
            )
        self._persist(state="rolled_back", verdict_reason=reason)
        log.warning("canary rolled back: %s (%s)", self.st.version.id, reason)

    def abort(self, reason: str = "operator abort") -> None:
        self.rollback(reason)
        self.st.state = "aborted"
        self._persist(state="aborted", verdict_reason=reason)

    # -- reporting --------------------------------------------------------
    def status(self) -> dict[str, Any]:
        return {
            "state": self.st.state,
            "version": self.st.version.to_dict(),
            "config": self.config.to_dict(),
            "elapsed_s": round(time.monotonic() - self.st.started_at, 1),
            "last_action": self.st.last_action,
            "reason": self.st.verdict_reason,
            "live": self.windows[VARIANT_LIVE].stats(),
            "candidate": self.windows[VARIANT_CANDIDATE].stats(),
        }


def resume_rollout(server, scope: Optional[str] = None):
    """Re-adopt a persisted mid-canary rollout after a restart (PR-5
    follow-up). `server` is anything RolloutController can drive — the
    QueryServer itself or a tenant rollout host. Returns the re-started
    controller, or None when there is nothing (or nothing valid) to
    resume.

    Double-checked against the registry: the persisted record says
    "canary", but if the version's registry status moved on (another
    server promoted/rolled it back while this one was down), the stale
    record is ignored — the registry is the source of truth."""
    storage = server.storage
    if scope is None:
        inst = server.runtime.instance
        scope = f"{inst.engine_id}/{inst.engine_variant}"
    rec = (
        LifecycleRecordStore(storage)
        .fold(ROLLOUT_ENTITY, scope)
        .get(scope)
    )
    if not rec or rec.get("state") != "canary":
        return None
    version = ModelRegistry(storage).get(rec.get("version_id") or "")
    if version is None:
        stale = "version record missing from the registry"
    elif scope.startswith("tenant/"):
        # tenant scopes share version records (two tenants of one
        # engine canary the same trained version by default), so the
        # GLOBAL status field cannot prove THIS scope's rollout
        # finished: another tenant promoting the shared version flips
        # it to "live" while this scope is still mid-bake. Only
        # globally disqualifying states stop a tenant resume —
        # rolled_back (judged bad somewhere) and archived (retention
        # may have collected the blob).
        stale = (
            f"version {version.id} is {version.status}"
            if version.status in ("rolled_back", "archived") else None
        )
    else:
        # the default scope IS the variant's one serving scope: any
        # move off "canary" means this rollout finished elsewhere
        stale = (
            f"version {version.id} is {version.status}"
            if version.status != "canary" else None
        )
    if stale is not None:
        # retire the stale per-scope record: left as "canary" it would
        # be re-considered — and its baseline warmed and pinned — on
        # every restart and sync pass forever
        try:
            LifecycleRecordStore(storage).append(
                ROLLOUT_ENTITY, scope,
                {"state": "aborted", "verdict_reason": f"not resumed: {stale}"},
            )
        except Exception:
            log.warning(
                "could not retire stale rollout record for scope %s",
                scope, exc_info=True,
            )
        log.warning(
            "persisted rollout for scope %s not resumed: %s", scope, stale
        )
        return None
    try:
        config = RolloutConfig.from_env(**(rec.get("config") or {}))
    except (TypeError, ValueError):
        log.warning(
            "persisted rollout config for scope %s is malformed; "
            "resuming with env defaults", scope,
        )
        config = RolloutConfig.from_env()
    controller = RolloutController(server, version, config, scope=scope)
    controller.start(resume_started_wall=rec.get("started_wall"))
    log.info(
        "re-adopted persisted rollout of %s (scope %s)", version.id, scope
    )
    return controller
