"""DataView: cached derived frames keyed by (query, data version)
(VERDICT r2 #7; reference data/view/DataView.scala:37-110)."""

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.data.view import DataView


def _storage(tmp_path):
    cfg = StorageConfig(
        sources={
            "SQL": SourceConfig(
                "SQL", "sqlite", {"PATH": str(tmp_path / "dv.db")}
            )
        },
        repositories={
            "METADATA": "SQL", "EVENTDATA": "SQL", "MODELDATA": "SQL",
        },
    )
    s = Storage(cfg)
    app_id = s.get_meta_data_apps().insert(App(0, "dvapp"))
    s.get_events().init_app(app_id)
    return s, app_id


def _seed(storage, app_id, n=60, seed=0):
    rng = np.random.RandomState(seed)
    storage.get_events().insert_batch(
        [
            Event(event="rate", entity_type="user",
                  entity_id=f"u{rng.randint(8)}",
                  target_entity_type="item",
                  target_entity_id=f"i{rng.randint(12)}",
                  properties={"rating": float(rng.randint(1, 6))})
            for _ in range(n)
        ],
        app_id,
    )


def test_cache_hit_and_write_invalidation(tmp_path):
    storage, app_id = _storage(tmp_path)
    _seed(storage, app_id)
    view = DataView(str(tmp_path / "view"))
    kwargs = dict(
        app_name="dvapp", entity_type="user", target_entity_type="item",
        event_names=["rate"], value_prop="rating",
    )
    f1 = view.find_frame(storage, **kwargs)
    base = dict(DataView.stats)
    f2 = view.find_frame(storage, **kwargs)
    assert DataView.stats["hits"] == base["hits"] + 1
    # cached frame is IDENTICAL to the folded one
    np.testing.assert_array_equal(f1.entity_idx, f2.entity_idx)
    np.testing.assert_array_equal(f1.target_idx, f2.target_idx)
    np.testing.assert_array_equal(f1.value, f2.value)
    assert f1.entity_vocab.to_dict() == f2.entity_vocab.to_dict()
    assert f1.target_vocab.to_dict() == f2.target_vocab.to_dict()
    assert f2.entity_type == "user" and f2.target_entity_type == "item"

    # ANY write to the namespace invalidates: next read refolds
    _seed(storage, app_id, n=1, seed=99)
    base = dict(DataView.stats)
    f3 = view.find_frame(storage, **kwargs)
    assert DataView.stats["misses"] == base["misses"] + 1
    assert len(f3) == len(f1) + 1


def test_second_train_skips_event_fold(tmp_path, monkeypatch):
    """The VERDICT's acceptance check: retraining an unchanged window must
    not re-scan the event store (asserted via a backend-call counter)."""
    from predictionio_tpu.data.storage.sqlite import SqliteEventStore
    from predictionio_tpu.workflow.core import run_train

    storage, app_id = _storage(tmp_path)
    _seed(storage, app_id, n=120)

    calls = {"n": 0}
    orig = SqliteEventStore.find_frame

    def counting(self, *a, **kw):
        calls["n"] += 1
        return orig(self, *a, **kw)

    monkeypatch.setattr(SqliteEventStore, "find_frame", counting)

    variant = {
        "id": "dv",
        "engineFactory":
            "predictionio_tpu.engines.recommendation.RecommendationEngine",
        "datasource": {"params": {
            "app_name": "dvapp",
            "use_data_view": True,
            "data_view_dir": str(tmp_path / "view"),
        }},
        "algorithms": [
            {"name": "als", "params": {"rank": 4, "num_iterations": 2}}
        ],
    }
    inst1 = run_train(storage, variant)
    assert inst1.status == "COMPLETED"
    folds_first = calls["n"]
    assert folds_first >= 1

    inst2 = run_train(storage, variant)
    assert inst2.status == "COMPLETED"
    assert calls["n"] == folds_first  # second train: zero event folds

    # new data → the fold runs again
    _seed(storage, app_id, n=5, seed=7)
    run_train(storage, variant)
    assert calls["n"] == folds_first + 1


def test_superseded_cache_entries_evicted(tmp_path):
    import os

    storage, app_id = _storage(tmp_path)
    _seed(storage, app_id)
    view_dir = str(tmp_path / "view")
    view = DataView(view_dir)
    kwargs = dict(app_name="dvapp", entity_type="user",
                  target_entity_type="item", event_names=["rate"],
                  value_prop="rating")
    for i in range(4):  # write → refold cycle, 4 versions of one query
        view.find_frame(storage, **kwargs)
        _seed(storage, app_id, n=1, seed=100 + i)
    frames = [f for f in os.listdir(view_dir) if f.startswith("frame_")]
    assert len(frames) == 1  # only the newest version survives


def test_signature_distinguishes_delete_plus_replayed_insert(tmp_path):
    """The collision case: delete one event, then insert one with a
    HISTORICAL creationTime — count and max(creationTime) are unchanged,
    but the signature must still move (code-review r3)."""
    import datetime as dt

    from predictionio_tpu.data.storage.base import EventQuery

    storage, app_id = _storage(tmp_path)
    _seed(storage, app_id, n=10)
    events = storage.get_events()
    s0 = events.data_signature(app_id)
    victim = next(iter(events.find(EventQuery(app_id=app_id))))
    events.delete(victim.event_id, app_id)
    old_t = dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc)
    events.insert(
        Event(event="rate", entity_type="user", entity_id="replayed",
              target_entity_type="item", target_entity_id="i0",
              event_time=old_t, creation_time=old_t),
        app_id,
    )
    assert events.data_signature(app_id) != s0
