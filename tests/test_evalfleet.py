"""Fleet-scale evaluation & auto-tuning tests (ISSUE 20): param-space
DSL, combinable metric partials, durable EvalRun/EvalResult records with
exactly-once convergence, the eval driver's fan-out/re-dispatch/finalize
loop, chaos kill -9 of an eval worker mid-shard, grid-grouped fleet
metrics matching the sequential MetricEvaluator to 1e-5, the tuning→
retrain loop (preset park → periodic overlay → lineage stamp), the
adaptive CAS settle window, and the canary offline prior."""

import json
import math
import os
import threading
import time

import pytest

from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.deploy.scheduler import (
    JobQueue,
    SchedulerConfig,
    storage_config_to_json,
)
from predictionio_tpu.evalfleet.driver import (
    EVAL_DRIVER_THREAD,
    EvalDriver,
    EvalDriverConfig,
)
from predictionio_tpu.evalfleet.records import EvalRecordStore
from predictionio_tpu.evalfleet.specs import (
    EvalSpec,
    HeldOutRMSE,
    MAPAtK,
    NDCGAtK,
    ParamAxis,
    PrecisionAtK,
    combine_partials,
    expand_points,
    group_points,
    metric_finalize,
    metric_partial,
    point_fragment,
    resolve_metric,
)
from predictionio_tpu.evalfleet.tuning import (
    PresetStore,
    RetrainPreset,
    apply_preset,
    offline_prior_multiplier,
    park_winner,
    tune,
)
from predictionio_tpu.fleet.coordinator import (
    FleetConfig,
    FleetMember,
    measure_write_visibility_skew,
)

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_DIR = os.path.dirname(TESTS_DIR)

GRID_VARIANT = {
    "id": "grid",
    "engineFactory": "sample_engine.GridEngineFactory",
    "datasource": {"params": {"folds": 2, "queries": 4}},
    "preparator": {"params": {"id": 1}},
    "algorithms": [{"name": "grid", "params": {"weight": 0.0}}],
    "serving": {},
}

WEIGHTS = [0.05, 0.15, 0.25, 0.37, 0.45, 0.55, 0.65, 0.75]
BEST_INDEX = 3  # weight 0.37 == GridAlgo.BEST_WEIGHT


def _grid_spec(weights=WEIGHTS, folds=2, sleep_s=0.0):
    variant = json.loads(json.dumps(GRID_VARIANT))
    if sleep_s:
        variant["datasource"]["params"]["sleep_s"] = sleep_s
    return EvalSpec(
        variant=variant,
        axes=[ParamAxis(path="algorithms.0.params.weight",
                        values=list(weights))],
        metric={"class": "sample_engine.GridScore"},
        folds=folds,
    )


def _scheduler_config(tmp_path, **kw) -> SchedulerConfig:
    cfg = SchedulerConfig(
        poll_interval_s=0.1,
        heartbeat_interval_s=0.2,
        stale_after_s=1.0,
        log_dir=str(tmp_path / "job-logs"),
        child_env={
            "PYTHONPATH": os.pathsep.join([REPO_DIR, TESTS_DIR]),
            "JAX_PLATFORMS": "cpu",
        },
    )
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def _wait_for(predicate, timeout=60.0, interval=0.1, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def _run_shards_inprocess(storage, driver, run, tmp_path):
    """Execute every pending shard of `run` by calling the eval worker's
    main() in-process — the subprocess contract without the subprocess."""
    from predictionio_tpu.evalfleet import worker as eval_worker

    for job_id in list(run.shards):
        job = driver.queue.get(job_id)
        spec_path = tmp_path / f"{job_id}.spec.json"
        result_path = tmp_path / f"{job_id}.result.json"
        spec_path.write_text(json.dumps({
            "job_id": job_id,
            "storage": storage_config_to_json(storage.config),
            "variant": job.variant,
            "result_path": str(result_path),
        }))
        rc = eval_worker.main(["worker", str(spec_path)])
        assert rc == 0, f"eval shard {job_id} exited {rc}"


@pytest.fixture()
def mem_storage():
    return Storage(StorageConfig(
        sources={"M": SourceConfig("M", "memory", {})},
        repositories={
            "METADATA": "M", "EVENTDATA": "M", "MODELDATA": "M",
        },
    ))


# ---------------------------------------------------------------------------
# param-space DSL + metric specs
# ---------------------------------------------------------------------------


class TestSpecDSL:
    def test_expand_points_axis_major_and_isolated(self):
        spec = EvalSpec(
            variant=dict(GRID_VARIANT),
            axes=[
                ParamAxis("algorithms.0.params.weight", [0.1, 0.2]),
                ParamAxis("datasource.params.queries", [4, 8, 16]),
            ],
        )
        points = expand_points(spec)
        assert len(points) == 6
        # axis-major: first axis varies slowest
        assert [p["algorithms"][0]["params"]["weight"] for p in points] == [
            0.1, 0.1, 0.1, 0.2, 0.2, 0.2,
        ]
        assert [p["datasource"]["params"]["queries"] for p in points] == [
            4, 8, 16, 4, 8, 16,
        ]
        # deep copies: mutating one point leaks nowhere
        points[0]["algorithms"][0]["params"]["weight"] = 99
        assert points[3]["algorithms"][0]["params"]["weight"] == 0.2
        assert GRID_VARIANT["algorithms"][0]["params"]["weight"] == 0.0

    def test_range_expansion(self):
        lin = ParamAxis.from_dict({
            "path": "algorithms.0.params.w",
            "range": {"from": 0.0, "to": 1.0, "steps": 5},
        })
        assert lin.values == pytest.approx([0.0, 0.25, 0.5, 0.75, 1.0])
        log = ParamAxis.from_dict({
            "path": "algorithms.0.params.w",
            "range": {"from": 0.01, "to": 1.0, "steps": 3, "scale": "log"},
        })
        assert log.values == pytest.approx([0.01, 0.1, 1.0])
        with pytest.raises(ValueError):
            ParamAxis.from_dict({
                "path": "algorithms.0.params.w",
                "range": {"from": -1, "to": 1, "steps": 2, "scale": "log"},
            })

    def test_axis_validation(self):
        with pytest.raises(ValueError):  # not a stage key
            ParamAxis.from_dict({"path": "engineFactory", "values": [1]})
        with pytest.raises(ValueError):  # no values
            ParamAxis.from_dict({"path": "serving.params.x"})
        with pytest.raises(ValueError):  # empty values
            ParamAxis.from_dict({"path": "serving.params.x", "values": []})

    def test_set_path_errors(self):
        spec = EvalSpec(
            variant=dict(GRID_VARIANT),
            axes=[ParamAxis("algorithms.5.params.weight", [1])],
        )
        with pytest.raises(ValueError):  # list index out of range
            expand_points(spec)

    def test_group_points_by_grid_compatibility(self):
        # same datasource/preparator/serving + single same-named algo
        # → one grid group regardless of algo params
        spec = _grid_spec(weights=[0.1, 0.2, 0.3], folds=0)
        assert group_points(expand_points(spec)) == [[0, 1, 2]]
        # a datasource axis splits the space into incompatible groups
        spec2 = EvalSpec(
            variant=dict(GRID_VARIANT),
            axes=[
                ParamAxis("algorithms.0.params.weight", [0.1, 0.2]),
                ParamAxis("datasource.params.queries", [4, 8]),
            ],
        )
        groups = group_points(expand_points(spec2))
        assert sorted(groups) == [[0, 2], [1, 3]]

    def test_point_fragment_strips_non_stage_keys(self):
        frag = point_fragment(expand_points(_grid_spec(folds=0))[0])
        assert set(frag) <= {"datasource", "preparator", "algorithms",
                             "serving"}
        assert "engineFactory" not in frag

    def test_spec_roundtrip(self, tmp_path):
        spec = _grid_spec()
        path = tmp_path / "eval.json"
        path.write_text(json.dumps(spec.to_dict()))
        back = EvalSpec.load(str(path))
        assert back.to_dict() == spec.to_dict()

    def test_spec_requires_engine_factory(self):
        with pytest.raises(ValueError):
            EvalSpec(variant={"id": "x"})


class TestMetrics:
    def test_resolve_by_name_and_class(self):
        m = resolve_metric("map@5")
        assert isinstance(m, MAPAtK) and m.k == 5
        m = resolve_metric({"name": "precision", "k": 3})
        assert isinstance(m, PrecisionAtK) and m.k == 3
        m = resolve_metric({"name": "ndcg@7"})
        assert isinstance(m, NDCGAtK) and m.k == 7
        m = resolve_metric("rmse")
        assert isinstance(m, HeldOutRMSE) and not m.higher_is_better
        m = resolve_metric({"class": "sample_engine.GridScore"})
        assert m.header() == "GridScore"
        with pytest.raises(ValueError):
            resolve_metric("nope")
        with pytest.raises(ValueError):
            resolve_metric(42)

    def test_ranking_metrics(self):
        data = [(None, [(
            None,
            {"items": ["a", "b", "x", "c"]},
            {"items": ["a", "c", "d"]},
        )])]
        p = resolve_metric("precision@4").calculate(None, data)
        assert p == pytest.approx(2 / 4)
        ap = resolve_metric("map@4").calculate(None, data)
        # hits at ranks 1 and 4: (1/1 + 2/4) / min(3, 4)
        assert ap == pytest.approx((1.0 + 0.5) / 3)
        ndcg = resolve_metric("ndcg@4").calculate(None, data)
        dcg = 1 / math.log2(2) + 1 / math.log2(5)
        idcg = sum(1 / math.log2(i + 2) for i in range(3))
        assert ndcg == pytest.approx(dcg / idcg)

    def test_rmse_partials_pool_exactly(self):
        # pooled RMSE over both folds != mean of per-fold RMSEs; the
        # partial contract must produce the POOLED value
        fold_a = [(None, [(None, {"rating": 3.0}, {"rating": 1.0})])]
        fold_b = [(None, [(None, {"rating": 5.0}, {"rating": 4.0}),
                          (None, {"rating": 2.0}, {"rating": 2.0})])]
        m = HeldOutRMSE()
        parts = [metric_partial(m, None, fold_a),
                 metric_partial(m, None, fold_b)]
        total, count = combine_partials(parts)
        combined = metric_finalize(m, total, count)
        pooled = m.calculate(None, fold_a + fold_b)
        assert combined == pytest.approx(pooled, abs=1e-12)
        per_fold_mean = (m.calculate(None, fold_a)
                         + m.calculate(None, fold_b)) / 2
        assert abs(combined - per_fold_mean) > 1e-6

    def test_average_metric_partials_match_full_calculation(self):
        data = [
            (None, [(None, {"items": ["a"]}, {"items": ["a", "b"]})]),
            (None, [(None, {"items": ["b", "c"]}, {"items": ["c"]})]),
        ]
        m = resolve_metric("precision@2")
        parts = [metric_partial(m, None, [fold]) for fold in data]
        total, count = combine_partials(parts)
        assert metric_finalize(m, total, count) == pytest.approx(
            m.calculate(None, data), abs=1e-12
        )


# ---------------------------------------------------------------------------
# durable records: idempotency, fold merge, lineage, GC
# ---------------------------------------------------------------------------


class TestEvalRecords:
    def test_partials_idempotent_and_folds_merge(self, mem_storage):
        rec = EvalRecordStore(mem_storage)
        run = rec.create_run("eng", {}, 2, 1, 2, "GridScore")
        # a requeued shard rewrites the SAME fold field — no duplicate
        rec.record_partial(run.id, 0, 0, {"sum": 1.0, "count": 2})
        rec.record_partial(run.id, 0, 0, {"sum": 1.5, "count": 2},
                           params={"algorithms": []})
        rec.record_partial(run.id, 0, 1, {"sum": 2.0, "count": 2})
        results = rec.results(run.id)
        assert set(results) == {0}
        partials = rec.point_partials(results[0])
        assert set(partials) == {"fold_0", "fold_1"}
        assert partials["fold_0"] == {"sum": 1.5, "count": 2}  # LWW
        assert results[0]["params"] == {"algorithms": []}

    def test_run_crud_and_filters(self, mem_storage):
        rec = EvalRecordStore(mem_storage)
        a = rec.create_run("e1", {}, 1, 1, 1, "m", tenant="acme")
        time.sleep(0.01)
        b = rec.create_run("e2", {}, 1, 1, 1, "m")
        rec.update_run(b.id, status="completed", winner_index=0)
        got = rec.get_run(b.id)
        assert got.status == "completed" and got.winner_index == 0
        assert [r.id for r in rec.list_runs()] == [b.id, a.id]
        assert [r.id for r in rec.list_runs(engine_id="e1")] == [a.id]
        assert [r.id for r in rec.list_runs(status="completed")] == [b.id]
        assert [r.id for r in rec.list_runs(tenant="acme")] == [a.id]
        assert rec.get_run("eval-nope") is None

    def test_lineage_link(self, mem_storage):
        rec = EvalRecordStore(mem_storage)
        run = rec.create_run("eng", {}, 1, 1, 1, "m")
        rec.link_model_version(run.id, "mv-1", job_id="job-x")
        rec.link_model_version(run.id, "mv-2", job_id="job-y")
        got = rec.get_run(run.id)
        assert set(got.links) == {"mv-1", "mv-2"}
        assert got.links["mv-1"]["job_id"] == "job-x"
        assert got.winner_model_version == "mv-2"

    def test_gc_keeps_running_and_newest(self, mem_storage):
        rec = EvalRecordStore(mem_storage)
        runs = []
        for i in range(4):
            r = rec.create_run(f"e{i}", {}, 1, 1, 1, "m")
            rec.record_partial(r.id, 0, None, {"sum": 1, "count": 1})
            runs.append(r)
            time.sleep(0.01)
        # oldest two terminal, third running, newest terminal
        rec.update_run(runs[0].id, status="completed")
        rec.update_run(runs[1].id, status="failed")
        rec.update_run(runs[3].id, status="completed")
        assert rec.gc(keep=2) > 0
        left = {r.id for r in rec.list_runs()}
        # the running run survives any GC; oldest terminal beyond keep=2
        # (runs[0]) is purged with its results
        assert runs[2].id in left and runs[0].id not in left
        assert runs[1].id in left and runs[3].id in left
        assert rec.results(runs[0].id) == {}

    def test_purge_run_drops_results(self, mem_storage):
        rec = EvalRecordStore(mem_storage)
        run = rec.create_run("eng", {}, 2, 1, 1, "m")
        rec.record_partial(run.id, 0, None, {"sum": 1, "count": 1})
        rec.record_partial(run.id, 1, None, {"sum": 2, "count": 1})
        assert rec.purge_run(run.id) >= 3
        assert rec.get_run(run.id) is None
        assert rec.results(run.id) == {}


# ---------------------------------------------------------------------------
# driver: fan-out, in-process convergence, parity with MetricEvaluator
# ---------------------------------------------------------------------------


class TestEvalDriver:
    def test_fleet_parity_with_sequential_metric_evaluator(
        self, fresh_storage, tmp_path
    ):
        """Grid-grouped fleet eval (per-fold shards, combinable partials,
        durable records) reproduces the sequential MetricEvaluator's
        per-point scores to 1e-5 on the same splits."""
        spec = _grid_spec(weights=WEIGHTS[:6])
        driver = EvalDriver(fresh_storage)
        run = driver.submit(spec)
        # 6 compatible points → 1 grid group × 2 folds = 2 shards
        assert run.num_points == 6 and run.num_groups == 1
        assert len(run.shards) == 2
        _run_shards_inprocess(fresh_storage, driver, run, tmp_path)
        run = driver.poll_once(run.id)
        assert run.status == "completed"

        # sequential reference on the same splits
        import sample_engine
        from predictionio_tpu.controller.evaluation import MetricEvaluator
        from predictionio_tpu.core.base import RuntimeContext, WorkflowParams

        engine = sample_engine.GridEngineFactory().apply()
        points = expand_points(spec)
        eps = [engine.params_from_variant_json(p) for p in points]
        ctx = RuntimeContext(storage=fresh_storage, mesh=None, mode="eval")
        eval_data = engine.batch_eval(ctx, eps)
        seq = MetricEvaluator(sample_engine.GridScore()).evaluate(
            ctx, None, eval_data, WorkflowParams()
        )

        fleet_scores = driver.scores(run)
        assert all(s["complete"] for s in fleet_scores)
        for fleet, ref in zip(fleet_scores, seq.engine_params_scores):
            assert fleet["score"] == pytest.approx(ref.score, abs=1e-5)
        assert run.winner_index == seq.best_index == BEST_INDEX
        assert run.winner_params["algorithms"][0]["params"]["weight"] == \
            pytest.approx(0.37)

    def test_grid_group_trains_one_program_per_fold(self, fresh_storage):
        """Every point in a grid-compatible group shares ONE train_grid
        device program per fold (GridModel.grid_size == group size), and
        fold_indices narrows the evaluated splits."""
        import sample_engine
        from predictionio_tpu.core.base import RuntimeContext

        spec = _grid_spec(weights=WEIGHTS)
        engine = sample_engine.GridEngineFactory().apply()
        eps = [engine.params_from_variant_json(p)
               for p in expand_points(spec)]
        ctx = RuntimeContext(storage=fresh_storage, mesh=None, mode="eval")
        out = engine.batch_eval(ctx, eps, fold_indices=[1])
        assert len(out) == len(eps)
        for _ep, data in out:
            assert len(data) == 1  # only fold 1 evaluated
            info, qpas = data[0]
            assert info.id == 1
            for _q, p, _a in qpas:
                assert p.grid_size == len(eps)
        with pytest.raises(ValueError):
            engine.batch_eval(ctx, eps, fold_indices=[5])

    def test_redispatch_and_exhaustion(self, fresh_storage, tmp_path):
        spec = _grid_spec(weights=[0.1, 0.2], folds=0)
        driver = EvalDriver(
            fresh_storage,
            EvalDriverConfig(poll_interval_s=0.05, redispatch_limit=1),
        )
        run = driver.submit(spec)
        assert len(run.shards) == 1
        (job_id,) = run.shards
        queue = JobQueue(fresh_storage)
        queue.update(job_id, status="failed", last_error="boom")
        run = driver.poll_once(run.id)
        # one fresh shard job enqueued; the failed one marked redispatched
        assert run.status == "running" and len(run.shards) == 2
        assert run.shards[job_id]["redispatched"] == 1
        new_id = next(j for j in run.shards if j != job_id)
        assert queue.get(new_id).status == "queued"
        # fail the replacement too → budget exhausted → run fails
        queue.update(new_id, status="failed", last_error="boom again")
        run = driver.poll_once(run.id)
        assert run.status == "failed"
        assert "exhausted" in run.last_error
        # but completed records still win: a redispatch that landed
        # between polls would have flipped complete instead
        _run_shards_inprocess(
            fresh_storage, driver,
            type(run)(id=run.id, engine_id=run.engine_id,
                      shards={new_id: run.shards[new_id]}),
            tmp_path,
        )
        assert all(s["complete"] for s in driver.scores(
            driver.records.get_run(run.id)))

    def test_driver_thread_start_stop_joins(self, mem_storage):
        rec = EvalRecordStore(mem_storage)
        run = rec.create_run("eng", {"metric": "map"}, 0, 0, 1, "MAPAtK@10")
        driver = EvalDriver(mem_storage,
                            EvalDriverConfig(poll_interval_s=0.05))
        driver.start(run.id)
        _wait_for(
            lambda: (rec.get_run(run.id) or run).status == "completed",
            timeout=10, what="empty run to finalize",
        )
        driver.stop()
        assert not any(
            t.name == EVAL_DRIVER_THREAD and t.is_alive()
            for t in threading.enumerate()
        )

    def test_status_payload(self, fresh_storage, tmp_path):
        spec = _grid_spec(weights=[0.3, 0.4], folds=2)
        driver = EvalDriver(fresh_storage)
        run = driver.submit(spec)
        st = driver.status(run.id)
        assert st["points_total"] == 2 and st["points_done"] == 0
        assert len(st["shards"]) == 2
        assert {s["status"] for s in st["shards"]} == {"queued"}
        _run_shards_inprocess(fresh_storage, driver, run, tmp_path)
        st = driver.status(run.id)
        assert st["points_done"] == 2
        assert all(s["complete"] for s in st["points"])
        with pytest.raises(KeyError):
            driver.status("eval-nope")


# ---------------------------------------------------------------------------
# chaos e2e: kill -9 an eval worker mid-shard on a 2-worker fleet
# ---------------------------------------------------------------------------


class TestChaosFleetEval:
    def test_kill9_mid_shard_converges_exactly_once(
        self, fresh_storage, tmp_path
    ):
        """2-worker fleet, 8-point grid, one worker kill -9'd while its
        shard sleeps inside read_eval: the survivor steals the stale
        claim, re-runs the shard, and every point converges to exactly
        one EvalResult (idempotent fold fields, no duplicates)."""
        spec = _grid_spec(weights=WEIGHTS, folds=2, sleep_s=0.6)
        members = [
            FleetMember(
                fresh_storage,
                scheduler_config=_scheduler_config(tmp_path / f"w{i}"),
                fleet_config=FleetConfig(
                    heartbeat_interval_s=0.1, adaptive_settle=False
                ),
            )
            for i in range(2)
        ]
        driver = EvalDriver(
            fresh_storage, EvalDriverConfig(poll_interval_s=0.2)
        )
        queue = JobQueue(fresh_storage)
        for m in members:
            m.start()
        victim = None
        try:
            run = driver.submit(spec)
            assert run.num_points == 8 and len(run.shards) == 2

            def running_jobs():
                return [j for j in queue.list()
                        if j.id in run.shards and j.status == "running"]

            _wait_for(lambda: running_jobs(), timeout=30,
                      what="a shard to start running")
            # kill -9 the member that owns a running shard
            owner = running_jobs()[0].worker_id
            victim = next(m for m in members if m.worker_id == owner)
            victim.stop(kill_child=True)

            run = driver.wait(run.id, timeout_s=120)
            assert run.status == "completed", run.last_error
        finally:
            for m in members:
                if m is not victim:
                    m.stop()

        # exactly-once: one EvalResult per point, each with exactly the
        # two expected fold fields — re-runs rewrote, never duplicated
        results = driver.records.results(run.id)
        assert sorted(results) == list(range(8))
        for rec in results.values():
            assert set(driver.records.point_partials(rec)) == {
                "fold_0", "fold_1"
            }
        assert run.winner_index == BEST_INDEX
        assert run.winner_params["algorithms"][0]["params"]["weight"] == \
            pytest.approx(0.37)
        # at least one shard was re-claimed after the kill
        attempts = [queue.get(j).attempt for j in run.shards]
        generations = [queue.get(j).generation for j in run.shards]
        assert max(attempts) >= 1 or max(generations) >= 2


# ---------------------------------------------------------------------------
# the tuning→retrain loop
# ---------------------------------------------------------------------------


class TestTuningLoop:
    def test_tune_parks_winner_and_next_retrain_trains_it(
        self, fresh_storage, tmp_path
    ):
        """`pio tune` end-to-end: fleet eval → winner parked as retrain
        preset → the NEXT periodic retrain trains the winning params and
        stamps the lineage pointer back onto the eval run."""
        spec = _grid_spec(weights=[0.1, 0.37, 0.7], folds=0)
        member = FleetMember(
            fresh_storage,
            scheduler_config=_scheduler_config(tmp_path),
            fleet_config=FleetConfig(
                heartbeat_interval_s=0.1, adaptive_settle=False
            ),
        )
        member.start()
        try:
            driver = EvalDriver(
                fresh_storage, EvalDriverConfig(poll_interval_s=0.2)
            )
            run, preset = tune(
                fresh_storage, spec, timeout_s=90, driver=driver
            )
            assert run.status == "completed" and preset is not None
            assert preset.params["algorithms"][0]["params"]["weight"] == \
                pytest.approx(0.37)
            assert PresetStore(fresh_storage).get("grid").run_id == run.id

            # periodic retrain with the ORIGINAL (weight 0.0) variant
            queue = JobQueue(fresh_storage)
            job = queue.submit(dict(GRID_VARIANT), period_s=0.2,
                               timeout_s=60)
            _wait_for(
                lambda: queue.get(job.id).status == "completed",
                timeout=60, what="periodic train job",
            )
            # the follow-up job carries the parked winner + lineage marker

            def next_job():
                return [j for j in queue.list()
                        if j.id != job.id and j.kind == "train"]

            _wait_for(lambda: next_job(), timeout=10,
                      what="next periodic job")
            nxt = next_job()[0]
            assert nxt.variant["algorithms"][0]["params"]["weight"] == \
                pytest.approx(0.37)
            assert nxt.variant["evalRun"] == run.id
            _wait_for(
                lambda: queue.get(nxt.id).status == "completed",
                timeout=60, what="winner retrain job",
            )
        finally:
            member.stop()
        done = JobQueue(fresh_storage).get(nxt.id)
        assert done.model_version
        linked = EvalRecordStore(fresh_storage).get_run(run.id)
        # lineage pointer: winning params → the ModelVersion they trained
        # (a further period may have linked again; membership is the
        # invariant, winner_model_version tracks the newest link)
        assert done.model_version in linked.links
        assert linked.winner_model_version
        assert linked.links[done.model_version]["job_id"] == nxt.id

    def test_preset_tenant_scoping(self, mem_storage):
        store = PresetStore(mem_storage)
        store.park(RetrainPreset(engine_id="e", params={"serving": {}},
                                 run_id="eval-g"))
        store.park(RetrainPreset(engine_id="e", params={"serving": {}},
                                 tenant="acme", run_id="eval-t"))
        assert store.get("e").run_id == "eval-g"
        assert store.get("e", tenant="acme").run_id == "eval-t"
        # unknown tenant falls back to the global preset
        assert store.get("e", tenant="other").run_id == "eval-g"
        assert store.clear("e", tenant="acme") > 0
        assert store.get("e", tenant="acme").run_id == "eval-g"

    def test_apply_preset_overlay_and_marker(self, mem_storage):
        variant = dict(GRID_VARIANT)
        # no preset → identity
        assert apply_preset(mem_storage, variant, "grid") is variant
        PresetStore(mem_storage).park(RetrainPreset(
            engine_id="grid",
            params={"algorithms": [{"name": "grid",
                                    "params": {"weight": 0.37}}]},
            run_id="eval-w",
        ))
        merged = apply_preset(mem_storage, variant, "grid")
        assert merged["algorithms"][0]["params"]["weight"] == 0.37
        assert merged["evalRun"] == "eval-w"
        # non-searched stages untouched
        assert merged["datasource"] == variant["datasource"]
        assert merged["engineFactory"] == variant["engineFactory"]
        # the original variant is not mutated
        assert "evalRun" not in variant

    def test_park_winner_requires_completed_run(self, mem_storage):
        rec = EvalRecordStore(mem_storage)
        run = rec.create_run("e", {}, 1, 1, 1, "m")
        with pytest.raises(ValueError):
            park_winner(mem_storage, run)


class TestOfflinePrior:
    def _runs(self, storage, cand_score, live_score,
              metric="map@5", live_metric=None):
        rec = EvalRecordStore(storage)
        live_run = rec.create_run("e", {"metric": live_metric or metric},
                                  1, 1, 1,
                                  resolve_metric(live_metric or metric)
                                  .header())
        rec.update_run(live_run.id, status="completed",
                       winner_score=live_score)
        rec.link_model_version(live_run.id, "mv-live")
        time.sleep(0.01)
        cand_run = rec.create_run("e", {"metric": metric}, 1, 1, 1,
                                  resolve_metric(metric).header())
        rec.update_run(cand_run.id, status="completed",
                       winner_score=cand_score)
        rec.link_model_version(cand_run.id, "mv-cand")
        return rec

    def test_worse_candidate_stretches_bake(self, mem_storage, monkeypatch):
        monkeypatch.setenv("PIO_TUNE_STRICT_BAKE", "3.0")
        self._runs(mem_storage, cand_score=0.2, live_score=0.8)
        mult, reason = offline_prior_multiplier(
            mem_storage, "e", "mv-cand", "mv-live"
        )
        assert mult == 3.0 and "worse than live" in reason

    def test_better_or_equal_candidate_keeps_bake(self, mem_storage):
        self._runs(mem_storage, cand_score=0.9, live_score=0.8)
        assert offline_prior_multiplier(
            mem_storage, "e", "mv-cand", "mv-live"
        ) == (1.0, None)

    def test_missing_evidence_is_neutral(self, mem_storage):
        # no runs at all / no live version → never blocks
        assert offline_prior_multiplier(
            mem_storage, "e", "mv-cand", "mv-live"
        ) == (1.0, None)
        assert offline_prior_multiplier(
            mem_storage, "e", "mv-cand", None
        ) == (1.0, None)

    def test_metric_mismatch_is_neutral(self, mem_storage):
        self._runs(mem_storage, cand_score=0.2, live_score=0.8,
                   metric="map@5", live_metric="ndcg@5")
        assert offline_prior_multiplier(
            mem_storage, "e", "mv-cand", "mv-live"
        ) == (1.0, None)

    def test_flag_off_disables_prior(self, mem_storage, monkeypatch):
        monkeypatch.setenv("PIO_TUNE_PRIOR", "0")
        self._runs(mem_storage, cand_score=0.2, live_score=0.8)
        assert offline_prior_multiplier(
            mem_storage, "e", "mv-cand", "mv-live"
        ) == (1.0, None)


# ---------------------------------------------------------------------------
# adaptive CAS claim settle window
# ---------------------------------------------------------------------------


class TestAdaptiveSettle:
    def test_pinned_env_wins(self, mem_storage, monkeypatch):
        monkeypatch.setenv("PIO_CAS_SETTLE_S", "0.75")
        m = FleetMember(mem_storage)
        m._adapt_claim_settle()
        assert m.scheduler.config.claim_settle_s == 0.75

    def test_bad_pin_keeps_default(self, mem_storage, monkeypatch):
        monkeypatch.setenv("PIO_CAS_SETTLE_S", "fast")
        m = FleetMember(mem_storage)
        before = m.scheduler.config.claim_settle_s
        m._adapt_claim_settle()
        assert m.scheduler.config.claim_settle_s == before

    def test_adaptive_clamps_to_floor(self, mem_storage, monkeypatch):
        monkeypatch.delenv("PIO_CAS_SETTLE_S", raising=False)
        # in-memory visibility skew is ~0 → the floor clamp holds
        m = FleetMember(mem_storage)
        m._adapt_claim_settle()
        assert m.scheduler.config.claim_settle_s == pytest.approx(0.02)

    def test_adaptive_clamps_to_ceiling(self, mem_storage, monkeypatch):
        monkeypatch.delenv("PIO_CAS_SETTLE_S", raising=False)
        from predictionio_tpu.fleet import coordinator as coord

        monkeypatch.setattr(
            coord, "measure_write_visibility_skew", lambda s: 100.0
        )
        m = FleetMember(mem_storage)
        m._adapt_claim_settle()
        assert m.scheduler.config.claim_settle_s == pytest.approx(2.0)

    def test_disabled_keeps_configured_default(self, mem_storage,
                                               monkeypatch):
        monkeypatch.delenv("PIO_CAS_SETTLE_S", raising=False)
        m = FleetMember(
            mem_storage, fleet_config=FleetConfig(adaptive_settle=False)
        )
        before = m.scheduler.config.claim_settle_s
        m._adapt_claim_settle()
        assert m.scheduler.config.claim_settle_s == before

    def test_probe_measures_and_cleans_up(self, mem_storage):
        from predictionio_tpu.deploy.registry import LifecycleRecordStore

        skew = measure_write_visibility_skew(mem_storage, probes=2)
        assert skew >= 0.0
        store = LifecycleRecordStore(mem_storage)
        assert store.fold("pio_settle_probe") == {}


# ---------------------------------------------------------------------------
# surfacing: admin GET /evals
# ---------------------------------------------------------------------------


class TestAdminEvals:
    @pytest.fixture()
    def admin(self, fresh_storage):
        from predictionio_tpu.tools.admin import AdminServer

        srv = AdminServer(fresh_storage, ip="127.0.0.1", port=0)
        port = srv.start()
        yield fresh_storage, port
        srv.stop()

    def _get(self, port, path):
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                return r.status, json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"null")

    def test_list_and_detail(self, admin):
        storage, port = admin
        driver = EvalDriver(storage)
        run = driver.submit(_grid_spec(weights=[0.3, 0.4], folds=2),
                            tenant="acme")
        status, listing = self._get(port, "/evals")
        assert status == 200
        assert [r["id"] for r in listing] == [run.id]
        assert listing[0]["tenant"] == "acme"
        status, listing = self._get(port, "/evals?tenant=other")
        assert status == 200 and listing == []
        status, detail = self._get(port, f"/evals/{run.id}")
        assert status == 200
        assert detail["points_total"] == 2
        assert len(detail["shards"]) == 2
        assert self._get(port, "/evals/eval-nope")[0] == 404
