"""Deploy-server HTTP tests: query serving, hot reload, feedback loop."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.data.api.server import EventServer, EventServerConfig
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import AccessKey, App
from predictionio_tpu.workflow.core import run_train
from predictionio_tpu.workflow.server import (
    QueryServer,
    QueryServerConfig,
    latest_completed_runtime,
)

VARIANT = {
    "id": "qsrv",
    "engineFactory": "predictionio_tpu.engines.recommendation.RecommendationEngine",
    "datasource": {"params": {"app_name": "qapp"}},
    "algorithms": [
        {"name": "als", "params": {"rank": 8, "num_iterations": 6}}
    ],
}


def seed(storage, n_users=8, seed=0):
    apps = storage.get_meta_data_apps()
    app = apps.get_by_name("qapp")
    app_id = app.id if app else apps.insert(App(id=0, name="qapp"))
    events = storage.get_events()
    events.init_app(app_id)
    rng = np.random.RandomState(seed)
    batch = []
    for u in range(n_users):
        for _ in range(20):
            i = rng.randint(0, 5) + (u % 2) * 5
            batch.append(
                Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties={"rating": 5.0},
                )
            )
    events.insert_batch(batch, app_id)
    return app_id


def post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "null")


def get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=15
    ) as r:
        return r.status, r.read().decode()


@pytest.fixture()
def served(fresh_storage):
    seed(fresh_storage)
    run_train(fresh_storage, VARIANT)
    runtime = latest_completed_runtime(fresh_storage, "qsrv", "0", "qsrv")
    srv = QueryServer(
        fresh_storage, runtime, QueryServerConfig(ip="127.0.0.1", port=0)
    )
    port = srv.start()
    yield fresh_storage, srv, port
    srv.stop()


def test_queries(served):
    _, srv, port = served
    status, body = post(port, "/queries.json", {"user": "u0", "num": 3})
    assert status == 200
    assert len(body["item_scores"]) == 3
    items = {s["item"] for s in body["item_scores"]}
    assert items <= {f"i{i}" for i in range(5)}  # cohort-0 items

    # unknown user → 200 with empty result (graceful)
    status, body = post(port, "/queries.json", {"user": "ghost"})
    assert status == 200 and body["item_scores"] == []


def test_query_validation(served):
    _, _, port = served
    status, body = post(port, "/queries.json", {"user": "u0", "bogus": 1})
    assert status == 400
    assert "unknown params" in body["message"]

    status, body = post(port, "/queries.json", [1, 2])
    assert status == 400

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/queries.json", data=b"{nope",
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=15)
    assert ei.value.code == 400


def test_status_page_and_bookkeeping(served):
    _, srv, port = served
    post(port, "/queries.json", {"user": "u0"})
    post(port, "/queries.json", {"user": "u1"})
    status, html = get(port, "/")
    assert status == 200
    assert "qsrv" in html and "Requests" in html
    assert srv.request_count == 2
    assert srv.avg_serving_sec > 0


def test_hot_reload_swaps_to_latest(served):
    storage, srv, port = served
    first_id = srv.runtime.instance.id
    # new data + retrain → new COMPLETED instance
    seed(storage, seed=1)
    run_train(storage, VARIANT)
    status, body = get(port, "/reload")
    assert status == 200
    assert srv.runtime.instance.id != first_id
    status, body = post(port, "/queries.json", {"user": "u0", "num": 2})
    assert status == 200 and len(body["item_scores"]) == 2


def test_micro_batching(fresh_storage):
    """Concurrent queries coalesce into batched device calls and still get
    the right per-user answers."""
    import concurrent.futures

    seed(fresh_storage)
    run_train(fresh_storage, VARIANT)
    runtime = latest_completed_runtime(fresh_storage, "qsrv", "0", "qsrv")
    srv = QueryServer(
        fresh_storage,
        runtime,
        QueryServerConfig(
            ip="127.0.0.1", port=0, micro_batch=True, batch_window_ms=10.0
        ),
    )
    port = srv.start()
    try:
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            futures = {
                u: pool.submit(post, port, "/queries.json", {"user": f"u{u}", "num": 3})
                for u in range(8)
            }
            results = {u: f.result() for u, f in futures.items()}
        for u, (status, body) in results.items():
            assert status == 200
            items = {s["item"] for s in body["item_scores"]}
            lo, hi = (0, 5) if u % 2 == 0 else (5, 10)
            cohort = {f"i{i}" for i in range(lo, hi)}
            assert items <= cohort, (u, items)
        # validation still 400s through the batched path
        status, body = post(port, "/queries.json", {"user": "u0", "oops": 1})
        assert status == 400
    finally:
        srv.stop()


def test_default_config_batches(served):
    """Micro-batching is ON by default (VERDICT r1 #6: the measured fast
    path must be the default path)."""
    _, srv, _ = served
    assert srv.dispatcher is not None
    assert srv.config.micro_batch


def test_load_32_clients_qps_and_p99(served):
    """32 concurrent clients against the DEFAULT config: sustained qps and
    bounded p99, and the adaptive window + device-time bookkeeping move."""
    import concurrent.futures
    import time as _t

    _, srv, port = served
    n_clients, n_per = 32, 8
    latencies = []
    lat_lock = __import__("threading").Lock()

    def client(u):
        for _ in range(n_per):
            t0 = _t.perf_counter()
            status, body = post(
                port, "/queries.json", {"user": f"u{u % 8}", "num": 3}
            )
            dt = _t.perf_counter() - t0
            assert status == 200
            with lat_lock:
                latencies.append(dt)

    t0 = _t.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(n_clients) as pool:
        list(pool.map(client, range(n_clients)))
    wall = _t.perf_counter() - t0
    total = n_clients * n_per
    qps = total / wall
    p99 = sorted(latencies)[int(0.99 * (len(latencies) - 1))]
    # VERDICT r2 #2 / r3 #3 / r4 #5: the bar tracks measured capability
    # (CPU-local serving measures ~1160 qps on a single-core host now
    # that TCP_NODELAY removed the ~40 ms delayed-ACK stall per HTTP
    # response) instead of sitting far below it; override on
    # slower/contended CI hosts via PIO_TEST_QPS_BAR
    import os as _os

    qps_bar = float(_os.environ.get("PIO_TEST_QPS_BAR", "700"))
    p99_bar = float(_os.environ.get("PIO_TEST_P99_BAR", "1.0"))
    assert qps >= qps_bar, f"qps {qps:.1f} under load target {qps_bar}"
    assert p99 < p99_bar, f"p99 {p99 * 1000:.0f} ms over {p99_bar * 1000:.0f} ms"
    # device-side latency is bookkept separately from end-to-end
    assert srv.predict_count > 0
    assert srv.avg_predict_sec <= srv.avg_serving_sec


def test_feedback_loop(fresh_storage):
    app_id = seed(fresh_storage)
    fresh_storage.get_meta_data_access_keys().insert(
        AccessKey(key="FB", app_id=app_id, events=())
    )
    es = EventServer(
        fresh_storage, EventServerConfig(ip="127.0.0.1", port=0)
    )
    es_port = es.start()
    run_train(fresh_storage, VARIANT)
    runtime = latest_completed_runtime(fresh_storage, "qsrv", "0", "qsrv")
    srv = QueryServer(
        fresh_storage,
        runtime,
        QueryServerConfig(
            ip="127.0.0.1",
            port=0,
            feedback=True,
            event_server_url=f"http://127.0.0.1:{es_port}",
            access_key="FB",
        ),
    )
    port = srv.start()
    try:
        status, _ = post(port, "/queries.json", {"user": "u0"})
        assert status == 200
        deadline = time.time() + 10
        found = []
        while time.time() < deadline and not found:
            found = list(
                fresh_storage.get_events().find_single_entity(
                    app_id, "pio_pr", runtime.instance.id,
                    event_names=["predict"],
                )
            )
            time.sleep(0.1)
        assert found, "feedback predict event never arrived"
        props = found[0].properties
        assert props.get_opt("query", dict) == {"user": "u0"}
    finally:
        srv.stop()
        es.stop()


def test_dispatcher_coalesces_under_device_occupancy():
    """Drain-until-idle policy (VERDICT r3 #3): while one batch occupies
    the (request-serialized) device path, concurrent arrivals coalesce
    into ONE next batch instead of fragmenting into per-query dispatches."""
    import time as _t
    from concurrent.futures import ThreadPoolExecutor

    from predictionio_tpu.workflow.server import _BatchDispatcher

    batch_sizes = []

    class _SlowAlgo:
        serving_context = None

        def batch_predict(self, ctx, model, queries):
            batch_sizes.append(len(queries))
            _t.sleep(0.05)  # the "device" is busy for 50 ms
            return [(qx, f"p{qx}") for qx, _q in queries]

    class _Serving:
        def serve(self, q, preds):
            return preds[0]

    class _Owner:
        def bookkeep_predict(self, *_a):
            pass

    class _RT:
        algorithms = [_SlowAlgo()]
        models = [None]
        serving = _Serving()

    rt = _RT()
    disp = _BatchDispatcher(
        _Owner(), window_ms=2.0, max_batch=64, max_window_ms=60.0,
        pipeline_depth=4,
    )
    try:
        disp.submit("warm", rt)  # first dispatch; occupies the device
        batch_sizes.clear()

        def client(i):
            # stagger arrivals over ~15 ms — all inside the first
            # in-flight batch's 50 ms occupancy window
            _t.sleep(0.001 * (i % 15))
            return disp.submit(f"q{i}", rt)

        with ThreadPoolExecutor(24) as pool:
            results = list(pool.map(client, range(24)))
        assert len(results) == 24
        # 24 staggered queries must NOT become 24 dispatches; the policy
        # coalesces what arrives behind an in-flight batch. Bounds are
        # generous (≤12 fragments, one batch ≥4) so a CPU-starved CI
        # host that stretches the arrival stagger doesn't flake this.
        assert sum(batch_sizes) == 24
        assert len(batch_sizes) <= 12, f"fragmented into {batch_sizes}"
        assert max(batch_sizes) >= 4, f"no deep batch formed: {batch_sizes}"
    finally:
        disp.stop()
