"""Observability: XLA profiler hook, per-stage timings on the
EngineInstance row, remote log shipping (--log-url), and (ISSUE 1) the
unified metrics registry — /metrics exposition on every server, trace-id
propagation, access logs, stats retention."""

import json
import logging
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

from predictionio_tpu.core.base import WorkflowParams
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.workflow.core import run_train

VARIANT = {
    "id": "obs",
    "engineFactory":
        "predictionio_tpu.engines.recommendation.RecommendationEngine",
    "datasource": {"params": {"app_name": "obsapp"}},
    "algorithms": [{"name": "als", "params": {"rank": 4, "num_iterations": 2}}],
}


@pytest.fixture()
def storage():
    cfg = StorageConfig(
        sources={"MEM": SourceConfig("MEM", "memory", {})},
        repositories={
            "METADATA": "MEM", "EVENTDATA": "MEM", "MODELDATA": "MEM",
        },
    )
    s = Storage(cfg)
    app_id = s.get_meta_data_apps().insert(App(0, "obsapp"))
    events = s.get_events()
    events.init_app(app_id)
    rng = np.random.RandomState(0)
    events.insert_batch(
        [
            Event(event="rate", entity_type="user",
                  entity_id=f"u{rng.randint(6)}",
                  target_entity_type="item",
                  target_entity_id=f"i{rng.randint(10)}",
                  properties={"rating": float(rng.randint(1, 6))})
            for _ in range(120)
        ],
        app_id,
    )
    return s


def test_stage_timings_recorded_on_instance(storage):
    inst = run_train(storage, VARIANT)
    assert inst.status == "COMPLETED"
    timings = json.loads(inst.env["stage_timings"])
    assert set(timings) == {"read", "prepare", "train", "persist"}
    assert all(v >= 0 for v in timings.values())
    # the recorded row round-trips through storage too
    stored = storage.get_meta_data_engine_instances().get(inst.id)
    assert json.loads(stored.env["stage_timings"]) == timings


def test_profile_dir_produces_trace(storage, tmp_path):
    profile_dir = str(tmp_path / "xla-trace")
    inst = run_train(
        storage, VARIANT,
        workflow_params=WorkflowParams(profile_dir=profile_dir),
    )
    assert inst.status == "COMPLETED"
    # jax.profiler.trace writes plugins/profile/<ts>/*.{trace.json.gz,xplane.pb}
    produced = []
    for root, _dirs, files in os.walk(profile_dir):
        produced.extend(files)
    assert produced, f"no trace files under {profile_dir}"


class _Collector(BaseHTTPRequestHandler):
    received: list[dict] = []

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n).decode()
        for line in body.splitlines():
            if line.strip():
                type(self).received.append(json.loads(line))
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, *a):
        pass


@pytest.fixture()
def collector():
    _Collector.received = []
    srv = HTTPServer(("127.0.0.1", 0), _Collector)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}/logs", _Collector.received
    srv.shutdown()


def test_remote_log_shipping_handler(collector):
    from predictionio_tpu.utils.logship import RemoteLogHandler

    url, received = collector
    logger = logging.getLogger("predictionio_tpu.test.shipper")
    handler = RemoteLogHandler(url, flush_interval=0.1)
    handler.setFormatter(logging.Formatter("%(message)s"))
    logger.addHandler(handler)
    try:
        logger.warning("shipped line %d", 1)
        logger.error("shipped line %d", 2)
        deadline = time.time() + 5
        while len(received) < 2 and time.time() < deadline:
            time.sleep(0.05)
    finally:
        logger.removeHandler(handler)
        handler.close()
    messages = [r["message"] for r in received]
    assert "shipped line 1" in messages and "shipped line 2" in messages
    levels = {r["level"] for r in received}
    assert {"WARNING", "ERROR"} <= levels


def test_query_server_ships_logs(storage, collector):
    """--log-url wiring on the deploy server: server-side log records reach
    the collector (reference CreateServer.scala:441-452)."""
    from predictionio_tpu.workflow.server import (
        QueryServer,
        QueryServerConfig,
        latest_completed_runtime,
    )

    url, received = collector
    run_train(storage, VARIANT)
    runtime = latest_completed_runtime(storage, "obs", "0", "obs")
    srv = QueryServer(
        storage, runtime,
        QueryServerConfig(ip="127.0.0.1", port=0, log_url=url),
    )
    srv.start()
    try:
        # INFO must ship: --log-url promises INFO-level records even when
        # no logging config exists (attach lowers the package logger level)
        logging.getLogger("predictionio_tpu.workflow.server").info(
            "serving log line for the collector"
        )
        deadline = time.time() + 5
        while not received and time.time() < deadline:
            time.sleep(0.05)
    finally:
        srv.stop()
    assert any(
        "serving log line" in r["message"] for r in received
    ), received


# -- unified metrics registry + /metrics + tracing (ISSUE 1) ---------------

def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=15) as r:
        return r.status, dict(r.headers), r.read().decode()


def _post(url, body, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=15) as r:
        return r.status, dict(r.headers), json.loads(r.read().decode())


def _assert_valid_exposition(text):
    """Every non-comment line must be `name[{labels}] value`, every
    histogram's +Inf bucket must equal its _count."""
    import re

    counts, infs = {}, {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.fullmatch(
            r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)", line
        )
        assert m, f"invalid exposition line: {line!r}"
        name, labels, value = m.groups()
        if name.endswith("_count"):
            counts[(name[:-len("_count")], labels or "")] = float(value)
        if name.endswith("_bucket") and 'le="+Inf"' in (labels or ""):
            key = re.sub(r',?le="\+Inf"', "", labels).replace("{}", "")
            infs[(name[:-len("_bucket")], key or "")] = float(value)
    for key, inf_count in infs.items():
        assert counts.get(key) == inf_count, (key, inf_count, counts)


@pytest.fixture()
def query_served(storage):
    from predictionio_tpu.workflow.server import (
        QueryServer,
        QueryServerConfig,
        latest_completed_runtime,
    )

    run_train(storage, VARIANT)
    runtime = latest_completed_runtime(storage, "obs", "0", "obs")
    srv = QueryServer(
        storage, runtime, QueryServerConfig(ip="127.0.0.1", port=0)
    )
    port = srv.start()
    yield srv, port
    srv.stop()


def test_query_server_metrics_scrape(query_served):
    srv, port = query_served
    status, _h, _b = _post(
        f"http://127.0.0.1:{port}/queries.json", {"user": "u0", "num": 2}
    )
    assert status == 200
    status, headers, text = _get(f"http://127.0.0.1:{port}/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    _assert_valid_exposition(text)
    # acceptance: request counter + latency histogram + the query-server
    # specific distributions, all in one scrape
    assert 'http_requests_total{server="query"' in text
    assert "http_request_seconds_bucket" in text
    assert "serve_seconds_bucket" in text
    assert "predict_seconds_bucket" in text
    assert "batch_size_bucket" in text  # micro-batching is on by default
    assert "batch_queue_wait_seconds_bucket" in text
    # JAX runtime gauges sampled on scrape (CPU test backend still counts)
    assert "jax_jit_compile_count" in text
    assert "jax_live_buffer_count" in text
    # train ran in this process → default-registry stages merge into scrape
    assert 'train_stage_seconds_bucket{stage="train"' in text
    # the registry replaced the running averages: properties derive from it
    assert srv.request_count >= 1
    assert srv.avg_serving_sec > 0
    assert srv.metrics.histogram("serve_seconds").quantile(0.5) > 0


def test_trace_id_round_trips_and_hits_access_log(query_served):
    _srv, port = query_served
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(json.loads(record.getMessage()))

    access_logger = logging.getLogger("predictionio_tpu.access")
    handler = _Capture()
    old_level = access_logger.level
    access_logger.addHandler(handler)
    access_logger.setLevel(logging.INFO)
    try:
        status, headers, _b = _post(
            f"http://127.0.0.1:{port}/queries.json", {"user": "u0"},
            headers={"X-Request-ID": "abc"},
        )
        assert status == 200
        assert headers["X-Request-ID"] == "abc"  # client id echoes back
        # no client id → server generates one
        status, headers, _b = _post(
            f"http://127.0.0.1:{port}/queries.json", {"user": "u0"}
        )
        assert len(headers["X-Request-ID"]) == 32
        # ids outside the safe charset are REPLACED, not echoed — the
        # header goes back out in the response, so hostile bytes must
        # never round-trip
        status, headers, _b = _post(
            f"http://127.0.0.1:{port}/queries.json", {"user": "u0"},
            headers={"X-Request-ID": "bad id with spaces"},
        )
        assert headers["X-Request-ID"] != "bad id with spaces"
        assert len(headers["X-Request-ID"]) == 32
    finally:
        access_logger.removeHandler(handler)
        access_logger.setLevel(old_level)
    by_trace = {r["trace_id"]: r for r in records}
    assert "abc" in by_trace, records
    rec = by_trace["abc"]
    assert rec["server"] == "query"
    assert rec["path"] == "/queries.json"
    assert rec["status"] == 200
    assert rec["duration_ms"] > 0


def test_event_server_metrics_scrape(storage):
    from predictionio_tpu.data.api.server import (
        EventServer,
        EventServerConfig,
    )
    from predictionio_tpu.data.storage.base import AccessKey

    app = storage.get_meta_data_apps().get_by_name("obsapp")
    storage.get_meta_data_access_keys().insert(
        AccessKey(key="OBSKEY", app_id=app.id, events=())
    )
    es = EventServer(storage, EventServerConfig(ip="127.0.0.1", port=0))
    port = es.start()
    try:
        status, headers, _b = _post(
            f"http://127.0.0.1:{port}/events.json?accessKey=OBSKEY",
            {"event": "rate", "entityType": "user", "entityId": "u1"},
            headers={"X-Request-ID": "evt-1"},
        )
        assert status == 201
        assert headers["X-Request-ID"] == "evt-1"
        _s, _h, text = _get(f"http://127.0.0.1:{port}/metrics")
        _assert_valid_exposition(text)
        assert 'http_requests_total{server="event"' in text
        assert 'path="/events.json",status="201"' in text
        assert "http_request_seconds_bucket" in text
        assert "events_ingested_total 1" in text
    finally:
        es.stop()


def test_dashboard_and_storage_server_metrics_scrape(storage, tmp_path):
    from predictionio_tpu.data.api.storage_server import StorageServer
    from predictionio_tpu.tools.dashboard import Dashboard

    dash = Dashboard(storage, ip="127.0.0.1", port=0)
    dport = dash.start()
    ss = StorageServer(storage, host="127.0.0.1", port=0).start()
    try:
        _get(f"http://127.0.0.1:{dport}/")  # generate one request
        _s, _h, text = _get(f"http://127.0.0.1:{dport}/metrics")
        _assert_valid_exposition(text)
        assert 'http_requests_total{server="dashboard"' in text

        _get(f"http://127.0.0.1:{ss.port}/health")
        _s, _h, text = _get(f"http://127.0.0.1:{ss.port}/metrics")
        _assert_valid_exposition(text)
        assert 'http_requests_total{server="storage"' in text
    finally:
        ss.shutdown()
        dash.stop()


def test_storage_rpc_counter(storage):
    """RPCs through the remote client land in storage_rpc_total."""
    from predictionio_tpu.data.api.storage_server import StorageServer
    from predictionio_tpu.data.storage.registry import (
        SourceConfig,
        Storage,
        StorageConfig,
    )

    ss = StorageServer(storage, host="127.0.0.1", port=0).start()
    try:
        remote = Storage(StorageConfig(
            sources={"R": SourceConfig(
                "R", "remote", {"HOST": "127.0.0.1", "PORT": str(ss.port)}
            )},
            repositories={
                "METADATA": "R", "EVENTDATA": "R", "MODELDATA": "R",
            },
        ))
        assert remote.get_meta_data_apps().get_by_name("obsapp") is not None
        _s, _h, text = _get(f"http://127.0.0.1:{ss.port}/metrics")
        assert 'storage_rpc_total{dao="apps",method="get_by_name"} 1' in text
    finally:
        ss.shutdown()


def test_stats_retention_cap():
    """Satellite: hourly Stats buckets are pruned past the retention
    horizon instead of leaking forever."""
    import datetime as dt

    from predictionio_tpu.data.api.stats import Stats
    from predictionio_tpu.data.event import Event

    stats = Stats(retention_hours=24)
    ev = Event(event="rate", entity_type="user", entity_id="u1")
    now = dt.datetime.now(dt.timezone.utc)
    for hours_ago in (30, 26, 25):  # beyond retention
        stats.update(1, 201, ev, now=now - dt.timedelta(hours=hours_ago))
    for hours_ago in (23, 1):  # inside retention
        stats.update(1, 201, ev, now=now - dt.timedelta(hours=hours_ago))
    stats.update(1, 201, ev, now=now)  # fresh update triggers the prune
    hours = stats.get(1)["hours"]
    assert len(hours) == 3, hours  # 23h, 1h, now — the stale three pruned
    total = sum(c["count"] for h in hours for c in h["counts"])
    assert total == 3
    # a second app's fresh bucket is untouched by app-1 churn
    stats.update(2, 201, ev, now=now)
    assert len(stats.get(2)["hours"]) == 1


def test_logship_trace_id_and_recovery(collector):
    """Satellite: shipped records carry the active trace id; a recovered
    collector logs its recovery and re-arms the outage warning."""
    from predictionio_tpu.obs.tracing import trace_context
    from predictionio_tpu.utils.logship import RemoteLogHandler

    url, received = collector
    logger = logging.getLogger("predictionio_tpu.test.traceship")
    handler = RemoteLogHandler(url, flush_interval=0.05)
    handler.setFormatter(logging.Formatter("%(message)s"))
    logger.addHandler(handler)
    try:
        with trace_context("trace-xyz"):
            logger.warning("inside the request")
        logger.warning("outside any request")
        deadline = time.time() + 5
        while len(received) < 2 and time.time() < deadline:
            time.sleep(0.05)
    finally:
        logger.removeHandler(handler)
        handler.close()
    by_msg = {r["message"]: r for r in received}
    assert by_msg["inside the request"]["trace_id"] == "trace-xyz"
    assert "trace_id" not in by_msg["outside any request"]

    # recovery: simulate an outage having warned, then ship successfully
    handler2 = RemoteLogHandler(url, flush_interval=3600)
    try:
        handler2._warned = True
        recovery = []

        class _Cap(logging.Handler):
            def emit(self, record):
                recovery.append(record.getMessage())

        ship_logger = logging.getLogger("pio.logship")
        cap = _Cap()
        ship_logger.addHandler(cap)
        ship_logger.setLevel(logging.INFO)
        try:
            assert handler2._ship([{"message": "hello"}])
        finally:
            ship_logger.removeHandler(cap)
        assert handler2._warned is False  # re-armed for the next outage
        assert any("recovered" in m for m in recovery), recovery
    finally:
        handler2.close()


# ---------------------------------------------------------------------------
# ISSUE 3 satellites: /debug/traces filters + jaxmon late-import re-arm
# ---------------------------------------------------------------------------


def test_debug_traces_filters(storage):
    """?min_duration_ms= and ?error=1 pull only slow/errored traces."""
    import uuid

    from predictionio_tpu.obs import spans as _spans
    from predictionio_tpu.tools.admin import AdminServer

    recorder = _spans.get_default_recorder()

    def mk(name, duration, error):
        tid = uuid.uuid4().hex
        recorder.record(
            _spans.Span(
                trace_id=tid, span_id=_spans.new_span_id(), name=name,
                start=time.time(), duration=duration, error=error,
            ),
            finalize=True,
        )
        return tid

    slow_id = mk("t.slow", 0.9, False)     # kept: slow
    err_id = mk("t.err", 0.001, True)      # kept: error
    srv = AdminServer(storage, ip="127.0.0.1", port=0)
    srv.start()
    try:
        def fetch(params):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/traces?{params}",
                timeout=10,
            ) as r:
                return json.loads(r.read().decode())["traces"]

        slow = fetch("min_duration_ms=500")
        assert any(s["trace_id"] == slow_id for s in slow)
        assert all(s["duration_ms"] >= 500 for s in slow)
        errs = fetch("error=1")
        assert any(s["trace_id"] == err_id for s in errs)
        assert all(s["error"] for s in errs)
        both = fetch("error=1&min_duration_ms=500")
        assert all(
            s["error"] and s["duration_ms"] >= 500 for s in both
        )
        assert not any(s["trace_id"] == err_id for s in both)
        # filters respect the limit AFTER filtering
        limited = fetch("min_duration_ms=500&limit=1")
        assert len(limited) <= 1
    finally:
        srv.stop()


def test_jaxmon_rearm_at_scrape_time(monkeypatch):
    """The late-import gap: gauges wired before jax imports must arm the
    compile listener at scrape time, not stay stuck at 0 forever."""
    import sys

    from predictionio_tpu.obs import jaxmon

    calls = []
    monkeypatch.setattr(jaxmon, "_listener_installed", False)
    monkeypatch.setattr(
        jaxmon, "ensure_compile_listener", lambda: calls.append(1)
    )
    # no jax loaded → scrape must NOT trigger the (expensive) import
    monkeypatch.delitem(sys.modules, "jax", raising=False)
    jaxmon._compile_count_now()
    assert calls == []
    # jax has since been imported → the next scrape arms the listener
    sys.modules.setdefault("jax", __import__("types"))
    try:
        jaxmon._compile_count_now()
        jaxmon._compile_seconds_now()
    finally:
        if not hasattr(sys.modules.get("jax"), "__version__"):
            sys.modules.pop("jax", None)
    assert calls == [1, 1]
