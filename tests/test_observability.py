"""Observability (VERDICT r2 #6): XLA profiler hook, per-stage timings on
the EngineInstance row, and remote log shipping (--log-url)."""

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

from predictionio_tpu.core.base import WorkflowParams
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.workflow.core import run_train

VARIANT = {
    "id": "obs",
    "engineFactory":
        "predictionio_tpu.engines.recommendation.RecommendationEngine",
    "datasource": {"params": {"app_name": "obsapp"}},
    "algorithms": [{"name": "als", "params": {"rank": 4, "num_iterations": 2}}],
}


@pytest.fixture()
def storage():
    cfg = StorageConfig(
        sources={"MEM": SourceConfig("MEM", "memory", {})},
        repositories={
            "METADATA": "MEM", "EVENTDATA": "MEM", "MODELDATA": "MEM",
        },
    )
    s = Storage(cfg)
    app_id = s.get_meta_data_apps().insert(App(0, "obsapp"))
    events = s.get_events()
    events.init_app(app_id)
    rng = np.random.RandomState(0)
    events.insert_batch(
        [
            Event(event="rate", entity_type="user",
                  entity_id=f"u{rng.randint(6)}",
                  target_entity_type="item",
                  target_entity_id=f"i{rng.randint(10)}",
                  properties={"rating": float(rng.randint(1, 6))})
            for _ in range(120)
        ],
        app_id,
    )
    return s


def test_stage_timings_recorded_on_instance(storage):
    inst = run_train(storage, VARIANT)
    assert inst.status == "COMPLETED"
    timings = json.loads(inst.env["stage_timings"])
    assert set(timings) == {"read", "prepare", "train", "persist"}
    assert all(v >= 0 for v in timings.values())
    # the recorded row round-trips through storage too
    stored = storage.get_meta_data_engine_instances().get(inst.id)
    assert json.loads(stored.env["stage_timings"]) == timings


def test_profile_dir_produces_trace(storage, tmp_path):
    profile_dir = str(tmp_path / "xla-trace")
    inst = run_train(
        storage, VARIANT,
        workflow_params=WorkflowParams(profile_dir=profile_dir),
    )
    assert inst.status == "COMPLETED"
    # jax.profiler.trace writes plugins/profile/<ts>/*.{trace.json.gz,xplane.pb}
    produced = []
    for root, _dirs, files in os.walk(profile_dir):
        produced.extend(files)
    assert produced, f"no trace files under {profile_dir}"


class _Collector(BaseHTTPRequestHandler):
    received: list[dict] = []

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n).decode()
        for line in body.splitlines():
            if line.strip():
                type(self).received.append(json.loads(line))
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, *a):
        pass


@pytest.fixture()
def collector():
    _Collector.received = []
    srv = HTTPServer(("127.0.0.1", 0), _Collector)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}/logs", _Collector.received
    srv.shutdown()


def test_remote_log_shipping_handler(collector):
    from predictionio_tpu.utils.logship import RemoteLogHandler

    url, received = collector
    logger = logging.getLogger("predictionio_tpu.test.shipper")
    handler = RemoteLogHandler(url, flush_interval=0.1)
    handler.setFormatter(logging.Formatter("%(message)s"))
    logger.addHandler(handler)
    try:
        logger.warning("shipped line %d", 1)
        logger.error("shipped line %d", 2)
        deadline = time.time() + 5
        while len(received) < 2 and time.time() < deadline:
            time.sleep(0.05)
    finally:
        logger.removeHandler(handler)
        handler.close()
    messages = [r["message"] for r in received]
    assert "shipped line 1" in messages and "shipped line 2" in messages
    levels = {r["level"] for r in received}
    assert {"WARNING", "ERROR"} <= levels


def test_query_server_ships_logs(storage, collector):
    """--log-url wiring on the deploy server: server-side log records reach
    the collector (reference CreateServer.scala:441-452)."""
    from predictionio_tpu.workflow.server import (
        QueryServer,
        QueryServerConfig,
        latest_completed_runtime,
    )

    url, received = collector
    run_train(storage, VARIANT)
    runtime = latest_completed_runtime(storage, "obs", "0", "obs")
    srv = QueryServer(
        storage, runtime,
        QueryServerConfig(ip="127.0.0.1", port=0, log_url=url),
    )
    srv.start()
    try:
        # INFO must ship: --log-url promises INFO-level records even when
        # no logging config exists (attach lowers the package logger level)
        logging.getLogger("predictionio_tpu.workflow.server").info(
            "serving log line for the collector"
        )
        deadline = time.time() + 5
        while not received and time.time() < deadline:
            time.sleep(0.05)
    finally:
        srv.stop()
    assert any(
        "serving log line" in r["message"] for r in received
    ), received
