"""Continuous batching, tenant-aware drain, and the serve-dtype /
sharded-similar engine wiring (ISSUE 11).

The dispatcher tests drive `_BatchDispatcher` directly with a fake
runtime whose batch_predict sleeps — the same harness shape
test_query_server uses for its drain tests — so batching decisions are
observable as recorded batch sizes rather than wall-clock flakiness
wherever possible."""

import threading
import time

import numpy as np
import pytest

from predictionio_tpu.workflow import server as S


class _Owner:
    metrics = None
    tenant_weight = None

    def bookkeep_predict(self, s, n):
        pass

    def count_shed(self, r):
        pass


class _Serving:
    def serve(self, q, preds):
        return preds[0]


def _runtime(device_s=0.0):
    class _Algo:
        serving_context = None

        def batch_predict(self, ctx, model, queries):
            if device_s:
                time.sleep(device_s)
            return [(i, i) for i, _ in queries]

        def predict(self, model, query):
            return 0

    class _RT:
        algorithms = [_Algo()]
        models = [None]
        serving = _Serving()

    return _RT()


def _record_batches(d):
    sizes = []
    orig = d._run_group

    def wrap(rt, group):
        sizes.append(len(group))
        return orig(rt, group)

    d._run_group = wrap
    return sizes


def test_batching_mode_validated():
    with pytest.raises(ValueError):
        S._BatchDispatcher(_Owner(), 2.0, 64, 60.0, 1, batching="bogus")


def test_continuous_coalesces_arrivals_into_inflight_bucket():
    """With one slow bucket in flight, arrivals trickling in must join
    ONE assembling bucket that dispatches on retirement — the windowed
    drain at a short max_window splits the same stream into fragments."""

    def run(mode, max_window_ms):
        d = S._BatchDispatcher(
            _Owner(), 1.0, 64, max_window_ms, 1, batching=mode
        )
        sizes = _record_batches(d)
        rt = _runtime(device_s=0.25)
        threads = [
            threading.Thread(
                target=lambda: d.submit(object(), rt, timeout=10)
            )
        ]
        threads[0].start()
        time.sleep(0.05)  # bucket A is now in flight (sleeping)
        for _ in range(10):
            t = threading.Thread(
                target=lambda: d.submit(object(), rt, timeout=10)
            )
            t.start()
            threads.append(t)
            time.sleep(0.015)  # trickle while A flies
        for t in threads:
            t.join()
        d.stop()
        return sizes

    cont = run("continuous", 30.0)
    # bucket A (1 query) + ONE coalesced bucket for the trickle (a
    # straggler bucket can appear if the last arrival lands after the
    # retirement break)
    assert cont[0] == 1
    assert len(cont) <= 3, cont
    assert max(cont[1:]) >= 8, cont
    windowed = run("windowed", 30.0)
    # the 30 ms window fragments the 150 ms trickle into several buckets
    assert len(windowed) >= len(cont), (windowed, cont)


def test_continuous_retirement_signal_counts():
    d = S._BatchDispatcher(_Owner(), 1.0, 8, 30.0, 2, batching="continuous")
    rt = _runtime()
    for _ in range(3):
        assert d.submit(object(), rt, timeout=5) == 0
    assert d._retired >= 1
    d.stop()


def test_tenant_drain_closes_round_once_all_tenants_represented():
    """Windowed mode + tenants + a busy device: tenant_drain=True
    closes the assembling round the moment every backlogged tenant is
    represented — later arrivals form the NEXT round — while
    tenant_drain=False keeps lingering and absorbs them into one deep
    bucket. Asserted on batch composition, not wall-clock."""

    def run(tenant_drain):
        d = S._BatchDispatcher(
            _Owner(), 50.0, 64, 2000.0, 1, batching="windowed",
            tenant_drain=tenant_drain,
        )
        sizes = _record_batches(d)
        rt = _runtime(device_s=0.5)
        threads = [
            threading.Thread(
                target=lambda: d.submit(object(), rt, timeout=10)
            )
        ]
        threads[0].start()
        time.sleep(0.1)  # bucket A in flight for the next ~0.4 s
        for tid in ("t1", "t2"):
            t = threading.Thread(
                target=lambda tid=tid: d.submit(
                    object(), rt, timeout=10, tenant=tid
                )
            )
            t.start()
            threads.append(t)
        time.sleep(0.15)  # the tenant round assembles while A flies
        for _ in range(5):  # late arrivals, still before A retires
            t = threading.Thread(
                target=lambda: d.submit(object(), rt, timeout=10)
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        d.stop()
        return sizes

    drained = run(True)
    # A=1, the closed tenant round of exactly 2, then the late 5
    assert drained[0] == 1
    assert 2 in drained, drained
    lingered = run(False)
    # the linger absorbed the late arrivals into one deep bucket
    assert max(lingered) >= 7, lingered


def test_fair_queue_backlogged():
    from predictionio_tpu.tenancy.fair import FairQueue

    class _Item:
        def __init__(self, tenant):
            self.tenant = tenant

    q = FairQueue()
    assert q.backlogged() == set()
    q.put(_Item(None))
    q.put(_Item("a"))
    assert q.backlogged() == {None, "a"}
    q.get_nowait()
    q.get_nowait()
    assert q.backlogged() == set()


# ---------------------------------------------------------------------------
# engine wiring: serve_dtype + sharded similar families
# ---------------------------------------------------------------------------


def _als_factors(rng, u=30, i=200, k=8):
    from predictionio_tpu.data.store.bimap import BiMap
    from predictionio_tpu.models import als

    return als.ALSFactors(
        user_factors=rng.standard_normal((u, k)).astype(np.float32),
        item_factors=rng.standard_normal((i, k)).astype(np.float32),
        user_vocab=BiMap({f"u{n}": n for n in range(u)}),
        item_vocab=BiMap({f"i{n}": n for n in range(i)}),
    )


def test_recommendation_serve_dtype_int8_end_to_end():
    from predictionio_tpu.engines.recommendation.engine import (
        ALSAlgorithm,
        ALSAlgorithmParams,
        ALSModel,
        Query,
    )

    rng = np.random.RandomState(0)
    f = _als_factors(rng)
    algo = ALSAlgorithm(ALSAlgorithmParams(serve_dtype="int8"))
    model = ALSModel(f, serve_dtype="int8")
    out = algo._predict_batch(
        model, [Query(user="u1", num=5), Query(user="u2", num=3,
                                               blacklist=["i0", "i1"])]
    )
    assert len(out[0].item_scores) == 5
    assert {s.item for s in out[1].item_scores}.isdisjoint({"i0", "i1"})
    # the staged state really is int8 and the cache charge halves
    sv = model.serving_state()
    assert sv.dtype == "int8" and str(sv.items.dtype) == "int8"
    f32_bytes = f.user_factors.nbytes + f.item_factors.nbytes
    assert model.resident_device_bytes() < f32_bytes


def test_similarproduct_sharded_matches_host_ranking():
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 2:
        pytest.skip("needs the forced multi-device CPU mesh")
    from predictionio_tpu.engines.similarproduct.engine import (
        ALSSimilarAlgorithm,
        ALSSimilarParams,
        Query,
        SimilarModel,
    )

    rng = np.random.RandomState(1)
    f = _als_factors(rng)
    q = Query(items=["i3", "i7"], num=6, blacklist=["i5"])
    host = ALSSimilarAlgorithm(ALSSimilarParams())
    host_model = SimilarModel(f)
    host_items = [
        s.item for s in host.predict(host_model, q).item_scores
    ]
    sharded = ALSSimilarAlgorithm(ALSSimilarParams(shard_serving=True))
    sh_model = SimilarModel(f)
    sh_out = sharded.predict(sh_model, q).item_scores
    sh_items = [s.item for s in sh_out]
    assert sh_model.sharded_info() is not None  # really went sharded
    assert sh_items == host_items
    assert "i5" not in sh_items and "i3" not in sh_items
    # SCORES must match too, not just the ranking — the same query
    # must not yield different values depending on device count
    host_scores = {
        s.item: s.score
        for s in host.predict(host_model, q).item_scores
    }
    for s in sh_out:
        assert abs(s.score - host_scores[s.item]) < 1e-4, (
            s.item, s.score, host_scores[s.item]
        )


def test_itemsim_sharded_on_the_fly_matches_precompute():
    jax = pytest.importorskip("jax")
    from predictionio_tpu.data.store.bimap import BiMap
    from predictionio_tpu.engines.itemsim.engine import (
        ItemSimAlgorithm,
        ItemSimAlgorithmParams,
        ItemSimModel,
        Query,
    )
    from predictionio_tpu.models import dimsum

    rng = np.random.RandomState(2)
    m = (rng.rand(40, 60) < 0.2).astype(np.float32)
    vocab = BiMap({f"i{n}": n for n in range(60)})
    scores, idx = dimsum.column_cosine_topn(m, top_n=60)
    pre = ItemSimModel(
        sim_scores=scores, sim_idx=idx, item_vocab=vocab, top_n=60
    )
    otf = ItemSimModel(
        sim_scores=np.zeros((0, 0), np.float32),
        sim_idx=np.zeros((0, 0), np.int64),
        item_vocab=vocab, top_n=60,
        item_vectors=np.ascontiguousarray(m.T),
    )
    algo = ItemSimAlgorithm(ItemSimAlgorithmParams(shard_serving=True))
    q = Query(items=["i3", "i9"], num=8)
    a = [s.item for s in algo.predict(pre, q).item_scores]
    b = [s.item for s in algo.predict(otf, q).item_scores]
    assert a == b
    if len(jax.devices()) >= 2:
        assert otf.sharded_info() is not None


def test_itemsim_model_unpickles_pre_issue11_state():
    """Models pickled before top_n/item_vectors existed must keep
    loading (the persisted-MODELDATA migration path) and serve via the
    precomputed-sim branch."""
    from predictionio_tpu.data.store.bimap import BiMap
    from predictionio_tpu.engines.itemsim.engine import (
        ItemSimAlgorithm,
        ItemSimAlgorithmParams,
        ItemSimModel,
        Query,
    )

    old_state = {
        "sim_scores": np.array([[0.9], [0.8]], np.float32),
        "sim_idx": np.array([[1], [0]], np.int64),
        "item_vocab": BiMap({"i0": 0, "i1": 1}),
    }
    model = ItemSimModel.__new__(ItemSimModel)
    model.__setstate__(old_state)
    assert model.top_n == 50 and model.item_vectors is None
    algo = ItemSimAlgorithm(ItemSimAlgorithmParams())
    out = algo.predict(model, Query(items=["i0"], num=1))
    assert [s.item for s in out.item_scores] == ["i1"]


def test_itemsim_sharded_model_pickles_without_runtime():
    import pickle

    from predictionio_tpu.data.store.bimap import BiMap
    from predictionio_tpu.engines.itemsim.engine import ItemSimModel

    m = np.eye(6, dtype=np.float32)
    model = ItemSimModel(
        sim_scores=np.zeros((0, 0), np.float32),
        sim_idx=np.zeros((0, 0), np.int64),
        item_vocab=BiMap({f"i{n}": n for n in range(6)}),
        top_n=3, item_vectors=m,
    )
    model.sharded_runtime()  # may stage (multi-device) or cache False
    clone = pickle.loads(pickle.dumps(model))
    assert getattr(clone, "_sharded_runtime", None) is None
    assert np.array_equal(clone.item_vectors, m)


def test_continuous_admission_caps_per_tenant():
    """ISSUE 14 satellite: while a bucket assembles in continuous mode
    with >1 tenant stream active, one tenant's backlog may claim at
    most max_batch // streams slots — the hog's overflow waits for the
    next bucket instead of filling this one ahead of other tenants."""
    d = S._BatchDispatcher(
        _Owner(), 1.0, 8, 30.0, 1, batching="continuous"
    )
    comps = []
    orig = d._run_group

    def wrap(rt, group):
        comps.append([p.tenant for p in group])
        return orig(rt, group)

    d._run_group = wrap
    rt = _runtime(device_s=0.3)
    threads = [
        threading.Thread(
            target=lambda: d.submit(object(), rt, timeout=10)
        )
    ]
    threads[0].start()
    time.sleep(0.05)  # bucket A in flight — the assembly window opens
    # both streams must be VISIBLE (queued) before the hog backlog can
    # fill the bucket, so the goods go first — the cap engages as soon
    # as more than one stream is active
    for tenant in ["good"] * 2 + ["hog"] * 10:
        t = threading.Thread(
            target=lambda tn=tenant: d.submit(
                object(), rt, timeout=10, tenant=tn
            )
        )
        t.start()
        threads.append(t)
        if tenant == "good":
            time.sleep(0.01)
    time.sleep(0.1)  # everything queued while A still flies
    for t in threads:
        t.join()
    d.stop()
    # bucket A is the solo blocker; the first capped bucket holds BOTH
    # good queries and at most 8 // 2 = 4 hog entries; hog overflow
    # lands in later buckets
    assert comps[0] == [None]
    first = comps[1]
    assert first.count("good") == 2, comps
    assert first.count("hog") <= 4, comps
    assert sum(c.count("hog") for c in comps) == 10


def test_admission_cap_noop_for_single_stream():
    """A solo tenant (or untenanted traffic) keeps the whole bucket —
    the cap only engages with competing streams."""
    d = S._BatchDispatcher(
        _Owner(), 1.0, 8, 30.0, 1, batching="continuous"
    )
    sizes = _record_batches(d)
    rt = _runtime(device_s=0.25)
    threads = [
        threading.Thread(
            target=lambda: d.submit(object(), rt, timeout=10, tenant="t")
        )
    ]
    threads[0].start()
    time.sleep(0.05)
    for _ in range(7):
        t = threading.Thread(
            target=lambda: d.submit(object(), rt, timeout=10, tenant="t")
        )
        t.start()
        threads.append(t)
    time.sleep(0.05)
    for t in threads:
        t.join()
    d.stop()
    assert sizes[0] == 1
    assert max(sizes[1:]) == 7, sizes  # uncapped single stream
