"""CCO kernel + Universal Recommender engine tests."""

import numpy as np
import pytest

from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.models import cco
from predictionio_tpu.workflow.core import prepare_deploy_models, run_train


class TestCCOKernel:
    def test_counts_and_llr_shape(self):
        # users 0-3 buy item 0 AND view thing 1 → strong correlation
        primary = cco.edges_to_indicator(
            np.array([0, 1, 2, 3, 4, 5]), np.array([0, 0, 0, 0, 1, 1]), 6, 2
        )
        secondary = cco.edges_to_indicator(
            np.array([0, 1, 2, 3, 4, 5]), np.array([1, 1, 1, 1, 0, 0]), 6, 2
        )
        scores, idx = cco.cross_occurrence_topn(primary, secondary, top_n=2)
        assert scores.shape == (2, 2) and idx.shape == (2, 2)
        # item 0's top correlator is thing 1; item 1's is thing 0
        assert idx[0, 0] == 1
        assert idx[1, 0] == 0
        assert scores[0, 0] > 0

    def test_no_cooccurrence_no_correlator(self):
        primary = cco.edges_to_indicator(np.array([0]), np.array([0]), 4, 1)
        secondary = cco.edges_to_indicator(np.array([1]), np.array([0]), 4, 1)
        scores, idx = cco.cross_occurrence_topn(primary, secondary, top_n=1)
        assert idx[0, 0] == -1  # never co-occurred → not a correlator

    def test_self_indicator_excludes_diagonal(self):
        # users 0-1 buy items {0,1} together; users 2-3 buy item 2 only —
        # so 0↔1 co-occurrence is informative (not universal)
        rows = np.array([0, 0, 1, 1, 2, 3])
        cols = np.array([0, 1, 0, 1, 2, 2])
        p = cco.edges_to_indicator(rows, cols, 4, 3)
        scores, idx = cco.cross_occurrence_topn(
            p, p, top_n=2, self_indicator=True
        )
        assert idx[0, 0] == 1  # item 0's correlator is item 1, not itself
        assert idx[1, 0] == 0
        assert 0 not in idx[0][idx[0] >= 0] or idx[0, 0] != 0  # no diagonal

    def test_uninformative_cooccurrence_scores_zero(self):
        """Everyone does everything → LLR = 0 → no correlators."""
        u = np.ones((8, 2), dtype=np.float32)
        scores, idx = cco.cross_occurrence_topn(u, u, top_n=2)
        assert (idx == -1).all()

    def test_score_history(self):
        idx = np.array([[1, 3, -1], [2, -1, -1]])
        vals = np.array([[2.0, 1.0, 9.9], [5.0, 9.9, 9.9]], dtype=np.float32)
        s = cco.score_history(idx, vals, np.array([3, 2]))
        assert s[0] == pytest.approx(1.0)  # hit on correlator 3 only
        assert s[1] == pytest.approx(5.0)  # hit on correlator 2
        assert cco.score_history(idx, vals, np.empty(0, int)).sum() == 0

    def test_mesh_sharded_matches_single(self, mesh8):
        # 17 users: deliberately NOT divisible by 8 — exercises padding
        rng = np.random.RandomState(0)
        p = (rng.rand(17, 6) > 0.5).astype(np.float32)
        s = (rng.rand(17, 5) > 0.5).astype(np.float32)
        v0, i0 = cco.cross_occurrence_topn(p, s, top_n=3)
        v1, i1 = cco.cross_occurrence_topn(p, s, top_n=3, mesh=mesh8)
        np.testing.assert_allclose(v0, v1, atol=1e-5)
        np.testing.assert_array_equal(i0, i1)


# ---------------------------------------------------------------------------
# engine end-to-end
# ---------------------------------------------------------------------------


UR_VARIANT = {
    "id": "ur",
    "engineFactory": "predictionio_tpu.engines.universal.UniversalRecommenderEngine",
    "datasource": {
        "params": {"app_name": "urapp", "indicators": ["buy", "view"]}
    },
    "algorithms": [
        {
            "name": "ur",
            "params": {"app_name": "urapp", "max_correlators_per_item": 10},
        }
    ],
}


@pytest.fixture()
def ur_storage(fresh_storage):
    """Cohort structure across two indicator types: even users buy items
    0-3 and view accessories a0-a1; odd users buy 4-7 and view a2-a3."""
    app_id = fresh_storage.get_meta_data_apps().insert(App(id=0, name="urapp"))
    fresh_storage.get_events().init_app(app_id)
    rng = np.random.RandomState(17)
    events = []
    for u in range(20):
        g = u % 2
        for _ in range(6):
            events.append(
                Event(event="buy", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="item",
                      target_entity_id=f"i{rng.randint(0, 4) + g * 4}")
            )
        for _ in range(4):
            events.append(
                Event(event="view", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="item",
                      target_entity_id=f"a{rng.randint(0, 2) + g * 2}")
            )
    fresh_storage.get_events().insert_batch(events, app_id)
    return fresh_storage, app_id


def deploy_ur(storage):
    inst = run_train(storage, UR_VARIANT)
    assert inst.status == "COMPLETED"
    engine, ep, models = prepare_deploy_models(storage, inst)
    algo = engine.make_algorithms(ep)[0]
    algo.set_serving_context(RuntimeContext(storage=storage, mode="serve"))
    return algo, models[0]


class TestUniversalRecommender:
    def test_recommends_cohort_items(self, ur_storage):
        storage, _ = ur_storage
        algo, model = deploy_ur(storage)
        from predictionio_tpu.engines.universal import Query

        pred = algo.predict(model, Query(user="u0", num=4, exclude_seen=False))
        assert pred.item_scores
        items = {s.item for s in pred.item_scores}
        assert items <= {"i0", "i1", "i2", "i3"}, items

    def test_exclude_seen_primary(self, ur_storage):
        storage, app_id = ur_storage
        algo, model = deploy_ur(storage)
        from predictionio_tpu.engines.universal import Query
        from predictionio_tpu.data.store.event_store import EventStoreFacade

        seen = {
            e.target_entity_id
            for e in EventStoreFacade(storage).find_by_entity(
                app_name="urapp", entity_type="user", entity_id="u0",
                event_names=["buy"],
            )
        }
        pred = algo.predict(model, Query(user="u0", num=8, exclude_seen=True))
        assert not ({s.item for s in pred.item_scores} & seen)

    def test_secondary_indicator_contributes(self, ur_storage):
        """A user with ONLY view history (no buys) still gets cohort
        recommendations via the view indicator — the point of multi-modal
        CCO."""
        storage, app_id = ur_storage
        algo, model = deploy_ur(storage)
        storage.get_events().insert_batch(
            [
                Event(event="view", entity_type="user", entity_id="lurker",
                      target_entity_type="item", target_entity_id="a0"),
                Event(event="view", entity_type="user", entity_id="lurker",
                      target_entity_type="item", target_entity_id="a1"),
            ],
            app_id,
        )
        from predictionio_tpu.engines.universal import Query

        pred = algo.predict(model, Query(user="lurker", num=4))
        assert pred.item_scores, "view-only user should get recommendations"
        items = {s.item for s in pred.item_scores}
        assert items <= {"i0", "i1", "i2", "i3"}, items

    def test_secondary_only_indicators_with_exclude_seen(self, ur_storage):
        """Keeping only the secondary indicator must still filter seen
        items in the PRIMARY item space (vocabulary mismatch regression)."""
        storage, _ = ur_storage
        variant = dict(UR_VARIANT)
        variant["algorithms"] = [
            {
                "name": "ur",
                "params": {
                    "app_name": "urapp",
                    "max_correlators_per_item": 10,
                    "indicators": ["view"],
                },
            }
        ]
        inst = run_train(storage, variant)
        engine, ep, models = prepare_deploy_models(storage, inst)
        algo = engine.make_algorithms(ep)[0]
        algo.set_serving_context(RuntimeContext(storage=storage, mode="serve"))
        from predictionio_tpu.engines.universal import Query
        from predictionio_tpu.data.store.event_store import EventStoreFacade

        seen = {
            e.target_entity_id
            for e in EventStoreFacade(storage).find_by_entity(
                app_name="urapp", entity_type="user", entity_id="u0",
                event_names=["buy"],
            )
        }
        pred = algo.predict(model=models[0], query=Query(user="u0", num=8))
        items = {s.item for s in pred.item_scores}
        assert not (items & seen)
        # recommendations still flow from the view indicator
        pred2 = algo.predict(models[0], Query(user="u0", num=8, exclude_seen=False))
        assert pred2.item_scores

    def test_unknown_user_empty(self, ur_storage):
        storage, _ = ur_storage
        algo, model = deploy_ur(storage)
        from predictionio_tpu.engines.universal import Query

        assert algo.predict(model, Query(user="ghost")).item_scores == []

    def test_self_cleaning_window_wired(self, ur_storage):
        storage, app_id = ur_storage
        # duplicate events + old events to clean
        import datetime as dt

        old = dt.datetime.now(dt.timezone.utc) - dt.timedelta(days=90)
        storage.get_events().insert(
            Event(event="buy", entity_type="user", entity_id="u0",
                  target_entity_type="item", target_entity_id="i0",
                  event_time=old),
            app_id,
        )
        variant = dict(UR_VARIANT)
        variant["datasource"] = {
            "params": {
                "app_name": "urapp",
                "indicators": ["buy", "view"],
                "event_window": {
                    "duration": "30 days",
                    "remove_duplicates": True,
                },
            }
        }
        inst = run_train(storage, variant)
        assert inst.status == "COMPLETED"
        # the 90-day-old event was aged out of the store
        from predictionio_tpu.data.storage.base import EventQuery

        remaining = [
            e for e in storage.get_events().find(EventQuery(app_id=app_id))
            if e.event_time <= old
        ]
        assert remaining == []


class TestDeviceBatchServing:
    """VERDICT r2 #5: the UR serving hot path is one device dispatch."""

    def _tables(self, rng, n_items, n_things, top_n):
        idx = rng.randint(0, n_things, (n_items, top_n)).astype(np.int32)
        # -1-pad a ragged tail like real correlator tables
        for i in range(0, n_items, 3):
            idx[i, top_n // 2:] = -1
        scores = rng.rand(n_items, top_n).astype(np.float32) + 0.1
        scores[idx < 0] = 0.0
        return idx, scores

    def test_batch_matches_score_history_reference(self):
        from predictionio_tpu.models import cco

        rng = np.random.RandomState(5)
        n_items = 500
        tables = [
            self._tables(rng, n_items, n_things, 16) + (n_things,)
            for n_things in (300, 120)
        ]
        B, H = 6, 20
        hists = []
        for _, _, j in tables:
            h = np.full((B, H), -1, np.int32)
            for b in range(B):
                n = rng.randint(0, H)
                h[b, :n] = rng.randint(0, j, n)
            hists.append(h)
        exclude = np.full((B, 8), -1, np.int32)
        exclude[0, :3] = [1, 2, 3]
        vals, idx = cco.batch_score_topk(tables, hists, exclude, k=n_items)

        for b in range(B):
            expect = np.zeros(n_items, np.float32)
            for (cidx, csc, _j), h in zip(tables, hists):
                hh = h[b][h[b] >= 0]
                expect += cco.score_history(cidx, csc, hh)
            got = np.zeros(n_items, np.float32)
            got[idx[b]] = np.maximum(vals[b], 0.0)
            expect[exclude[b][exclude[b] >= 0]] = 0.0  # device masks these
            np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)

    def test_catalog_scale_qps(self):
        """10^5-item catalog: the batched program must sustain real
        throughput (measured on the CPU test backend; the JSON-visible
        bench numbers come from bench.py on the chip)."""
        import time

        from predictionio_tpu.models import cco

        import jax.numpy as jnp

        rng = np.random.RandomState(9)
        n_items = 100_000
        cidx, csc = self._tables(rng, n_items, 80_000, 50)
        # device-resident tables, as URModel.device_tables stages them —
        # re-uploading 20 MB of correlators per batch is NOT the product
        # configuration
        tables = [(jnp.asarray(cidx), jnp.asarray(csc), 80_000)]
        B, H = 64, 100
        hist = np.full((B, H), -1, np.int32)
        for b in range(B):
            hist[b] = rng.randint(0, 80_000, H)
        exclude = np.full((B, 8), -1, np.int32)
        vals, idx = cco.batch_score_topk(tables, [hist], exclude, k=64)  # warm
        t0 = time.perf_counter()
        n_reps = 3
        for _ in range(n_reps):
            vals, idx = cco.batch_score_topk(tables, [hist], exclude, k=64)
        dt = (time.perf_counter() - t0) / n_reps
        qps = B / dt
        assert vals.shape == (B, 64)
        # CPU-backend floor; the device path exists precisely so this does
        # not degrade to per-(query x indicator) numpy loops
        assert qps > 40, f"batched UR qps {qps:.0f}"


def test_blocked_cco_matches_unblocked():
    """Item-blocked CCO (the 1e5-catalog HBM fix) is exact vs single-shot."""
    import numpy as np

    from predictionio_tpu.models import cco

    rng = np.random.RandomState(4)
    P = (rng.rand(60, 300) < 0.1).astype(np.float32)
    S = (rng.rand(60, 150) < 0.15).astype(np.float32)
    for self_ind, sec in ((True, P), (False, S)):
        v1, i1 = cco.cross_occurrence_topn(P, sec, 8, self_indicator=self_ind)
        v2, i2 = cco.cross_occurrence_topn(
            P, sec, 8, self_indicator=self_ind, block_items=64
        )
        np.testing.assert_allclose(v1, v2, rtol=1e-6)
        assert (i1 == i2).all()
