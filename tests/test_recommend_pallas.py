"""Fused serving-kernel parity + int8 serving state + donated publish
(ISSUE 11).

The fused Pallas recommend+top-k kernel must agree EXACTLY with the
XLA two-step reference (`ops.topk.masked_top_k` over `q @ itf.T`) —
values, indices, and tie order — in interpret mode on CPU; int8
serving must agree with its own plain-XLA int8 reference exactly and
with f32 scoring within the quantization bound; and the fold-in
publish path must be copy-on-write: a runtime swap mid-flight leaves
every reader of the OLD staged state with correct, unchanged answers
(donation only ever touches buffers the publish privately created)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from predictionio_tpu.data.store.bimap import BiMap  # noqa: E402
from predictionio_tpu.models import als  # noqa: E402
from predictionio_tpu.ops.recommend_pallas import (  # noqa: E402
    fused_recommend_topk,
    pad_items,
    pick_item_tile,
    quantize_rows_jnp,
    quantize_rows_np,
)
from predictionio_tpu.ops.topk import NEG_INF, masked_top_k  # noqa: E402


def _pad(itf, i_p):
    out = np.zeros((i_p, itf.shape[1]), itf.dtype)
    out[: itf.shape[0]] = itf
    return out


def _fused(uf, itf, k, mask=None):
    from predictionio_tpu.ops.recommend_pallas import pack_mask_np

    i_p = pad_items(itf.shape[0])
    bits = None
    if mask is not None:
        # exclusion ships bit-packed (ISSUE 14): 1/32 the f32 bytes
        bits = jnp.asarray(pack_mask_np(mask, i_p))
    return fused_recommend_topk(
        jnp.asarray(uf), jnp.asarray(_pad(itf, i_p)), None, None, bits,
        k=k, n_items=itf.shape[0], interpret=True,
    )


# ---------------------------------------------------------------------------
# kernel parity (interpret mode)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [1, 5, 128, 300])
def test_fused_parity_unmasked(k):
    rng = np.random.RandomState(0)
    uf = rng.standard_normal((8, 10)).astype(np.float32)
    itf = rng.standard_normal((300, 10)).astype(np.float32)
    ref_v, ref_i = masked_top_k(jnp.asarray(uf @ itf.T), k, None)
    v, ix = _fused(uf, itf, k)
    assert np.array_equal(np.asarray(ref_i), np.asarray(ix))
    np.testing.assert_allclose(
        np.asarray(ref_v), np.asarray(v), rtol=1e-6
    )


def test_fused_parity_masked():
    rng = np.random.RandomState(1)
    uf = rng.standard_normal((8, 10)).astype(np.float32)
    itf = rng.standard_normal((300, 10)).astype(np.float32)
    mask = rng.rand(8, 300) < 0.4
    ref_v, ref_i = masked_top_k(
        jnp.asarray(uf @ itf.T), 17, jnp.asarray(mask)
    )
    v, ix = _fused(uf, itf, 17, mask=mask)
    assert np.array_equal(np.asarray(ref_i), np.asarray(ix))
    np.testing.assert_allclose(
        np.asarray(ref_v), np.asarray(v), rtol=1e-6
    )


def test_fused_fully_masked_row_matches_reference():
    """A row whose every item is excluded must return NEG_INF values at
    the reference's tie order (indices 0..k-1)."""
    rng = np.random.RandomState(2)
    uf = rng.standard_normal((2, 4)).astype(np.float32)
    itf = rng.standard_normal((200, 4)).astype(np.float32)
    mask = np.zeros((2, 200), bool)
    mask[1, :] = True
    ref_v, ref_i = masked_top_k(
        jnp.asarray(uf @ itf.T), 6, jnp.asarray(mask)
    )
    v, ix = _fused(uf, itf, 6, mask=mask)
    assert np.array_equal(np.asarray(ref_i), np.asarray(ix))
    assert np.all(np.asarray(v)[1] == NEG_INF)


def test_fused_tie_breaking_matches_lax_top_k():
    """Equal scores everywhere — the stable (lowest index first) order
    must match lax.top_k bit-for-bit, including across tile
    boundaries."""
    uf = np.ones((2, 4), np.float32)
    itf = np.tile(np.array([[1, 0, 0, 0]], np.float32), (260, 1))
    ref_v, ref_i = masked_top_k(jnp.asarray(uf @ itf.T), 140, None)
    v, ix = _fused(uf, itf, 140)
    assert np.array_equal(np.asarray(ref_i), np.asarray(ix))

    # duplicated score blocks straddling the 128-row tile boundary
    rng = np.random.RandomState(3)
    base = rng.standard_normal((130, 6)).astype(np.float32)
    itf2 = np.concatenate([base, base])  # every score appears twice
    uf2 = rng.standard_normal((3, 6)).astype(np.float32)
    ref_v, ref_i = masked_top_k(jnp.asarray(uf2 @ itf2.T), 50, None)
    v2, ix2 = _fused(uf2, itf2, 50)
    assert np.array_equal(np.asarray(ref_i), np.asarray(ix2))


def test_fused_small_catalog_k_equals_n():
    rng = np.random.RandomState(4)
    uf = rng.standard_normal((1, 8)).astype(np.float32)
    itf = rng.standard_normal((7, 8)).astype(np.float32)
    ref_v, ref_i = masked_top_k(jnp.asarray(uf @ itf.T), 7, None)
    v, ix = _fused(uf, itf, 7)
    assert np.array_equal(np.asarray(ref_i), np.asarray(ix))


def test_pick_item_tile_always_divides():
    for n in (128, 256, 384, 26744 + 72, 1024, 2048, 131072):
        n_p = pad_items(n)
        t = pick_item_tile(n_p)
        assert t > 0 and n_p % t == 0


# ---------------------------------------------------------------------------
# int8 quantized serving
# ---------------------------------------------------------------------------


def test_int8_kernel_matches_xla_int8_reference_exactly():
    rng = np.random.RandomState(5)
    uf = rng.standard_normal((8, 10)).astype(np.float32)
    itf = rng.standard_normal((300, 10)).astype(np.float32)
    q8, qs = quantize_rows_np(uf)
    i8, isc = quantize_rows_np(itf)
    i_p = pad_items(300)
    i8_p = np.zeros((i_p, 10), np.int8)
    i8_p[:300] = i8
    isc_p = np.ones((1, i_p), np.float32)
    isc_p[0, :300] = isc
    v, ix = fused_recommend_topk(
        jnp.asarray(q8), jnp.asarray(i8_p), jnp.asarray(qs[:, None]),
        jnp.asarray(isc_p), k=10, n_items=300, interpret=True,
    )
    s_ref = (
        q8.astype(np.int32) @ i8.T.astype(np.int32)
    ).astype(np.float32) * qs[:, None] * isc[None, :]
    rv, ri = masked_top_k(jnp.asarray(s_ref), 10, None)
    assert np.array_equal(np.asarray(ri), np.asarray(ix))
    np.testing.assert_allclose(np.asarray(rv), np.asarray(v), rtol=1e-5)


def test_int8_round_trip_score_agreement_bound():
    """Per-row symmetric int8 quantization of BOTH sides: the score
    error is bounded by ~2/127 per side of the max-magnitude product —
    assert a 2.5% relative bound on this workload and that dequantized
    factors round-trip within one quantization step."""
    rng = np.random.RandomState(6)
    uf = rng.standard_normal((64, 10)).astype(np.float32)
    itf = rng.standard_normal((500, 10)).astype(np.float32)
    q8, qs = quantize_rows_np(uf)
    i8, isc = quantize_rows_np(itf)
    # round trip: |deq - orig| <= scale/2 per element
    deq = q8.astype(np.float32) * qs[:, None]
    assert np.all(np.abs(deq - uf) <= qs[:, None] / 2 + 1e-7)
    s_f32 = uf @ itf.T
    s_int8 = (
        q8.astype(np.int32) @ i8.T.astype(np.int32)
    ).astype(np.float32) * qs[:, None] * isc[None, :]
    denom = np.abs(s_f32).max()
    assert np.max(np.abs(s_int8 - s_f32)) / denom < 0.025
    # traced quantizer agrees with the host one
    qj, sj = quantize_rows_jnp(jnp.asarray(uf))
    assert np.array_equal(np.asarray(qj), q8)
    np.testing.assert_allclose(np.asarray(sj)[:, 0], qs, rtol=1e-6)


def _factors(rng, u=50, i=300, k=10):
    return als.ALSFactors(
        user_factors=rng.standard_normal((u, k)).astype(np.float32),
        item_factors=rng.standard_normal((i, k)).astype(np.float32),
        user_vocab=BiMap({f"u{n}": n for n in range(u)}),
        item_vocab=BiMap({f"i{n}": n for n in range(i)}),
    )


@pytest.mark.parametrize("dtype", ["f32", "int8"])
@pytest.mark.parametrize("mode", [None, "interpret"])
def test_recommend_serving_parity(dtype, mode):
    """The staged-state path must match the legacy recommend exactly at
    f32 (either kernel mode), and at int8 match its own int8 scoring
    across modes — a mode change never changes scores."""
    import dataclasses

    rng = np.random.RandomState(7)
    f = _factors(rng)
    ref_v, ref_i = als.recommend(f, np.arange(8), 10)
    sv = dataclasses.replace(
        als.stage_serving(f, serve_dtype=dtype), mode=mode
    )
    v, ix = als.recommend_serving(sv, np.arange(8), 10)
    if dtype == "f32":
        assert np.array_equal(ix, ref_i)
        np.testing.assert_allclose(v, ref_v, rtol=1e-5)
    else:
        # int8 vs the XLA int8 path (mode=None) must be identical
        sv0 = dataclasses.replace(sv, mode=None)
        v0, ix0 = als.recommend_serving(sv0, np.arange(8), 10)
        assert np.array_equal(ix, ix0)
        np.testing.assert_allclose(v, v0, rtol=1e-5)
    # masked never returns an excluded item
    mask = rng.rand(8, 300) < 0.5
    v2, ix2 = als.recommend_serving(
        sv, np.arange(8), 10, exclude_mask=mask
    )
    assert not np.any(mask[np.arange(8)[:, None], ix2])


# ---------------------------------------------------------------------------
# donated publish + swap safety
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", ["f32", "int8"])
def test_serving_publish_rows_is_copy_on_write(dtype):
    rng = np.random.RandomState(8)
    f = _factors(rng)
    sv = als.stage_serving(f, serve_dtype=dtype)
    before_v, before_i = als.recommend_serving(sv, [0, 1], 10)
    new_rows = rng.standard_normal((2, 10)).astype(np.float32)
    sv2 = als.serving_publish_rows(
        sv, user_rows=[0, 1], user_vals=new_rows
    )
    # the OLD state still serves the OLD answers (readers are safe)
    again_v, again_i = als.recommend_serving(sv, [0, 1], 10)
    assert np.array_equal(before_i, again_i)
    np.testing.assert_allclose(before_v, again_v, rtol=1e-7)
    # the successor serves the published rows
    v2, _ = als.recommend_serving(sv2, [0, 1], 10)
    assert not np.allclose(before_v, v2)


@pytest.mark.parametrize("dtype", ["f32", "int8"])
def test_serving_publish_growth_donates_only_private_buffers(dtype):
    """Vocab growth uses the donated fast path — and the old state's
    buffers must remain alive and correct (donation only applies to the
    freshly-grown private successor)."""
    rng = np.random.RandomState(9)
    f = _factors(rng)
    sv = als.stage_serving(f, serve_dtype=dtype)
    old_v, old_i = als.recommend_serving(sv, [3], 10)
    # grow users beyond the staged extent and items beyond the pad
    i_p = int(sv.items.shape[0])
    sv2 = als.serving_publish_rows(
        sv,
        user_rows=[50, 51], user_vals=np.ones((2, 10), np.float32),
        item_rows=[i_p, i_p + 1],
        item_vals=rng.standard_normal((2, 10)).astype(np.float32),
        n_users=52, n_items=i_p + 2,
    )
    assert sv2.n_users == 52 and sv2.n_items == i_p + 2
    # grown users are servable; old state unchanged (mid-flight reader)
    gv, gi = als.recommend_serving(sv2, [50], 10)
    assert gv.shape == (1, 10)
    again_v, again_i = als.recommend_serving(sv, [3], 10)
    assert np.array_equal(old_i, again_i)
    np.testing.assert_allclose(old_v, again_v, rtol=1e-7)


def test_vocab_growth_within_pad_does_not_retrace_serving():
    """n_items rides the serving jit as a TRACED scalar: an online fold
    tick that grows the item vocab within the pad headroom must reuse
    the compiled serving program (a retrace per growth tick would dwarf
    the row-publish saving the COW path exists for)."""
    rng = np.random.RandomState(42)
    f = _factors(rng, u=20, i=100, k=8)
    sv = als.stage_serving(f, serve_dtype="int8")
    als.recommend_serving(sv, [0, 1], 5)
    inner = als._serve_recommend_jit.__wrapped__
    n0 = inner._cache_size()
    sv2 = als.serving_publish_rows(
        sv, item_rows=[100, 101, 102],
        item_vals=rng.standard_normal((3, 8)).astype(np.float32),
        n_items=103,
    )
    v, ix = als.recommend_serving(sv2, [0, 1], 5)
    assert inner._cache_size() == n0
    assert ix.max() <= 102  # the grown rows are really servable


def test_fold_in_clone_carries_serving_state_via_row_publish():
    """online/foldin.py:_clone_model threads dirty rows into
    ALSModel.adopt_serving: the clone's staged state reflects the fold
    WITHOUT a restage, keeps the serve dtype, and drops the carry when
    a changed side has no row attribution."""
    from predictionio_tpu.engines.recommendation.engine import ALSModel
    from predictionio_tpu.online.foldin import ALSFoldIn

    rng = np.random.RandomState(10)
    f = _factors(rng)
    model = ALSModel(f, serve_dtype="int8")
    sv = model.serving_state()
    assert sv.dtype == "int8"
    new_uf = f.user_factors.copy()
    solved = rng.standard_normal((2, 10)).astype(np.float32)
    new_uf[[1, 2]] = solved
    import dataclasses

    nf = dataclasses.replace(f, user_factors=new_uf)
    clone = ALSFoldIn._clone_model(
        model, nf, items_changed=False,
        dirty_users=([1, 2], solved),
    )
    assert clone.serve_dtype == "int8"
    assert clone._serving_state is not None
    # the clone's staged state serves the folded rows (quantized)
    v_new, _ = als.recommend_serving(clone._serving_state, [1], 5)
    v_model = als.recommend_serving(
        als.stage_serving(nf, serve_dtype="int8"), [1], 5
    )[0]
    np.testing.assert_allclose(v_new, v_model, rtol=1e-5)
    # no row attribution for a changed side -> carry dropped
    clone2 = ALSFoldIn._clone_model(
        model, nf, items_changed=False
    )
    assert clone2._serving_state is None


# ---------------------------------------------------------------------------
# sharded twin (forced multi-device CPU mesh, interpret mode)
# ---------------------------------------------------------------------------


def test_sharded_fused_recommend_parity(monkeypatch):
    if len(jax.devices()) < 2:
        pytest.skip("needs the forced multi-device CPU mesh")
    from predictionio_tpu.fleet.runtime import ShardedRuntime

    rng = np.random.RandomState(11)
    uf = rng.standard_normal((40, 8)).astype(np.float32)
    itf = rng.standard_normal((570, 8)).astype(np.float32)
    fused = ShardedRuntime(uf, itf, serve_mode="interpret")
    plain = ShardedRuntime(uf, itf, serve_mode="off")
    assert fused.serve_mode == "interpret" and plain.serve_mode is None
    v, ix = fused.recommend(np.arange(6), 10)
    v2, ix2 = plain.recommend(np.arange(6), 10)
    assert np.array_equal(ix, ix2)
    np.testing.assert_allclose(v, v2, rtol=1e-5)
    mask = rng.rand(6, 570) < 0.4
    v, ix = fused.recommend(np.arange(6), 10, exclude_mask=mask)
    v2, ix2 = plain.recommend(np.arange(6), 10, exclude_mask=mask)
    assert np.array_equal(ix, ix2)


def test_sharded_similar_vectors_ranking():
    if len(jax.devices()) < 2:
        pytest.skip("needs the forced multi-device CPU mesh")
    from predictionio_tpu.fleet.runtime import ShardedRuntime

    rng = np.random.RandomState(12)
    itf = rng.standard_normal((300, 8)).astype(np.float32)
    srt = ShardedRuntime(np.zeros((0, 8), np.float32), itf)
    vecs = rng.standard_normal((3, 8)).astype(np.float32)
    vals, idx = srt.similar_vectors(vecs, 7)
    qn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
    fn = itf / np.linalg.norm(itf, axis=1, keepdims=True)
    ref = np.argsort(-(qn @ fn.T), axis=1, kind="stable")[:, :7]
    assert np.array_equal(idx, ref)
    # exclusion mask respected
    mask = np.zeros((3, 300), bool)
    mask[:, ref[:, 0]] = True
    _, idx2 = srt.similar_vectors(vecs, 7, exclude_mask=mask)
    for r in range(3):
        assert ref[r, 0] not in idx2[r]


# ---------------------------------------------------------------------------
# devprof dtype-aware roofline (ISSUE 11 satellite)
# ---------------------------------------------------------------------------


def test_devprof_dtype_peaks(monkeypatch):
    from predictionio_tpu.obs import devprof

    monkeypatch.setenv("PIO_PEAK_FLOPS", "100e12")
    assert devprof.platform_info()["peak_flops"] == 100e12
    # the central override pins every dtype unless a dtyped env is set
    assert devprof.platform_info("int8")["peak_flops"] == 100e12
    monkeypatch.setenv("PIO_PEAK_FLOPS_INT8", "200e12")
    assert devprof.platform_info("int8")["peak_flops"] == 200e12
    assert devprof.platform_info("f32")["peak_flops"] == 100e12
    monkeypatch.setenv("PIO_PEAK_FLOPS_F32", "50e12")
    assert devprof.platform_info("f32")["peak_flops"] == 50e12
    # dtyped mfu uses the dtyped peak
    assert devprof.mfu(1e12, 1.0, "int8") == pytest.approx(1 / 200)
    assert devprof.mfu(1e12, 1.0, "f32") == pytest.approx(1 / 50)


def test_devprof_executable_reports_dtype(monkeypatch):
    """An instrumented executable with a dtype_of hook rooflines its
    MFU against that dtype's peak and surfaces `dtype` in the report."""
    from predictionio_tpu.obs import devprof

    monkeypatch.setenv("PIO_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("PIO_PEAK_FLOPS_INT8", "4e12")
    prof = devprof.DeviceProfiler()
    monkeypatch.setattr(devprof, "_profiler", prof)

    fn = jax.jit(lambda a, b: (a @ b))
    wrapped = devprof.instrument(
        "test.int8_mm", fn, dtype_of=lambda a, k: "int8"
    )
    x = jnp.asarray(
        np.random.RandomState(0).randint(-3, 3, (64, 64)), jnp.int8
    )
    np.asarray(wrapped(x.astype(jnp.float32), x.astype(jnp.float32).T))
    rep = prof.executable("test.int8_mm")
    assert rep is not None
    assert rep.get("dtype") == "int8"
    if rep.get("mfu") is not None:
        # the dtyped denominator was used
        assert rep["peak_flops_dtype"] == 4e12


def test_serving_jit_reports_int8_dtype():
    """End to end: an int8 staged-serving call lands in devprof with
    dtype int8 on the als.recommend_serving executable."""
    from predictionio_tpu.obs import devprof

    rng = np.random.RandomState(13)
    f = _factors(rng, u=16, i=200)
    sv = als.stage_serving(f, serve_dtype="int8")
    als.recommend_serving(sv, np.arange(4), 5)
    rep = devprof.get_profiler().executable("als.recommend_serving")
    assert rep is not None and rep.get("dtype") in ("int8", "f32")
