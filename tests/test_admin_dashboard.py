"""Admin REST API + dashboard tests (ports of reference AdminAPISpec +
Dashboard smoke)."""

import json
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.tools.admin import AdminServer
from predictionio_tpu.tools.dashboard import Dashboard


def req(port, path, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data,
        headers={"Content-Type": "application/json"}, method=method,
    )
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            raw = resp.read().decode()
            return resp.status, raw
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture()
def admin(fresh_storage):
    srv = AdminServer(fresh_storage, ip="127.0.0.1", port=0)
    port = srv.start()
    yield fresh_storage, port
    srv.stop()


class TestAdminAPI:
    def test_status_and_app_crud(self, admin):
        storage, port = admin
        status, raw = req(port, "/")
        assert status == 200 and json.loads(raw)["status"] == "alive"

        status, raw = req(port, "/cmd/app", "POST", {"name": "adm1"})
        assert status == 201
        created = json.loads(raw)
        assert created["name"] == "adm1" and created["accessKey"]

        status, raw = req(port, "/cmd/app", "POST", {"name": "adm1"})
        assert status == 409

        status, raw = req(port, "/cmd/app")
        apps = json.loads(raw)
        assert [a["name"] for a in apps] == ["adm1"]
        assert apps[0]["accessKeys"] == [created["accessKey"]]

        status, _ = req(port, "/cmd/app/adm1/data", "DELETE")
        assert status == 200
        status, _ = req(port, "/cmd/app/adm1", "DELETE")
        assert status == 200
        status, _ = req(port, "/cmd/app/adm1", "DELETE")
        assert status == 404

    def test_create_requires_name(self, admin):
        _, port = admin
        status, raw = req(port, "/cmd/app", "POST", {})
        assert status == 400


class TestDashboard:
    def test_lists_completed_evaluations(self, fresh_storage):
        # seed a completed evaluation via the real workflow
        from predictionio_tpu.controller import Evaluation
        from predictionio_tpu.controller.metrics import AverageMetric
        from predictionio_tpu.workflow.evaluation import run_evaluation
        import sample_engine as se
        from test_evaluation import ep_with_algo

        class M(AverageMetric):
            def calculate_one(self, q, p, a):
                return p.algo_id

        class E(Evaluation):
            engine = se.Engine0Factory().apply()
            metric = M()

        inst, _ = run_evaluation(fresh_storage, E(), [ep_with_algo(4)])

        srv = Dashboard(fresh_storage, ip="127.0.0.1", port=0)
        port = srv.start()
        try:
            status, html_page = req(port, "/")
            assert status == 200
            assert inst.id in html_page and "4.0" in html_page

            status, detail = req(port, f"/engine_instances/{inst.id}.html")
            assert status == 200 and "M" in detail
            status, js = req(port, f"/engine_instances/{inst.id}.json")
            assert status == 200 and json.loads(js)["bestScore"] == 4.0

            status, _ = req(port, "/engine_instances/nope.html")
            assert status == 404
        finally:
            srv.stop()


class TestAdminTenants:
    def test_tenant_crud_and_quota(self, admin):
        storage, port = admin
        status, raw = req(port, "/tenants")
        assert status == 200 and json.loads(raw) == []

        status, raw = req(port, "/tenants", "POST", {
            "id": "acme", "engine_id": "rec", "weight": 2.0, "qps": 50,
        })
        assert status == 201
        t = json.loads(raw)
        assert t["engine_variant"] == "rec" and t["qps"] == 50.0

        # upsert of an existing tenant is a 200, not a duplicate
        status, raw = req(port, "/tenants", "POST", {
            "id": "acme", "engine_id": "rec", "weight": 3.0,
        })
        assert status == 200 and json.loads(raw)["weight"] == 3.0

        # malformed records 400 (bad id charset / missing engine)
        status, _ = req(port, "/tenants", "POST", {"id": "a/b",
                                                   "engine_id": "rec"})
        assert status == 400
        status, _ = req(port, "/tenants", "POST", {"id": "ok"})
        assert status == 400

        status, raw = req(port, "/tenants/acme/quota", "POST", {
            "qps": 10, "max_concurrency": 4,
        })
        assert status == 200
        t = json.loads(raw)
        assert t["qps"] == 10.0 and t["max_concurrency"] == 4
        status, _ = req(port, "/tenants/ghost/quota", "POST", {"qps": 1})
        assert status == 404
        status, _ = req(port, "/tenants/acme/quota", "POST", {"bogus": 1})
        assert status == 400

        status, raw = req(port, "/tenants/acme")
        assert status == 200 and json.loads(raw)["qps"] == 10.0
        status, raw = req(port, "/tenants")
        assert [x["id"] for x in json.loads(raw)] == ["acme"]

        status, _ = req(port, "/tenants/acme", "DELETE")
        assert status == 200
        status, _ = req(port, "/tenants/acme", "DELETE")
        assert status == 404

    def test_dashboard_tenants_panel(self, fresh_storage):
        from predictionio_tpu.tenancy import Tenant, TenantStore
        from predictionio_tpu.tools.dashboard import Dashboard

        TenantStore(fresh_storage).upsert(Tenant(
            id="acme", engine_id="rec", qps=25.0,
            description="<b>needs escaping</b>",
        ))
        dash = Dashboard(fresh_storage, ip="127.0.0.1", port=0)
        port = dash.start()
        try:
            status, raw = req(port, "/")
            assert status == 200
            assert "Tenants" in raw and "acme" in raw
            assert "<b>needs escaping</b>" not in raw  # escaped
            assert "&lt;b&gt;" in raw
        finally:
            dash.stop()
