"""Device-profiling layer (ISSUE 3): executable registry, degradation
contract, padding-waste accounting, /debug/profile + capture endpoints,
and the query-server acceptance path (batched queries → nonzero flops,
MFU in (0, 1], padding histogram with samples)."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.obs import devprof
from predictionio_tpu.obs.registry import MetricsRegistry


@pytest.fixture(autouse=True)
def _fresh_profiler():
    devprof.get_profiler().clear()
    yield
    devprof.get_profiler().clear()


# ---------------------------------------------------------------------------
# degradation contract — profiling must never break the caller
# ---------------------------------------------------------------------------


class _FakeJit:
    """Duck-typed 'jitted' callable whose AOT surface misbehaves."""

    def __init__(self, result=42.0, lower_raises=False, cost_raises=False):
        self.result = result
        self.lower_raises = lower_raises
        self.cost_raises = cost_raises
        self.calls = 0

    def __call__(self, *args, **kwargs):
        self.calls += 1
        return self.result

    def lower(self, *args, **kwargs):
        if self.lower_raises:
            raise RuntimeError("private API moved")
        outer = self

        class _Lowered:
            def cost_analysis(self):
                if outer.cost_raises:
                    raise RuntimeError("cost_analysis drifted")
                return {"flops": 123.0, "bytes accessed": 456.0}

            def compile(self):
                raise RuntimeError("no backend here")

        return _Lowered()


def test_no_lower_attribute_degrades_to_zero_analysis():
    fn = lambda x: x + 1  # plain callable: no .lower at all
    wrapped = devprof.instrument("t.nolower", fn)
    assert wrapped(2) == 3
    prof = devprof.get_profiler().executable("t.nolower")
    assert prof is not None
    assert prof["invocations"] == 1
    assert prof["flops_total"] == 0.0
    assert prof["cost_analysis_ok"] is False


def test_lower_raising_counts_invocations_without_flops():
    fake = _FakeJit(lower_raises=True)
    wrapped = devprof.instrument("t.lowerfail", fake)
    for _ in range(3):
        assert wrapped(1.0) == 42.0
    prof = devprof.get_profiler().executable("t.lowerfail")
    assert fake.calls == 3
    assert prof["invocations"] == 3
    assert prof["flops_total"] == 0.0


def test_cost_analysis_raising_degrades_but_still_counts():
    fake = _FakeJit(cost_raises=True)
    wrapped = devprof.instrument("t.costfail", fake)
    wrapped(1.0)
    prof = devprof.get_profiler().executable("t.costfail")
    assert prof["invocations"] == 1
    assert prof["cost_analysis_ok"] is False
    assert prof["flops_total"] == 0.0
    # memory path failing (compile raises) must not poison anything
    wrapped_m = devprof.instrument("t.memfail", _FakeJit(), memory=True)
    wrapped_m(1.0)
    prof = devprof.get_profiler().executable("t.memfail")
    assert prof["flops_total"] == 123.0
    assert prof["memory_analysis_ok"] is False


def test_wrapped_function_exception_propagates_once():
    calls = {"n": 0}

    def boom(x):
        calls["n"] += 1
        raise ValueError("query-level contract violation")

    wrapped = devprof.instrument("t.boom", boom)
    with pytest.raises(ValueError):
        wrapped(1)
    assert calls["n"] == 1  # never re-executed by profiler bookkeeping


def test_failed_first_call_does_not_poison_signature():
    """A raising first call must release its reserved analysis slot so a
    later successful call still gets analyzed."""
    state = {"fail": True}
    inner = _FakeJit()

    def flaky(*args, **kwargs):
        if state["fail"]:
            raise RuntimeError("transient")
        return inner(*args, **kwargs)

    flaky.lower = inner.lower
    wrapped = devprof.instrument("t.flaky", flaky)
    with pytest.raises(RuntimeError):
        wrapped(1.0)
    state["fail"] = False
    wrapped(1.0)
    prof = devprof.get_profiler().executable("t.flaky")
    assert prof["invocations"] == 1  # the failed call never accounted
    assert prof["flops_total"] == 123.0  # ...and analysis still ran


def test_disabled_via_env_is_pure_passthrough(monkeypatch):
    monkeypatch.setenv("PIO_DEVPROF", "0")
    wrapped = devprof.instrument("t.disabled", _FakeJit())
    wrapped(1.0)
    assert devprof.get_profiler().executable("t.disabled") is None


def test_jax_absent_passthrough_and_empty_report(monkeypatch):
    import sys

    monkeypatch.delitem(sys.modules, "jax", raising=False)
    fake = _FakeJit()
    wrapped = devprof.instrument("t.nojax", fake)
    assert wrapped(1.0) == 42.0
    assert fake.calls == 1
    # nothing recorded — the wrapper never engaged
    assert devprof.get_profiler().executable("t.nojax") is None
    rep = devprof.report()
    assert rep["executables"] == []
    assert rep["platform"]["platform"] is None
    assert rep["totals"]["invocations"] == 0


def test_platform_missing_from_peak_table_yields_no_mfu(monkeypatch):
    monkeypatch.setattr(devprof, "PEAK_TABLE", {})
    monkeypatch.delenv("PIO_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("PIO_PEAK_HBM_BPS", raising=False)
    info = devprof.platform_info()
    assert info["peak_flops"] is None
    assert info["peak_source"] == "none"
    assert devprof.mfu(1e9, 1.0) is None
    fake = _FakeJit()
    wrapped = devprof.instrument("t.nopeak", fake)
    wrapped(1.0)
    prof = devprof.get_profiler().executable("t.nopeak")
    assert prof["invocations"] == 1
    assert "mfu" not in prof  # derived fields absent, not wrong


def test_env_peak_override(monkeypatch):
    monkeypatch.setenv("PIO_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("PIO_PEAK_HBM_BPS", "1e11")
    info = devprof.platform_info()
    assert info["peak_flops"] == 1e12
    assert info["peak_source"] == "env"
    assert devprof.mfu(5e11, 1.0) == 0.5
    assert devprof.hbm_fraction(5e10, 1.0) == 0.5
    # clamped at 1.0
    assert devprof.mfu(5e13, 1.0) == 1.0


# ---------------------------------------------------------------------------
# real jit integration
# ---------------------------------------------------------------------------


def test_real_jit_cost_memory_and_scale():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    @jax.jit
    def mm(a, b):
        return a @ b

    wrapped = devprof.instrument("t.matmul", mm, memory=True)
    x = np.ones((64, 64), np.float32)
    for _ in range(4):
        wrapped(x, x)
    prof = devprof.get_profiler().executable("t.matmul")
    assert prof["invocations"] == 4
    assert prof["signatures"] == 1
    assert prof["cost_analysis_ok"]
    # 2*64^3 = 524288 flops per call
    assert prof["flops_per_call"] == pytest.approx(2 * 64**3, rel=0.05)
    assert prof["flops_total"] == pytest.approx(4 * 2 * 64**3, rel=0.05)
    assert prof["memory_analysis_ok"]
    assert prof["argument_bytes"] == 2 * 64 * 64 * 4
    assert prof["output_bytes"] == 64 * 64 * 4
    assert prof["device_seconds"] > 0
    assert 0 < prof["mfu"] <= 1.0
    # second shape → second signature
    y = np.ones((32, 32), np.float32)
    wrapped(y, y)
    prof = devprof.get_profiler().executable("t.matmul")
    assert prof["signatures"] == 2

    # scale_by: static-kwarg loop correction multiplies per-call flops
    from functools import partial

    @partial(jax.jit, static_argnames=("iterations",))
    def loopy(a, *, iterations):
        return jax.lax.fori_loop(0, iterations, lambda i, c: c @ a, a)

    w2 = devprof.instrument("t.loopy", loopy, scale_by="iterations")
    w2(x, iterations=7)
    prof = devprof.get_profiler().executable("t.loopy")
    assert prof["flops_scaled_by"] == "iterations"
    assert prof["flops_total"] == pytest.approx(7 * prof["flops_per_call"])

    # attribute access forwards to the wrapped jit (AOT surface intact)
    assert hasattr(wrapped, "lower")
    snap = devprof.snapshot()
    assert snap.invocations == 6
    assert snap.flops > 0


def test_nested_dispatch_passes_through_untimed():
    jax = pytest.importorskip("jax")

    inner = devprof.instrument("t.inner", jax.jit(lambda a: a * 2))

    @jax.jit
    def outer(a):
        return inner(a) + 1

    out = outer(np.ones(4, np.float32))
    np.testing.assert_allclose(np.asarray(out), 3.0)
    # the traced call must NOT have recorded (timing tracers is bogus)
    assert devprof.get_profiler().executable("t.inner") is None
    # a top-level dispatch of the same wrapper records normally
    inner(np.ones(4, np.float32))
    assert devprof.get_profiler().executable("t.inner")["invocations"] == 1


# ---------------------------------------------------------------------------
# padding accounting
# ---------------------------------------------------------------------------


def test_record_batch_padding_and_summary():
    reg = MetricsRegistry()
    devprof.record_batch_padding(5, 8, flops=8000.0, registry=reg)
    devprof.record_batch_padding(8, 8, flops=1000.0, registry=reg)
    s = devprof.padding_summary(registry=reg)
    assert s["batches"] == 2
    assert s["rows_real"] == 13
    assert s["rows_padded"] == 16
    # only the padded batch wastes: 8000 * 3/8 = 3000
    assert s["wasted_flops"] == pytest.approx(3000.0)
    assert 0 < s["mean_padding_ratio"] < 0.375 + 1e-9
    # degenerate inputs are inert
    devprof.record_batch_padding(3, 0, registry=reg)
    devprof.record_batch_padding(10, 8, flops=100.0, registry=reg)  # clamped
    s = devprof.padding_summary(registry=reg)
    assert s["batches"] == 3
    assert s["wasted_flops"] == pytest.approx(3000.0)


def test_external_seconds_attribution():
    devprof.get_profiler().record_external("t.dispatcher", 0.25, 3)
    prof = devprof.get_profiler().executable("t.dispatcher")
    assert prof["device_seconds"] == pytest.approx(0.25)
    assert prof["invocations"] == 3


# ---------------------------------------------------------------------------
# gauges + report shape
# ---------------------------------------------------------------------------


def test_devprof_gauges_render_on_registry():
    jax = pytest.importorskip("jax")

    wrapped = devprof.instrument("t.gauge", jax.jit(lambda a: a + 1))
    wrapped(np.ones((8, 8), np.float32))
    reg = MetricsRegistry()
    devprof.install_devprof_gauges(reg)
    text = reg.render()
    assert "devprof_executables 1" in text
    assert "devprof_invocations_total 1" in text
    assert "devprof_device_seconds_total" in text
    rep = devprof.report()
    assert rep["totals"]["invocations"] == 1
    assert rep["executables"][0]["name"] == "t.gauge"


def test_capture_requires_jax_and_serializes(monkeypatch, tmp_path):
    import sys

    with pytest.raises(ValueError):
        devprof.capture_trace(str(tmp_path), 0.0)
    monkeypatch.delitem(sys.modules, "jax", raising=False)
    with pytest.raises(RuntimeError, match="jax is not loaded"):
        devprof.capture_trace(str(tmp_path), 0.5)


# ---------------------------------------------------------------------------
# acceptance e2e: query server → /debug/profile
# ---------------------------------------------------------------------------


@pytest.fixture
def mem_storage():
    from predictionio_tpu.data.storage.registry import (
        SourceConfig,
        Storage,
        StorageConfig,
    )

    return Storage(StorageConfig(
        sources={"MEM": SourceConfig("MEM", "memory", {})},
        repositories={
            "METADATA": "MEM", "EVENTDATA": "MEM", "MODELDATA": "MEM",
        },
    ))


def _get_json(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return json.loads(r.read().decode())


def test_query_server_debug_profile_acceptance(mem_storage, monkeypatch):
    """The ISSUE 3 acceptance criterion: after a round of batched
    queries, GET /debug/profile reports ≥1 executable with nonzero
    flops, a derived MFU in (0, 1], and a batch_padding_ratio histogram
    with samples."""
    pytest.importorskip("jax")
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.workflow.core import run_train
    from predictionio_tpu.workflow.server import (
        QueryServer,
        QueryServerConfig,
        latest_completed_runtime,
    )

    app_id = mem_storage.get_meta_data_apps().insert(App(0, "profapp"))
    events = mem_storage.get_events()
    events.init_app(app_id)
    rng = np.random.RandomState(0)
    batch = [
        Event(
            event="rate", entity_type="user", entity_id=f"u{u}",
            target_entity_type="item", target_entity_id=f"i{rng.randint(20)}",
            properties={"rating": float(rng.randint(1, 6))},
        )
        for u in range(12) for _ in range(15)
    ]
    events.insert_batch(batch, app_id)
    variant = {
        "id": "profrec",
        "engineFactory":
            "predictionio_tpu.engines.recommendation.RecommendationEngine",
        "datasource": {"params": {"app_name": "profapp"}},
        "algorithms": [
            {"name": "als", "params": {"rank": 4, "num_iterations": 3}}
        ],
    }
    run_train(mem_storage, variant)
    runtime = latest_completed_runtime(mem_storage, "profrec", "0", "profrec")
    srv = QueryServer(
        mem_storage, runtime, QueryServerConfig(ip="127.0.0.1", port=0)
    )
    port = srv.start()
    try:
        # a round of concurrent queries so the dispatcher coalesces
        def post_one(u):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/queries.json",
                data=json.dumps({"user": f"u{u}", "num": 5}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            urllib.request.urlopen(req, timeout=30).read()

        threads = [
            threading.Thread(target=post_one, args=(u % 12,))
            for u in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        rep = _get_json(port, "/debug/profile")
        execs = [e for e in rep["executables"] if e["flops_total"] > 0]
        assert execs, "no executable with nonzero flops on /debug/profile"
        with_mfu = [e for e in execs if "mfu" in e]
        assert with_mfu, "no executable derived an MFU"
        for e in with_mfu:
            assert 0 < e["mfu"] <= 1.0
        assert rep["padding"]["batches"] > 0
        assert rep["padding"]["rows_padded"] >= rep["padding"]["rows_real"]

        # padding histogram also rides /metrics (merged default registry)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            text = r.read().decode()
        assert "batch_padding_ratio_count" in text
        assert "devprof_invocations_total" in text

        # capture endpoint is guarded: no PIO_PROFILE_CAPTURE_DIR → 403
        monkeypatch.delenv("PIO_PROFILE_CAPTURE_DIR", raising=False)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/debug/profile/capture",
            data=b"{}", headers={"Content-Type": "application/json"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 403
    finally:
        srv.stop()


def test_debug_profile_on_data_plane_server(mem_storage):
    """A server in a process that (notionally) never ran device work
    still serves a valid, possibly-empty profile — never a 500."""
    from predictionio_tpu.tools.admin import AdminServer

    devprof.get_profiler().clear()
    srv = AdminServer(mem_storage, ip="127.0.0.1", port=0)
    srv.start()
    try:
        rep = _get_json(srv.port, "/debug/profile")
        assert "executables" in rep and "platform" in rep
        assert rep["totals"]["invocations"] == 0
    finally:
        srv.stop()
