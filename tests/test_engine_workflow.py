"""Controller + workflow tests via the id-stamping fake engine zoo
(pattern: reference EngineTest.scala / EngineWorkflowTest.scala)."""

import dataclasses

import pytest

from predictionio_tpu.controller import (
    EmptyParams,
    EngineParams,
    ParamsError,
    RuntimeContext,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    WorkflowParams,
    extract_params,
    params_class_of,
    resolve_engine,
)
from predictionio_tpu.controller.persistent import RetrainOnDeploy
from predictionio_tpu.core.base import PersistentModelManifest
from predictionio_tpu.workflow.core import (
    engine_instance_to_engine_params,
    prepare_deploy_models,
    run_train,
)

import sample_engine as se


def make_ep(algos=(("algo0", se.AP(id=3)),), serving=("", EmptyParams())):
    return EngineParams(
        data_source_params=("", se.DSP(id=1)),
        preparator_params=("", se.PP(id=2)),
        algorithm_params_list=tuple(algos),
        serving_params=serving,
    )


def engine0():
    return resolve_engine(se.Engine0Factory)


class TestParamsExtraction:
    def test_params_class_of(self):
        assert params_class_of(se.Algo0) is se.AP
        assert params_class_of(se.NoParamsAlgo) is None

    def test_strict_unknown_key(self):
        with pytest.raises(ParamsError, match="unknown params"):
            extract_params(se.AP, {"id": 1, "bogus": 2})

    def test_defaults_fill_missing(self):
        p = extract_params(se.DSP, {"id": 5})
        assert p == se.DSP(id=5, error=False)

    def test_value_type_validation(self):
        import dataclasses as dc

        @dc.dataclass
        class Q:
            n: int = 1
            maybe: int | None = None  # PEP 604 union must be enforced too
            tags: list[str] = dc.field(default_factory=list)

        with pytest.raises(ParamsError, match="expects int"):
            extract_params(Q, {"n": "five"})
        with pytest.raises(ParamsError, match="expects"):
            extract_params(Q, {"maybe": "five"})
        with pytest.raises(ParamsError, match="expects list"):
            extract_params(Q, {"tags": "a"})
        assert extract_params(Q, {"maybe": 3, "tags": ["a"]}) == Q(1, 3, ["a"])

    def test_variant_json_roundtrip(self):
        variant = {
            "id": "v1",
            "engineFactory": "sample_engine.Engine0Factory",
            "datasource": {"params": {"id": 1}},
            "preparator": {"params": {"id": 2}},
            "algorithms": [
                {"name": "algo0", "params": {"id": 3}},
                {"name": "algo1", "params": {"id": 4}},
            ],
            "serving": {"name": "sum"},
        }
        ep = engine0().params_from_variant_json(variant)
        assert ep.data_source_params == ("", se.DSP(id=1))
        assert ep.preparator_params == ("", se.PP(id=2))
        assert ep.algorithm_params_list == (
            ("algo0", se.AP(id=3)),
            ("algo1", se.AP(id=4)),
        )
        assert ep.serving_params[0] == "sum"

    def test_variant_unbound_algo_name(self):
        variant = {
            "id": "v1",
            "engineFactory": "x",
            "algorithms": [{"name": "missing", "params": {}}],
        }
        with pytest.raises(ParamsError, match="not bound"):
            engine0().params_from_variant_json(variant)


class TestEngineTrain:
    def test_id_stamping_through_pipeline(self):
        models = engine0().train(RuntimeContext(), make_ep())
        assert models == [se.Model0(algo_id=3, td_id=1, p_id=2)]

    def test_multi_algo(self):
        ep = make_ep(algos=(("algo0", se.AP(id=3)), ("algo1", se.AP(id=7))))
        models = engine0().train(RuntimeContext(), ep)
        assert [m.algo_id for m in models] == [3, 7]

    def test_noparams_doer_path(self):
        ep = make_ep(algos=(("noparams", EmptyParams()),))
        models = engine0().train(RuntimeContext(), ep)
        assert models[0].algo_id == -1

    def test_stop_after_read(self):
        ctx = RuntimeContext(workflow_params=WorkflowParams(stop_after_read=True))
        with pytest.raises(StopAfterReadInterruption):
            engine0().train(ctx, make_ep())

    def test_stop_after_prepare(self):
        ctx = RuntimeContext(workflow_params=WorkflowParams(stop_after_prepare=True))
        with pytest.raises(StopAfterPrepareInterruption):
            engine0().train(ctx, make_ep())

    def test_sanity_check_dirty_data_raises(self):
        ep = dataclasses.replace(
            make_ep(), data_source_params=("", se.DSP(id=1, error=True))
        )
        with pytest.raises(ValueError, match="dirty"):
            engine0().train(RuntimeContext(), ep)

    def test_sanity_check_skipped(self):
        ep = dataclasses.replace(
            make_ep(), data_source_params=("", se.DSP(id=1, error=True))
        )
        ctx = RuntimeContext(workflow_params=WorkflowParams(skip_sanity_check=True))
        models = engine0().train(ctx, ep)
        assert models[0].td_id == 1


class TestEngineEval:
    def test_eval_serving_and_supplement(self):
        ep = make_ep(serving=("supp", EmptyParams()))
        results = engine0().eval(RuntimeContext(), ep)
        assert len(results) == 2  # two eval sets from DataSource0
        ei, qpa = results[0]
        assert ei.id == 0
        q, p, a = qpa[0]
        assert q.q == a.q == p.q
        assert p.supplemented  # supplement ran before predict
        assert (p.td_id, p.p_id, p.algo_id) == (1, 2, 3)

    def test_eval_multi_algo_sum_serving(self):
        ep = make_ep(
            algos=(("algo0", se.AP(id=3)), ("algo1", se.AP(id=7))),
            serving=("sum", EmptyParams()),
        )
        results = engine0().eval(RuntimeContext(), ep)
        _, qpa = results[0]
        assert qpa[0][1].algo_id == 10


VARIANT = {
    "id": "default",
    "engineFactory": "sample_engine.Engine0Factory",
    "datasource": {"params": {"id": 1}},
    "preparator": {"params": {"id": 2}},
    "algorithms": [{"name": "algo0", "params": {"id": 3}}],
    "serving": {},
}


class TestRunTrain:
    def test_lifecycle_and_model_blob(self, fresh_storage):
        inst = run_train(fresh_storage, VARIANT)
        assert inst.status == "COMPLETED"
        stored = fresh_storage.get_meta_data_engine_instances().get(inst.id)
        assert stored is not None and stored.status == "COMPLETED"
        latest = fresh_storage.get_meta_data_engine_instances().get_latest_completed(
            "default", "0", "default"
        )
        assert latest is not None and latest.id == inst.id

        engine, ep, models = prepare_deploy_models(fresh_storage, stored)
        assert models == [se.Model0(algo_id=3, td_id=1, p_id=2)]
        assert ep.algorithm_params_list == (("algo0", se.AP(id=3)),)

    def test_aborted_on_failure(self, fresh_storage):
        bad = dict(VARIANT, datasource={"params": {"id": 1, "error": True}})
        with pytest.raises(ValueError, match="dirty"):
            run_train(fresh_storage, bad)
        rows = fresh_storage.get_meta_data_engine_instances().get_all()
        assert [r.status for r in rows] == ["ABORTED"]

    def test_engine_instance_params_roundtrip(self, fresh_storage):
        inst = run_train(fresh_storage, VARIANT)
        stored = fresh_storage.get_meta_data_engine_instances().get(inst.id)
        ep = engine_instance_to_engine_params(engine0(), stored)
        assert ep.data_source_params == ("", se.DSP(id=1))
        assert ep.algorithm_params_list == (("algo0", se.AP(id=3)),)

    def test_named_serving_survives_roundtrip(self, fresh_storage):
        """Deploy must rebind the same named Serving class the train run
        used — not silently fall back to the ''-named binding."""
        variant = dict(VARIANT, serving={"name": "sum"})
        inst = run_train(fresh_storage, variant)
        stored = fresh_storage.get_meta_data_engine_instances().get(inst.id)
        ep = engine_instance_to_engine_params(engine0(), stored)
        assert ep.serving_params[0] == "sum"
        serving = engine0().make_serving(ep)
        assert type(serving).__name__ == "SumServing"


class TestPersistenceMatrix:
    def test_persistent_model_manifest(self, fresh_storage, tmp_path, monkeypatch):
        monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path / "pm"))
        variant = {
            "id": "pm",
            "engineFactory": "sample_engine.PersistentEngineFactory",
            "datasource": {"params": {"id": 1}},
            "preparator": {"params": {"id": 2}},
            "algorithms": [{"name": "", "params": {"id": 9}}],
        }
        inst = run_train(fresh_storage, variant)
        from predictionio_tpu.controller.persistent import deserialize_models

        blob = fresh_storage.get_model_data_models().get(inst.id)
        persisted = deserialize_models(blob.models)
        assert isinstance(persisted[0], PersistentModelManifest)
        assert persisted[0].class_name.endswith("PersistentModel0")

        _, _, models = prepare_deploy_models(
            fresh_storage, fresh_storage.get_meta_data_engine_instances().get(inst.id)
        )
        assert models[0] == se.PersistentModel0(algo_id=9, td_id=1, p_id=2)

    def test_unserializable_model_retrains_on_deploy(self, fresh_storage):
        variant = {
            "id": "un",
            "engineFactory": "sample_engine.UnserializableEngineFactory",
            "datasource": {"params": {"id": 1}},
            "preparator": {"params": {"id": 2}},
            "algorithms": [{"name": "", "params": {"id": 5}}],
        }
        inst = run_train(fresh_storage, variant)
        from predictionio_tpu.controller.persistent import deserialize_models

        blob = fresh_storage.get_model_data_models().get(inst.id)
        persisted = deserialize_models(blob.models)
        assert persisted == [RetrainOnDeploy(algo_index=0)]

        _, _, models = prepare_deploy_models(
            fresh_storage, fresh_storage.get_meta_data_engine_instances().get(inst.id)
        )
        assert isinstance(models[0], se.UnserializableModel)
        assert (models[0].algo_id, models[0].td_id) == (5, 1)


def test_train_registers_engine_manifest(fresh_storage):
    """VERDICT r2 #10: a successful train upserts the EngineManifest row
    (the reference registered at `pio build` — RegisterEngine.scala:32;
    here registration happens when the factory provably runs)."""
    storage, variant = fresh_storage, VARIANT
    inst = run_train(storage, variant)
    assert inst.status == "COMPLETED"
    m = storage.get_meta_data_engine_manifests().get(
        inst.engine_id, inst.engine_version
    )
    assert m is not None
    assert m.engine_factory == variant["engineFactory"]
    assert m.name == variant["id"]
    # retrain upserts, not duplicates
    run_train(storage, variant)
    all_m = storage.get_meta_data_engine_manifests().get_all()
    assert len([x for x in all_m if x.id == inst.engine_id]) == 1
