"""Histogram random forest (models/forest.py) — MLlib RandomForest parity
(reference add-algorithm RandomForestAlgorithm.scala)."""

import numpy as np
import pytest

from predictionio_tpu.models import classify, forest


@pytest.fixture(scope="module")
def multimodal():
    """3 blobs per class → multimodal within-class structure that a
    linear/NB model cannot capture but trees can."""
    rng = np.random.RandomState(0)
    n_per, C, D = 300, 4, 8
    cents = rng.randn(C, 3, D) * 3
    xs, ys = [], []
    for c in range(C):
        for b in range(3):
            xs.append(cents[c, b] + rng.randn(n_per // 3, D))
            ys.append(np.full(n_per // 3, c))
    x = np.concatenate(xs).astype(np.float32)
    y = np.concatenate(ys).astype(np.int32)
    perm = rng.permutation(len(x))
    x, y = x[perm], y[perm]
    ntr = int(0.8 * len(x))
    return x[:ntr], y[:ntr], x[ntr:], y[ntr:], C


class TestForest:
    def test_beats_naive_bayes_on_multimodal(self, multimodal):
        xtr, ytr, xte, yte, C = multimodal
        rf = forest.train_random_forest(xtr, ytr, C, n_trees=20, max_depth=6)
        acc_rf = (rf.predict(xte) == yte).mean()
        nb = classify.train_naive_bayes(np.abs(xtr), ytr, C)
        acc_nb = (nb.predict(np.abs(xte)) == yte).mean()
        assert acc_rf >= acc_nb, (acc_rf, acc_nb)
        assert acc_rf > 0.9, acc_rf

    def test_proba_normalized(self, multimodal):
        xtr, ytr, xte, _, C = multimodal
        rf = forest.train_random_forest(xtr, ytr, C, n_trees=5, max_depth=4)
        p = rf.predict_proba(xte)
        assert p.shape == (len(xte), C)
        np.testing.assert_allclose(p.sum(-1), 1.0, atol=1e-5)
        assert (p >= 0).all()

    def test_deterministic_given_seed(self, multimodal):
        xtr, ytr, _, _, C = multimodal
        a = forest.train_random_forest(xtr, ytr, C, n_trees=4, max_depth=4,
                                       seed=7)
        b = forest.train_random_forest(xtr, ytr, C, n_trees=4, max_depth=4,
                                       seed=7)
        assert (a.routes_f == b.routes_f).all()
        assert (a.routes_t == b.routes_t).all()
        np.testing.assert_array_equal(a.leaf_proba, b.leaf_proba)

    def test_early_stop_pure_node(self):
        """A perfectly separable 1-feature dataset: the root splits once,
        children are pure → deeper levels are leaves (feature == -1) and
        routing still lands every sample in the right class."""
        rng = np.random.RandomState(1)
        x = np.concatenate([rng.rand(50, 1), rng.rand(50, 1) + 5.0]).astype(
            np.float32
        )
        y = np.concatenate([np.zeros(50), np.ones(50)]).astype(np.int32)
        rf = forest.train_random_forest(x, y, 2, n_trees=3, max_depth=4)
        assert (rf.predict(x) == y).all()
        # below the first split every internal node is a leaf marker
        assert (rf.features[:, 2:, :] == -1).all()

    def test_mesh_parity(self, mesh8, multimodal):
        xtr, ytr, _, _, C = multimodal
        a = forest.train_random_forest(xtr, ytr, C, n_trees=4, max_depth=4)
        b = forest.train_random_forest(xtr, ytr, C, n_trees=4, max_depth=4,
                                       mesh=mesh8)
        assert (a.routes_f == b.routes_f).all()
        assert (a.routes_t == b.routes_t).all()
        np.testing.assert_allclose(a.leaf_proba, b.leaf_proba, atol=1e-5)
