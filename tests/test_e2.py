"""e2 library tests (ports of reference CategoricalNaiveBayesTest,
MarkovChainTest, BinaryVectorizerTest, CrossValidationTest)."""

import math

import numpy as np
import pytest

from predictionio_tpu.e2 import (
    BinaryVectorizer,
    CategoricalNaiveBayes,
    LabeledPoint,
    MarkovChain,
    split_data,
)


# fixture modeled on reference NaiveBayesFixture (sunny/rainy play tennis)
POINTS = [
    LabeledPoint("yes", ("sunny", "hot")),
    LabeledPoint("yes", ("sunny", "mild")),
    LabeledPoint("yes", ("overcast", "mild")),
    LabeledPoint("no", ("rainy", "hot")),
]


class TestCategoricalNaiveBayes:
    def test_priors_and_likelihoods(self):
        model = CategoricalNaiveBayes.train(POINTS)
        assert model.priors["yes"] == pytest.approx(math.log(3 / 4))
        assert model.priors["no"] == pytest.approx(math.log(1 / 4))
        assert model.likelihoods["yes"][0]["sunny"] == pytest.approx(
            math.log(2 / 3)
        )
        assert model.likelihoods["no"][1]["hot"] == pytest.approx(math.log(1.0))

    def test_predict(self):
        model = CategoricalNaiveBayes.train(POINTS)
        assert model.predict(("sunny", "mild")) == "yes"
        assert model.predict(("rainy", "hot")) == "no"

    def test_log_score_unknown_label_and_default(self):
        model = CategoricalNaiveBayes.train(POINTS)
        assert model.log_score(LabeledPoint("maybe", ("sunny", "hot"))) is None
        # unseen feature value → -inf without a default
        s = model.log_score(LabeledPoint("yes", ("foggy", "hot")))
        assert s == float("-inf")
        # with a default likelihood: min of knowns minus 1
        s = model.log_score(
            LabeledPoint("yes", ("foggy", "hot")),
            default_likelihood=lambda ls: min(ls) - 1.0,
        )
        assert s is not None and s > float("-inf")

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            CategoricalNaiveBayes.train([])


class TestMarkovChain:
    def test_row_normalized_topn(self):
        # transitions: 0→1 ×3, 0→2 ×1, 1→2 ×2
        model = MarkovChain.train(
            np.array([0, 0, 1]), np.array([1, 2, 2]), np.array([3, 1, 2]),
            n_states=3, top_n=2,
        )
        assert model.transition[0] == pytest.approx([0, 0.75, 0.25])
        assert model.transition[1] == pytest.approx([0, 0, 1.0])
        assert model.transition[2] == pytest.approx([0, 0, 0])  # unseen row

    def test_topn_prunes(self):
        model = MarkovChain.train(
            np.array([0, 0, 0]), np.array([0, 1, 2]), np.array([5, 3, 1]),
            n_states=3, top_n=2,
        )
        # smallest entry (0→2) pruned, rest renormalized
        assert model.transition[0] == pytest.approx([5 / 8, 3 / 8, 0])

    def test_predict(self):
        model = MarkovChain.train(
            np.array([0, 1]), np.array([1, 0]), np.array([1, 1]),
            n_states=2, top_n=2,
        )
        out = model.predict(np.array([1.0, 0.0]))
        assert out == pytest.approx([0.0, 1.0])


class TestBinaryVectorizer:
    def test_fit_and_encode(self):
        maps = [{"color": "red", "size": "L"}, {"color": "blue", "size": "L"}]
        vec = BinaryVectorizer.fit(maps, ["color", "size"])
        assert vec.num_features == 3  # (color,red),(color,blue),(size,L)
        v = vec.to_binary({"color": "red", "size": "L"})
        assert v.sum() == 2.0
        # unseen value and unindexed property are ignored
        v2 = vec.to_binary({"color": "green", "weight": "9"})
        assert v2.sum() == 0.0

    def test_property_restriction(self):
        vec = BinaryVectorizer.fit([{"a": "1", "b": "2"}], ["a"])
        assert set(vec.index) == {("a", "1")}

    def test_to_matrix(self):
        maps = [{"a": "1"}, {"a": "2"}]
        vec = BinaryVectorizer.fit(maps, ["a"])
        m = vec.to_matrix(maps)
        assert m.shape == (2, 2)
        assert m.sum() == 2.0


class TestSplitData:
    def test_folds_partition(self):
        data = list(range(10))
        folds = split_data(3, data)
        assert len(folds) == 3
        for train, test in folds:
            assert sorted(train + test) == data
        all_test = [x for _, test in folds for x in test]
        assert sorted(all_test) == data  # each element tested exactly once

    def test_bad_k(self):
        with pytest.raises(ValueError):
            split_data(0, [1])
