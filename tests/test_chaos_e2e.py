"""Chaos e2e (ISSUE 4 acceptance): storage outages — injected and real
(killed daemon) — must not lose events (WAL spill + ordered replay, no
duplicates); the query server keeps serving its loaded model when model
reload fails and sheds expired-deadline queries with 503 + Retry-After;
the storage client's breaker opens on outage and recovers through the
half-open probe."""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from predictionio_tpu.data.api.server import EventServer, EventServerConfig
from predictionio_tpu.data.api.storage_server import StorageServer
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    EventQuery,
    StorageCircuitOpenError,
)
from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.resilience import faults
from predictionio_tpu.resilience.breaker import reset_breakers

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _clean_faults_and_breakers():
    faults.clear()
    reset_breakers()
    yield
    faults.clear()
    reset_breakers()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post_event(port, key, entity_id):
    body = json.dumps({
        "event": "buy", "entityType": "user", "entityId": entity_id,
        "targetEntityType": "item", "targetEntityId": "i1",
    }).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/events.json?accessKey={key}",
        data=body, headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "null")


def _get(port, path, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", headers=headers or {}
    )
    with urllib.request.urlopen(req, timeout=15) as r:
        return r.status, r.read().decode(), dict(r.headers)


def _remote_storage(port: int) -> Storage:
    """Remote-backed Storage with fast-failure knobs so chaos tests don't
    sit out production retry budgets."""
    cfg = StorageConfig(
        sources={
            "RMT": SourceConfig("RMT", "remote", {
                "HOST": "127.0.0.1", "PORT": str(port),
                "RETRY_ATTEMPTS": "2", "RETRY_BASE_DELAY": "0.01",
                "BREAKER_THRESHOLD": "2", "BREAKER_COOLDOWN": "0.3",
            }),
        },
        repositories={
            "METADATA": "RMT", "EVENTDATA": "RMT", "MODELDATA": "RMT",
        },
    )
    return Storage(cfg)


def _daemon_storage(tmp_path) -> Storage:
    return Storage(StorageConfig(
        sources={
            "SQL": SourceConfig(
                "SQL", "sqlite", {"PATH": str(tmp_path / "chaos.db")}
            ),
        },
        repositories={
            "METADATA": "SQL", "EVENTDATA": "SQL", "MODELDATA": "SQL",
        },
    ))


# ---------------------------------------------------------------------------
# injected storage outage: spill → 202 → replay, zero loss / zero dupes
# ---------------------------------------------------------------------------


def test_injected_storage_outage_spills_and_replays(tmp_path):
    daemon = StorageServer(
        _daemon_storage(tmp_path), host="127.0.0.1", port=0
    ).start()
    srv = None
    try:
        storage = _remote_storage(daemon.port)
        app_id = storage.get_meta_data_apps().insert(App(0, "chaosapp"))
        storage.get_events().init_app(app_id)
        storage.get_meta_data_access_keys().insert(
            AccessKey(key="CK", app_id=app_id)
        )
        srv = EventServer(storage, EventServerConfig(
            ip="127.0.0.1", port=0, wal_dir=str(tmp_path / "wal"),
            wal_replay_interval_s=0.1,
        ))
        port = srv.start()

        # healthy path first
        status, body = _post_event(port, "CK", "u-ok")
        assert status == 201 and "eventId" in body

        # total storage outage, injected: every RPC attempt errors
        faults.install(faults.FaultSpec("storage.rpc", "error", 1.0))
        statuses = []
        for i in range(5):
            status, body = _post_event(port, "CK", f"u-spill-{i}")
            statuses.append(status)
            assert status == 202, body
            assert body.get("walId")
        assert statuses == [202] * 5  # accepted-and-durable, never 5xx

        _s, metrics, _h = _get(port, "/metrics")
        assert "event_wal_spilled_total 5" in metrics
        # the breaker tripped open during the outage and is on /metrics
        assert "resilience_breaker_state" in metrics

        # storage recovers: the background replayer drains the WAL (poll
        # on the replay counter — it increments after the inserts land,
        # so it is the race-free completion signal)
        faults.clear()
        deadline = time.time() + 15
        metrics = ""
        while time.time() < deadline:
            _s, metrics, _h = _get(port, "/metrics")
            if "event_wal_replayed_total 5" in metrics:
                break
            time.sleep(0.1)
        assert "event_wal_replayed_total 5" in metrics
        events = list(storage.get_events().find(EventQuery(app_id=app_id)))
        ids = sorted(e.entity_id for e in events)
        assert ids == sorted(
            ["u-ok"] + [f"u-spill-{i}" for i in range(5)]
        ), f"zero-loss/zero-dup violated: {ids}"
    finally:
        if srv is not None:
            srv.stop()
        daemon.shutdown()


# ---------------------------------------------------------------------------
# real outage: storage daemon killed mid-ingest, then restarted
# ---------------------------------------------------------------------------


def _spawn_daemon(tmp_path, port):
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": str(REPO) + os.pathsep + env.get("PYTHONPATH", ""),
        "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "shared.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
    })
    return subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.data.api.storage_server",
         "--host", "127.0.0.1", "--port", str(port)],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )


def _wait_health(port, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=2
            ) as r:
                if r.status == 200:
                    return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError(f"storage daemon on :{port} never became healthy")


def test_killed_daemon_mid_ingest_spills_then_replays(tmp_path):
    port = _free_port()
    proc = _spawn_daemon(tmp_path, port)
    srv = None
    try:
        _wait_health(port)
        storage = _remote_storage(port)
        app_id = storage.get_meta_data_apps().insert(App(0, "killapp"))
        storage.get_events().init_app(app_id)
        storage.get_meta_data_access_keys().insert(
            AccessKey(key="KK", app_id=app_id)
        )
        srv = EventServer(storage, EventServerConfig(
            ip="127.0.0.1", port=0, wal_dir=str(tmp_path / "wal"),
            wal_replay_interval_s=0.1,
        ))
        es_port = srv.start()
        for i in range(5):
            status, _ = _post_event(es_port, "KK", f"u-live-{i}")
            assert status == 201

        # kill the daemon mid-ingest — a REAL outage, not an injected one
        proc.kill()
        proc.wait(timeout=10)
        for i in range(5):
            status, body = _post_event(es_port, "KK", f"u-outage-{i}")
            assert status == 202, body

        # bring the daemon back on the same port + database
        proc = _spawn_daemon(tmp_path, port)
        _wait_health(port)

        deadline = time.time() + 20
        ids = []
        while time.time() < deadline:
            try:
                ids = [
                    e.entity_id for e in storage.get_events().find(
                        EventQuery(app_id=app_id)
                    )
                ]
            except Exception:
                ids = []
            if len(ids) >= 10:
                break
            time.sleep(0.2)
        assert sorted(ids) == sorted(
            [f"u-live-{i}" for i in range(5)]
            + [f"u-outage-{i}" for i in range(5)]
        ), f"zero-loss/zero-dup violated after daemon restart: {ids}"
    finally:
        if srv is not None:
            srv.stop()
        proc.kill()
        proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# breaker lifecycle against a real endpoint: open → fail fast → probe → close
# ---------------------------------------------------------------------------


def test_breaker_opens_then_half_open_probe_recovers(tmp_path):
    from predictionio_tpu.data.storage.base import StorageUnreachableError
    from predictionio_tpu.data.storage.remote import RemoteEventStore

    port = _free_port()
    daemon = StorageServer(
        _daemon_storage(tmp_path), host="127.0.0.1", port=port
    ).start()
    store = RemoteEventStore({
        "HOST": "127.0.0.1", "PORT": str(port),
        "RETRY_ATTEMPTS": "2", "RETRY_BASE_DELAY": "0.01",
        "BREAKER_THRESHOLD": "2", "BREAKER_COOLDOWN": "0.4",
    })
    store.init_app(1)
    daemon.shutdown()
    # in-proc shutdown closes the LISTENER; the established keep-alive
    # socket would still answer (its handler thread lives on), so drop
    # the pooled connection to simulate the daemon actually dying
    conn = getattr(store._client._local, "conn", None)
    if conn is not None:
        conn.close()
        store._client._local.conn = None

    breaker = store._client.breaker_for("events")
    for _ in range(2):  # two real failures trip the threshold
        with pytest.raises(StorageUnreachableError):
            store.init_app(1)
    assert breaker.state == "open"

    # open breaker fails FAST — no socket, no retry budget
    t0 = time.perf_counter()
    with pytest.raises(StorageCircuitOpenError):
        store.init_app(1)
    assert time.perf_counter() - t0 < 0.05

    # endpoint recovers; after the cooldown the next call is the probe
    daemon2 = StorageServer(
        _daemon_storage(tmp_path), host="127.0.0.1", port=port
    ).start()
    try:
        time.sleep(0.45)
        assert breaker.state == "half_open"
        assert store.init_app(1) is True  # probe succeeds ...
        assert breaker.state == "closed"  # ... and closes the breaker
        assert store.init_app(1) is True  # normal service resumed
    finally:
        daemon2.shutdown()


# ---------------------------------------------------------------------------
# query server: stale-model serving + deadline shedding
# ---------------------------------------------------------------------------


VARIANT = {
    "id": "chaosq",
    "engineFactory":
        "predictionio_tpu.engines.recommendation.RecommendationEngine",
    "datasource": {"params": {"app_name": "chaosq"}},
    "algorithms": [
        {"name": "als", "params": {"rank": 4, "num_iterations": 3}}
    ],
}


@pytest.fixture()
def served_chaos(fresh_storage):
    import numpy as np

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.workflow.core import run_train
    from predictionio_tpu.workflow.server import (
        QueryServer,
        QueryServerConfig,
        latest_completed_runtime,
    )

    apps = fresh_storage.get_meta_data_apps()
    app_id = apps.insert(App(id=0, name="chaosq"))
    events = fresh_storage.get_events()
    events.init_app(app_id)
    rng = np.random.RandomState(0)
    events.insert_batch(
        [
            Event(event="rate", entity_type="user", entity_id=f"u{u}",
                  target_entity_type="item",
                  target_entity_id=f"i{rng.randint(0, 5)}",
                  properties={"rating": 5.0})
            for u in range(4) for _ in range(10)
        ],
        app_id,
    )
    run_train(fresh_storage, VARIANT)
    runtime = latest_completed_runtime(fresh_storage, "chaosq", "0", "chaosq")
    srv = QueryServer(
        fresh_storage, runtime, QueryServerConfig(ip="127.0.0.1", port=0)
    )
    port = srv.start()
    yield srv, port
    srv.stop()


def _post_query(port, body, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/queries.json",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "null"), dict(e.headers)


def test_query_server_serves_stale_model_when_model_load_fails(served_chaos):
    """Model loading breaks (storage outage / corrupt blob): /reload
    fails loudly but the LAST-LOADED runtime keeps answering queries."""
    srv, port = served_chaos
    first_instance = srv.runtime.instance.id
    status, body, _ = _post_query(port, {"user": "u0", "num": 2})
    assert status == 200

    faults.install(faults.FaultSpec("model.load", "error", 1.0))
    status, _, _ = _post_query(port, {"user": "u0", "num": 2})
    assert status == 200  # serving never touches the fault point
    req = urllib.request.Request(f"http://127.0.0.1:{port}/reload")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=15)
    assert ei.value.code == 500
    assert srv.runtime.instance.id == first_instance  # old model retained
    status, body, _ = _post_query(port, {"user": "u0", "num": 2})
    assert status == 200 and "item_scores" in body


def test_expired_deadline_is_shed_with_503_retry_after(served_chaos):
    srv, port = served_chaos
    status, body, headers = _post_query(
        port, {"user": "u0", "num": 2}, headers={"X-PIO-Deadline": "0"}
    )
    assert status == 503
    assert headers.get("Retry-After") == "1"
    assert "shed" in body["message"]
    assert srv.metrics.counter(
        "queries_shed_total", "", ("reason",)
    ).value(reason="deadline") >= 1
    # a generous deadline flows through and the query still answers
    status, body, _ = _post_query(
        port, {"user": "u0", "num": 2}, headers={"X-PIO-Deadline": "10000"}
    )
    assert status == 200
