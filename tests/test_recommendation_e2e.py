"""End-to-end: events → ALS recommendation engine → train → deploy → predict.

The zero→aha loop of the reference (quickstart: app new → import events →
train → deploy → query), minus HTTP (covered by server tests)."""

import numpy as np
import pytest

from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.engines.recommendation import (
    ALSAlgorithmParams,
    DataSourceParams,
    Query,
    RecommendationDataSource,
)
from predictionio_tpu.workflow.core import prepare_deploy_models, run_train

VARIANT = {
    "id": "recommendation-test",
    "engineFactory": "predictionio_tpu.engines.recommendation.RecommendationEngine",
    "datasource": {
        "params": {"app_name": "testapp", "event_names": ["rate", "buy"]}
    },
    "algorithms": [
        {
            "name": "als",
            "params": {
                "rank": 8,
                "num_iterations": 8,
                "implicit_prefs": True,
                "lambda_": 0.05,
            },
        }
    ],
}


@pytest.fixture()
def seeded_storage(fresh_storage):
    """Two user cohorts with disjoint item-group preferences."""
    apps = fresh_storage.get_meta_data_apps()
    app_id = apps.insert(App(id=0, name="testapp"))
    events = fresh_storage.get_events()
    events.init_app(app_id)
    rng = np.random.RandomState(7)
    batch = []
    for u in range(10):
        group = u % 2
        for _ in range(30):
            item = rng.randint(0, 4) + group * 4  # items 0-3 vs 4-7
            batch.append(
                Event(
                    event="rate",
                    entity_type="user",
                    entity_id=f"u{u}",
                    target_entity_type="item",
                    target_entity_id=f"i{item}",
                    properties={"rating": float(rng.randint(3, 6))},
                )
            )
        # a couple of weak cross-group "buy" events (weight 1.0)
        batch.append(
            Event(
                event="buy",
                entity_type="user",
                entity_id=f"u{u}",
                target_entity_type="item",
                target_entity_id=f"i{(1 - group) * 4}",
            )
        )
    events.insert_batch(batch, app_id)
    return fresh_storage


def test_train_deploy_predict(seeded_storage):
    inst = run_train(seeded_storage, VARIANT)
    assert inst.status == "COMPLETED"

    stored = seeded_storage.get_meta_data_engine_instances().get(inst.id)
    engine, ep, models = prepare_deploy_models(seeded_storage, stored)
    algo = engine.make_algorithms(ep)[0]
    serving = engine.make_serving(ep)

    # cohort-0 user should rank cohort-0 items (i0-i3) on top
    q = serving.supplement(Query(user="u0", num=4))
    pred = serving.serve(q, [algo.predict(models[0], q)])
    assert len(pred.item_scores) == 4
    top_items = {s.item for s in pred.item_scores}
    assert len(top_items & {"i0", "i1", "i2", "i3"}) >= 3, top_items
    scores = [s.score for s in pred.item_scores]
    assert scores == sorted(scores, reverse=True)


def test_unknown_user_empty_result(seeded_storage):
    inst = run_train(seeded_storage, VARIANT)
    stored = seeded_storage.get_meta_data_engine_instances().get(inst.id)
    engine, ep, models = prepare_deploy_models(seeded_storage, stored)
    algo = engine.make_algorithms(ep)[0]
    pred = algo.predict(models[0], Query(user="nobody", num=5))
    assert pred.item_scores == []


def test_whitelist_blacklist(seeded_storage):
    inst = run_train(seeded_storage, VARIANT)
    stored = seeded_storage.get_meta_data_engine_instances().get(inst.id)
    engine, ep, models = prepare_deploy_models(seeded_storage, stored)
    algo = engine.make_algorithms(ep)[0]

    wl = algo.predict(models[0], Query(user="u0", num=8, whitelist=["i5", "i6"]))
    assert {s.item for s in wl.item_scores} <= {"i5", "i6"}

    bl = algo.predict(models[0], Query(user="u0", num=8, blacklist=["i0", "i1"]))
    assert not ({"i0", "i1"} & {s.item for s in bl.item_scores})


def test_categories_filter(seeded_storage):
    # tag items with category $set properties, retrain, filter
    app_id = seeded_storage.get_meta_data_apps().get_by_name("testapp").id
    events = seeded_storage.get_events()
    for i in range(8):
        events.insert(
            Event(
                event="$set",
                entity_type="item",
                entity_id=f"i{i}",
                properties={"categories": ["even" if i % 2 == 0 else "odd"]},
            ),
            app_id,
        )
    cat_variant = dict(
        VARIANT,
        datasource={
            "params": {"app_name": "testapp", "read_item_categories": True}
        },
    )
    inst = run_train(seeded_storage, cat_variant)
    stored = seeded_storage.get_meta_data_engine_instances().get(inst.id)
    engine, ep, models = prepare_deploy_models(seeded_storage, stored)
    algo = engine.make_algorithms(ep)[0]

    pred = algo.predict(models[0], Query(user="u0", num=8, categories=["even"]))
    items = {s.item for s in pred.item_scores}
    assert items and all(int(it[1:]) % 2 == 0 for it in items), items

    # categories AND blacklist compose
    pred = algo.predict(
        models[0],
        Query(user="u0", num=8, categories=["even"], blacklist=["i0"]),
    )
    items = {s.item for s in pred.item_scores}
    assert "i0" not in items and all(int(it[1:]) % 2 == 0 for it in items)


def test_batch_predict_matches_single(seeded_storage):
    inst = run_train(seeded_storage, VARIANT)
    stored = seeded_storage.get_meta_data_engine_instances().get(inst.id)
    engine, ep, models = prepare_deploy_models(seeded_storage, stored)
    algo = engine.make_algorithms(ep)[0]
    queries = [(i, Query(user=f"u{i}", num=3)) for i in range(4)]
    batch = dict(algo.batch_predict(RuntimeContext(), models[0], queries))
    for i, q in queries:
        single = algo.predict(models[0], q)
        assert [s.item for s in batch[i].item_scores] == [
            s.item for s in single.item_scores
        ]


def test_evaluation_grid_precision_at_k(seeded_storage):
    """Full tuning loop: grid over ALS rank, Precision@K picks a winner
    (reference `pio eval` path)."""
    from predictionio_tpu.controller import EmptyParams, Evaluation, EngineParams
    from predictionio_tpu.engines.recommendation import RecommendationEngine
    from predictionio_tpu.engines.recommendation.engine import (
        ALSAlgorithmParams,
        PrecisionAtK,
    )
    from predictionio_tpu.workflow.evaluation import run_evaluation

    dsp = DataSourceParams(app_name="testapp", eval_k=2, goal_threshold=4.0)
    grid = [
        EngineParams(
            data_source_params=("", dsp),
            preparator_params=("", EmptyParams()),
            algorithm_params_list=(
                ("als", ALSAlgorithmParams(rank=r, num_iterations=5)),
            ),
            serving_params=("", EmptyParams()),
        )
        for r in (4, 8)
    ]

    class RecEval(Evaluation):
        engine = RecommendationEngine().apply()
        metric = PrecisionAtK(k=5)

    inst, result = run_evaluation(seeded_storage, RecEval(), grid)
    assert inst.status == "EVALCOMPLETED"
    assert 0.0 <= result.best_score.score <= 1.0
    # each user has ≤4 cohort items and ~1-2 relevant held-out ones, so the
    # Precision@5 ceiling is ~0.3; assert we're clearly above zero (the
    # model ranks cohort items at the top)
    assert result.best_score.score > 0.1
    import json as _json

    parsed = _json.loads(result.to_json())
    assert len(parsed["scores"]) == 2
    assert parsed["metric"] == "Precision@5"


def test_read_eval_folds(seeded_storage):
    ds = RecommendationDataSource(
        DataSourceParams(app_name="testapp", eval_k=3, goal_threshold=4.0)
    )
    ctx = RuntimeContext(storage=seeded_storage)
    sets = ds.read_eval(ctx)
    assert len(sets) == 3
    total_train = sum(len(td.rows) for td, _, _ in sets)
    full = ds.read_training(ctx)
    assert total_train == 2 * len(full.rows)  # each fold holds out 1/3
    for td, ei, qa in sets:
        assert len(qa) > 0
        for q, a in qa:
            assert a.items  # only users with relevant held-out items
