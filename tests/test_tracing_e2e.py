"""Cross-process span tracing e2e (ISSUE 2 acceptance): a query through
the query server backed by remote storage yields ONE trace holding the
root server span, the micro-batch queue/device child spans, and the
storage RPC client span parented to the request — with the storage
daemon's own server span parented under the client span via
`X-Parent-Span`. Plus the `X-Request-ID`-on-RPC regression test and the
`pio trace` console commands."""

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.obs.spans import get_default_recorder
from predictionio_tpu.obs.tracing import trace_context


@pytest.fixture()
def keep_all_traces():
    """Tail sampling would probabilistically drop fast, clean test
    traffic — keep everything for the duration of a test."""
    rec = get_default_recorder()
    old = (rec.sample_rate, rec.max_traces)
    rec.sample_rate, rec.max_traces = 1.0, 2048
    yield rec
    rec.sample_rate, rec.max_traces = old


# -- satellite regression: RPCs carry X-Request-ID (+ X-Parent-Span) --------


class _HeaderCapture(BaseHTTPRequestHandler):
    captured: list[dict] = []

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        self.rfile.read(n)
        type(self).captured.append(dict(self.headers))
        body = json.dumps({"ok": True, "result": None}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def test_remote_client_propagates_trace_headers(keep_all_traces):
    """PR-1 gap: `RemoteClient.call` shipped NO `X-Request-ID`, so the
    storage daemon's access logs could not be correlated with the
    calling request. Every RPC must now carry the active trace id and
    the client span's id."""
    from predictionio_tpu.data.storage.remote import RemoteClient

    _HeaderCapture.captured = []
    srv = HTTPServer(("127.0.0.1", 0), _HeaderCapture)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        client = RemoteClient({
            "HOST": "127.0.0.1", "PORT": str(srv.server_address[1]),
        })
        with trace_context("rpc-regress-1"):
            client.call("apps", "get_by_name", "whatever")
        # outside any trace: the client span mints a trace id, so the
        # daemon STILL gets a correlatable id on every single RPC
        client.call("apps", "get_all")
    finally:
        srv.shutdown()
    assert len(_HeaderCapture.captured) == 2
    in_trace, bare = _HeaderCapture.captured
    assert in_trace["X-Request-ID"] == "rpc-regress-1"
    assert in_trace.get("X-Parent-Span"), "client span id must propagate"
    assert bare.get("X-Request-ID"), "RPC outside a trace still carries an id"
    # and the client span landed in the recorder under the right trace
    spans = keep_all_traces.get_trace("rpc-regress-1")
    rpc = [s for s in spans if s.name == "storage.rpc"]
    assert rpc and rpc[0].attrs["dao"] == "apps"
    assert in_trace["X-Parent-Span"] == rpc[0].span_id


# -- acceptance e2e ---------------------------------------------------------


UR_VARIANT = {
    "id": "trace-ur",
    "engineFactory":
        "predictionio_tpu.engines.universal.UniversalRecommenderEngine",
    "datasource": {
        "params": {"app_name": "traceapp", "indicators": ["buy"]}
    },
    "algorithms": [
        {
            "name": "ur",
            "params": {"app_name": "traceapp", "indicators": ["buy"]},
        }
    ],
}


def _get_json(url):
    with urllib.request.urlopen(url, timeout=15) as r:
        return r.status, json.loads(r.read().decode())


def test_trace_spans_cross_process_query(keep_all_traces):
    """The acceptance path: query server + storage daemon (remote
    EVENTDATA, so the UR history fetch RPCs at serve time), one traced
    query, one merged span tree, valid Perfetto export."""
    from predictionio_tpu.data.api.storage_server import StorageServer
    from predictionio_tpu.workflow.core import run_train
    from predictionio_tpu.workflow.server import (
        QueryServer,
        QueryServerConfig,
        latest_completed_runtime,
    )

    backing = Storage(StorageConfig(
        sources={"MEM": SourceConfig("MEM", "memory", {})},
        repositories={
            "METADATA": "MEM", "EVENTDATA": "MEM", "MODELDATA": "MEM",
        },
    ))
    daemon = StorageServer(backing, host="127.0.0.1", port=0).start()
    srv = None
    try:
        remote = Storage(StorageConfig(
            sources={"R": SourceConfig(
                "R", "remote",
                {"HOST": "127.0.0.1", "PORT": str(daemon.port)},
            )},
            repositories={
                "METADATA": "R", "EVENTDATA": "R", "MODELDATA": "R",
            },
        ))
        app_id = remote.get_meta_data_apps().insert(App(0, "traceapp"))
        remote.get_events().init_app(app_id)
        # two cohorts over 8 items so cross-occurrence has signal
        events = [
            Event(event="buy", entity_type="user", entity_id=f"u{u}",
                  target_entity_type="item",
                  target_entity_id=f"i{(u % 2) * 4 + j}")
            for u in range(12) for j in range(4)
        ]
        remote.get_events().insert_batch(events, app_id)

        inst = run_train(remote, UR_VARIANT)
        assert inst.status == "COMPLETED"
        runtime = latest_completed_runtime(
            remote, "trace-ur", "0", "trace-ur"
        )
        srv = QueryServer(
            remote, runtime, QueryServerConfig(ip="127.0.0.1", port=0)
        )
        port = srv.start()

        trace_id = "e2e-trace-accept"
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/queries.json",
            data=json.dumps(
                {"user": "u0", "num": 4, "exclude_seen": True}
            ).encode(),
            headers={
                "Content-Type": "application/json",
                "X-Request-ID": trace_id,
            },
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            assert r.headers["X-Request-ID"] == trace_id

        # the root span records just after the response bytes go out —
        # poll /debug/traces (which also exercises the endpoint)
        spans = None
        deadline = time.time() + 10
        while time.time() < deadline:
            status, data = _get_json(
                f"http://127.0.0.1:{port}/debug/traces?trace_id={trace_id}"
            )
            if status == 200:
                spans = data["spans"]
                break
            time.sleep(0.05)
        assert spans, "trace never appeared on /debug/traces"

        by_name: dict = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        # ONE trace: root server span of the query server...
        roots = [
            s for s in by_name["server.request"]
            if s["attrs"]["server"] == "query"
        ]
        assert len(roots) == 1
        root = roots[0]
        assert root["parent_span_id"] is None
        assert root["attrs"]["path"] == "/queries.json"
        # ...micro-batch queue + device child spans under the root...
        queue = by_name["batch.queue_wait"][0]
        device = by_name["batch.device_dispatch"][0]
        assert queue["parent_span_id"] == root["span_id"]
        assert device["parent_span_id"] == root["span_id"]
        assert "batch.assemble" in by_name
        assert "batch.result_transfer" in by_name
        # ...the storage RPC client span parented to the request (under
        # the device span the history fetch ran in)...
        rpcs = by_name["storage.rpc"]
        fetch = [s for s in rpcs if s["attrs"]["dao"] == "events"]
        assert fetch, rpcs
        assert all(s["parent_span_id"] == device["span_id"] for s in fetch)
        # ...and the storage DAEMON's server span parented under the rpc
        # client span across the process boundary via X-Parent-Span
        daemon_spans = [
            s for s in by_name["server.request"]
            if s["attrs"]["server"] == "storage"
        ]
        assert daemon_spans
        client_ids = {s["span_id"] for s in rpcs}
        assert all(
            s["parent_span_id"] in client_ids for s in daemon_spans
        )

        # Perfetto export of that trace validates as Chrome trace JSON
        export = keep_all_traces.perfetto_export(trace_id)
        parsed = json.loads(json.dumps(export))
        xs = [e for e in parsed["traceEvents"] if e["ph"] == "X"]
        assert {e["args"]["trace_id"] for e in xs} == {trace_id}
        assert len(xs) == len(spans)
        assert all(e["ph"] in ("X", "M") for e in parsed["traceEvents"])
        assert all(
            isinstance(e["ts"], (int, float))
            and isinstance(e["dur"], (int, float))
            for e in xs
        )
        procs = {
            e["args"]["name"]
            for e in parsed["traceEvents"] if e["ph"] == "M"
        }
        assert "query" in procs and "storage" in procs
        # the endpoint serves the same export
        status, remote_export = _get_json(
            f"http://127.0.0.1:{port}/debug/traces"
            f"?trace_id={trace_id}&format=perfetto"
        )
        assert status == 200
        assert len(remote_export["traceEvents"]) == len(
            parsed["traceEvents"]
        )
        # and format=perfetto WITHOUT a trace_id exports all retained
        # traces (what `pio trace export --url` with no id requests)
        status, all_export = _get_json(
            f"http://127.0.0.1:{port}/debug/traces?format=perfetto"
        )
        assert status == 200
        assert len(all_export["traceEvents"]) >= len(parsed["traceEvents"])

        # the summary listing shows it
        _s, listing = _get_json(
            f"http://127.0.0.1:{port}/debug/traces?limit=2048"
        )
        assert any(
            t["trace_id"] == trace_id for t in listing["traces"]
        )
        assert listing["sampling"]["sample_rate"] == 1.0

        # keep-alive reuse: a SECOND query on the same persistent
        # connection (same handler thread) must get a fresh, fully
        # parented trace — no span context may leak from the first
        import http.client as _hc

        conn = _hc.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            for tid2 in ("ka-trace-1", "ka-trace-2"):
                conn.request(
                    "POST", "/queries.json",
                    body=json.dumps({"user": "u1", "num": 2}).encode(),
                    headers={
                        "Content-Type": "application/json",
                        "X-Request-ID": tid2,
                    },
                )
                resp = conn.getresponse()
                assert resp.status == 200
                resp.read()
        finally:
            conn.close()
        deadline = time.time() + 10
        while time.time() < deadline:
            spans2 = keep_all_traces.get_trace("ka-trace-2")
            if spans2:
                break
            time.sleep(0.05)
        assert spans2
        assert all(s.trace_id == "ka-trace-2" for s in spans2)
        ids1 = {s.span_id for s in keep_all_traces.get_trace("ka-trace-1")}
        roots2 = [
            s for s in spans2
            if s.name == "server.request" and s.attrs["server"] == "query"
        ]
        assert len(roots2) == 1 and roots2[0].parent_span_id is None
        # every child parents within ITS trace, never into the previous
        # request's spans
        for s in spans2:
            assert s.parent_span_id not in ids1

        # the TRAIN trace exists too: stages as spans, RPC children
        train_traces = [
            t for t in listing["traces"] if t["root"] == "train"
        ]
        assert train_traces
        train_spans = keep_all_traces.get_trace(
            train_traces[0]["trace_id"]
        )
        names = {s.name for s in train_spans}
        assert {"train", "train.read", "train.train",
                "train.algorithm", "train.persist"} <= names
        # the read stage's storage RPCs hang off the train trace
        assert any(s.name == "storage.rpc" for s in train_spans)
    finally:
        if srv is not None:
            srv.stop()
        daemon.shutdown()


def test_pio_trace_console(keep_all_traces, tmp_path, capsys):
    from predictionio_tpu.tools.console import main

    with trace_context("cli-trace-1"):
        with keep_all_traces.span("server.request", server="query",
                                  path="/queries.json"):
            with keep_all_traces.span("batch.device_dispatch"):
                pass

    assert main(["trace", "list", "--limit", "2048"]) == 0
    out = capsys.readouterr().out
    assert "cli-trace-1" in out

    assert main(["trace", "show", "cli-trace-1"]) == 0
    out = capsys.readouterr().out
    assert "server.request" in out
    assert "batch.device_dispatch" in out

    dest = tmp_path / "trace.json"
    assert main(["trace", "export", "cli-trace-1",
                 "--output", str(dest)]) == 0
    exported = json.loads(dest.read_text())
    assert any(
        e["ph"] == "X" and e["args"]["trace_id"] == "cli-trace-1"
        for e in exported["traceEvents"]
    )

    assert main(["trace", "show", "no-such-trace"]) == 1
