"""Online learning end-to-end (ISSUE 9 acceptance): a brand-new user's
events fold into the LIVE serving model and `recommend` personalizes
without a retrain; a consumer killed mid-tick resumes from its durable
cursor with no lost and no double-applied events; injected drift pauses
fold-in, fires an alert, and leaves the last-good model serving."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.online import (
    OnlineConsumer,
    OnlineConsumerConfig,
    ServerApplyHost,
)
from predictionio_tpu.resilience import faults
from predictionio_tpu.workflow.core import run_train
from predictionio_tpu.workflow.server import (
    QueryServer,
    QueryServerConfig,
    build_runtime,
)

VARIANT = {
    "id": "onl",
    "engineFactory":
        "predictionio_tpu.engines.recommendation.RecommendationEngine",
    "datasource": {"params": {"app_name": "onlapp"}},
    "algorithms": [
        {"name": "als", "params": {"rank": 8, "num_iterations": 4}}
    ],
}

# two disjoint taste clusters: even users rate items 0-4, odd users 5-9
N_SEED_EVENTS_PER_USER = 20


def _seed(storage, n_users=8, seed=0):
    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(id=0, name="onlapp"))
    events = storage.get_events()
    events.init_app(app_id)
    rng = np.random.RandomState(seed)
    batch = []
    for u in range(n_users):
        for _ in range(N_SEED_EVENTS_PER_USER):
            i = rng.randint(0, 5) + (u % 2) * 5
            batch.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties={"rating": 5.0},
            ))
    events.insert_batch(batch, app_id)
    return app_id


def _post(port, path, body, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "null")


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=30
    ) as r:
        return r.status, json.loads(r.read().decode())


@pytest.fixture()
def served(fresh_storage):
    """A live query server over a trained model, no consumer yet."""
    app_id = _seed(fresh_storage)
    inst = run_train(fresh_storage, VARIANT)
    runtime = build_runtime(fresh_storage, inst)
    srv = QueryServer(
        fresh_storage, runtime,
        QueryServerConfig(ip="127.0.0.1", port=0, batch_window_ms=1.0),
    )
    port = srv.start()
    yield fresh_storage, srv, port, app_id
    faults.clear()
    srv.stop()


def _rate(uid, items, rating=5.0):
    return [
        Event(
            event="rate", entity_type="user", entity_id=uid,
            target_entity_type="item", target_entity_id=i,
            properties={"rating": rating},
        )
        for i in items
    ]


class TestColdStartFoldIn:
    def test_new_user_personalized_without_retrain(self, served):
        """The headline acceptance: a brand-new user's events stream in
        AFTER the model trained; the running consumer folds them and
        `recommend` answers personalized (non-empty, cluster-matching)
        results — with no retrain and zero serving interruption."""
        storage, srv, port, app_id = served
        tick_s = 0.1
        srv.attach_online(
            app_id,
            OnlineConsumerConfig(tick_s=tick_s, from_latest=True),
        )
        # an unknown user gets the empty (popularity-fallback-free)
        # result — the "before" picture
        status, body = _post(
            port, "/queries.json", {"user": "newbie", "num": 5}
        )
        assert status == 200 and body["item_scores"] == []

        storage.get_events().insert_batch(
            _rate("newbie", ["i5", "i6", "i7"]), app_id
        )
        t0 = time.perf_counter()
        deadline = time.monotonic() + 30.0
        scores = []
        while time.monotonic() < deadline:
            status, body = _post(
                port, "/queries.json", {"user": "newbie", "num": 5}
            )
            assert status == 200
            if body["item_scores"]:
                scores = body["item_scores"]
                break
            time.sleep(0.02)
        visible_after = time.perf_counter() - t0
        assert scores, "new user never became visible to serving"
        # personalized, not popularity: the top items come from the
        # odd-user cluster (i5..i9) this user's ratings match
        top = {s["item"] for s in scores[:3]}
        assert top <= {f"i{j}" for j in range(5, 10)}, scores
        # visibility latency is tick-bounded (generous CI slack: the
        # bench asserts the tight < 2-tick bar on quiet hardware)
        assert visible_after < 30.0
        st = _get(port, "/online/status")[1]
        assert st["state"] == "attached"
        assert st["counters"]["events_folded"] >= 3
        assert st["counters"]["users_folded"] >= 1

    def test_new_item_folds_symmetrically(self, served):
        storage, srv, port, app_id = served
        consumer = srv.attach_online(
            app_id, OnlineConsumerConfig(tick_s=60, from_latest=True),
        )
        consumer.stop()  # drive ticks manually
        # three odd-cluster users rate a brand-new item
        storage.get_events().insert_batch(
            [e for u in ("u1", "u3", "u5") for e in _rate(u, ["fresh"])],
            app_id,
        )
        out = consumer.tick()
        assert out["stats"]["items_added"] == 1
        assert out["stats"]["items_folded"] == 1
        # the new item is servable: similar odd-cluster users see it
        # scored (it shares their taste vector)
        ix, model = consumer.foldin.find_model(srv.runtime)
        assert model.factors.item_vocab.get("fresh") is not None
        row = model.factors.item_vocab("fresh")
        assert np.abs(model.factors.item_factors[row]).sum() > 0

    def test_new_item_overflow_carries_to_next_tick(self, served):
        """New items beyond max_items_per_tick must not be stranded with
        zero factor rows: the overflow solves on the following ticks."""
        import dataclasses as _dc

        storage, srv, port, app_id = served
        consumer = srv.attach_online(
            app_id, OnlineConsumerConfig(tick_s=60, from_latest=True),
        )
        consumer.stop()
        consumer.foldin.config = _dc.replace(
            consumer.foldin.config, max_items_per_tick=2
        )
        # an EXISTING user (nonzero factors — a brand-new user rating
        # only brand-new items is mutually zero-signal for single-pass
        # fold-in) rates 5 brand-new items in one tick
        storage.get_events().insert_batch(
            _rate("u1", [f"bulk{j}" for j in range(5)]), app_id
        )
        out = consumer.tick()
        assert out["stats"]["items_added"] == 5
        assert out["stats"]["items_folded"] == 2
        # a tick of IRRELEVANT traffic must also drain the carry (not
        # just a fully idle stream)
        storage.get_events().insert(
            Event(event="$set", entity_type="user", entity_id="u1",
                  properties={"plan": "pro"}),
            app_id,
        )
        out = consumer.tick()
        folded = 2 + out["stats"]["items_folded"]
        # the stream goes QUIET: idle ticks drain the rest
        for k in range(3):
            out = consumer.tick()
            if "stats" in out and out["stats"]:
                folded += out["stats"]["items_folded"]
        assert folded == 5
        assert consumer.foldin.pending_items == []
        assert consumer.tick() == {"idle": "no new events"}
        _ix, model = consumer.foldin.find_model(srv.runtime)
        for j in range(5):
            row = model.factors.item_vocab(f"bulk{j}")
            assert np.abs(model.factors.item_factors[row]).sum() > 0, (
                f"bulk{j} left with a zero factor row"
            )

    def test_discarded_tick_keeps_item_carry(self, served):
        """A discarded fold result (here: a lost swap race — a retrain
        promoting mid-tick; same path as a drift breach) must not
        consume the carried item-solve list — the commit happens only
        on a successful publish."""
        import dataclasses as _dc

        storage, srv, port, app_id = served
        consumer = srv.attach_online(
            app_id, OnlineConsumerConfig(tick_s=60, from_latest=True),
        )
        consumer.stop()
        consumer.foldin.config = _dc.replace(
            consumer.foldin.config, max_items_per_tick=2
        )
        storage.get_events().insert_batch(
            _rate("u1", [f"held{j}" for j in range(4)]), app_id
        )
        assert consumer.tick()["stats"]["items_folded"] == 2
        pending_before = consumer.foldin.pending_items
        assert len(pending_before) == 2
        # the drain tick loses the publish race → result discarded
        host = consumer.host
        orig_swap = host.swap
        host.swap = lambda old, new: False
        out = consumer.tick()
        assert out == {"retry": "runtime changed during fold"}
        assert consumer.foldin.pending_items == pending_before
        host.swap = orig_swap
        out = consumer.tick()  # clean drain publishes and commits
        assert out["stats"]["items_folded"] == 2
        assert consumer.foldin.pending_items == []

    def test_online_pause_resume_endpoints(self, served):
        storage, srv, port, app_id = served
        srv.attach_online(
            app_id, OnlineConsumerConfig(tick_s=60, from_latest=True),
        )
        status, st = _post(port, "/online/pause", {"reason": "ops"})
        assert status == 200 and st["paused"] == "ops"
        status, st = _post(port, "/online/resume", {})
        assert status == 200 and st["paused"] is None
        # detached server answers 404 on pause and "detached" on status
        srv.online.stop()
        srv.online = None
        assert _post(port, "/online/pause", {})[0] == 404
        assert _get(port, "/online/status")[1]["state"] == "detached"


class TestCursorCrashResume:
    def test_killed_mid_tick_no_loss_no_double_apply(self, served):
        """Chaos acceptance: the consumer dies BETWEEN applying a fold
        and persisting its cursor — the worst-case window. A fresh
        consumer resumes from the durable cursor; the fold counters
        show every relevant event applied exactly once."""
        storage, srv, port, app_id = served
        cfg = OnlineConsumerConfig(tick_s=60, from_latest=True)
        c1 = OnlineConsumer(
            storage, ServerApplyHost(srv), app_id, cfg,
        )
        # phase 1: a clean tick lands and persists
        storage.get_events().insert_batch(
            _rate("crash-a", ["i5", "i6"]), app_id
        )
        out = c1.tick()
        assert out["folded"] == 2
        # phase 2: crash mid-tick, AFTER the runtime swap
        storage.get_events().insert_batch(
            _rate("crash-b", ["i7", "i8", "i9"]), app_id
        )
        c1._crash_after_apply = True
        with pytest.raises(RuntimeError):
            c1.tick()
        # the fold DID reach serving...
        status, body = _post(
            port, "/queries.json", {"user": "crash-b", "num": 3}
        )
        assert status == 200 and body["item_scores"]
        # ...but was never accounted: the durable record still says 2
        c2 = OnlineConsumer(
            storage, ServerApplyHost(srv), app_id, cfg,
        )
        assert c2.counters["events_folded"] == 2
        out = c2.tick()  # replays the un-persisted window
        assert out["folded"] == 3
        # exactly-once accounting: 5 relevant events inserted → folded
        # counter says exactly 5, not 2 (lost) and not 8 (double)
        assert c2.counters["events_folded"] == 5
        assert c2.counters["events_consumed"] == 5
        assert c2.tick() == {"idle": "no new events"}
        assert c2.counters["events_folded"] == 5
        # the replayed fold is idempotent in model state: crash-b still
        # answers, and from the same history
        status, body = _post(
            port, "/queries.json", {"user": "crash-b", "num": 3}
        )
        assert status == 200 and body["item_scores"]


class TestDriftGuard:
    def test_injected_drift_pauses_alerts_and_serves_last_good(
        self, served
    ):
        """Chaos acceptance: a corrupting fault on the fold solve drives
        score drift past the threshold → fold-in pauses, a monitor
        alert fires, the cursor freezes, and serving keeps answering
        from the last-good model. Clearing the fault and resuming
        re-folds the same window cleanly."""
        from predictionio_tpu.obs.monitor import get_monitor

        storage, srv, port, app_id = served
        consumer = srv.attach_online(
            app_id,
            OnlineConsumerConfig(
                tick_s=60, from_latest=True, drift_threshold=0.5,
            ),
        )
        consumer.stop()  # manual ticks
        baseline_runtime = srv.runtime
        _status, before = _post(
            port, "/queries.json", {"user": "u1", "num": 3}
        )

        # every existing user re-rates → every user row re-solves, all
        # of them corrupted by the injected fault
        storage.get_events().insert_batch(
            [e for u in range(8) for e in _rate(f"u{u}", ["i2"], 3.0)],
            app_id,
        )
        faults.install(faults.FaultSpec("online.fold", "corrupt", 1.0))
        out = consumer.tick()
        assert "paused" in out and out["drift"] > 0.5
        assert consumer.paused
        # last-good model serves: the runtime reference never moved and
        # answers are unchanged
        assert srv.runtime is baseline_runtime
        _status, after = _post(
            port, "/queries.json", {"user": "u1", "num": 3}
        )
        assert after == before
        # the cursor did NOT advance (nothing lost)
        assert consumer.counters["events_consumed"] == 0
        # the alert is pio-alerts visible and firing, under a
        # per-consumer name (two scopes must not share one alert)
        payload = get_monitor().alerts_payload()
        assert consumer.alert_name in payload["firing"]
        assert consumer.alert_name.endswith(consumer.cursor_id)
        st = _get(port, "/online/status")[1]
        assert st["paused"]

        # recovery: clear the fault, resume, re-fold the window cleanly
        faults.clear()
        consumer.resume()
        out = consumer.tick()
        assert out.get("folded") == 8
        assert consumer.paused is None
        assert srv.runtime is not baseline_runtime
        assert (
            consumer.alert_name
            not in get_monitor().alerts_payload()["firing"]
        )

    def test_retrain_auto_resumes_drift_pause(self, served):
        """The alert's other documented recovery path: a retrain landing
        while DRIFT-paused rebases the baseline and resumes fold-in
        without an explicit /online/resume (operator pauses stay)."""
        from predictionio_tpu.obs.monitor import get_monitor

        storage, srv, port, app_id = served
        consumer = srv.attach_online(
            app_id,
            OnlineConsumerConfig(
                tick_s=60, from_latest=True, drift_threshold=0.5,
            ),
        )
        consumer.stop()
        storage.get_events().insert_batch(
            [e for u in range(8) for e in _rate(f"u{u}", ["i2"], 3.0)],
            app_id,
        )
        faults.install(faults.FaultSpec("online.fold", "corrupt", 1.0))
        assert "paused" in consumer.tick()
        faults.clear()
        # a retrain lands and is reloaded — no explicit resume
        run_train(storage, VARIANT)
        srv.reload()
        out = consumer.tick()
        assert consumer.paused is None
        assert out.get("folded") == 8
        assert (
            consumer.alert_name
            not in get_monitor().alerts_payload()["firing"]
        )
        # an OPERATOR pause does NOT auto-clear on retrain
        consumer.pause("operator hold")
        run_train(storage, VARIANT)
        srv.reload()
        assert consumer.tick() == {"paused": "operator hold"}

    def test_drift_cooldown_delays_resume_after_retrain(self, served):
        """ISSUE 19 satellite: with PIO_ONLINE_DRIFT_COOLDOWN_S (here
        via config) a drift-paused consumer does NOT resume the moment a
        retrain lands — it waits out the cool-down, then the next tick
        re-probes drift by folding and stays resumed when clean."""
        storage, srv, port, app_id = served
        consumer = srv.attach_online(
            app_id,
            OnlineConsumerConfig(
                tick_s=60, from_latest=True, drift_threshold=0.5,
                drift_cooldown_s=0.4,
            ),
        )
        consumer.stop()
        storage.get_events().insert_batch(
            [e for u in range(8) for e in _rate(f"u{u}", ["i2"], 3.0)],
            app_id,
        )
        faults.install(faults.FaultSpec("online.fold", "corrupt", 1.0))
        assert "paused" in consumer.tick()
        faults.clear()
        run_train(storage, VARIANT)
        srv.reload()
        # the retrain alone no longer resumes: this tick sees the new
        # runtime, rebases, and starts the cool-down clock
        out = consumer.tick()
        assert "paused" in out
        assert consumer.status()["cooling_down"] is True
        assert consumer.paused is not None
        # ... and once the cool-down expires, the next tick resumes and
        # the fold itself is the drift re-probe
        time.sleep(0.45)
        out = consumer.tick()
        assert consumer.paused is None
        assert out.get("folded") == 8
        assert consumer.status()["cooling_down"] is False
        # an OPERATOR pause never auto-resumes, cool-down or not
        consumer.pause("operator hold")
        run_train(storage, VARIANT)
        srv.reload()
        time.sleep(0.45)
        assert consumer.tick() == {"paused": "operator hold"}

    def test_error_fault_fails_tick_without_cursor_advance(self, served):
        storage, srv, port, app_id = served
        consumer = srv.attach_online(
            app_id, OnlineConsumerConfig(tick_s=60, from_latest=True),
        )
        consumer.stop()
        storage.get_events().insert_batch(_rate("ef", ["i1"]), app_id)
        faults.install(faults.FaultSpec("online.fold", "error", 1.0))
        with pytest.raises(faults.FaultInjected):
            consumer.tick()
        assert consumer.counters["events_consumed"] == 0
        faults.clear()
        assert consumer.tick()["folded"] == 1


class TestControlPlane:
    def test_admin_online_view_and_dashboard_panel(self, served):
        from predictionio_tpu.tools.admin import AdminServer

        storage, srv, port, app_id = served
        consumer = srv.attach_online(
            app_id, OnlineConsumerConfig(tick_s=60, from_latest=True),
        )
        consumer.stop()
        storage.get_events().insert_batch(_rate("adm", ["i5"]), app_id)
        consumer.tick()
        admin = AdminServer(storage, ip="127.0.0.1", port=0)
        admin_port = admin.start()
        try:
            status, body = _get(admin_port, "/online")
            assert status == 200
            rows = body["consumers"]
            assert len(rows) == 1
            assert rows[0]["cursor_id"] == consumer.cursor_id
            assert rows[0]["events_folded"] == 1
        finally:
            admin.stop()

    def test_same_version_rebuild_refolds_overlay(self, served):
        """A runtime rebuilt from the SAME trained instance (operator
        /reload, cache eviction) discards the fold overlay — the cursor
        rewinds to the baseline watermark and the window re-folds, so a
        folded cold-start user survives the rebuild."""
        storage, srv, port, app_id = served
        consumer = srv.attach_online(
            app_id, OnlineConsumerConfig(tick_s=60, from_latest=True),
        )
        consumer.stop()
        storage.get_events().insert_batch(
            _rate("phoenix", ["i5", "i6"]), app_id
        )
        assert consumer.tick()["folded"] == 2
        status, body = _post(
            port, "/queries.json", {"user": "phoenix", "num": 3}
        )
        assert body["item_scores"]
        # rebuild from the SAME version: the overlay is gone...
        srv.reload()
        status, body = _post(
            port, "/queries.json", {"user": "phoenix", "num": 3}
        )
        assert body["item_scores"] == []
        # ...until the next tick rewinds and re-folds it
        out = consumer.tick()
        assert out["folded"] == 2
        status, body = _post(
            port, "/queries.json", {"user": "phoenix", "num": 3}
        )
        assert body["item_scores"]

    def test_retrain_rebases_drift_baseline(self, served):
        """A retrain swapping the runtime mid-stream becomes the new
        drift baseline; folding continues on top of it."""
        storage, srv, port, app_id = served
        consumer = srv.attach_online(
            app_id, OnlineConsumerConfig(tick_s=60, from_latest=True),
        )
        consumer.stop()
        storage.get_events().insert_batch(_rate("rb", ["i5"]), app_id)
        assert consumer.tick()["folded"] == 1
        old_baseline = consumer.guard._baseline
        # a retrain lands and the operator reloads
        run_train(storage, VARIANT)
        srv.reload()
        storage.get_events().insert_batch(_rate("rb2", ["i6"]), app_id)
        out = consumer.tick()
        assert out["folded"] == 1
        assert consumer.guard._baseline is not old_baseline
        # the fresh model serves the folded user
        status, body = _post(
            port, "/queries.json", {"user": "rb2", "num": 3}
        )
        assert status == 200 and body["item_scores"]
