"""Unified metrics registry (ISSUE 1): counter/gauge/histogram semantics,
concurrent updates, Prometheus text rendering."""

import math
import re
import threading

import pytest

from predictionio_tpu.obs.registry import (
    BATCH_SIZE_BUCKETS,
    MetricsRegistry,
    render_merged,
)


@pytest.fixture()
def reg():
    return MetricsRegistry()


# -- counters ---------------------------------------------------------------

def test_counter_inc_and_labels(reg):
    c = reg.counter("reqs_total", "requests", ("path", "status"))
    c.inc(path="/a", status=200)
    c.inc(path="/a", status=200)
    c.inc(3, path="/b", status=404)
    assert c.value(path="/a", status=200) == 2
    assert c.value(path="/b", status=404) == 3
    assert c.value(path="/c", status=500) == 0
    assert c.total == 5


def test_counter_rejects_negative_and_bad_labels(reg):
    c = reg.counter("c_total", "", ("x",))
    with pytest.raises(ValueError):
        c.inc(-1, x="a")
    with pytest.raises(ValueError):
        c.inc(y="a")  # undeclared label
    with pytest.raises(ValueError):
        c.inc()  # missing label


def test_reregistration_same_name_same_family(reg):
    a = reg.counter("same_total", "", ("x",))
    b = reg.counter("same_total", "", ("x",))
    assert a is b
    with pytest.raises(ValueError):
        reg.histogram("same_total")  # type conflict
    with pytest.raises(ValueError):
        reg.counter("same_total", "", ("y",))  # label-set conflict


def test_histogram_bucket_conflict_is_loud(reg):
    a = reg.histogram("h_seconds", "", buckets=BATCH_SIZE_BUCKETS)
    assert reg.histogram("h_seconds", "", buckets=BATCH_SIZE_BUCKETS) is a
    with pytest.raises(ValueError):
        reg.histogram("h_seconds")  # different (default latency) buckets


# -- gauges -----------------------------------------------------------------

def test_gauge_set_inc_dec(reg):
    g = reg.gauge("temp", "", ("zone",))
    g.set(4.5, zone="a")
    g.inc(zone="a")
    g.dec(0.5, zone="a")
    assert g.value(zone="a") == 5.0


def test_gauge_callback_sampled_at_read(reg):
    box = {"v": 1.0}
    g = reg.gauge_callback("live", "sampled", lambda: box["v"])
    assert g.value() == 1.0
    box["v"] = 7.0
    assert g.value() == 7.0
    assert "live 7" in reg.render()


def test_gauge_callback_failure_reads_zero(reg):
    def boom():
        raise RuntimeError("sampling failed")

    g = reg.gauge_callback("bad", "", boom)
    assert g.value() == 0.0  # scrape must never 500 on a bad sampler


# -- histograms -------------------------------------------------------------

def test_histogram_count_sum_mean(reg):
    h = reg.histogram("lat_seconds", "", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count == 3
    assert h.sum == pytest.approx(5.55)
    assert h.mean == pytest.approx(5.55 / 3)


def test_histogram_quantiles_interpolate(reg):
    h = reg.histogram("q_seconds", "", buckets=(1.0, 2.0, 4.0))
    for _ in range(100):
        h.observe(1.5)  # all samples in the (1, 2] bucket
    # interpolation stays inside the bucket for every quantile
    assert 1.0 <= h.quantile(0.5) <= 2.0
    assert 1.0 <= h.quantile(0.99) <= 2.0
    # empty histogram → 0
    h2 = reg.histogram("q2_seconds", "")
    assert h2.quantile(0.5) == 0.0


def test_histogram_overflow_bucket(reg):
    h = reg.histogram("of_seconds", "", buckets=(1.0,))
    h.observe(100.0)
    assert h.count == 1
    # +Inf-bucket samples are estimated at the highest finite edge
    assert h.quantile(0.5) == 1.0
    text = reg.render()
    assert 'of_seconds_bucket{le="1"} 0' in text
    assert 'of_seconds_bucket{le="+Inf"} 1' in text


def test_histogram_lower_bound_for_count_values(reg):
    h = reg.histogram(
        "bs", "", buckets=BATCH_SIZE_BUCKETS, lower_bound=1
    )
    for _ in range(10):
        h.observe(1)  # every batch had size 1
    # quantiles can never dip below the legal minimum (no p50 of 0.5)
    assert h.quantile(0.5) == 1.0
    assert h.quantile(0.99) == 1.0
    with pytest.raises(ValueError):  # lower_bound drift is loud too
        reg.histogram("bs", "", buckets=BATCH_SIZE_BUCKETS)


def test_histogram_labeled(reg):
    h = reg.histogram(
        "batch_size", "", ("server",), buckets=BATCH_SIZE_BUCKETS
    )
    h.observe(3, server="query")
    h.observe(64, server="query")
    assert h.count_of(server="query") == 2
    assert h.sum_of(server="query") == 67


# -- concurrency ------------------------------------------------------------

def test_concurrent_updates_lose_nothing(reg):
    c = reg.counter("hits_total", "", ("worker",))
    h = reg.histogram("work_seconds", "", buckets=(0.5, 1.0))
    n_threads, n_iter = 8, 2000

    def worker(i):
        for _ in range(n_iter):
            c.inc(worker=str(i % 2))
            h.observe(0.25)

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.total == n_threads * n_iter
    assert h.count == n_threads * n_iter
    assert h.sum == pytest.approx(0.25 * n_threads * n_iter)


# -- exposition -------------------------------------------------------------

def _parse_samples(text):
    """Minimal Prometheus text parser: {(name, labelstr): float}."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.fullmatch(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)", line)
        assert m, f"unparseable exposition line: {line!r}"
        value = float("inf") if m.group(3) == "+Inf" else float(m.group(3))
        out[(m.group(1), m.group(2) or "")] = value
    return out


def test_prometheus_rendering_full_document(reg):
    reg.counter("a_total", "things", ("k",)).inc(k='with"quote')
    reg.gauge("b", "a gauge").set(2.5)
    h = reg.histogram("c_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(50.0)
    text = reg.render()
    # HELP/TYPE lines present for each family
    for frag in (
        "# HELP a_total things", "# TYPE a_total counter",
        "# TYPE b gauge", "# TYPE c_seconds histogram",
    ):
        assert frag in text, text
    samples = _parse_samples(text)
    # label escaping round-trips
    assert samples[("a_total", '{k="with\\"quote"}')] == 1
    assert samples[("b", "")] == 2.5
    # cumulative buckets are monotone and +Inf equals count
    b1 = samples[("c_seconds_bucket", '{le="0.1"}')]
    b2 = samples[("c_seconds_bucket", '{le="1"}')]
    binf = samples[("c_seconds_bucket", '{le="+Inf"}')]
    assert b1 <= b2 <= binf
    assert binf == samples[("c_seconds_count", "")] == 3
    assert samples[("c_seconds_sum", "")] == pytest.approx(50.55)


def test_render_merged_first_registry_wins():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("shared_total", "").inc()
    b.counter("shared_total", "").inc(10)
    b.counter("only_b_total", "").inc(2)
    text = render_merged(a, b)
    samples = _parse_samples(text)
    assert samples[("shared_total", "")] == 1  # a shadows b
    assert samples[("only_b_total", "")] == 2
    assert text.count("# TYPE shared_total") == 1  # no duplicate families


def test_snapshot_shape(reg):
    reg.counter("n_total", "", ("x",)).inc(x="1")
    h = reg.histogram("t_seconds", "", buckets=(1.0, 2.0))
    h.observe(1.5)
    snap = reg.snapshot()
    assert snap["n_total"]["type"] == "counter"
    assert snap["n_total"]["values"][0] == {"labels": {"x": "1"}, "value": 1}
    row = snap["t_seconds"]["values"][0]
    assert row["count"] == 1 and row["sum"] == pytest.approx(1.5)
    for q in ("p50", "p95", "p99"):
        assert 1.0 <= row[q] <= 2.0
    assert not math.isnan(row["mean"])
