"""Horizontally-sharded event storage (the HBase region-server role).

Unit layer: ShardedEventStore over in-memory children — routing,
entity locality, ordered merge, by-id broadcast, aggregation. Daemon
layer: TWO storage-daemon processes, each holding a disjoint entity
shard of one app's events; a sharded client ingests through both and a
partitioned training read streams each shard from its own daemon only.
"""

import datetime as dt
import os
import subprocess
import sys
from pathlib import Path

import numpy as np

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import EventQuery, shard_of
from predictionio_tpu.data.storage.memory import MemoryEventStore
from predictionio_tpu.data.storage.sharded import ShardedEventStore

from test_remote_storage import _free_port, _wait_health

REPO = Path(__file__).resolve().parent.parent
UTC = dt.timezone.utc


def _mk(n_shards=3):
    children = [MemoryEventStore() for _ in range(n_shards)]
    store = ShardedEventStore(stores=children)
    store.init_app(1)
    return store, children


def _events(n=40, seed=0):
    rng = np.random.RandomState(seed)
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    return [
        Event(
            event="rate", entity_type="user", entity_id=f"u{i % 11}",
            target_entity_type="item", target_entity_id=f"i{i % 5}",
            properties={"rating": float(rng.randint(1, 6))},
            event_time=t0 + dt.timedelta(minutes=i),
        )
        for i in range(n)
    ]


class TestShardedUnit:
    def test_routing_and_entity_locality(self):
        store, children = _mk()
        ids = store.insert_batch(_events(), 1)
        assert len(ids) == 40 and all(ids)
        for sx, child in enumerate(children):
            for e in child.find(EventQuery(app_id=1)):
                assert shard_of(e.entity_id, 3) == sx
        # every shard got something at 11 entities over 3 shards
        counts = [
            len(list(c.find(EventQuery(app_id=1)))) for c in children
        ]
        assert all(c > 0 for c in counts) and sum(counts) == 40

    def test_merged_find_is_time_ordered(self):
        store, _ = _mk()
        store.insert_batch(_events(), 1)
        got = list(store.find(EventQuery(app_id=1)))
        assert len(got) == 40
        times = [e.event_time for e in got]
        assert times == sorted(times)
        rev = list(store.find(EventQuery(app_id=1, reversed=True)))
        assert [e.event_time for e in rev] == sorted(times, reverse=True)
        lim = list(store.find(EventQuery(app_id=1, limit=7)))
        assert [e.event_id for e in lim] == [e.event_id for e in got[:7]]

    def test_entity_query_hits_one_shard(self):
        store, children = _mk()
        store.insert_batch(_events(), 1)
        got = list(store.find(EventQuery(app_id=1, entity_id="u3")))
        assert got and all(e.entity_id == "u3" for e in got)
        home = children[shard_of("u3", 3)]
        assert len(got) == len(
            list(home.find(EventQuery(app_id=1, entity_id="u3")))
        )

    def test_partitioned_read_goes_straight_to_child(self):
        store, children = _mk()
        store.insert_batch(_events(), 1)
        for s in range(3):
            via_composite = {
                e.event_id
                for e in store.find(EventQuery(app_id=1, shard=(s, 3)))
            }
            direct = {
                e.event_id for e in children[s].find(EventQuery(app_id=1))
            }
            assert via_composite == direct
        # non-matching shard count still partitions correctly (filtered
        # per child + merged)
        union = set()
        for s in range(2):
            part = {
                e.event_id
                for e in store.find(EventQuery(app_id=1, shard=(s, 2)))
            }
            assert not (part & union)
            union |= part
        assert len(union) == 40

    def test_get_delete_broadcast_and_signature(self):
        store, _ = _mk()
        ids = store.insert_batch(_events(), 1)
        e = store.get(ids[5], 1)
        assert e is not None
        sig1 = store.data_signature(1)
        assert store.delete(ids[5], 1)
        assert store.get(ids[5], 1) is None
        assert not store.delete(ids[5], 1)
        assert store.data_signature(1) != sig1

    def test_aggregate_properties_union(self):
        store, _ = _mk()
        store.insert_batch(
            [
                Event(event="$set", entity_type="user", entity_id=f"u{i}",
                      properties={"plan": f"p{i}"})
                for i in range(9)
            ],
            1,
        )
        props = store.aggregate_properties(1, "user")
        assert len(props) == 9
        assert props["u4"].get("plan") == "p4"


def _daemon_env(tmp_path, tag):
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": str(REPO) + os.pathsep + env.get("PYTHONPATH", ""),
        "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / f"shard{tag}.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
    })
    return env


def test_two_daemon_sharded_ingest_and_partitioned_read(tmp_path):
    """End to end: events ingested through a 2-daemon sharded store land
    disjointly; shard=(i, 2) reads stream from daemon i alone."""
    procs, ports = [], []
    try:
        for tag in (0, 1):
            port = _free_port()
            ports.append(port)
            procs.append(subprocess.Popen(
                [
                    sys.executable, "-m",
                    "predictionio_tpu.data.api.storage_server",
                    "--host", "127.0.0.1", "--port", str(port),
                ],
                env=_daemon_env(tmp_path, tag), cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            ))
        for port in ports:
            _wait_health(port)

        store = ShardedEventStore(
            {"SHARDS": ",".join(f"127.0.0.1:{p}" for p in ports)}
        )
        store.init_app(7)
        events = _events(n=60, seed=3)
        ids = store.insert_batch(events, 7)
        assert len(ids) == 60 and all(ids)

        # disjoint partitioned reads, one per daemon, covering everything
        parts = [
            {e.event_id for e in store.find(EventQuery(app_id=7, shard=(s, 2)))}
            for s in range(2)
        ]
        assert parts[0] and parts[1]
        assert not (parts[0] & parts[1])
        assert len(parts[0] | parts[1]) == 60

        # each daemon REALLY holds only its shard (ask it directly)
        from predictionio_tpu.data.storage.remote import RemoteEventStore

        for s, port in enumerate(ports):
            direct = RemoteEventStore({"HOST": "127.0.0.1", "PORT": str(port)})
            held = list(direct.find(EventQuery(app_id=7)))
            assert held and {e.event_id for e in held} == parts[s]
            assert all(shard_of(e.entity_id, 2) == s for e in held)

        # merged full read is time-ordered and complete
        got = list(store.find(EventQuery(app_id=7)))
        assert len(got) == 60
        times = [e.event_time for e in got]
        assert times == sorted(times)
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)
