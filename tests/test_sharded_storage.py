"""Horizontally-sharded event storage (the HBase region-server role).

Unit layer: ShardedEventStore over in-memory children — routing,
entity locality, ordered merge, by-id broadcast, aggregation. Daemon
layer: TWO storage-daemon processes, each holding a disjoint entity
shard of one app's events; a sharded client ingests through both and a
partitioned training read streams each shard from its own daemon only.
"""

import datetime as dt
import os
import subprocess
import sys
from pathlib import Path

import numpy as np

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import EventQuery, shard_of
from predictionio_tpu.data.storage.memory import MemoryEventStore
from predictionio_tpu.data.storage.sharded import ShardedEventStore

from test_remote_storage import _free_port, _wait_health

REPO = Path(__file__).resolve().parent.parent
UTC = dt.timezone.utc


def _mk(n_shards=3):
    children = [MemoryEventStore() for _ in range(n_shards)]
    store = ShardedEventStore(stores=children)
    store.init_app(1)
    return store, children


def _events(n=40, seed=0):
    rng = np.random.RandomState(seed)
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    return [
        Event(
            event="rate", entity_type="user", entity_id=f"u{i % 11}",
            target_entity_type="item", target_entity_id=f"i{i % 5}",
            properties={"rating": float(rng.randint(1, 6))},
            event_time=t0 + dt.timedelta(minutes=i),
        )
        for i in range(n)
    ]


class TestShardedUnit:
    def test_routing_and_entity_locality(self):
        store, children = _mk()
        ids = store.insert_batch(_events(), 1)
        assert len(ids) == 40 and all(ids)
        for sx, child in enumerate(children):
            for e in child.find(EventQuery(app_id=1)):
                assert shard_of(e.entity_id, 3) == sx
        # every shard got something at 11 entities over 3 shards
        counts = [
            len(list(c.find(EventQuery(app_id=1)))) for c in children
        ]
        assert all(c > 0 for c in counts) and sum(counts) == 40

    def test_merged_find_is_time_ordered(self):
        store, _ = _mk()
        store.insert_batch(_events(), 1)
        got = list(store.find(EventQuery(app_id=1)))
        assert len(got) == 40
        times = [e.event_time for e in got]
        assert times == sorted(times)
        rev = list(store.find(EventQuery(app_id=1, reversed=True)))
        assert [e.event_time for e in rev] == sorted(times, reverse=True)
        lim = list(store.find(EventQuery(app_id=1, limit=7)))
        assert [e.event_id for e in lim] == [e.event_id for e in got[:7]]

    def test_entity_query_hits_one_shard(self):
        store, children = _mk()
        store.insert_batch(_events(), 1)
        got = list(store.find(EventQuery(app_id=1, entity_id="u3")))
        assert got and all(e.entity_id == "u3" for e in got)
        home = children[shard_of("u3", 3)]
        assert len(got) == len(
            list(home.find(EventQuery(app_id=1, entity_id="u3")))
        )

    def test_partitioned_read_goes_straight_to_child(self):
        store, children = _mk()
        store.insert_batch(_events(), 1)
        for s in range(3):
            via_composite = {
                e.event_id
                for e in store.find(EventQuery(app_id=1, shard=(s, 3)))
            }
            direct = {
                e.event_id for e in children[s].find(EventQuery(app_id=1))
            }
            assert via_composite == direct
        # non-matching shard count still partitions correctly (filtered
        # per child + merged)
        union = set()
        for s in range(2):
            part = {
                e.event_id
                for e in store.find(EventQuery(app_id=1, shard=(s, 2)))
            }
            assert not (part & union)
            union |= part
        assert len(union) == 40

    def test_get_delete_broadcast_and_signature(self):
        store, _ = _mk()
        ids = store.insert_batch(_events(), 1)
        e = store.get(ids[5], 1)
        assert e is not None
        sig1 = store.data_signature(1)
        assert store.delete(ids[5], 1)
        assert store.get(ids[5], 1) is None
        assert not store.delete(ids[5], 1)
        assert store.data_signature(1) != sig1

    def test_aggregate_properties_union(self):
        store, _ = _mk()
        store.insert_batch(
            [
                Event(event="$set", entity_type="user", entity_id=f"u{i}",
                      properties={"plan": f"p{i}"})
                for i in range(9)
            ],
            1,
        )
        props = store.aggregate_properties(1, "user")
        assert len(props) == 9
        assert props["u4"].get("plan") == "p4"


def _daemon_env(tmp_path, tag):
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": str(REPO) + os.pathsep + env.get("PYTHONPATH", ""),
        "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / f"shard{tag}.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
    })
    return env


def test_two_daemon_sharded_ingest_and_partitioned_read(tmp_path):
    """End to end: events ingested through a 2-daemon sharded store land
    disjointly; shard=(i, 2) reads stream from daemon i alone."""
    procs, ports = [], []
    try:
        for tag in (0, 1):
            port = _free_port()
            ports.append(port)
            procs.append(subprocess.Popen(
                [
                    sys.executable, "-m",
                    "predictionio_tpu.data.api.storage_server",
                    "--host", "127.0.0.1", "--port", str(port),
                ],
                env=_daemon_env(tmp_path, tag), cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            ))
        for port in ports:
            _wait_health(port)

        store = ShardedEventStore(
            {"SHARDS": ",".join(f"127.0.0.1:{p}" for p in ports)}
        )
        store.init_app(7)
        events = _events(n=60, seed=3)
        ids = store.insert_batch(events, 7)
        assert len(ids) == 60 and all(ids)

        # disjoint partitioned reads, one per daemon, covering everything
        parts = [
            {e.event_id for e in store.find(EventQuery(app_id=7, shard=(s, 2)))}
            for s in range(2)
        ]
        assert parts[0] and parts[1]
        assert not (parts[0] & parts[1])
        assert len(parts[0] | parts[1]) == 60

        # each daemon REALLY holds only its shard (ask it directly)
        from predictionio_tpu.data.storage.remote import RemoteEventStore

        for s, port in enumerate(ports):
            direct = RemoteEventStore({"HOST": "127.0.0.1", "PORT": str(port)})
            held = list(direct.find(EventQuery(app_id=7)))
            assert held and {e.event_id for e in held} == parts[s]
            assert all(shard_of(e.entity_id, 2) == s for e in held)

        # merged full read is time-ordered and complete
        got = list(store.find(EventQuery(app_id=7)))
        assert len(got) == 60
        times = [e.event_time for e in got]
        assert times == sorted(times)
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


def test_two_daemons_two_processes_train(tmp_path):
    """The full HBase picture: TWO daemons each holding one entity shard,
    TWO jax.distributed processes each streaming ONLY its own daemon
    (the sharded store routes shard=(i,2) straight to child i), factors
    equal to a full-read train. Reuses test_partitioned_reads' child."""
    from test_partitioned_reads import _CHILD, N_EDGES, N_ITEMS, N_USERS, RANK, ITERS

    procs, ports = [], []
    try:
        for tag in (0, 1):
            port = _free_port()
            ports.append(port)
            procs.append(subprocess.Popen(
                [
                    sys.executable, "-m",
                    "predictionio_tpu.data.api.storage_server",
                    "--host", "127.0.0.1", "--port", str(port),
                ],
                env=_daemon_env(tmp_path, tag), cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            ))
        for port in ports:
            _wait_health(port)
        shards = ",".join(f"127.0.0.1:{p}" for p in ports)

        # seed through the sharded client
        rng = np.random.RandomState(7)
        rows = rng.randint(0, N_USERS, N_EDGES)
        cols = rng.randint(0, N_ITEMS, N_EDGES)
        vals = rng.randint(1, 6, N_EDGES)
        store = ShardedEventStore({"SHARDS": shards})
        app_id = 9
        store.init_app(app_id)
        t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
        store.insert_batch(
            [
                Event(event="rate", entity_type="user", entity_id=f"u{r}",
                      target_entity_type="item", target_entity_id=f"i{c}",
                      properties={"rating": float(v)}, event_time=t0)
                for r, c, v in zip(rows, cols, vals)
            ],
            app_id,
        )

        child_env = dict(os.environ)
        child_env.update({
            "PYTHONPATH": str(REPO) + os.pathsep + str(REPO / "tests")
            + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "PIO_STORAGE_SOURCES_SH_TYPE": "sharded",
            "PIO_STORAGE_SOURCES_SH_SHARDS": shards,
            "PIO_STORAGE_SOURCES_M_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SH",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        })
        coord_port = _free_port()
        out_path = tmp_path / "factors.npz"
        child = (
            _CHILD.replace("{n_users}", str(N_USERS))
            .replace("{n_items}", str(N_ITEMS))
            .replace("{rank}", str(RANK))
            .replace("{iters}", str(ITERS))
        )
        children = [
            subprocess.Popen(
                [
                    sys.executable, "-c", child,
                    f"127.0.0.1:{coord_port}", str(pid), str(app_id),
                    str(out_path),
                ],
                env=child_env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            for pid in (0, 1)
        ]
        outs = [p.communicate(timeout=300) for p in children]
        for p, (out, err) in zip(children, outs):
            assert p.returncode == 0, f"child failed:\n{out}\n{err[-3000:]}"
            assert "CHILD-OK" in out
        shard_counts = {}
        for out, _err in outs:
            for line in out.splitlines():
                if line.startswith("SHARD-ROWS"):
                    _tag, pid, n = line.split()
                    shard_counts[int(pid)] = int(n)
        assert shard_counts[0] + shard_counts[1] == N_EDGES
        assert 0 < shard_counts[0] < N_EDGES

        with np.load(out_path) as z:
            uf2, itf2 = z["uf"], z["itf"]

        # reference: full-read train over the same gathered (shard 0 then
        # shard 1) edge order
        from predictionio_tpu.models import als
        from predictionio_tpu.parallel.mesh import make_mesh

        r_, c_, v_ = [], [], []
        for s in range(2):
            for e in store.find(EventQuery(app_id=app_id, shard=(s, 2))):
                r_.append(int(e.entity_id[1:]))
                c_.append(int(e.target_entity_id[1:]))
                v_.append(float(e.properties.get("rating")))
        ref = als.train(
            np.asarray(r_, np.int32), np.asarray(c_, np.int32),
            np.asarray(v_, np.float32), N_USERS, N_ITEMS,
            als.ALSParams(rank=RANK, iterations=ITERS, implicit_prefs=True),
            mesh=make_mesh(),
        )
        np.testing.assert_allclose(uf2, ref.user_factors, rtol=2e-3, atol=1e-4)
        np.testing.assert_allclose(itf2, ref.item_factors, rtol=2e-3, atol=1e-4)
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


class TestShardedEdgeCases:
    def test_explicit_id_insert_rehomes_across_shards(self):
        store, children = _mk()
        e1 = Event(event="rate", entity_type="user", entity_id="u1",
                   target_entity_type="item", target_entity_id="i1",
                   event_id="fixed-id")
        store.insert(e1, 1)
        # replay the same id under a DIFFERENT entity (different shard)
        other = next(
            f"u{k}" for k in range(50)
            if shard_of(f"u{k}", 3) != shard_of("u1", 3)
        )
        e2 = Event(event="rate", entity_type="user", entity_id=other,
                   target_entity_type="item", target_entity_id="i2",
                   event_id="fixed-id")
        store.insert(e2, 1)
        live = [e for e in store.find(EventQuery(app_id=1))
                if e.event_id == "fixed-id"]
        assert len(live) == 1 and live[0].entity_id == other
        assert store.get("fixed-id", 1).entity_id == other
        # batch replay re-homes too
        store.insert_batch([e1], 1)
        live = [e for e in store.find(EventQuery(app_id=1))
                if e.event_id == "fixed-id"]
        assert len(live) == 1 and live[0].entity_id == "u1"

    def test_out_of_range_shard_is_empty_not_crash(self):
        store, _ = _mk()
        store.insert_batch(_events(), 1)
        assert list(store.find(EventQuery(app_id=1, shard=(3, 3)))) == []

    def test_auth_key_passed_to_children(self):
        from predictionio_tpu.data.storage.sharded import ShardedEventStore

        s = ShardedEventStore(
            {"SHARDS": "127.0.0.1:1,127.0.0.1:2", "AUTH_KEY": "sekrit"}
        )
        assert all(
            child._client.auth_key == "sekrit" for child in s._stores
        )


# -- failure story (VERDICT r4 #3): retries, attribution, degraded reads ----


class _FlakyStore(MemoryEventStore):
    """Raises StorageError on the first `fail_n` calls of each wrapped
    method, then behaves normally — a daemon mid-restart."""

    def __init__(self, fail_n=1):
        super().__init__()
        self.fail_n = fail_n
        self.calls = 0

    def _maybe_fail(self):
        self.calls += 1
        if self.calls <= self.fail_n:
            from predictionio_tpu.data.storage.base import (
                StorageUnreachableError,
            )

            raise StorageUnreachableError("transient hiccup")

    def find(self, query):
        self._maybe_fail()
        return super().find(query)

    def get(self, event_id, app_id, channel_id=None):
        self._maybe_fail()
        return super().get(event_id, app_id, channel_id)


class _DeadClient:
    """Transport stub for a gone daemon: health pings fail."""

    host, port = "10.0.0.9", 7070

    def ping(self):
        return False


class _DeadStore(MemoryEventStore):
    """Every data call fails — a daemon that is just gone."""

    def __init__(self):
        super().__init__()
        self._client = _DeadClient()

    def _die(self, *_a, **_k):
        from predictionio_tpu.data.storage.base import (
            StorageUnreachableError,
        )

        raise StorageUnreachableError("connection refused")

    find = get = delete = delete_batch = insert = insert_batch = _die
    aggregate_properties = data_signature = _die


class TestShardedFailures:
    def _mk_with(self, bad, bad_index=1, n=3, **kw):
        children = [MemoryEventStore() for _ in range(n)]
        children[bad_index] = bad
        store = ShardedEventStore(stores=children, retries=1, **kw)
        store.BACKOFF_BASE = 0.001  # keep test wall-clock tiny
        return store, children

    def test_transient_failure_retries_invisibly(self):
        store, _ = self._mk_with(_FlakyStore(fail_n=1))
        store.init_app(1)
        ids = store.insert_batch(_events(), 1)
        got = list(store.find(EventQuery(app_id=1)))
        assert len(got) == 40  # the flaky shard healed within the budget
        assert store.get(ids[0], 1) is not None

    def test_down_shard_error_names_the_shard(self):
        import pytest

        from predictionio_tpu.data.storage.sharded import ShardDownError

        store, children = self._mk_with(_DeadStore(), bad_index=2)
        for c in (children[0], children[1]):
            c.init_app(1)
        for e in _events(n=12):
            if shard_of(e.entity_id, 3) != 2:
                store.insert(e, 1)
        with pytest.raises(ShardDownError) as ei:
            list(store.find(EventQuery(app_id=1)))
        assert ei.value.shard_index == 2
        assert "shard 2" in str(ei.value)
        assert "10.0.0.9:7070" in str(ei.value)  # address included

    def test_allow_partial_degrades_and_records(self):
        store, children = self._mk_with(
            _DeadStore(), bad_index=1, allow_partial=True
        )
        for sx, c in enumerate(children):
            if sx != 1:
                c.init_app(1)
        events = _events()
        live = [e for e in events if shard_of(e.entity_id, 3) != 1]
        for e in live:
            store.insert(e, 1)
        got = list(store.find(EventQuery(app_id=1)))
        assert len(got) == len(live)  # the two healthy shards answered
        assert store.last_degraded_shards == [1]
        # aggregation degrades the same way
        props = store.aggregate_properties(1, "user")
        assert all(shard_of(k, 3) != 1 for k in props)
        assert store.last_degraded_shards == [1]

    def test_writes_never_partial(self):
        import pytest

        from predictionio_tpu.data.storage.sharded import ShardDownError

        store, _ = self._mk_with(
            _DeadStore(), bad_index=1, allow_partial=True
        )
        bad_entity = next(
            f"u{k}" for k in range(50) if shard_of(f"u{k}", 3) == 1
        )
        with pytest.raises(ShardDownError):
            store.insert(
                Event(event="rate", entity_type="user",
                      entity_id=bad_entity), 1,
            )

    def test_health_reports_per_shard(self):
        store, _ = self._mk_with(_DeadStore(), bad_index=0)
        h = store.health()
        assert [x["alive"] for x in h] == [False, True, True]
        assert h[0]["shard"] == 0 and h[0]["error"]
        assert all("address" in x for x in h)


def test_daemon_killed_mid_find_names_shard(tmp_path):
    """The done-bar test: two real daemons, one killed mid-stream; the
    composite read fails loudly naming the dead shard."""
    import pytest

    from predictionio_tpu.data.storage.sharded import ShardDownError

    procs, ports = [], []
    try:
        for tag in (0, 1):
            port = _free_port()
            ports.append(port)
            procs.append(subprocess.Popen(
                [
                    sys.executable, "-m",
                    "predictionio_tpu.data.api.storage_server",
                    "--host", "127.0.0.1", "--port", str(port),
                ],
                env=_daemon_env(tmp_path, tag), cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            ))
        for port in ports:
            _wait_health(port)
        store = ShardedEventStore(
            {"SHARDS": ",".join(f"127.0.0.1:{p}" for p in ports),
             "RETRIES": "1"},
        )
        store.BACKOFF_BASE = 0.01
        store.init_app(3)
        store.insert_batch(_events(n=60, seed=1), 3)
        # force paging so the stream is genuinely mid-flight when the
        # daemon dies (page size is a client-side attribute)
        for child in store._stores:
            child.FIND_PAGE = 5
        it = store.find(EventQuery(app_id=3))
        for _ in range(4):  # consume into the first pages of both shards
            next(it)
        procs[1].kill()
        procs[1].wait(timeout=10)
        with pytest.raises(ShardDownError) as ei:
            list(it)
        assert ei.value.shard_index == 1
        assert str(ports[1]) in ei.value.address
        # health now pinpoints the dead daemon
        h = store.health()
        assert h[0]["alive"] and not h[1]["alive"]
        # the healthy shard keeps serving partitioned reads
        part0 = list(store.find(EventQuery(app_id=3, shard=(0, 2))))
        assert part0
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


def test_partial_batch_write_reports_per_position():
    """A bulk write with one dead shard raises PartialBatchWriteError
    whose ids align per input position — persisted events keep their
    ids so the batch endpoint can report accurate per-event statuses."""
    import pytest

    from predictionio_tpu.data.storage.sharded import (
        PartialBatchWriteError,
    )

    children = [MemoryEventStore(), _DeadStore()]
    store = ShardedEventStore(stores=children, retries=0)
    children[0].init_app(1)
    events = _events(n=20)
    with pytest.raises(PartialBatchWriteError) as ei:
        store.insert_batch(events, 1)
    ids = ei.value.ids
    assert len(ids) == 20
    for e, eid in zip(events, ids):
        if shard_of(e.entity_id, 2) == 0:
            assert eid is not None  # persisted on the healthy shard
        else:
            assert eid is None
    assert any(i is None for i in ids) and any(i is not None for i in ids)


class _TogglableStore(MemoryEventStore):
    """A memory child whose connectivity can be cut at will."""

    def __init__(self):
        super().__init__()
        self.down = False

    def _gate(self):
        if self.down:
            from predictionio_tpu.data.storage.base import (
                StorageUnreachableError,
            )

            raise StorageUnreachableError("daemon gone")

    def find(self, query):
        self._gate()
        return super().find(query)

    def find_entities_batch(self, *a, **k):
        self._gate()
        return super().find_entities_batch(*a, **k)

    def get(self, *a, **k):
        self._gate()
        return super().get(*a, **k)

    def insert_batch(self, *a, **k):
        self._gate()
        return super().insert_batch(*a, **k)

    def aggregate_properties(self, *a, **k):
        self._gate()
        return super().aggregate_properties(*a, **k)


class TestReplication:
    """REPLICAS=2 (VERDICT r4 #3 stretch): successor replication makes
    reads survive a down shard COMPLETELY."""

    def _mk(self, n=3):
        children = [_TogglableStore() for _ in range(n)]
        store = ShardedEventStore(stores=children, retries=0)
        store.replicas = 2
        store.BACKOFF_BASE = 0.001
        store.init_app(1)
        return store, children

    def test_writes_land_on_home_and_successor(self):
        store, children = self._mk()
        store.insert_batch(_events(), 1)
        for e in _events():
            home = shard_of(e.entity_id, 3)
            follower = (home + 1) % 3
            holders = [
                sx for sx, c in enumerate(children)
                if any(
                    x.entity_id == e.entity_id
                    for x in c.find(EventQuery(app_id=1))
                )
            ]
            assert set(holders) == {home, follower}

    def test_broadcast_find_has_no_duplicates(self):
        store, _ = self._mk()
        store.insert_batch(_events(), 1)
        got = list(store.find(EventQuery(app_id=1)))
        assert len(got) == 40
        assert len({e.event_id for e in got}) == 40
        times = [e.event_time for e in got]
        assert times == sorted(times)

    def test_reads_survive_a_down_shard(self):
        store, children = self._mk()
        store.insert_batch(_events(), 1)
        dead = 1
        children[dead].down = True
        # entity read on the dead home fails over to the replica
        victim = next(
            f"u{k}" for k in range(50) if shard_of(f"u{k}", 3) == dead
        )
        got = list(store.find(EventQuery(app_id=1, entity_id=victim)))
        ref = [e for e in _events() if e.entity_id == victim]
        assert len(got) == len(ref) > 0
        # partitioned read of the dead shard's partition: complete
        part = list(store.find(EventQuery(app_id=1, shard=(dead, 3))))
        assert len(part) == sum(
            1 for e in _events() if shard_of(e.entity_id, 3) == dead
        )
        # broadcast read: complete + no duplicates
        got_all = list(store.find(EventQuery(app_id=1)))
        assert len(got_all) == 40
        assert len({e.event_id for e in got_all}) == 40
        # batched entity read: dead home's group answered by replica
        out = store.find_entities_batch(1, "user", [victim, "u0"])
        assert len(out[victim]) == len(ref)

    def test_two_down_shards_still_raise(self):
        import pytest

        from predictionio_tpu.data.storage.sharded import ShardDownError

        store, children = self._mk()
        store.insert_batch(_events(), 1)
        children[1].down = True
        children[2].down = True
        victim = next(
            f"u{k}" for k in range(50) if shard_of(f"u{k}", 3) == 1
        )
        # home (1) and its replica (2) both down → loud failure
        with pytest.raises(ShardDownError):
            list(store.find(EventQuery(app_id=1, entity_id=victim)))

    def test_delete_removes_all_copies(self):
        store, children = self._mk()
        ids = store.insert_batch(_events(), 1)
        assert store.delete(ids[0], 1)
        for c in children:
            assert all(
                e.event_id != ids[0] for e in c.find(EventQuery(app_id=1))
            )

    def test_replica_write_failure_degrades_not_fails(self, caplog):
        store, children = self._mk()
        # the FOLLOWER of shard 0 is down; primaries on 0 still commit
        import logging as _logging

        victim_home = 0
        children[(victim_home + 1) % 3].down = True
        evs = [
            e for e in _events()
            if shard_of(e.entity_id, 3) == victim_home
        ]
        with caplog.at_level(_logging.ERROR):
            ids = store.insert_batch(evs, 1)
        assert all(ids)
        assert any("reduced redundancy" in r.message for r in caplog.records)


class _SlowStore(_TogglableStore):
    """A togglable store with a settable per-read stall (GC-pause twin)."""

    delay = 0.0

    def find_entities_batch(self, *a, **kw):
        import time as _time

        if self.delay:
            _time.sleep(self.delay)
        return super().find_entities_batch(*a, **kw)


class TestHedgedReads:
    """ISSUE 10 satellite: idempotent replica reads hedge after a
    p95-derived delay; first answer wins."""

    def _mk(self, n=3, replicas=2, **cfg):
        children = [_SlowStore() for _ in range(n)]
        store = ShardedEventStore(
            stores=children, config={"REPLICAS": str(replicas), **cfg}
        )
        store.init_app(1)
        return store, children

    def test_enabled_only_with_replicas(self):
        store, _ = self._mk(replicas=2)
        assert store.hedged_reads
        store, _ = self._mk(replicas=1)
        assert not store.hedged_reads
        store, _ = self._mk(replicas=2, HEDGED_READS="0")
        assert not store.hedged_reads

    def test_p95_delay_derivation(self):
        store, _ = self._mk()
        # cold start: conservative default
        assert store.hedge_delay_s() == store.HEDGE_DEFAULT_DELAY_S
        for _ in range(40):
            store._record_read_latency(0.001)
        store._record_read_latency(0.1)  # one outlier under p95
        d = store.hedge_delay_s()
        assert store.HEDGE_MIN_DELAY_S <= d < 0.1

    def test_hedge_beats_slow_primary(self):
        store, children = self._mk()
        for e in _events():
            store.insert(e, 1)
        ids = [f"u{i}" for i in range(11)]
        # warm the latency window with fast reads
        for _ in range(25):
            store.find_entities_batch(1, "user", ids)
        import time as _time

        # every shard is some entity's home: slow them ALL so each
        # group's hedge (to the fast follower copy) is what answers…
        # except followers are the same stores. Instead slow ONE shard:
        # only its home groups hedge.
        children[0].delay = 0.8
        t0 = _time.monotonic()
        out = store.find_entities_batch(1, "user", ids)
        dt_read = _time.monotonic() - t0
        assert dt_read < 0.7, dt_read  # hedge beat the stall
        assert set(out) == set(ids)
        from predictionio_tpu.obs import get_default_registry

        text = get_default_registry().render()
        assert "storage_hedged_reads_total" in text

    def test_hedged_result_matches_serial(self):
        store, children = self._mk()
        for e in _events():
            store.insert(e, 1)
        ids = [f"u{i}" for i in range(11)]
        baseline = store.find_entities_batch(1, "user", ids)
        store.hedged_reads = False
        serial = store.find_entities_batch(1, "user", ids)
        assert set(baseline) == set(serial)
        for k in baseline:
            assert len(baseline[k]) == len(serial[k])

    def test_down_primary_fails_over_through_hedge(self):
        store, children = self._mk()
        for e in _events():
            store.insert(e, 1)
        # find a user homed on shard 0, then kill shard 0 entirely
        ids = [f"u{i}" for i in range(11) if shard_of(f"u{i}", 3) == 0]
        assert ids
        children[0].down = True
        out = store.find_entities_batch(1, "user", ids)
        assert set(out) == set(ids)  # replica copies answered
