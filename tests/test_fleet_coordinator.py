"""Fleet coordination (ISSUE 10): CAS job claims, fenced steal, worker
records. No jax — this is pure control-plane code over the record
store."""

import threading
import time

import pytest

from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.deploy.scheduler import (
    JobQueue,
    SchedulerConfig,
    TrainScheduler,
)
from predictionio_tpu.fleet import (
    DistributedConfig,
    FleetConfig,
    FleetMember,
    WorkerInfo,
    WorkerRegistry,
    fleet_status,
)


@pytest.fixture()
def storage():
    return Storage(StorageConfig(
        sources={"M": SourceConfig("M", "memory", {})},
        repositories={
            "METADATA": "M", "EVENTDATA": "M", "MODELDATA": "M",
        },
    ))


VARIANT = {"id": "eng", "engineFactory": "tests.sample_engine.factory"}


class TestCasClaims:
    def test_single_claim_wins_and_writes_generation(self, storage):
        queue = JobQueue(storage)
        job = queue.submit(VARIANT)
        token = queue.claim(job, "w1")
        assert token is not None
        # the winner still owes the post-transition write
        queue.update(
            job.id, status="running", worker_id="w1",
            generation=1, claim_token=token, heartbeat_at=time.time(),
        )
        cur = queue.get(job.id)
        assert cur.generation == 1 and cur.claim_token == token
        assert queue.is_owner(cur)

    def test_two_concurrent_claims_one_winner(self, storage):
        """The CAS regression shape: two workers bid the same
        generation simultaneously; exactly one wins, and both agree
        who (claim_winner is deterministic over the bid record)."""
        queue_a, queue_b = JobQueue(storage), JobQueue(storage)
        job = queue_a.submit(VARIANT)
        barrier = threading.Barrier(2)
        results = {}

        def claim(name, q):
            snapshot = q.get(job.id)
            barrier.wait()
            results[name] = q.claim(snapshot, name, settle_s=0.15)

        threads = [
            threading.Thread(target=claim, args=("a", queue_a)),
            threading.Thread(target=claim, args=("b", queue_b)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wins = [n for n, tok in results.items() if tok is not None]
        assert len(wins) == 1, results
        assert queue_a.claim_winner(job.id, 1) == results[wins[0]]
        assert queue_b.claim_winner(job.id, 1) == results[wins[0]]

    def test_stale_bid_generation_never_rewins(self, storage):
        """A requeued job bumps generation, so the NEXT claim can't
        collide with the previous round's resolved bids."""
        queue = JobQueue(storage)
        job = queue.submit(VARIANT)
        t1 = queue.claim(job, "w1")
        assert t1 is not None
        queue.update(
            job.id, status="running", generation=1, claim_token=t1,
        )
        # owner requeues (infra backoff shape): generation bumps to 2
        queue.update(
            job.id, status="queued", generation=2, claim_token=None,
        )
        job2 = queue.get(job.id)
        t2 = queue.claim(job2, "w2")
        assert t2 is not None and t2 != t1
        assert queue.claim_winner(job.id, 3) == t2

    def test_claim_on_stale_snapshot_loses(self, storage):
        queue = JobQueue(storage)
        job = queue.submit(VARIANT)
        stale = queue.get(job.id)  # generation 0 snapshot
        t1 = queue.claim(stale, "w1")
        assert t1 is not None
        queue.update(
            job.id, status="running", generation=1, claim_token=t1,
        )
        # a second worker claiming from the SAME stale snapshot bids
        # generation 1 again — already resolved to w1, so it loses
        assert queue.claim(stale, "w2") is None

    def test_fenced_heartbeat_detects_steal(self, storage):
        queue = JobQueue(storage)
        job = queue.submit(VARIANT)
        t1 = queue.claim(job, "w1")
        queue.update(
            job.id, status="running", generation=1, claim_token=t1,
            heartbeat_at=time.time(),
        )
        eid, owned = queue.heartbeat_fenced(job.id, None, t1)
        assert owned and eid
        # steal: another scheduler re-queues the orphan (generation 2)
        job_now = queue.get(job.id)
        t2 = queue.claim(job_now, "w2", intent="steal")
        assert t2 is not None
        queue.update(
            job.id, status="queued", generation=2, claim_token=None,
        )
        _, owned = queue.heartbeat_fenced(job.id, eid, t1)
        assert not owned  # the wedged owner must kill its child

    def test_purge_drops_claim_records(self, storage):
        queue = JobQueue(storage)
        job = queue.submit(VARIANT)
        queue.claim(job, "w1")
        assert queue.purge(job.id) >= 2  # job events + claim bid
        assert queue.get(job.id) is None
        assert queue.claim_winner(job.id, 1) is None


class TestSchedulerRace:
    def _scheduler(self, storage, ran, name):
        cfg = SchedulerConfig(claim_settle_s=0.15, poll_interval_s=0.05)
        s = TrainScheduler(storage, cfg)
        s.worker_id = name
        s.peer_probe = lambda: 1  # peers exist → pay the settle window

        def fake_supervise(job, spec, result, log_path):
            ran.append((name, job.id))
            s.queue.update(
                job.id, status="completed",
                finished_at="now", claim_token=None,
            )

        s._supervise = fake_supervise
        return s

    def test_two_schedulers_one_queue_no_double_supervision(self, storage):
        """The acceptance-criteria regression: two schedulers drain one
        queue concurrently; every job is supervised by EXACTLY one."""
        queue = JobQueue(storage)
        jobs = [queue.submit(VARIANT) for _ in range(4)]
        ran: list = []
        s1 = self._scheduler(storage, ran, "w1")
        s2 = self._scheduler(storage, ran, "w2")
        barrier = threading.Barrier(2)

        def drain(s):
            barrier.wait()
            # several passes so both schedulers contend on every job
            # (a pause between passes lets engine-serialization yields'
            # not_before gates reopen)
            for _ in range(8):
                s.run_pending_once()
                time.sleep(0.1)

        threads = [
            threading.Thread(target=drain, args=(s,)) for s in (s1, s2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        supervised = [job_id for _, job_id in ran]
        assert sorted(supervised) == sorted(j.id for j in jobs), ran
        assert len(supervised) == len(set(supervised)), (
            f"double supervision: {ran}"
        )

    def test_orphan_steal_is_single_winner(self, storage):
        """Two resuming schedulers race to steal one stale orphan: one
        requeue, one attempt bump."""
        queue = JobQueue(storage)
        job = queue.submit(VARIANT)
        t1 = queue.claim(job, "dead-worker")
        queue.update(
            job.id, status="running", generation=1, claim_token=t1,
            worker_id="dead-worker", heartbeat_at=time.time() - 1000,
            attempt=1,
        )
        cfg = SchedulerConfig(claim_settle_s=0.15, stale_after_s=5.0)
        s1 = TrainScheduler(storage, cfg)
        s2 = TrainScheduler(storage, cfg)
        for s in (s1, s2):
            s.peer_probe = lambda: 1
        results = {}
        barrier = threading.Barrier(2)

        def resume(name, s):
            barrier.wait()
            results[name] = s.resume_orphans()

        threads = [
            threading.Thread(target=resume, args=("a", s1)),
            threading.Thread(target=resume, args=("b", s2)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        requeued = results["a"] + results["b"]
        assert requeued == [job.id], results  # exactly one steal won
        cur = queue.get(job.id)
        assert cur.status == "queued"
        assert cur.generation == 2  # the steal's CAS bump
        assert cur.attempt == 1  # no double bump


class TestWorkerFleet:
    def test_worker_registry_liveness(self, storage):
        reg = WorkerRegistry(storage)
        reg.upsert(WorkerInfo(id="w1", heartbeat_at=time.time()))
        reg.upsert(WorkerInfo(id="w2", heartbeat_at=time.time() - 1000))
        live = reg.live(stale_after_s=10)
        assert [w.id for w in live] == ["w1"]
        assert reg.gc(stale_after_s=60) == ["w2"]
        assert [w.id for w in reg.list()] == ["w1"]

    def test_fleet_member_lifecycle_and_peers(self, storage):
        m1 = FleetMember(
            storage,
            scheduler_config=SchedulerConfig(poll_interval_s=0.05),
            fleet_config=FleetConfig(heartbeat_interval_s=0.05),
        )
        m2 = FleetMember(
            storage,
            scheduler_config=SchedulerConfig(poll_interval_s=0.05),
            fleet_config=FleetConfig(heartbeat_interval_s=0.05),
        )
        m1.start()
        try:
            m2.start()
            try:
                deadline = time.time() + 5
                while time.time() < deadline and not m1.peers():
                    time.sleep(0.05)
                assert [w.id for w in m1.peers()] == [m2.worker_id]
                # the peer probe arms the settle window
                m1._peer_cache = (0.0, 0)  # drop cache
                assert m1.live_peer_count() >= 1
                assert m1.scheduler._claim_settle() > 0
                status = fleet_status(storage)
                assert status["live_workers"] == 2
            finally:
                m2.stop()
        finally:
            m1.stop()
        # clean stops deregister both records
        assert fleet_status(storage)["workers"] == []
        # a lone worker skips the settle wait entirely
        m3 = FleetMember(storage)
        m3.start()
        try:
            assert m3.scheduler._claim_settle() == 0.0
        finally:
            m3.stop()

    def test_crashed_member_leaves_stale_record(self, storage):
        m = FleetMember(
            storage, fleet_config=FleetConfig(heartbeat_interval_s=0.05)
        )
        m.start()
        m.stop(kill_child=True)  # crash simulation: record survives
        workers = fleet_status(storage, stale_after_s=0.0)["workers"]
        assert [w["id"] for w in workers] == [m.worker_id]


class TestDistributedConfig:
    def test_single_host_fallback(self):
        cfg = DistributedConfig()
        assert not cfg.multi_host
        assert cfg.initialize() is False  # no-op, no jax needed
        assert cfg.child_env() == {}

    def test_env_round_trip(self):
        cfg = DistributedConfig(
            coordinator_address="10.0.0.1:1234",
            num_processes=4,
            process_id=2,
        )
        assert cfg.multi_host
        env = cfg.child_env()
        back = DistributedConfig.from_env(env)
        assert back == cfg

    def test_validation(self):
        with pytest.raises(ValueError):
            DistributedConfig(num_processes=2)  # no coordinator
        with pytest.raises(ValueError):
            DistributedConfig(
                coordinator_address="x:1", num_processes=2, process_id=5
            )

    def test_from_json(self):
        cfg = DistributedConfig.from_json({
            "coordinator": "h:1", "num_processes": 2, "process_id": 1,
        })
        assert cfg.coordinator_address == "h:1"
        assert DistributedConfig.from_json(None) == DistributedConfig()


class TestClaimWedgeRecovery:
    def test_dead_winning_bid_unwedges(self, storage):
        """A claimant that dies between winning the bid and writing the
        record would otherwise own that generation forever; the resume
        pass bids PAST it and the job becomes claimable again."""
        queue = JobQueue(storage)
        job = queue.submit(VARIANT)
        # claim WITHOUT fields = win the bid but never write the record
        dead = queue.claim(job, "dead-worker")
        assert dead is not None
        assert queue.get(job.id).status == "queued"  # the wedge
        # every later claim of generation 1 loses to the dead bid
        assert queue.claim(queue.get(job.id), "w2") is None
        cfg = SchedulerConfig(stale_after_s=0.05)
        s = TrainScheduler(storage, cfg)
        time.sleep(0.1)  # let the dead bid go stale
        s.resume_orphans()
        cur = queue.get(job.id)
        assert cur.status == "queued" and cur.generation == 2
        # fresh generation: claims work again
        assert queue.claim(cur, "w3") is not None

    def test_live_bid_not_unwedged(self, storage):
        """A FRESH winning bid (a claimant mid-protocol) must not be
        bumped — only stale ones."""
        queue = JobQueue(storage)
        job = queue.submit(VARIANT)
        queue.claim(job, "live-worker")  # just bid, still writing
        s = TrainScheduler(storage, SchedulerConfig(stale_after_s=30.0))
        s.resume_orphans()
        assert queue.get(job.id).generation == 0  # untouched


class TestEngineSerializationAcrossWorkers:
    def test_second_worker_yields_while_engine_trains_elsewhere(
        self, storage
    ):
        """Two fleet members, two jobs of ONE engine: the junior
        claimant must yield (queued again, attempt not consumed) while
        the senior's train is running on the other worker."""
        queue = JobQueue(storage)
        job1 = queue.submit(VARIANT)
        job2 = queue.submit(VARIANT)  # same engine_id
        # worker A is mid-train on job1 (claimed + running record)
        t1 = queue.claim(job1, "workerA", fields=dict(
            status="running", worker_id="workerA",
            started_at="2026-01-01T00:00:00", heartbeat_at=time.time(),
            attempt=1,
        ))
        assert t1 is not None
        s = TrainScheduler(
            storage, SchedulerConfig(poll_interval_s=0.05)
        )
        supervised = []
        s._supervise = lambda *a, **k: supervised.append(a)
        s._run_job(queue.get(job2.id))
        assert supervised == []  # yielded, never supervised
        cur = queue.get(job2.id)
        assert cur.status == "queued"
        assert cur.attempt == 0  # the yield refunds the attempt
        assert cur.claim_token is None
        # once job1 finishes, job2 trains normally
        queue.update(job1.id, status="completed", claim_token=None)
        time.sleep(0.06)  # past the yield's not_before gate
        s._supervise = lambda *a, **k: supervised.append("ran")
        s._run_job(queue.get(job2.id))
        assert supervised == ["ran"]

    def test_heartbeat_resurrection_keeps_identity(self, storage):
        """A beat landing after a peer GC'd the record must rebuild it
        WITH its id — an id-less phantom would count as everyone's live
        peer forever."""
        reg = WorkerRegistry(storage)
        reg.upsert(WorkerInfo(id="w1", heartbeat_at=time.time()))
        reg.remove("w1")  # a peer's gc during our connectivity gap
        reg.heartbeat("w1", None, 0)
        assert [w.id for w in reg.list()] == ["w1"]


class TestWorkerDeviceInfo:
    """ISSUE 16: `pio fleet status` scrapes each live worker's /metrics
    for device counters (PIO_WORKER_METRICS_URL advertised at
    registration)."""

    def _metrics_server(self, body: str):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):
                pass

        srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    def test_device_families_allowlisted_and_summed(self):
        from predictionio_tpu.fleet.coordinator import worker_device_info

        srv = self._metrics_server(
            'jax_live_buffer_bytes{device="0"} 1000\n'
            'jax_live_buffer_bytes{device="1"} 2345\n'
            "jax_jit_compile_count 3\n"
            "http_requests_total 999\n"  # not a device family
        )
        try:
            info = worker_device_info(
                f"http://127.0.0.1:{srv.server_port}/metrics"
            )
        finally:
            srv.shutdown()
        assert info == {
            "jax_live_buffer_bytes": 3345.0,
            "jax_jit_compile_count": 3.0,
        }

    def test_unreachable_worker_yields_none(self):
        from predictionio_tpu.fleet.coordinator import worker_device_info

        assert worker_device_info("http://127.0.0.1:1/metrics") is None

    def test_fleet_status_attaches_device_info(self, storage, monkeypatch):
        from predictionio_tpu.fleet.coordinator import FleetConfig, FleetMember

        srv = self._metrics_server("jax_jit_compile_count 7\n")
        monkeypatch.setenv(
            "PIO_WORKER_METRICS_URL",
            f"http://127.0.0.1:{srv.server_port}/metrics",
        )
        m = FleetMember(
            storage, fleet_config=FleetConfig(heartbeat_interval_s=0.05)
        )
        m.start()
        try:
            rows = fleet_status(storage)["workers"]
            assert rows[0]["device_info"] == {
                "jax_jit_compile_count": 7.0
            }
            # probing suppressed on request (cheap status calls)
            rows = fleet_status(storage, probe_devices=False)["workers"]
            assert "device_info" not in rows[0]
        finally:
            m.stop()
            srv.shutdown()
