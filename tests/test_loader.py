"""Multi-host-shaped loader (parallel/loader.py): sharded staging must be
value-equal to a plain sharded device_put, and the process-count seam
must hold on a single process."""

import numpy as np
import pytest

from predictionio_tpu.parallel import loader


def test_process_seam_single_process():
    assert loader.process_count() == 1
    assert loader.process_index() == 0


def test_stage_rows_matches_device_put(mesh8):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.RandomState(0)
    x = rng.rand(41, 3).astype(np.float32)  # not divisible by 8
    y = rng.randint(0, 9, 41).astype(np.int32)
    xs, ys = loader.stage_rows(mesh8, x, y)
    assert xs.shape[0] % 8 == 0 and xs.shape[0] >= 41
    # values: original rows intact, padding zero
    np.testing.assert_array_equal(np.asarray(xs)[:41], x)
    assert (np.asarray(xs)[41:] == 0).all()
    np.testing.assert_array_equal(np.asarray(ys)[:41], y)
    # sharding: split over dp on axis 0
    ref = jax.device_put(
        np.concatenate([x, np.zeros((xs.shape[0] - 41, 3), np.float32)]),
        NamedSharding(mesh8, P("dp", None)),
    )
    assert xs.sharding.is_equivalent_to(ref.sharding, xs.ndim)
    np.testing.assert_array_equal(np.asarray(xs), np.asarray(ref))


def test_stage_edges_valid_column(mesh8):
    rows = np.arange(10, dtype=np.int32)
    cols = np.arange(10, dtype=np.int32)[::-1].copy()
    vals = np.linspace(1, 2, 10).astype(np.float32)
    r, c, v, ok = loader.stage_edges(mesh8, rows, cols, vals)
    ok_np = np.asarray(ok)
    assert ok_np[:10].sum() == 10 and ok_np[10:].sum() == 0


def test_frame_to_device_event_filter(mesh8):
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.store.columnar import EventFrame

    events = []
    for i in range(12):
        events.append(
            Event(
                event="view" if i % 2 == 0 else "buy",
                entity_type="user", entity_id=f"u{i % 3}",
                target_entity_type="item", target_entity_id=f"i{i % 4}",
            )
        )
    frame = EventFrame.from_events(events)
    e, t, v, ok = loader.frame_to_device(frame, mesh8, event_names=["buy"])
    assert int(np.asarray(ok).sum()) == 6  # only the buys

    mismatch = loader.frame_to_device(frame, mesh8, event_names=["nope"])
    assert int(np.asarray(mismatch[3]).sum()) == 0


def test_training_through_staged_arrays(mesh8):
    """Staged edges drive a real sharded ALS step and match host-array
    training — the loader is a drop-in seam, not a new semantics."""
    from predictionio_tpu.models import als

    rng = np.random.RandomState(2)
    rows = rng.randint(0, 20, 150).astype(np.int32)
    cols = rng.randint(0, 15, 150).astype(np.int32)
    vals = (rng.rand(150) * 4 + 1).astype(np.float32)
    params = als.ALSParams(rank=4, iterations=3)
    with mesh8:
        direct = als.train(rows, cols, vals, 20, 15, params, mesh=mesh8)
    staged = loader.stage_edges(mesh8, rows, cols, vals)
    # loader output is value-identical input — training from fetched
    # staged arrays must reproduce the direct path
    r, c, v, ok = (np.asarray(a) for a in staged)
    keep = ok > 0
    with mesh8:
        via_loader = als.train(
            r[keep], c[keep], v[keep], 20, 15, params, mesh=mesh8
        )
    np.testing.assert_allclose(
        direct.user_factors, via_loader.user_factors, atol=1e-5
    )
