"""parquetfs-specific behaviors beyond the shared contract suite: the
columnar projection fast path and segment/tombstone mechanics."""

import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import EventQuery
from predictionio_tpu.data.storage.parquetfs import ParquetFSEventStore
from predictionio_tpu.data.storage.sqlite import SqliteEventStore

UTC = dt.timezone.utc
APP = 1


def seed(store):
    store.init_app(APP)
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    events = []
    for u in range(6):
        for i in range(4):
            events.append(
                Event(
                    event="rate" if (u + i) % 2 == 0 else "view",
                    entity_type="user",
                    entity_id=f"u{u}",
                    target_entity_type="item",
                    target_entity_id=f"i{i}",
                    properties={"rating": float(u + i)} if (u + i) % 2 == 0 else {},
                    event_time=t0 + dt.timedelta(hours=u * 4 + i),
                )
            )
    return store.insert_batch(events, APP)


@pytest.fixture()
def pq_store(tmp_path):
    store = ParquetFSEventStore({"PATH": str(tmp_path / "pq")})
    yield store
    store.remove_app(APP)


def test_find_frame_matches_sqlite(tmp_path, pq_store):
    sq = SqliteEventStore({"PATH": str(tmp_path / "ev.db")})
    seed(pq_store)
    seed(sq)
    q = EventQuery(
        app_id=APP, entity_type="user", target_entity_type="item",
        event_names=["rate", "view"],
    )
    f_pq = pq_store.find_frame(q, value_prop="rating", default_value=1.0)
    f_sq = sq.find_frame(q, value_prop="rating", default_value=1.0)
    assert len(f_pq) == len(f_sq) == 24
    # same interactions regardless of backend
    r1 = sorted(zip(*[x.tolist() for x in f_pq.interactions("sum")]))
    r2 = sorted(zip(*[x.tolist() for x in f_sq.interactions("sum")]))
    # remap through vocabs to compare by string ids
    def named(frame, rows, cols, vals):
        iu, ii = frame.entity_vocab.inverse(), frame.target_vocab.inverse()
        return sorted((iu(r), ii(c), v) for r, c, v in zip(rows, cols, vals))

    assert named(f_pq, *f_pq.interactions("sum")) == named(
        f_sq, *f_sq.interactions("sum")
    )
    sq.remove_app(APP)


def test_zero_rating_not_defaulted(pq_store):
    pq_store.init_app(APP)
    pq_store.insert(
        Event(event="rate", entity_type="user", entity_id="u",
              target_entity_type="item", target_entity_id="i",
              properties={"rating": 0.0}),
        APP,
    )
    f = pq_store.find_frame(
        EventQuery(app_id=APP), value_prop="rating", default_value=5.0
    )
    assert f.value[0] == 0.0  # stored zero, NOT the default


def test_tombstones_excluded_from_frame(pq_store):
    ids = seed(pq_store)
    deleted = ids[0]
    assert pq_store.delete(deleted, APP)
    assert not pq_store.delete(deleted, APP)  # double delete → False
    f = pq_store.find_frame(EventQuery(app_id=APP))
    assert len(f) == 23
    assert pq_store.get(deleted, APP) is None


def test_delete_batch_single_pass(pq_store):
    ids = seed(pq_store)
    # batch of 3 existing + 1 unknown + 1 duplicate → 3 deleted
    n = pq_store.delete_batch([ids[0], ids[1], ids[2], "nope", ids[0]], APP)
    assert n == 3
    f = pq_store.find_frame(EventQuery(app_id=APP))
    assert len(f) == 21
    assert pq_store.delete_batch([], APP) == 0


def test_segments_accumulate_and_survive_reopen(tmp_path):
    store = ParquetFSEventStore({"PATH": str(tmp_path / "pq")})
    seed(store)
    store.flush()
    # new instance over the same directory sees everything
    reopened = ParquetFSEventStore({"PATH": str(tmp_path / "pq")})
    events = list(reopened.find(EventQuery(app_id=APP)))
    assert len(events) == 24
    # times ordered ascending by default
    times = [e.event_time for e in events]
    assert times == sorted(times)


def test_time_filtered_projection(pq_store):
    seed(pq_store)
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    f = pq_store.find_frame(
        EventQuery(
            app_id=APP,
            start_time=t0 + dt.timedelta(hours=8),
            until_time=t0 + dt.timedelta(hours=16),
        )
    )
    assert len(f) == 8  # users u2, u3 (4 events each)
    assert set(np.unique(f.time_ms)) <= {
        int((t0 + dt.timedelta(hours=h)).timestamp() * 1000)
        for h in range(8, 16)
    }
