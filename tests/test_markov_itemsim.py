"""Markov-chain + item-similarity (DIMSUM) engine families
(VERDICT r2 #8: two more template families from examples/experimental/,
finally consuming e2/markov_chain.py)."""

import datetime as dt
import json
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.workflow.core import run_train
from predictionio_tpu.workflow.server import (
    QueryServer,
    QueryServerConfig,
    latest_completed_runtime,
)

UTC = dt.timezone.utc


@pytest.fixture()
def storage():
    cfg = StorageConfig(
        sources={"MEM": SourceConfig("MEM", "memory", {})},
        repositories={
            "METADATA": "MEM", "EVENTDATA": "MEM", "MODELDATA": "MEM",
        },
    )
    s = Storage(cfg)
    app_id = s.get_meta_data_apps().insert(App(0, "seqapp"))
    s.get_events().init_app(app_id)
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    # deterministic sequences: i0→i1→i2 dominates; u3 breaks pattern once
    sequences = {
        "u0": ["i0", "i1", "i2", "i0", "i1", "i2"],
        "u1": ["i0", "i1", "i2"],
        "u2": ["i0", "i1"],
        "u3": ["i0", "i3"],
    }
    batch = []
    for u, seq in sequences.items():
        for k, item in enumerate(seq):
            batch.append(Event(
                event="view", entity_type="user", entity_id=u,
                target_entity_type="item", target_entity_id=item,
                event_time=t0 + dt.timedelta(minutes=k),
            ))
    s.get_events().insert_batch(batch, app_id)
    return s


def _post(port, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/queries.json",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=15) as r:
        return r.status, json.loads(r.read().decode())


MARKOV_VARIANT = {
    "id": "mkv",
    "engineFactory": "predictionio_tpu.engines.markov.MarkovEngine",
    "datasource": {"params": {"app_name": "seqapp"}},
    "algorithms": [{"name": "markov", "params": {"top_n": 10}}],
}

ITEMSIM_VARIANT = {
    "id": "ism",
    "engineFactory": "predictionio_tpu.engines.itemsim.ItemSimilarityEngine",
    "datasource": {"params": {"app_name": "seqapp",
                              "event_names": ["view"]}},
    "algorithms": [{"name": "dimsum", "params": {"top_n": 3}}],
}


class TestMarkovEngine:
    def test_train_and_predict_next_item(self, storage):
        inst = run_train(storage, MARKOV_VARIANT)
        assert inst.status == "COMPLETED"
        runtime = latest_completed_runtime(storage, "mkv", "0", "mkv")
        algo = runtime.algorithms[0]
        model = runtime.models[0]
        from predictionio_tpu.engines.markov import Query

        # after i0, i1 is the dominant next item (4 of 5 transitions)
        p = algo.predict(model, Query(items=["i0"], num=3))
        assert p.item_scores and p.item_scores[0].item == "i1"
        assert p.item_scores[0].score > 0.5
        # unknown item → empty result, not an error
        p = algo.predict(model, Query(items=["ghost"]))
        assert p.item_scores == []

    def test_markov_chain_probabilities(self, storage):
        """Transition semantics match the e2 kernel: rows normalize to 1."""
        run_train(storage, MARKOV_VARIANT)
        runtime = latest_completed_runtime(storage, "mkv", "0", "mkv")
        chain = runtime.models[0].chain
        rows = chain.transition.sum(axis=1)
        assert np.all((np.isclose(rows, 1.0)) | (rows == 0.0))

    def test_deploy_and_query_http(self, storage):
        run_train(storage, MARKOV_VARIANT)
        runtime = latest_completed_runtime(storage, "mkv", "0", "mkv")
        srv = QueryServer(
            storage, runtime, QueryServerConfig(ip="127.0.0.1", port=0)
        )
        port = srv.start()
        try:
            status, body = _post(port, {"items": ["i1"], "num": 2})
            assert status == 200
            items = [s["item"] for s in body["item_scores"]]
            assert items and items[0] == "i2"
        finally:
            srv.stop()


class TestItemSimEngine:
    def test_train_and_similar_items(self, storage):
        inst = run_train(storage, ITEMSIM_VARIANT)
        assert inst.status == "COMPLETED"
        runtime = latest_completed_runtime(storage, "ism", "0", "ism")
        algo = runtime.algorithms[0]
        model = runtime.models[0]
        from predictionio_tpu.engines.itemsim import Query

        # i1 and i2 are viewed by the same users → strongly similar
        p = algo.predict(model, Query(items=["i1"], num=3))
        assert p.item_scores
        assert p.item_scores[0].item in ("i0", "i2")
        assert "i1" not in [s.item for s in p.item_scores]  # never itself

    def test_similarity_matches_numpy_cosine(self, storage):
        run_train(storage, ITEMSIM_VARIANT)
        runtime = latest_completed_runtime(storage, "ism", "0", "ism")
        model = runtime.models[0]
        # rebuild the matrix and verify one similarity value exactly
        from predictionio_tpu.data.store.event_store import EventStoreFacade

        frame = EventStoreFacade(storage).find_frame(
            app_name="seqapp", entity_type="user", event_names=["view"]
        )
        m = np.zeros((frame.n_entities, frame.n_targets), np.float32)
        np.add.at(m, (frame.entity_idx, frame.target_idx), 1.0)
        va = model.item_vocab
        a, b = va.get("i0"), va.get("i1")
        expect = float(
            m[:, a] @ m[:, b]
            / (np.linalg.norm(m[:, a]) * np.linalg.norm(m[:, b]))
        )
        row = model.sim_idx[a].tolist()
        got = float(model.sim_scores[a][row.index(b)])
        assert got == pytest.approx(expect, rel=1e-5)

    def test_deploy_and_query_http(self, storage):
        run_train(storage, ITEMSIM_VARIANT)
        runtime = latest_completed_runtime(storage, "ism", "0", "ism")
        srv = QueryServer(
            storage, runtime, QueryServerConfig(ip="127.0.0.1", port=0)
        )
        port = srv.start()
        try:
            status, body = _post(port, {"items": ["i0"], "num": 3})
            assert status == 200 and body["item_scores"]
        finally:
            srv.stop()


def test_template_gallery_lists_new_families():
    from predictionio_tpu.tools.template import TEMPLATES

    assert "markov" in TEMPLATES and "itemsim" in TEMPLATES
    assert TEMPLATES["markov"].factory == "MarkovEngine"
    assert TEMPLATES["itemsim"].factory == "ItemSimilarityEngine"
