"""Metric family, MetricEvaluator, FastEvalEngine, eval workflow tests
(ports of reference MetricTest / MetricEvaluatorTest / FastEvalEngineTest /
EvaluationTest)."""

import json

import pytest

from predictionio_tpu.controller import EmptyParams, EngineParams, RuntimeContext
from predictionio_tpu.controller.evaluation import (
    Evaluation,
    MetricEvaluator,
)
from predictionio_tpu.controller.fast_eval import FastEvalEngine
from predictionio_tpu.controller.metrics import (
    AverageMetric,
    OptionAverageMetric,
    StdevMetric,
    SumMetric,
    ZeroMetric,
)
from predictionio_tpu.core.base import WorkflowParams
from predictionio_tpu.workflow.evaluation import run_evaluation

import sample_engine as se


# QPA data: q stamps flow from the fake engines; here metrics just see ints
def eval_data(*sets):
    """sets of [(q, p, a)] where each is an int triple."""
    return [(se.EvalInfo(id=i), list(s)) for i, s in enumerate(sets)]


class DiffMetric(AverageMetric):
    """|p - a| as error (lower is better)."""

    higher_is_better = False

    def calculate_one(self, q, p, a):
        return abs(p - a)


class MatchMetric(OptionAverageMetric):
    def calculate_one(self, q, p, a):
        if a is None:
            return None
        return 1.0 if p == a else 0.0


class TestMetrics:
    DATA = eval_data([(1, 2, 2), (2, 4, 2)], [(3, 6, 6)])

    def test_average(self):
        class M(AverageMetric):
            def calculate_one(self, q, p, a):
                return p

        assert M().calculate(RuntimeContext(), self.DATA) == pytest.approx(4.0)

    def test_option_average_skips_none(self):
        data = eval_data([(1, 5, 5), (2, 5, None), (3, 5, 3)])
        assert MatchMetric().calculate(RuntimeContext(), data) == pytest.approx(0.5)

    def test_stdev(self):
        class M(StdevMetric):
            def calculate_one(self, q, p, a):
                return p

        assert M().calculate(RuntimeContext(), self.DATA) == pytest.approx(
            1.632993, abs=1e-5
        )

    def test_sum_and_zero(self):
        class M(SumMetric):
            def calculate_one(self, q, p, a):
                return p

        assert M().calculate(RuntimeContext(), self.DATA) == 12.0
        assert ZeroMetric().calculate(RuntimeContext(), self.DATA) == 0.0

    def test_nan_never_wins(self):
        """A grid point whose metric is NaN (no defined scores) must lose
        to any real score — regardless of position in the grid."""
        data_nan = eval_data([(1, 5, None)])
        data_real = eval_data([(1, 5, 5), (2, 5, 3)])
        m = MatchMetric()
        nan_score = m.calculate(RuntimeContext(), data_nan)
        real_score = m.calculate(RuntimeContext(), data_real)
        assert m.compare(real_score, nan_score) > 0
        assert m.compare(nan_score, real_score) < 0
        assert m.compare(nan_score, nan_score) == 0

    def test_compare_direction(self):
        m = DiffMetric()
        assert m.compare(0.1, 0.5) > 0  # lower error is better
        class Up(AverageMetric):
            def calculate_one(self, q, p, a):
                return p

        assert Up().compare(0.5, 0.1) > 0


def ep_with_algo(algo_id: int) -> EngineParams:
    return EngineParams(
        data_source_params=("", se.DSP(id=1)),
        preparator_params=("", se.PP(id=2)),
        algorithm_params_list=(("algo0", se.AP(id=algo_id)),),
        serving_params=("", EmptyParams()),
    )


class AlgoIdMetric(AverageMetric):
    """Scores the algo_id stamped into predictions — deterministic ranking
    of grid points."""

    def calculate_one(self, q, p, a):
        return p.algo_id


class TestMetricEvaluator:
    def test_picks_best_and_writes_best_json(self, tmp_path):
        engine = se.Engine0Factory().apply()
        grid = [ep_with_algo(i) for i in (1, 5, 3)]
        ctx = RuntimeContext()
        data = engine.batch_eval(ctx, grid)
        out = tmp_path / "best.json"
        evaluator = MetricEvaluator(
            AlgoIdMetric(), [ZeroMetric()], output_path=str(out)
        )
        result = evaluator.evaluate(ctx, None, data, WorkflowParams())
        assert result.best_index == 1
        assert result.best_score.score == 5.0
        assert "AlgoIdMetric" in result.to_one_liner()
        best = json.loads(out.read_text())
        assert best["algorithms"][0]["params"]["id"] == 5
        parsed = json.loads(result.to_json())
        assert parsed["bestScore"] == 5.0
        assert len(parsed["scores"]) == 3


class TestEvaluationWorkflow:
    def test_run_evaluation_lifecycle(self, fresh_storage):
        class MyEval(Evaluation):
            engine = se.Engine0Factory().apply()
            metric = AlgoIdMetric()

        inst, result = run_evaluation(
            fresh_storage, MyEval(), [ep_with_algo(i) for i in (2, 7)]
        )
        assert inst.status == "EVALCOMPLETED"
        stored = fresh_storage.get_meta_data_evaluation_instances().get(inst.id)
        assert stored.status == "EVALCOMPLETED"
        assert "7.0" in stored.evaluator_results
        assert json.loads(stored.evaluator_results_json)["bestScore"] == 7.0
        completed = (
            fresh_storage.get_meta_data_evaluation_instances().get_completed()
        )
        assert [c.id for c in completed] == [inst.id]

    def test_no_grid_raises(self, fresh_storage):
        class MyEval(Evaluation):
            engine = se.Engine0Factory().apply()
            metric = AlgoIdMetric()

        with pytest.raises(ValueError, match="no engine params"):
            run_evaluation(fresh_storage, MyEval())


class TestFastEvalEngine:
    def make(self):
        from predictionio_tpu.controller import FirstServing

        return FastEvalEngine(
            se.DataSource0,
            se.Preparator0,
            {"algo0": se.Algo0, "algo1": se.Algo1},
            {"": FirstServing, "sum": se.SumServing},
        )

    def test_prefix_computation_counts(self):
        engine = self.make()
        ctx = RuntimeContext()
        # 3 grid points: same DS+prep, two distinct algo params
        grid = [ep_with_algo(1), ep_with_algo(1), ep_with_algo(2)]
        results = engine.batch_eval(ctx, grid)
        assert len(results) == 3
        # datasource read ONCE, preparator ran ONCE, algorithms trained
        # once per distinct params (2) — not once per grid point (3)
        assert engine.compute_counts == {
            "datasource": 1,
            "preparator": 1,
            "algorithms": 2,
        }

    def test_fast_eval_matches_plain_engine(self):
        fast = self.make()
        plain = se.Engine0Factory().apply()
        ctx = RuntimeContext()
        ep = ep_with_algo(4)
        r_fast = fast.eval(ctx, ep)
        r_plain = plain.eval(ctx, ep)
        assert r_fast == r_plain
