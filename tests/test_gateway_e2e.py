"""Gateway chaos e2e (ISSUE 15 acceptance): kill -9 one of three
replica PROCESSES under a 64-client hammer — zero in-deadline queries
lost (hedge/failover absorbs), the dead replica's breaker opens, it is
ejected, its `up{instance}` goes 0, and a restart re-admits it; plus
drain-is-zero-drop and stale-heartbeat ejection."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.gateway import (
    GatewayConfig,
    GatewayServer,
    ReplicaRegistry,
)
from predictionio_tpu.obs.monitor import get_monitor

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _sqlite_storage(tmp_path) -> Storage:
    return Storage(StorageConfig(
        sources={
            "SQL": SourceConfig(
                "SQL", "sqlite", {"PATH": str(tmp_path / "gateway.db")}
            ),
        },
        repositories={
            "METADATA": "SQL", "EVENTDATA": "SQL", "MODELDATA": "SQL",
        },
    ))


def _spawn_replica(tmp_path, rid: str, port: int,
                   slow_every: int = 0) -> subprocess.Popen:
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": str(REPO) + os.pathsep + env.get("PYTHONPATH", ""),
        "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "gateway.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
        "PIO_REPLICA_HEARTBEAT_S": "0.2",
        "JAX_PLATFORMS": "cpu",
    })
    out = open(tmp_path / f"{rid}.log", "w")
    argv = [
        sys.executable, "-m", "predictionio_tpu.gateway.replica_main",
        "--stub", "--ip", "127.0.0.1", "--port", str(port),
        "--replica-id", rid,
        "--state-dir", str(tmp_path / f"state-{rid}"),
    ]
    if slow_every:
        argv += ["--slow-every", str(slow_every), "--slow-ms", "400"]
    return subprocess.Popen(
        argv, env=env, cwd=REPO, stdout=out, stderr=subprocess.STDOUT,
    )


def _wait_routable(gw, n: int, timeout: float = 30.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        gw.sync_once()
        _ring, states = gw._route_snapshot()
        if sum(1 for st in states.values() if st.routable()) >= n:
            return
        time.sleep(0.2)
    raise TimeoutError(
        f"never reached {n} routable replicas; states="
        f"{[(rid, st.eject_reasons()) for rid, st in states.items()]}"
    )


def _post_query(gport, body, deadline_ms=8000, timeout=12):
    req = urllib.request.Request(
        f"http://127.0.0.1:{gport}/queries.json",
        data=json.dumps(body).encode(),
        headers={
            "Content-Type": "application/json",
            "X-PIO-Deadline": str(deadline_ms),
        },
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


class _Hammer:
    """N client threads looping queries until stopped; every request
    carries an 8 s deadline, so ANY failure is an in-deadline loss."""

    def __init__(self, gport: int, clients: int = 64):
        self.gport = gport
        self.clients = clients
        self.sent = 0
        self.failed: list[str] = []
        self.replicas_seen: set[str] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self):
        for i in range(self.clients):
            t = threading.Thread(
                target=self._run, args=(i,), name=f"hammer-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _run(self, i: int):
        n = 0
        while not self._stop.is_set():
            n += 1
            body = {"q": f"c{i}-{n}"}
            try:
                status, answer = _post_query(self.gport, body)
                with self._lock:
                    self.sent += 1
                    if status != 200:
                        self.failed.append(f"{body}: HTTP {status}")
                    else:
                        self.replicas_seen.add(answer["replica"])
            except Exception as e:
                with self._lock:
                    self.sent += 1
                    self.failed.append(f"{body}: {e}")

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=15)


@pytest.fixture()
def gateway_fleet(tmp_path):
    """3 stub replica subprocesses + an in-process gateway over shared
    sqlite storage."""
    storage = _sqlite_storage(tmp_path)
    procs = {}
    ports = {}
    for i in range(3):
        rid = f"r{i}"
        ports[rid] = _free_port()
        procs[rid] = _spawn_replica(tmp_path, rid, ports[rid])
    gw = GatewayServer(storage, GatewayConfig(
        ip="127.0.0.1", port=0, sync_interval_s=0.15,
        replica_stale_after_s=1.5, scrape=True, scrape_interval_s=0.4,
        hedge=True, hedge_min_ms=60.0,
        breaker_threshold=2, breaker_cooldown_s=0.5,
    ))
    gport = gw.start()
    try:
        _wait_routable(gw, 3)
        yield gw, gport, procs, ports, tmp_path, storage
    finally:
        gw.stop()
        for proc in procs.values():
            try:
                proc.kill()
                proc.wait(timeout=10)
            except Exception:
                pass


def test_kill9_replica_zero_inflight_loss_then_rejoin(gateway_fleet):
    """The acceptance chaos: 64 clients hammering, one of three
    replicas SIGKILLed mid-hammer. Zero in-deadline queries lost; the
    dead replica is ejected (breaker/heartbeat/up all say so) and a
    restart re-admits it."""
    gw, gport, procs, ports, tmp_path, _storage = gateway_fleet
    hammer = _Hammer(gport, clients=64)
    hammer.start()
    try:
        time.sleep(1.5)  # steady state, all three answering
        victim = procs.pop("r0")
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)
        time.sleep(3.0)  # hammer rides through the failure
    finally:
        hammer.stop()
    assert hammer.sent > 200, "hammer produced too little traffic"
    assert not hammer.failed, (
        f"{len(hammer.failed)}/{hammer.sent} in-deadline queries lost; "
        f"first: {hammer.failed[:5]}"
    )
    assert {"r1", "r2"} <= hammer.replicas_seen

    # ejection: the gateway stopped routing to r0, and says why
    deadline = time.time() + 15
    reasons: list = []
    while time.time() < deadline:
        gw.sync_once()
        _ring, states = gw._route_snapshot()
        st = states.get("r0")
        if st is not None and not st.routable():
            reasons = st.eject_reasons()
            break
        time.sleep(0.2)
    assert reasons, "dead replica was never ejected"

    # the passive signal agrees: up{instance=r0} goes 0 on the
    # gateway's embedded scraper
    deadline = time.time() + 15
    up = None
    while time.time() < deadline:
        up = get_monitor().tsdb.latest("up", {"instance": "r0"})
        if up == 0.0:
            break
        time.sleep(0.3)
    assert up == 0.0, f"up{{instance=r0}} never went 0 (last={up})"

    # restart with the SAME durable identity: re-admission
    procs["r0"] = _spawn_replica(tmp_path, "r0", _free_port())
    deadline = time.time() + 30
    readmitted = False
    while time.time() < deadline:
        gw.sync_once()
        _ring, states = gw._route_snapshot()
        st = states.get("r0")
        if st is not None and st.routable():
            readmitted = True
            break
        time.sleep(0.3)
    assert readmitted, "restarted replica was never re-admitted"
    # and it actually serves again through the gateway
    seen = set()
    for i in range(60):
        _status, answer = _post_query(gport, {"q": f"rejoin-{i}"})
        seen.add(answer["replica"])
    assert "r0" in seen, "re-admitted replica receives no traffic"


def test_drain_is_zero_drop(gateway_fleet):
    """Graceful drain under load: the drained replica finishes its
    in-flight queries, the gateway routes around it, nothing fails,
    and the replica process exits cleanly."""
    gw, gport, procs, ports, _tmp, storage = gateway_fleet
    hammer = _Hammer(gport, clients=32)
    hammer.start()
    try:
        time.sleep(1.0)
        req = urllib.request.Request(
            f"http://127.0.0.1:{gport}/gateway/drain",
            data=json.dumps({"replica": "r1"}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 202
        # the replica drains, stops, and its process exits 0
        proc = procs.pop("r1")
        assert proc.wait(timeout=60) == 0
        time.sleep(1.0)  # hammer keeps running on the survivors
    finally:
        hammer.stop()
    assert not hammer.failed, (
        f"drain dropped {len(hammer.failed)} queries; "
        f"first: {hammer.failed[:5]}"
    )
    # clean retirement removed the record
    deadline = time.time() + 10
    while time.time() < deadline:
        if ReplicaRegistry(storage).get("r1") is None:
            break
        time.sleep(0.2)
    assert ReplicaRegistry(storage).get("r1") is None
    gw.sync_once()
    _ring, states = gw._route_snapshot()
    assert "r1" not in states


def test_stale_heartbeat_ejection_and_recovery(gateway_fleet):
    """A wedged replica (SIGSTOP: alive socket, frozen heartbeat) is
    ejected on heartbeat staleness alone, and re-admitted when it
    thaws."""
    gw, _gport, procs, _ports, _tmp, _storage = gateway_fleet
    frozen = procs["r2"]
    os.kill(frozen.pid, signal.SIGSTOP)
    try:
        deadline = time.time() + 20
        ejected = False
        while time.time() < deadline:
            gw.sync_once()
            _ring, states = gw._route_snapshot()
            st = states.get("r2")
            if st is not None and "stale_heartbeat" in st.eject_reasons():
                ejected = True
                break
            time.sleep(0.2)
        assert ejected, "frozen replica was never ejected as stale"
    finally:
        os.kill(frozen.pid, signal.SIGCONT)
    deadline = time.time() + 20
    readmitted = False
    while time.time() < deadline:
        gw.sync_once()
        _ring, states = gw._route_snapshot()
        st = states.get("r2")
        if st is not None and st.routable():
            readmitted = True
            break
        time.sleep(0.2)
    assert readmitted, "thawed replica was never re-admitted"


def test_fleet_observability_chaos(tmp_path, monkeypatch):
    """ISSUE 16 acceptance: with tracing and the trace collector
    attached, one replica forced slow (hedges fire) and one SIGKILLed
    mid-hammer. The collector assembles cross-process traces (gateway
    root + attempt children + replica-side server spans), `pio trace
    show --fleet` renders one, the fleet-aggregated availability SLO
    fires, and the firing alert links exemplar trace ids."""
    from predictionio_tpu.obs.monitor import SLOSpec
    from predictionio_tpu.tools import console

    monkeypatch.setenv("PIO_TRACE_COLLECT", "1")
    storage = _sqlite_storage(tmp_path)
    procs, ports = {}, {}
    # r1 answers every 3rd query in 400 ms — over the 60 ms hedge
    # trigger, so hedged (two-attempt) traces exist from the start
    for rid, slow in (("r0", 0), ("r1", 3), ("r2", 0)):
        ports[rid] = _free_port()
        procs[rid] = _spawn_replica(
            tmp_path, rid, ports[rid], slow_every=slow
        )
    mon = get_monitor()
    old_slo_iv = mon.slo_interval_s
    old_sample_iv = mon.sampler_interval_s
    mon.slo_interval_s = 0.5
    mon.sampler_interval_s = 0.25
    # fleet-scoped SLO over the scraper's up{instance} series: one dead
    # replica of three (fraction 1/3) blows a 0.1 error budget
    mon.set_slos([SLOSpec(
        name="fleet-up", kind="up", aggregate="mean", objective=0.9,
        fast_window_s=3.0, window_s=6.0, burn_threshold=1.0,
        min_samples=1, for_s=0.0, resolve_s=300.0,
    )])
    gw = GatewayServer(storage, GatewayConfig(
        ip="127.0.0.1", port=0, sync_interval_s=0.15,
        replica_stale_after_s=1.5, scrape=True, scrape_interval_s=0.4,
        hedge=True, hedge_min_ms=60.0,
        breaker_threshold=2, breaker_cooldown_s=0.5,
    ))
    gport = gw.start()
    hammer = _Hammer(gport, clients=16)
    try:
        _wait_routable(gw, 3)
        col = get_monitor().collector
        assert col is not None, (
            "PIO_TRACE_COLLECT=1 + scrape must start a collector"
        )
        hammer.start()

        def _assembled_cross_process():
            """(trace_id, spans) of a trace with a rooted gateway-side
            tree: a gateway.request span, >=2 attempt children, and a
            replica-side server span parented under an attempt."""
            for row in col.summaries(limit=50):
                spans = col.get_trace(row["trace_id"])
                if not any(
                    not s.get("parent_span_id") for s in spans
                ):
                    continue
                gw_spans = {
                    s["span_id"] for s in spans
                    if s["name"] == "gateway.request"
                }
                attempts = [
                    s for s in spans if s["name"] == "gateway.attempt"
                    and s.get("parent_span_id") in gw_spans
                ]
                attempt_ids = {s["span_id"] for s in attempts}
                server_spans = [
                    s for s in spans if s["name"] == "server.request"
                    and s.get("parent_span_id") in attempt_ids
                    and (s.get("attrs") or {}).get("replica")
                ]
                if len(attempts) >= 2 and server_spans:
                    return row["trace_id"], spans
            return None

        deadline = time.time() + 40
        found = None
        while time.time() < deadline and found is None:
            found = _assembled_cross_process()
            if found is None:
                time.sleep(0.3)
        assert found, (
            "no cross-process trace assembled; status="
            f"{col.status()} summaries={col.summaries(limit=5)}"
        )
        tid, _spans_found = found
        # the operator path renders the same assembled trace
        assert console.main(["trace", "show", tid, "--fleet"]) == 0
        assert console.main(["trace", "list", "--fleet"]) == 0

        # chaos: SIGKILL a healthy replica mid-hammer
        procs["r0"].send_signal(signal.SIGKILL)
        procs["r0"].wait(timeout=10)

        # a failover/errored attempt against the dead replica shows up
        # in an assembled trace (error-kept), naming the dead replica
        def _failed_attempt_visible():
            for row in col.summaries(limit=80):
                for s in col.get_trace(row["trace_id"]):
                    if (
                        s["name"] == "gateway.attempt" and s.get("error")
                        and (s.get("attrs") or {}).get("replica") == "r0"
                    ):
                        return True
            return False

        deadline = time.time() + 30
        failed_seen = False
        while time.time() < deadline and not failed_seen:
            failed_seen = _failed_attempt_visible()
            if not failed_seen:
                time.sleep(0.3)
        assert failed_seen, (
            "killed replica's failed attempt never appeared in an "
            f"assembled trace; status={col.status()}"
        )

        # the fleet-aggregated SLO fires, and the firing row carries
        # exemplar trace ids plus the slowest assembled fleet traces
        deadline = time.time() + 45
        fired = None
        while time.time() < deadline and fired is None:
            payload = mon.alerts_payload()
            for row in payload.get("alerts", []):
                if row.get("slo") == "fleet-up" and (
                    row.get("state") == "firing"
                ):
                    fired = row
                    break
            if fired is None:
                time.sleep(0.5)
        assert fired, (
            "fleet-up SLO never fired after replica kill; "
            f"payload={mon.alerts_payload()}"
        )
        assert fired.get("exemplars"), (
            f"firing alert carried no exemplars: {fired}"
        )
        assert fired["exemplars"][0].get("trace_id")
        assert fired.get("fleet_traces"), (
            f"firing alert carried no fleet traces: {fired}"
        )
    finally:
        hammer.stop()
        gw.stop()
        mon.set_slos([])
        mon.slo_interval_s = old_slo_iv
        mon.sampler_interval_s = old_sample_iv
        for proc in procs.values():
            try:
                proc.kill()
                proc.wait(timeout=10)
            except Exception:
                pass
