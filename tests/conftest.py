"""Test harness: force an 8-device virtual CPU mesh so multi-chip sharding
paths are exercised without TPU hardware (reference analogue: Spark
`local[4]` SharedSparkContext, core/src/test/.../BaseTest.scala:15-55)."""

from predictionio_tpu.utils.cpuonly import force_cpu_platform

# override=False: an explicitly pre-set device count (e.g. a 16-device
# repro via XLA_FLAGS) is honored; otherwise the standard 8-device mesh
force_cpu_platform(n_devices=8, override=False)

import pytest  # noqa: E402

# thread-sanitizer integration (ISSUE 12): with PIO_TSAN=1 the lock
# constructors are patched before any test runs, and session teardown
# runs the thread-leak tripwire + writes the JSON findings report.
# Delegated so plain `python -m pytest tests/` needs no -p flag.
from predictionio_tpu.analysis import pytest_plugin as _tsan_plugin  # noqa: E402


def pytest_configure(config):
    _tsan_plugin.pytest_configure(config)


def pytest_sessionfinish(session, exitstatus):
    _tsan_plugin.pytest_sessionfinish(session, exitstatus)


@pytest.fixture(scope="session")
def mesh8():
    """An 8-device 'dp×mp' mesh on the virtual CPU devices."""
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devices = np.array(jax.devices()[:8]).reshape(4, 2)
    with Mesh(devices, ("dp", "mp")) as m:
        yield m


@pytest.fixture()
def fresh_storage(tmp_path):
    """A Storage wired to throwaway sqlite+localfs under tmp_path."""
    from predictionio_tpu.data.storage.registry import (
        SourceConfig,
        Storage,
        StorageConfig,
    )

    cfg = StorageConfig(
        sources={
            "TESTSQL": SourceConfig(
                "TESTSQL", "sqlite", {"PATH": str(tmp_path / "pio.db")}
            ),
            "TESTFS": SourceConfig("TESTFS", "localfs", {"PATH": str(tmp_path)}),
        },
        repositories={
            "METADATA": "TESTSQL",
            "EVENTDATA": "TESTSQL",
            "MODELDATA": "TESTFS",
        },
    )
    return Storage(cfg)
