"""Test harness: force an 8-device virtual CPU mesh so multi-chip sharding
paths are exercised without TPU hardware (reference analogue: Spark
`local[4]` SharedSparkContext, core/src/test/.../BaseTest.scala:15-55)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# If a TPU PJRT plugin was registered at interpreter start (sitecustomize),
# neuter its factory so lazy backend init can never dial TPU hardware from
# a unit test — tests must be hermetic CPU-only. The platform NAME must
# stay registered (not popped): Pallas registers MLIR lowerings for the
# "tpu" platform at import time and errors on unknown platforms.
try:  # pragma: no cover - depends on host environment
    import dataclasses as _dc

    # sitecustomize may have imported jax before this file ran and set
    # jax_platforms programmatically (e.g. "axon,cpu"); force it back.
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

    from jax._src import xla_bridge as _xb

    def _blocked_backend(*_a, **_k):
        raise RuntimeError("non-CPU backends are blocked in unit tests")

    for _name, _reg in list(getattr(_xb, "_backend_factories", {}).items()):
        if _name != "cpu":
            _xb._backend_factories[_name] = _dc.replace(
                _reg, factory=_blocked_backend, fail_quietly=True
            )
except Exception:
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    """An 8-device 'dp×mp' mesh on the virtual CPU devices."""
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devices = np.array(jax.devices()[:8]).reshape(4, 2)
    with Mesh(devices, ("dp", "mp")) as m:
        yield m


@pytest.fixture()
def fresh_storage(tmp_path):
    """A Storage wired to throwaway sqlite+localfs under tmp_path."""
    from predictionio_tpu.data.storage.registry import (
        SourceConfig,
        Storage,
        StorageConfig,
    )

    cfg = StorageConfig(
        sources={
            "TESTSQL": SourceConfig(
                "TESTSQL", "sqlite", {"PATH": str(tmp_path / "pio.db")}
            ),
            "TESTFS": SourceConfig("TESTFS", "localfs", {"PATH": str(tmp_path)}),
        },
        repositories={
            "METADATA": "TESTSQL",
            "EVENTDATA": "TESTSQL",
            "MODELDATA": "TESTFS",
        },
    )
    return Storage(cfg)
