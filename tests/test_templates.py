"""End-to-end tests for the classification, similarproduct, and ecommerce
engine templates (the remaining reference examples/ families)."""

import numpy as np
import pytest

from predictionio_tpu.controller import EmptyParams, EngineParams, RuntimeContext
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.workflow.core import prepare_deploy_models, run_train


def make_app(storage, name):
    app_id = storage.get_meta_data_apps().insert(App(id=0, name=name))
    storage.get_events().init_app(app_id)
    return app_id


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


@pytest.fixture()
def classify_storage(fresh_storage):
    """Two Gaussian-ish blobs: plan 'premium' has high attrs, 'free' low."""
    app_id = make_app(fresh_storage, "clsapp")
    rng = np.random.RandomState(3)
    events = []
    for i in range(60):
        premium = i % 2 == 0
        base = 8.0 if premium else 2.0
        events.append(
            Event(
                event="$set",
                entity_type="user",
                entity_id=f"u{i}",
                properties={
                    "attr0": float(base + rng.rand() * 2),
                    "attr1": float(base + rng.rand() * 2),
                    "attr2": float(rng.rand()),  # noise
                    "plan": "premium" if premium else "free",
                },
            )
        )
    fresh_storage.get_events().insert_batch(events, app_id)
    return fresh_storage


CLS_VARIANT = {
    "id": "cls",
    "engineFactory": "predictionio_tpu.engines.classification.ClassificationEngine",
    "datasource": {
        "params": {"app_name": "clsapp", "label_attr": "plan"}
    },
    "algorithms": [{"name": "naive", "params": {"lambda_": 1.0}}],
}


class TestClassification:
    def test_naive_bayes_end_to_end(self, classify_storage):
        inst = run_train(classify_storage, CLS_VARIANT)
        assert inst.status == "COMPLETED"
        engine, ep, models = prepare_deploy_models(classify_storage, inst)
        algo = engine.make_algorithms(ep)[0]
        from predictionio_tpu.engines.classification import Query

        assert algo.predict(models[0], Query([9.0, 9.0, 0.5])).label == "premium"
        assert algo.predict(models[0], Query([2.0, 2.5, 0.5])).label == "free"

    def test_logreg_variant(self, classify_storage):
        variant = dict(
            CLS_VARIANT,
            algorithms=[{"name": "logreg", "params": {"iterations": 300}}],
        )
        inst = run_train(classify_storage, variant)
        engine, ep, models = prepare_deploy_models(classify_storage, inst)
        algo = engine.make_algorithms(ep)[0]
        from predictionio_tpu.engines.classification import Query

        assert algo.predict(models[0], Query([9.0, 9.0, 0.5])).label == "premium"
        assert algo.predict(models[0], Query([2.0, 2.0, 0.5])).label == "free"

    def test_randomforest_variant(self, classify_storage):
        """engine.json-driven swap to the third algorithm (reference
        add-algorithm variant's whole point)."""
        variant = dict(
            CLS_VARIANT,
            algorithms=[
                {"name": "randomforest",
                 "params": {"num_trees": 10, "max_depth": 4}}
            ],
        )
        inst = run_train(classify_storage, variant)
        assert inst.status == "COMPLETED"
        engine, ep, models = prepare_deploy_models(classify_storage, inst)
        algo = engine.make_algorithms(ep)[0]
        from predictionio_tpu.engines.classification import Query

        assert algo.predict(models[0], Query([9.0, 9.0, 0.5])).label == "premium"
        assert algo.predict(models[0], Query([2.0, 2.0, 0.5])).label == "free"

    def test_eval_accuracy(self, classify_storage):
        from predictionio_tpu.controller import Evaluation
        from predictionio_tpu.engines.classification import ClassificationEngine
        from predictionio_tpu.engines.classification.engine import (
            Accuracy,
            DataSourceParams,
            NaiveBayesParams,
        )
        from predictionio_tpu.workflow.evaluation import run_evaluation

        dsp = DataSourceParams(app_name="clsapp", label_attr="plan", eval_k=3)
        grid = [
            EngineParams(
                data_source_params=("", dsp),
                preparator_params=("", EmptyParams()),
                algorithm_params_list=(("naive", NaiveBayesParams(lambda_=lam)),),
                serving_params=("", EmptyParams()),
            )
            for lam in (0.5, 2.0)
        ]

        class ClsEval(Evaluation):
            engine = ClassificationEngine().apply()
            metric = Accuracy()

        inst, result = run_evaluation(classify_storage, ClsEval(), grid)
        assert inst.status == "EVALCOMPLETED"
        # multinomial NB discriminates proportions, not magnitudes, so the
        # scale-separated blobs cap out below perfect — well above chance
        assert result.best_score.score > 0.75


# ---------------------------------------------------------------------------
# similarproduct
# ---------------------------------------------------------------------------


@pytest.fixture()
def similar_storage(fresh_storage):
    """Items 0-4 co-viewed by even users, 5-9 by odd users; likes mirror."""
    app_id = make_app(fresh_storage, "simapp")
    rng = np.random.RandomState(11)
    events = []
    for u in range(20):
        group = u % 2
        for _ in range(25):
            i = rng.randint(0, 5) + group * 5
            events.append(
                Event(
                    event="view", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                )
            )
        events.append(
            Event(
                event="like", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item",
                target_entity_id=f"i{group * 5}",
            )
        )
        events.append(
            Event(
                event="dislike", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item",
                target_entity_id=f"i{(1 - group) * 5}",
            )
        )
    fresh_storage.get_events().insert_batch(events, app_id)
    return fresh_storage


SIM_VARIANT = {
    "id": "sim",
    "engineFactory": "predictionio_tpu.engines.similarproduct.SimilarProductEngine",
    "datasource": {"params": {"app_name": "simapp"}},
    "algorithms": [
        {"name": "als", "params": {"rank": 4, "num_iterations": 10}}
    ],
}


class TestSimilarProduct:
    def test_similar_items_same_group(self, similar_storage):
        inst = run_train(similar_storage, SIM_VARIANT)
        engine, ep, models = prepare_deploy_models(similar_storage, inst)
        algo = engine.make_algorithms(ep)[0]
        from predictionio_tpu.engines.similarproduct import Query

        pred = algo.predict(models[0], Query(items=["i0", "i1"], num=3))
        assert len(pred.item_scores) == 3
        items = {s.item for s in pred.item_scores}
        assert "i0" not in items and "i1" not in items  # query items excluded
        # co-view structure dominates: top-3 mostly from the same group
        assert len(items & {"i2", "i3", "i4"}) >= 2, items

    def test_unknown_items_empty(self, similar_storage):
        inst = run_train(similar_storage, SIM_VARIANT)
        engine, ep, models = prepare_deploy_models(similar_storage, inst)
        algo = engine.make_algorithms(ep)[0]
        from predictionio_tpu.engines.similarproduct import Query

        assert algo.predict(models[0], Query(items=["nope"])).item_scores == []

    def test_multi_algo_sum_serving(self, similar_storage):
        variant = dict(
            SIM_VARIANT,
            algorithms=[
                {"name": "als", "params": {"rank": 8, "num_iterations": 8}},
                {"name": "like", "params": {"rank": 4, "num_iterations": 6}},
            ],
            serving={"name": "sum"},
        )
        inst = run_train(similar_storage, variant)
        engine, ep, models = prepare_deploy_models(similar_storage, inst)
        algos = engine.make_algorithms(ep)
        serving = engine.make_serving(ep)
        from predictionio_tpu.engines.similarproduct import Query

        q = Query(items=["i0"], num=4)
        preds = [a.predict(m, q) for a, m in zip(algos, models)]
        combined = serving.serve(q, preds)
        assert len(combined.item_scores) == 4
        assert type(serving).__name__ == "SumScoreServing"


# ---------------------------------------------------------------------------
# ecommerce
# ---------------------------------------------------------------------------


@pytest.fixture()
def ecomm_storage(fresh_storage):
    app_id = make_app(fresh_storage, "ecapp")
    rng = np.random.RandomState(13)
    events = []
    for i in range(8):
        events.append(
            Event(
                event="$set", entity_type="item", entity_id=f"i{i}",
                properties={"categories": ["tools" if i < 4 else "toys"]},
            )
        )
    for u in range(12):
        group = u % 2
        for _ in range(20):
            i = rng.randint(0, 4) + group * 4
            events.append(
                Event(
                    event="view", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                )
            )
    fresh_storage.get_events().insert_batch(events, app_id)
    return fresh_storage, app_id


EC_VARIANT = {
    "id": "ec",
    "engineFactory": "predictionio_tpu.engines.ecommerce.ECommerceEngine",
    "datasource": {"params": {"app_name": "ecapp"}},
    "algorithms": [
        {
            "name": "ecomm",
            "params": {
                "app_name": "ecapp",
                "rank": 8,
                "num_iterations": 8,
                "unseen_only": False,
            },
        }
    ],
}


def deploy(storage, variant):
    inst = run_train(storage, variant)
    engine, ep, models = prepare_deploy_models(storage, inst)
    algo = engine.make_algorithms(ep)[0]
    algo.set_serving_context(RuntimeContext(storage=storage, mode="serve"))
    return algo, models[0]


class TestECommerce:
    def test_basic_recommendation(self, ecomm_storage):
        storage, _ = ecomm_storage
        algo, model = deploy(storage, EC_VARIANT)
        from predictionio_tpu.engines.ecommerce import Query

        pred = algo.predict(model, Query(user="u0", num=4))
        items = {s.item for s in pred.item_scores}
        assert len(items & {"i0", "i1", "i2", "i3"}) >= 3

    def test_category_filter(self, ecomm_storage):
        storage, _ = ecomm_storage
        algo, model = deploy(storage, EC_VARIANT)
        from predictionio_tpu.engines.ecommerce import Query

        pred = algo.predict(model, Query(user="u0", num=8, categories=["toys"]))
        items = {s.item for s in pred.item_scores}
        assert items and items <= {"i4", "i5", "i6", "i7"}

    def test_unseen_only_filters_seen(self, ecomm_storage):
        storage, _ = ecomm_storage
        variant = dict(EC_VARIANT)
        variant["algorithms"] = [
            {
                "name": "ecomm",
                "params": dict(
                    EC_VARIANT["algorithms"][0]["params"], unseen_only=True
                ),
            }
        ]
        algo, model = deploy(storage, variant)
        from predictionio_tpu.engines.ecommerce import Query

        # u0 has seen a subset of i0-i3; those must not be recommended
        seen = algo._seen_items(algo.serving_context, "u0")
        assert seen  # fixture guarantees views
        pred = algo.predict(model, Query(user="u0", num=8))
        items = {s.item for s in pred.item_scores}
        assert not (items & seen)

    def test_unavailable_items_constraint(self, ecomm_storage):
        storage, app_id = ecomm_storage
        storage.get_events().insert(
            Event(
                event="$set", entity_type="constraint",
                entity_id="unavailableItems",
                properties={"items": ["i0", "i1"]},
            ),
            app_id,
        )
        algo, model = deploy(storage, EC_VARIANT)
        from predictionio_tpu.engines.ecommerce import Query

        pred = algo.predict(model, Query(user="u0", num=8))
        items = {s.item for s in pred.item_scores}
        assert not (items & {"i0", "i1"})

    def test_unknown_user_falls_back_to_recent_views(self, ecomm_storage):
        storage, app_id = ecomm_storage
        # train FIRST; the new user's views arrive after the model is built
        # (the realistic cold-start window the reference handles)
        algo, model = deploy(storage, EC_VARIANT)
        storage.get_events().insert_batch(
            [
                Event(
                    event="view", entity_type="user", entity_id="newbie",
                    target_entity_type="item", target_entity_id="i5",
                ),
                Event(
                    event="view", entity_type="user", entity_id="newbie",
                    target_entity_type="item", target_entity_id="i6",
                ),
            ],
            app_id,
        )
        from predictionio_tpu.engines.ecommerce import Query

        pred = algo.predict(model, Query(user="newbie", num=3))
        items = {s.item for s in pred.item_scores}
        # similar to toys group, basis items excluded
        assert items and "i5" not in items and "i6" not in items
        assert len(items & {"i4", "i7"}) >= 1, items

    def test_batch_predict_honors_eval_ctx(self, ecomm_storage):
        """Eval must measure the same live filters the deploy server
        applies — batch_predict threads the eval ctx into the store reads."""
        storage, _ = ecomm_storage
        variant = dict(EC_VARIANT)
        variant["algorithms"] = [
            {
                "name": "ecomm",
                "params": dict(
                    EC_VARIANT["algorithms"][0]["params"], unseen_only=True
                ),
            }
        ]
        inst = run_train(storage, variant)
        engine, ep, models = prepare_deploy_models(storage, inst)
        algo = engine.make_algorithms(ep)[0]
        # note: NO set_serving_context — the ctx comes from the caller
        from predictionio_tpu.engines.ecommerce import Query

        ctx = RuntimeContext(storage=storage, mode="eval")
        preds = dict(
            algo.batch_predict(ctx, models[0], [(0, Query(user="u0", num=8))])
        )
        seen = algo._seen_items(ctx, "u0")
        items = {s.item for s in preds[0].item_scores}
        assert seen and not (items & seen)

    def test_totally_unknown_user_empty(self, ecomm_storage):
        storage, _ = ecomm_storage
        algo, model = deploy(storage, EC_VARIANT)
        from predictionio_tpu.engines.ecommerce import Query

        assert algo.predict(model, Query(user="ghost")).item_scores == []
