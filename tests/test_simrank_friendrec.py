"""SimRank + friend-recommendation engine families (VERDICT r3 #10:
two more experimental-template demos — examples/experimental/
scala-parallel-friend-recommendation and scala-local-friend-recommendation)."""

import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.models import simrank
from predictionio_tpu.workflow.core import run_train
from predictionio_tpu.workflow.server import latest_completed_runtime

UTC = dt.timezone.utc


def _mem_storage(app_name):
    cfg = StorageConfig(
        sources={"MEM": SourceConfig("MEM", "memory", {})},
        repositories={
            "METADATA": "MEM", "EVENTDATA": "MEM", "MODELDATA": "MEM",
        },
    )
    s = Storage(cfg)
    app_id = s.get_meta_data_apps().insert(App(0, app_name))
    s.get_events().init_app(app_id)
    return s, app_id


class TestSimRankKernel:
    def test_matches_literal_definition(self):
        rng = np.random.RandomState(3)
        n = 24
        src = rng.randint(0, n, 80)
        dst = rng.randint(0, n, 80)
        keep = src != dst
        src, dst = src[keep], dst[keep]
        model = simrank.compute(src, dst, n, iterations=4)
        ref = simrank.simrank_reference(src, dst, n, iterations=4)
        np.testing.assert_allclose(model.scores, ref, rtol=1e-4, atol=1e-5)

    def test_properties(self):
        # triangle a→b, b→c, c→a: symmetric scores, unit diagonal
        model = simrank.compute(
            np.array([0, 1, 2]), np.array([1, 2, 0]), 3, iterations=8
        )
        s = model.scores
        np.testing.assert_allclose(np.diag(s), 1.0)
        np.testing.assert_allclose(s, s.T, atol=1e-6)
        assert ((s >= 0) & (s <= 1.0 + 1e-6)).all()


class TestSimRankEngine:
    def test_train_and_query(self):
        s, app_id = _mem_storage("srapp")
        # u0 and u1 are structurally similar: both followed by u2, u3
        batch = [
            Event(event="follow", entity_type="user", entity_id=f,
                  target_entity_type="user", target_entity_id=t)
            for f, t in [
                ("u2", "u0"), ("u3", "u0"), ("u2", "u1"), ("u3", "u1"),
                ("u4", "u5"),
            ]
        ]
        s.get_events().insert_batch(batch, app_id)
        variant = {
            "id": "sr",
            "engineFactory":
                "predictionio_tpu.engines.simrank.SimRankEngine",
            "datasource": {"params": {"app_name": "srapp"}},
            "algorithms": [
                {"name": "simrank", "params": {"iterations": 5}}
            ],
        }
        run_train(s, variant)
        rt = latest_completed_runtime(s, "sr", "0", "sr")
        algo, model = rt.algorithms[0], rt.models[0]
        from predictionio_tpu.engines.simrank.engine import Query

        # pair query: u0 ~ u1 share both in-neighbors {u2, u3}, whose own
        # similarity is 0 (no in-edges): S = C/4·(S22 + S23 + S32 + S33)
        # = 0.8·2/4 = 0.4 exactly
        pair = algo.predict(model, Query(user="u0", user2="u1"))
        assert pair.similarity == pytest.approx(0.4, rel=1e-5)
        # top-N query puts u1 first for u0
        top = algo.predict(model, Query(user="u0", num=3))
        assert top.user_scores and top.user_scores[0].user == "u1"
        # unknown user → empty
        assert algo.predict(model, Query(user="nope")).user_scores == []

    def test_max_nodes_guard(self):
        from predictionio_tpu.engines.simrank.engine import (
            DataSourceParams,
            SimRankDataSource,
        )
        from predictionio_tpu.core.base import RuntimeContext

        s, app_id = _mem_storage("bigapp")
        batch = [
            Event(event="follow", entity_type="user", entity_id=f"a{i}",
                  target_entity_type="user", target_entity_id=f"b{i}")
            for i in range(30)
        ]
        s.get_events().insert_batch(batch, app_id)
        ds = SimRankDataSource(
            DataSourceParams(app_name="bigapp", max_nodes=10)
        )
        with pytest.raises(ValueError, match="max_nodes"):
            ds.read_training(RuntimeContext(storage=s))


class TestFriendRecEngine:
    def _seed(self):
        s, app_id = _mem_storage("frapp")
        ev = s.get_events()
        sets = [
            ("user", "u0", {"keywords": {"1": 0.5, "2": 0.5}}),
            ("user", "u1", {"keywords": {"3": 1.0}}),
            ("item", "g0", {"keywords": {"1": 1.0, "2": 1.0}}),
            ("item", "g1", {"keywords": {"3": 0.2}}),
        ]
        ev.insert_batch(
            [
                Event(event="$set", entity_type=et, entity_id=eid,
                      properties=props)
                for et, eid, props in sets
            ],
            app_id,
        )
        return s

    def test_train_and_predict(self):
        s = self._seed()
        variant = {
            "id": "fr",
            "engineFactory":
                "predictionio_tpu.engines.friendrec.FriendRecommendationEngine",
            "datasource": {"params": {"app_name": "frapp"}},
            "algorithms": [
                {
                    "name": "keyword_similarity",
                    "params": {"sim_weight": 1.0, "threshold": 0.9},
                }
            ],
        }
        run_train(s, variant)
        rt = latest_completed_runtime(s, "fr", "0", "fr")
        algo, model = rt.algorithms[0], rt.models[0]
        from predictionio_tpu.engines.friendrec.engine import Query

        # u0·g0 = 0.5·1 + 0.5·1 = 1.0 ≥ 0.9 → accepted
        p = algo.predict(model, Query(user="u0", item="g0"))
        assert p.confidence == pytest.approx(1.0, rel=1e-5)
        assert p.acceptance
        # u1·g1 = 1.0·0.2 = 0.2 < 0.9 → rejected
        p = algo.predict(model, Query(user="u1", item="g1"))
        assert p.confidence == pytest.approx(0.2, rel=1e-5)
        assert not p.acceptance
        # disjoint keywords → 0; unseen → reference behavior (conf 0)
        assert algo.predict(
            model, Query(user="u0", item="g1")
        ).confidence == pytest.approx(0.0, abs=1e-6)
        assert algo.predict(
            model, Query(user="ghost", item="g0")
        ).confidence == 0.0

        # batched path agrees with the single path
        queries = [
            (0, Query(user="u0", item="g0")),
            (1, Query(user="ghost", item="g0")),
            (2, Query(user="u1", item="g1")),
        ]
        got = dict(algo.batch_predict(None, model, queries))
        assert got[0].confidence == pytest.approx(1.0, rel=1e-5)
        assert got[1].confidence == 0.0
        assert got[2].confidence == pytest.approx(0.2, rel=1e-5)


class TestFileDataSource:
    """DataSource SPI against a foreign store (VERDICT r3 #5 tail:
    reference custom-datasource/mongo-datasource demos)."""

    def test_file_ratings_train_and_recommend(self, tmp_path):
        ratings = tmp_path / "ratings.dat"
        lines = []
        rng = np.random.RandomState(2)
        for u in range(20):
            for i in rng.choice(15, 6, replace=False):
                lines.append(f"u{u}::i{i}::{rng.randint(1, 6)}")
        ratings.write_text("\n".join(lines))

        s, _app = _mem_storage("fileapp")  # storage only holds metadata
        variant = {
            "id": "filerec",
            "engineFactory": "predictionio_tpu.engines.recommendation."
            "FileRecommendationEngine",
            "datasource": {"params": {"filepath": str(ratings)}},
            "algorithms": [
                {"name": "als", "params": {"rank": 6, "num_iterations": 3}}
            ],
        }
        run_train(s, variant)
        rt = latest_completed_runtime(s, "filerec", "0", "filerec")
        algo, model = rt.algorithms[0], rt.models[0]
        from predictionio_tpu.engines.recommendation.engine import (
            Query as RecQuery,
        )

        p = algo.predict(model, RecQuery(user="u0", num=5))
        assert len(p.item_scores) == 5
        assert all(sc.item.startswith("i") for sc in p.item_scores)

    def test_bad_line_raises(self, tmp_path):
        bad = tmp_path / "bad.dat"
        bad.write_text("u1::i1\n")
        from predictionio_tpu.core.base import RuntimeContext
        from predictionio_tpu.engines.recommendation.engine import (
            FileDataSourceParams,
            FileRatingsDataSource,
        )

        with pytest.raises(ValueError, match="bad ratings line"):
            FileRatingsDataSource(
                FileDataSourceParams(filepath=str(bad))
            ).read_training(RuntimeContext())
