"""Chaos e2e (ISSUE 19 acceptance): kill -9 the primary storage daemon
mid-ingest under a multi-writer hammer with a live fold-in consumer. An
elected follower must serve with ZERO acked events lost and ZERO
double-delivered revisions, the zombie primary's epoch must be fenced
everywhere, and the consumer must resume exactly-once on the follower —
with `replication_ship_total` / `replication_lag_revisions` observable
throughout."""

import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import StorageError
from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.data.api.storage_server import StorageServer
from predictionio_tpu.data.storage.replication import (
    FollowerLink,
    ReplicaReadStorage,
    ReplicationConfig,
    SegmentShipper,
    elect_and_promote,
)
from predictionio_tpu.deploy.registry import LifecycleRecordStore
from predictionio_tpu.obs.registry import get_default_registry
from predictionio_tpu.online.consumer import (
    OnlineConsumer,
    OnlineConsumerConfig,
)
from predictionio_tpu.resilience import faults
from predictionio_tpu.resilience.breaker import reset_breakers

REPO = Path(__file__).resolve().parent.parent
APP = 3


@pytest.fixture(autouse=True)
def _clean_faults_and_breakers():
    faults.clear()
    reset_breakers()
    yield
    faults.clear()
    reset_breakers()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_health(port, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=2
            ) as r:
                if r.status == 200:
                    return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError(f"storage daemon on :{port} never became healthy")


def _metrics(port: int) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ) as r:
        return r.read().decode()


def _spawn_primary(tmp_path, port, follower_ports):
    """Primary storage daemon subprocess: segmentfs event store with
    aggressive sealing (segments ship mid-test, not just WAL frames) and
    the shipper enabled at min_acks=1 — every acked insert reached at
    least one follower before the client saw the ack."""
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": str(REPO) + os.pathsep + env.get("PYTHONPATH", ""),
        "PIO_STORAGE_SOURCES_SEG_TYPE": "segmentfs",
        "PIO_STORAGE_SOURCES_SEG_PATH": str(tmp_path / "primary"),
        "PIO_STORAGE_SOURCES_SEG_SEAL_EVENTS": "200",
        "PIO_STORAGE_SOURCES_SEG_SEAL_INTERVAL_S": "0.05",
        "PIO_STORAGE_SOURCES_SEG_SEAL_AGE_S": "0.05",
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SEG",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        "PIO_REPL_FOLLOWERS": ",".join(
            f"127.0.0.1:{p}" for p in follower_ports
        ),
        "PIO_REPL_MIN_ACKS": "1",
        "PIO_REPL_SHIP_INTERVAL_S": "0.05",
        "PIO_REPL_EPOCH": "1",
    })
    return subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.data.api.storage_server",
         "--host", "127.0.0.1", "--port", str(port)],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )


def _follower_storage(tmp_path, name) -> Storage:
    return Storage(StorageConfig(
        sources={
            "REP": SourceConfig("REP", "segmentfs-replica", {
                "PATH": str(tmp_path / name),
                "SEAL_INTERVAL_S": "3600",
            }),
            "M": SourceConfig("M", "memory", {}),
        },
        repositories={
            "METADATA": "M", "EVENTDATA": "REP", "MODELDATA": "M",
        },
    ))


def _remote_storage(port: int) -> Storage:
    return Storage(StorageConfig(
        sources={
            "RMT": SourceConfig("RMT", "remote", {
                "HOST": "127.0.0.1", "PORT": str(port),
                "RETRY_ATTEMPTS": "2", "RETRY_BASE_DELAY": "0.01",
                "BREAKER_THRESHOLD": "2", "BREAKER_COOLDOWN": "0.3",
            }),
        },
        repositories={
            "METADATA": "RMT", "EVENTDATA": "RMT", "MODELDATA": "RMT",
        },
    ))


def _mem_storage() -> Storage:
    return Storage(StorageConfig(
        sources={"M": SourceConfig("M", "memory", {})},
        repositories={
            "METADATA": "M", "EVENTDATA": "M", "MODELDATA": "M",
        },
    ))


class _StubHost:
    scope = "server"

    def __init__(self):
        self.runtime = object()

    def current(self):
        return self.runtime

    def swap(self, old, new):
        if self.runtime is old:
            self.runtime = new
            return True
        return False


BATCH = 16


def _hammer(port, writer_id, acked, stop):
    """One writer: acked batch inserts until the primary dies. An
    insert_batch that returns acked the WHOLE batch (min_acks=1 held it
    until a follower applied the frame); ids of a raised batch are
    un-acked — the zero-loss contract covers only ids appended to
    `acked` BEFORE the exception."""
    store = _remote_storage(port).get_events()
    k = 0
    while not stop.is_set():
        eids = [f"w{writer_id}-{k + j}" for j in range(BATCH)]
        try:
            store.insert_batch([Event(
                event="rate", entity_type="user", entity_id=eid,
                target_entity_type="item", target_entity_id=f"i{k % 7}",
                properties={"rating": float(k % 5 + 1)},
            ) for eid in eids], APP)
        except Exception:
            return  # primary gone (or under-replicated ack) — stop
        acked.extend(eids)
        k += BATCH


def test_primary_kill9_failover_zero_loss(tmp_path):
    p_primary, p_a, p_b = _free_port(), _free_port(), _free_port()
    storage_a = _follower_storage(tmp_path, "replicaA")
    storage_b = _follower_storage(tmp_path, "replicaB")
    store_a, store_b = storage_a.get_events(), storage_b.get_events()
    store_a.init_app(APP)
    store_b.init_app(APP)
    srv_a = StorageServer(storage_a, host="127.0.0.1", port=p_a).start()
    srv_b = StorageServer(storage_b, host="127.0.0.1", port=p_b).start()
    proc = _spawn_primary(tmp_path, p_primary, [p_a, p_b])
    consumer = None
    consumer2 = None
    try:
        _wait_health(p_primary)
        ctl = _mem_storage()
        records = LifecycleRecordStore(ctl)

        # live fold-in consumer reading from follower A (ISSUE 19:
        # per-replica cursor name; cursor records stay on control)
        consumer = OnlineConsumer(
            ReplicaReadStorage(ctl, store_a, [APP]), _StubHost(), APP,
            OnlineConsumerConfig(
                tick_s=3600, name=f"online/{APP}/replica-a"
            ),
        )

        # multi-writer hammer against the primary daemon
        acked: list[str] = []
        stop = threading.Event()
        writers = [
            threading.Thread(
                target=_hammer, args=(p_primary, w, acked, stop),
                daemon=True,
            )
            for w in range(4)
        ]
        for t in writers:
            t.start()
        deadline = time.time() + 60
        while len(acked) < 600 and time.time() < deadline:
            time.sleep(0.05)
            consumer.tick()  # consuming WHILE the hammer runs
        assert len(acked) >= 600, "hammer never reached takeoff"
        # replication is observable on the primary's /metrics while it
        # is still alive — WAL frames (sync hook) must have shipped, and
        # with SEAL_EVENTS=200 whole segments must have shipped too
        m = _metrics(p_primary)
        assert "replication_ship_total" in m
        assert 'kind="wal"' in m and 'kind="segment"' in m

        # ---- kill -9 mid-ingest, writers still hammering -----------------
        proc.kill()
        proc.wait(timeout=10)
        stop.set()
        for t in writers:
            t.join(timeout=30)
        n_acked = len(acked)
        assert n_acked >= 600

        # ---- fenced failover: both followers stand concurrently ----------
        link_a = FollowerLink(f"127.0.0.1:{p_a}", timeout_s=10.0)
        link_b = FollowerLink(f"127.0.0.1:{p_b}", timeout_s=10.0)
        dead = FollowerLink(f"127.0.0.1:{p_primary}", timeout_s=10.0)
        results = {}
        barrier = threading.Barrier(2)

        def _stand(name, store, peers):
            barrier.wait()
            results[name] = elect_and_promote(
                records, store, name, peers=peers, settle_s=0.3
            )

        ca = threading.Thread(
            target=_stand, args=("replica-a", store_a, [link_b, dead])
        )
        cb = threading.Thread(
            target=_stand, args=("replica-b", store_b, [link_a, dead])
        )
        ca.start()
        cb.start()
        ca.join(timeout=30)
        cb.join(timeout=30)
        winners = [n for n, gen in results.items() if gen is not None]
        assert len(winners) == 1, f"split brain: {results}"
        winner = store_a if winners[0] == "replica-a" else store_b
        loser = store_b if winner is store_a else store_a
        assert results[winners[0]] == 2  # epoch 1 was the dead primary's
        assert winner.role == "primary" and winner.epoch == 2

        # the winner was gated on being at least as caught up as every
        # reachable peer, and watermarks are contiguous prefixes — so
        # every acked event is there, exactly once
        ids = [e.entity_id for e in winner.find_since(APP, 0)]
        assert len(ids) == len(set(ids)), "double-delivered revisions"
        missing = set(acked) - set(ids)
        assert not missing, f"lost {len(missing)} acked events"

        # ---- promoted follower serves writes immediately -----------------
        winner.insert_batch([Event(
            event="rate", entity_type="user", entity_id="post-failover",
            target_entity_type="item", target_entity_id="i1",
            properties={"rating": 5.0},
        )], APP)

        # ---- re-replicate: winner ships to the surviving follower -------
        loser_port = p_b if loser is store_b else p_a
        sh2 = SegmentShipper(
            winner,
            ReplicationConfig(
                followers=(f"127.0.0.1:{loser_port}",), timeout_s=10.0
            ),
            epoch=2,
        )
        deadline = time.time() + 30
        while time.time() < deadline:
            sh2.pass_once()
            if loser.replication_lag(APP)["lag"] == 0 and \
                    loser.latest_revision(APP) == \
                    winner.latest_revision(APP):
                break
            time.sleep(0.05)
        assert loser.latest_revision(APP) == winner.latest_revision(APP)
        assert loser.epoch == 2  # adopted from the epoch-2 frames

        # ---- zombie fencing ----------------------------------------------
        # a zombie primary's late epoch-1 frame is un-replayable on BOTH
        # survivors: the promoted store refuses frames outright, the
        # follower fences the stale epoch
        zombie = (APP, None, 1, 0, [1], [[
            "z", "rate", "user", "z", "item", "i1", {}, 0, None, None, 0,
        ]], 1)
        with pytest.raises(StorageError):
            winner.replication_apply_wal(*zombie)
        with pytest.raises(StorageError, match="fenced"):
            loser.replication_apply_wal(*zombie)
        # lag is observable wherever the replica's registry renders
        assert "replication_lag_revisions" in get_default_registry().render()

        # ---- consumer resumes exactly-once on the follower ---------------
        # store_a holds the full replicated stream now (it is either the
        # winner or the caught-up loser); drain the consumer
        for _ in range(200):
            if not consumer.tick().get("consumed"):
                break
        total = store_a.latest_revision(APP)
        first_run = dict(consumer.counters)
        # every event id is unique, so exactly-once across the failover
        # means the counter equals the number of live events — no id
        # consumed twice, none skipped
        assert first_run["events_consumed"] == len(
            store_a.find_since(APP, 0)
        )
        consumer.stop()

        # restart: the durable per-replica cursor resumes — nothing is
        # re-consumed, nothing is skipped
        consumer2 = OnlineConsumer(
            ReplicaReadStorage(ctl, store_a, [APP]), _StubHost(), APP,
            OnlineConsumerConfig(
                tick_s=3600, name=f"online/{APP}/replica-a"
            ),
        )
        assert consumer2.tick().get("consumed", 0) == 0
        assert consumer2.counters["events_consumed"] == \
            first_run["events_consumed"]
        assert total == store_a.latest_revision(APP)
    finally:
        if proc.poll() is None:
            proc.kill()
        if consumer is not None:
            consumer.stop()
        if consumer2 is not None:
            consumer2.stop()
        srv_a.shutdown()
        srv_b.shutdown()
