"""Multi-tenant serving units (ISSUE 6): DRR fairness math, LRU cache
eviction rules (pinned / in-flight immunity), quota accounting, tenant
records, and the bounded tenant metric labels."""

import queue as stdlib_queue
import threading
import time

import pytest

from predictionio_tpu.obs.registry import MetricsRegistry
from predictionio_tpu.tenancy.cache import ModelCache, ModelLoadError
from predictionio_tpu.tenancy.fair import FairQueue
from predictionio_tpu.tenancy.quota import (
    QuotaEnforcer,
    QuotaExceeded,
    TokenBucket,
)
from predictionio_tpu.tenancy.tenants import Tenant, TenantStore


class _Item:
    def __init__(self, tenant, i):
        self.tenant = tenant
        self.i = i

    def __repr__(self):
        return f"{self.tenant}:{self.i}"


# ---------------------------------------------------------------------------
# deficit round robin
# ---------------------------------------------------------------------------


class TestFairQueue:
    def test_fifo_degenerate_single_stream(self):
        q = FairQueue()
        for i in range(10):
            q.put(_Item(None, i))
        assert [q.get_nowait().i for i in range(10)] == list(range(10))
        with pytest.raises(stdlib_queue.Empty):
            q.get_nowait()

    def test_hog_cannot_starve_light_tenants(self):
        """A 100-deep hog backlog vs two light tenants: the light
        tenants' items all drain within the first few rounds instead of
        waiting behind the whole hog queue (the FIFO failure mode)."""
        q = FairQueue()
        for i in range(100):
            q.put(_Item("hog", i))
        for i in range(5):
            q.put(_Item("a", i))
            q.put(_Item("b", i))
        drained = [q.get_nowait() for _ in range(110)]
        # equal weights: in the first 15 pops each tenant got ~5 slots,
        # so a and b are fully served almost immediately
        a_done = max(i for i, it in enumerate(drained) if it.tenant == "a")
        b_done = max(i for i, it in enumerate(drained) if it.tenant == "b")
        assert a_done < 16 and b_done < 16, (a_done, b_done)
        # and hog still got everything eventually, in its own order
        hog = [it.i for it in drained if it.tenant == "hog"]
        assert hog == list(range(100))

    def test_weights_scale_share(self):
        """weight=3 drains 3 slots per round against weight=1."""
        weights = {"heavy": 3.0, "light": 1.0}
        q = FairQueue(weight_of=lambda t: weights.get(t, 1.0))
        for i in range(30):
            q.put(_Item("heavy", i))
            q.put(_Item("light", i))
        first = [q.get_nowait() for _ in range(24)]
        heavy = sum(1 for it in first if it.tenant == "heavy")
        light = len(first) - heavy
        assert heavy == pytest.approx(18, abs=2), (heavy, light)

    def test_fractional_weights_make_progress(self):
        """Weights < 1 accumulate deficit over rotations instead of
        wedging the queue."""
        q = FairQueue(weight_of=lambda t: 0.3)
        for i in range(9):
            q.put(_Item("a", i))
            q.put(_Item("b", i))
        drained = [q.get_nowait() for _ in range(18)]
        assert len(drained) == 18
        assert q.qsize() == 0

    def test_blocking_get_timeout_and_wakeup(self):
        q = FairQueue()
        with pytest.raises(stdlib_queue.Empty):
            q.get(timeout=0.05)
        got = []

        def consumer():
            got.append(q.get(timeout=5.0))

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        q.put(_Item("x", 1))
        t.join(timeout=5.0)
        assert got and got[0].i == 1

    def test_idle_tenant_banks_no_priority(self):
        """A tenant whose queue drained and re-fills later competes
        fresh — it does not accumulate deficit while idle."""
        q = FairQueue()
        q.put(_Item("a", 0))
        assert q.get_nowait().tenant == "a"
        for i in range(10):
            q.put(_Item("b", i))
        q.put(_Item("a", 1))
        drained = [q.get_nowait() for _ in range(11)]
        a_pos = next(i for i, it in enumerate(drained) if it.tenant == "a")
        assert a_pos <= 2  # interleaved promptly, not first-by-credit

    def test_depths_snapshot(self):
        q = FairQueue()
        q.put(_Item("a", 0))
        q.put(_Item("a", 1))
        q.put(_Item(None, 0))
        assert q.depths() == {"a": 2, "(default)": 1}


# ---------------------------------------------------------------------------
# quotas
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


class TestQuota:
    def test_token_bucket_refill_and_debt(self):
        clock = _Clock()
        b = TokenBucket(rate_per_s=2.0, burst=4.0, now_fn=clock)
        assert b.try_take(4.0) == 0.0  # burst available up front
        wait = b.try_take(1.0)
        assert wait == pytest.approx(0.5)  # 1 token / 2 per sec
        clock.t += 0.5
        assert b.try_take(1.0) == 0.0
        b.debit(3.0)  # post-paid: may go negative
        assert b.balance() < 0

    def test_qps_quota_admits_and_rejects(self):
        clock = _Clock()
        q = QuotaEnforcer(now_fn=clock)
        q.configure(Tenant(id="t", engine_id="e", qps=2.0))
        q.admit("t")
        q.admit("t")  # burst = max(qps, 1) = 2
        with pytest.raises(QuotaExceeded) as ei:
            q.admit("t")
        assert ei.value.resource == "qps"
        assert ei.value.retry_after_s > 0
        clock.t += 1.0  # refill 2 tokens
        q.admit("t")
        snap = q.snapshot("t")["t"]
        assert snap["admitted"] == 3
        assert snap["rejected"]["qps"] == 1

    def test_concurrency_quota_and_release(self):
        q = QuotaEnforcer(now_fn=_Clock())
        q.configure(Tenant(id="t", engine_id="e", max_concurrency=2))
        q.admit("t")
        q.admit("t")
        with pytest.raises(QuotaExceeded) as ei:
            q.admit("t")
        assert ei.value.resource == "concurrency"
        q.release("t")
        q.admit("t")  # slot freed

    def test_device_seconds_post_paid(self):
        clock = _Clock()
        q = QuotaEnforcer(now_fn=clock)
        q.configure(Tenant(id="t", engine_id="e", device_seconds_per_s=0.5))
        q.admit("t")  # bucket starts positive
        q.charge_device("t", 10.0)  # deep debt
        with pytest.raises(QuotaExceeded) as ei:
            q.admit("t")
        assert ei.value.resource == "device_seconds"
        clock.t += 30.0  # 15 device-seconds refilled > debt
        q.admit("t")
        assert q.snapshot("t")["t"]["device_seconds"] == pytest.approx(10.0)

    def test_unlimited_tenant_never_rejected(self):
        q = QuotaEnforcer(now_fn=_Clock())
        q.configure(Tenant(id="t", engine_id="e"))
        for _ in range(100):
            q.admit("t")

    def test_reconfigure_keeps_bucket_state(self):
        """A tenant refresh with unchanged rates must not refill a hog's
        spent bucket."""
        clock = _Clock()
        q = QuotaEnforcer(now_fn=clock)
        t = Tenant(id="t", engine_id="e", qps=1.0)
        q.configure(t)
        q.admit("t")
        q.configure(t)  # refresh tick
        with pytest.raises(QuotaExceeded):
            q.admit("t")


# ---------------------------------------------------------------------------
# model cache
# ---------------------------------------------------------------------------


class _FakeCacheTenant:
    def __init__(self, tid):
        self.id = tid


def _make_cache(capacity):
    loads = []
    cache = ModelCache(
        storage=None, capacity=capacity,
        build=lambda inst: f"runtime-{inst}",
    )
    cache.resolve_version = (  # type: ignore[method-assign]
        lambda tenant: (loads.append(tenant.id) or (f"v-{tenant.id}", tenant.id))
    )
    return cache, loads


class TestModelCache:
    def test_hit_miss_reload_accounting(self):
        cache, loads = _make_cache(capacity=1)
        t1, t2 = _FakeCacheTenant("t1"), _FakeCacheTenant("t2")
        e1 = cache.acquire(t1)
        cache.release(e1)
        cache.release(cache.acquire(t1))  # hit
        cache.release(cache.acquire(t2))  # miss → evicts t1 (capacity 1)
        cache.release(cache.acquire(t1))  # transparent reload
        s = cache.stats()
        assert s["hits"] == 1 and s["misses"] == 3
        assert s["reloads"] == 1 and s["evictions"] == 2
        assert loads == ["t1", "t2", "t1"]

    def test_lru_eviction_order(self):
        cache, _ = _make_cache(capacity=2)
        t = {k: _FakeCacheTenant(k) for k in ("a", "b", "c")}
        cache.release(cache.acquire(t["a"]))
        cache.release(cache.acquire(t["b"]))
        cache.release(cache.acquire(t["a"]))  # refresh a's recency
        cache.release(cache.acquire(t["c"]))  # evicts b (LRU), not a
        entries = cache.stats()["entries"]
        assert set(entries) == {"a", "c"}

    def test_inflight_runtime_never_evicted(self):
        cache, _ = _make_cache(capacity=1)
        t1, t2 = _FakeCacheTenant("t1"), _FakeCacheTenant("t2")
        lease = cache.acquire(t1)  # held: in-flight query
        cache.release(cache.acquire(t2))  # over capacity, t1 unevictable
        entries = cache.stats()["entries"]
        assert "t1" in entries  # survived, soft-over-capacity
        cache.release(lease)
        cache.release(cache.acquire(_FakeCacheTenant("t3")))
        assert "t1" not in cache.stats()["entries"]  # now evictable

    def test_pinned_runtime_never_evicted(self):
        cache, _ = _make_cache(capacity=1)
        t1, t2 = _FakeCacheTenant("t1"), _FakeCacheTenant("t2")
        cache.release(cache.acquire(t1))
        cache.pin("t1", on=True)
        cache.release(cache.acquire(t2))
        assert "t1" in cache.stats()["entries"]
        cache.pin("t1", on=False)
        cache.release(cache.acquire(_FakeCacheTenant("t3")))
        assert "t1" not in cache.stats()["entries"]

    def test_put_runtime_swaps_and_preserves_pin(self):
        cache, _ = _make_cache(capacity=2)
        t1 = _FakeCacheTenant("t1")
        cache.release(cache.acquire(t1))
        cache.pin("t1", on=True)
        cache.put_runtime("t1", "runtime-new", version_key="v-new")
        e = cache.stats()["entries"]["t1"]
        assert e["version"] == "v-new" and e["pinned"]
        assert cache.acquire(t1).runtime == "runtime-new"

    def test_load_failure_raises_model_load_error(self):
        cache = ModelCache(
            storage=None, capacity=1,
            build=lambda inst: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        cache.resolve_version = lambda tenant: ("v", "inst")  # type: ignore
        with pytest.raises(ModelLoadError):
            cache.acquire(_FakeCacheTenant("t1"))

    def test_sync_prefetches_on_version_drift(self):
        versions = {"t1": "v1"}
        cache = ModelCache(
            storage=None, capacity=2,
            build=lambda inst: f"runtime-{inst}",
        )
        cache.resolve_version = (  # type: ignore[method-assign]
            lambda tenant: (versions[tenant.id], versions[tenant.id])
        )
        t1 = _FakeCacheTenant("t1")
        cache.release(cache.acquire(t1))
        assert cache.sync([t1]) == 0  # no drift
        versions["t1"] = "v2"  # a promote landed
        assert cache.sync([t1]) == 1
        entry = cache.acquire(t1)
        assert entry.runtime == "runtime-v2" and entry.version_key == "v2"
        assert cache.stats()["misses"] == 1  # the swap was not a miss


# ---------------------------------------------------------------------------
# tenant records
# ---------------------------------------------------------------------------


class TestTenantStore:
    def test_crud_roundtrip(self, fresh_storage):
        store = TenantStore(fresh_storage)
        t = store.upsert(Tenant(
            id="acme", engine_id="rec", weight=2.0, qps=100.0,
            description="the acme corp",
        ))
        assert t.engine_variant == "rec"  # defaulted
        got = store.get("acme")
        assert got.weight == 2.0 and got.qps == 100.0
        assert store.get("nope") is None
        store.upsert(Tenant(id="zeta", engine_id="rec"))
        assert [x.id for x in store.list()] == ["acme", "zeta"]
        assert store.delete("zeta") > 0
        assert store.get("zeta") is None

    def test_set_quota_updates_only_quota_fields(self, fresh_storage):
        store = TenantStore(fresh_storage)
        store.upsert(Tenant(id="acme", engine_id="rec", qps=10.0))
        t = store.set_quota("acme", qps=50.0, weight=3.0)
        assert t.qps == 50.0 and t.weight == 3.0
        assert store.get("acme").qps == 50.0
        with pytest.raises(KeyError):
            store.set_quota("nope", qps=1.0)
        with pytest.raises(ValueError):
            store.set_quota("acme", bogus=1.0)

    def test_validation(self):
        with pytest.raises(ValueError):
            Tenant(id="bad/id", engine_id="rec")  # slash breaks routing
        with pytest.raises(ValueError):
            Tenant(id="ok", engine_id="")
        with pytest.raises(ValueError):
            Tenant(id="ok", engine_id="rec", weight=0)
        t = Tenant(id="ok", engine_id="rec", qps=0)
        assert t.qps is None  # 0 means unlimited


# ---------------------------------------------------------------------------
# bounded tenant metric labels (cardinality guard)
# ---------------------------------------------------------------------------


def test_tenant_metric_labels_bounded(fresh_storage):
    from predictionio_tpu.tenancy.mux import OVERFLOW_LABEL, TenantMux

    mux = TenantMux(
        fresh_storage, metrics=MetricsRegistry(), cache_capacity=2,
        label_max=3,
    )
    labels = {mux.label(f"tenant-{i}") for i in range(50)}
    # 3 real labels + the shared overflow — a 50-tenant churn cannot
    # mint 50 metric children
    assert len(labels) == 4 and OVERFLOW_LABEL in labels
    # known labels stay stable
    assert mux.label("tenant-0") == "tenant-0"


# ---------------------------------------------------------------------------
# deleted-tenant cleanup + warm_and_pin (review hardening)
# ---------------------------------------------------------------------------


def test_deleted_tenant_releases_quota_and_cache(fresh_storage):
    from predictionio_tpu.tenancy.mux import TenantMux

    mux = TenantMux(
        fresh_storage, metrics=MetricsRegistry(), cache_capacity=2,
        refresh_s=0.0, sync_s=3600.0,
    )
    # fake-load a runtime so the cache holds state for the tenant
    mux.cache._build_fn = lambda inst: "rt"
    mux.cache.resolve_version = lambda tenant: ("v1", "inst")
    store = TenantStore(fresh_storage)
    store.upsert(Tenant(id="acme", engine_id="rec", qps=5.0))
    mux.refresh(force=True)
    mux.admit("acme")
    mux.done("acme", mux.cache.acquire(store.get("acme")))
    assert mux.quota.snapshot("acme")
    assert "acme" in mux.cache.stats()["entries"]

    store.delete("acme")
    mux.refresh(force=True)
    # quota buckets, cache entry, and host state all released — a
    # same-id recreate must not inherit the dead tenant's debt
    assert mux.quota.snapshot("acme") == {}
    assert "acme" not in mux.cache.stats()["entries"]
    with pytest.raises(Exception):
        mux.admit("acme")  # UnknownTenant


def test_warm_and_pin_leaves_entry_pinned():
    cache, _ = _make_cache(capacity=1)
    t1, t2 = _FakeCacheTenant("t1"), _FakeCacheTenant("t2")
    cache.warm_and_pin(t1)
    e = cache.stats()["entries"]["t1"]
    assert e["pinned"] and e["refs"] == 0
    # pinned with zero refs: survives capacity pressure immediately —
    # the window between warm and a later pin() call is gone
    cache.release(cache.acquire(t2))
    assert "t1" in cache.stats()["entries"]


def test_resume_latch_survives_failed_first_refresh(fresh_storage):
    """A storage blip during the first sync pass must not consume the
    one-shot rollout re-adoption: the latch is only set after a clean
    pass over a SUCCESSFUL refresh, and a raising per-tenant resume
    keeps it open for the next pass."""
    from predictionio_tpu.tenancy.mux import TenantMux

    mux = TenantMux(
        fresh_storage, metrics=MetricsRegistry(), cache_capacity=2,
        refresh_s=0.0, sync_s=3600.0,
    )
    store = TenantStore(fresh_storage)
    store.upsert(Tenant(id="acme", engine_id="rec"))

    def _down():
        raise RuntimeError("storage down")

    orig_list = mux.store.list
    mux.store.list = _down
    mux.sync()
    assert not mux._resumed, "failed refresh consumed the re-adoption"
    mux.store.list = orig_list

    calls: list = []

    def _boom(t):
        calls.append(t.id)
        raise RuntimeError("transient resume failure")

    mux._resume_rollout = _boom
    mux.sync()
    assert calls == ["acme"]
    assert not mux._resumed, "failed per-tenant resume latched anyway"

    mux._resume_rollout = lambda t: calls.append(f"ok:{t.id}")
    mux.sync()
    assert mux._resumed and calls[-1] == "ok:acme"


def test_resume_gives_up_after_repeated_failures(fresh_storage):
    """A PERMANENTLY unservable baseline (blob GC'd, instance purged)
    must not keep the resume pass — record folds plus a failing model
    build — churning every sync for the life of the process: after 3
    consecutive failures the tenant is skipped and the latch sets."""
    from predictionio_tpu.tenancy.mux import TenantMux

    mux = TenantMux(
        fresh_storage, metrics=MetricsRegistry(), cache_capacity=2,
        refresh_s=0.0, sync_s=3600.0,
    )
    store = TenantStore(fresh_storage)
    store.upsert(Tenant(id="acme", engine_id="rec"))
    calls: list = []

    def _boom(t):
        calls.append(t.id)
        raise RuntimeError("baseline unservable")

    mux._resume_rollout = _boom
    for _ in range(5):
        mux.sync()
    assert len(calls) == 3, "give-up cap did not bound the retries"
    assert mux._resumed, "latch never set after the give-up"


def test_stop_freezes_cache_gauges_and_releases_mux(fresh_storage):
    """stop() must replace the registry's gauge closures (they close
    over the mux) with constants: otherwise the process-global registry
    keeps the dead mux — and every resident runtime in its cache —
    alive for the rest of the process."""
    import gc
    import weakref

    from predictionio_tpu.tenancy.mux import TenantMux

    reg = MetricsRegistry()
    mux = TenantMux(
        fresh_storage, metrics=reg, cache_capacity=2,
        refresh_s=0.0, sync_s=3600.0,
    )
    mux.cache._build_fn = lambda inst: "rt"
    mux.cache.resolve_version = lambda tenant: ("v1", "inst")
    store = TenantStore(fresh_storage)
    store.upsert(Tenant(id="acme", engine_id="rec"))
    mux.refresh(force=True)
    mux.cache.release(mux.cache.acquire(store.get("acme")))
    mux.stop()

    ref = weakref.ref(mux.cache)
    del mux
    gc.collect()
    assert ref() is None, (
        "registry gauge closure kept the dead mux's cache alive"
    )
    # /metrics still renders the frozen final values
    assert "tenant_cache_resident 1" in reg.render()
