"""REAL two-process multi-host training (VERDICT r2 #4).

Launches two OS processes that `jax.distributed.initialize` against a
local coordinator on the CPU backend (4 virtual devices each → one
8-device mesh spanning both processes), stage per-process row slices
through parallel/loader.py, and train ALS through the public als.train
API. The resulting factors must match a single-process run over the same
8-device mesh — same GSPMD program, different process topology.

Reference analogue: executor-partitioned event reads feeding MLlib ALS
(HBPEvents.scala:84-90). Until round 3 this seam had only ever executed
in one process; this test is the proof it is a capability, not a design.
"""

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

N_USERS, N_ITEMS, N_EDGES, RANK, ITERS = 64, 32, 2000, 8, 3


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _make_data():
    rng = np.random.RandomState(7)
    rows = rng.randint(0, N_USERS, N_EDGES).astype(np.int32)
    cols = rng.randint(0, N_ITEMS, N_EDGES).astype(np.int32)
    vals = rng.randint(1, 6, N_EDGES).astype(np.float32)
    return rows, cols, vals


_CHILD = textwrap.dedent(
    """
    import os, sys
    from predictionio_tpu.utils.cpuonly import force_cpu_platform
    force_cpu_platform(n_devices=4)
    import jax

    coordinator, pid, out_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    import numpy as np
    from predictionio_tpu.models import als
    from predictionio_tpu.parallel.mesh import make_mesh

    sys.path.insert(0, os.path.join("{repo}", "tests"))
    from test_multihost import _make_data, N_USERS, N_ITEMS, RANK, ITERS

    rows, cols, vals = _make_data()
    mesh = make_mesh()  # all 8 devices, spanning both processes
    m = als.train(
        rows, cols, vals, N_USERS, N_ITEMS,
        als.ALSParams(rank=RANK, iterations=ITERS, implicit_prefs=True),
        mesh=mesh,
    )
    if pid == 0:
        np.savez(out_path, uf=m.user_factors, itf=m.item_factors)
    print("CHILD-OK", pid)
    """
)


def test_two_process_training_matches_single_process(tmp_path):
    port = _free_port()
    out_path = tmp_path / "factors.npz"
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-c", _CHILD.replace("{repo}", str(REPO)),
                f"127.0.0.1:{port}", str(pid), str(out_path),
            ],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"child failed:\n{out}\n{err[-3000:]}"
        assert "CHILD-OK" in out

    with np.load(out_path) as z:
        uf2, itf2 = z["uf"], z["itf"]

    # single-process reference over the same 8-device mesh (pytest runs
    # under the conftest CPU forcing with 8 virtual devices)
    from predictionio_tpu.models import als
    from predictionio_tpu.parallel.mesh import make_mesh

    rows, cols, vals = _make_data()
    ref = als.train(
        rows, cols, vals, N_USERS, N_ITEMS,
        als.ALSParams(rank=RANK, iterations=ITERS, implicit_prefs=True),
        mesh=make_mesh(),
    )
    np.testing.assert_allclose(uf2, ref.user_factors, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(itf2, ref.item_factors, rtol=1e-4, atol=1e-5)
