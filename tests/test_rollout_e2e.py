"""Canary rollout end-to-end (ISSUE 5 acceptance): live traffic through
a 10%-ish canary on the real recommendation engine, a deliberately
faulted candidate (variant-scoped PR-4 fault points), automatic
rollback with zero dropped queries, and a zero-drop promote hot-swap."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.deploy.registry import ModelRegistry
from predictionio_tpu.resilience import faults
from predictionio_tpu.workflow.core import run_train
from predictionio_tpu.workflow.server import (
    QueryServer,
    QueryServerConfig,
    build_runtime,
)

VARIANT = {
    "id": "roll",
    "engineFactory": "predictionio_tpu.engines.recommendation.RecommendationEngine",
    "datasource": {"params": {"app_name": "rollapp"}},
    "algorithms": [
        {"name": "als", "params": {"rank": 8, "num_iterations": 4}}
    ],
}


def _seed(storage, n_users=8, seed=0):
    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(id=0, name="rollapp"))
    events = storage.get_events()
    events.init_app(app_id)
    rng = np.random.RandomState(seed)
    batch = []
    for u in range(n_users):
        for _ in range(20):
            i = rng.randint(0, 5) + (u % 2) * 5
            batch.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties={"rating": 5.0},
            ))
    events.insert_batch(batch, app_id)
    return app_id


def _post(port, path, body, timeout=20):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "null")


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=20
    ) as r:
        return r.status, json.loads(r.read().decode())


@pytest.fixture()
def served(fresh_storage):
    """A live query server (model A) plus a registered `trained` model
    version (model B) ready to canary."""
    _seed(fresh_storage)
    inst_a = run_train(fresh_storage, VARIANT)  # model A → live
    inst_b = run_train(fresh_storage, VARIANT)  # model B → the candidate
    version_b = ModelRegistry(fresh_storage).register(inst_b)
    runtime = build_runtime(fresh_storage, inst_a)
    srv = QueryServer(
        fresh_storage, runtime,
        QueryServerConfig(ip="127.0.0.1", port=0, batch_window_ms=1.0),
    )
    port = srv.start()
    yield fresh_storage, srv, port, version_b
    faults.clear()
    srv.stop()


class Hammer:
    """Closed-loop client pool recording every (status, body) — the
    zero-dropped-queries ledger: every submitted query must come back as
    an HTTP response, never a connection error or a stopped-server 500."""

    def __init__(self, port, n_clients=8):
        self.port = port
        self.n_clients = n_clients
        self.results: list[tuple[int, dict]] = []
        self.transport_errors: list[str] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def _run(self, cid):
        i = 0
        while not self._stop.is_set():
            i += 1
            try:
                # vary user AND num: sticky routing hashes the raw body,
                # so the body space must be wide enough for a 10%
                # fraction to catch a share of it
                status, body = _post(
                    self.port, "/queries.json",
                    {
                        "user": f"u{(cid * 131 + i) % 8}",
                        "num": (cid * 17 + i) % 50 + 1,
                    },
                )
                with self._lock:
                    self.results.append((status, body))
            except Exception as e:  # dropped: no HTTP response at all
                with self._lock:
                    self.transport_errors.append(repr(e))

    def __enter__(self):
        for c in range(self.n_clients):
            t = threading.Thread(target=self._run, args=(c,), daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def __exit__(self, *exc):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)

    def snapshot(self):
        with self._lock:
            return list(self.results), list(self.transport_errors)


def _wait_for(predicate, timeout=60.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


class TestCanaryE2E:
    def test_faulted_candidate_rolls_back_zero_dropped(self, served):
        """The headline acceptance: 10% canary, candidate flipped bad
        via the PR-4 fault registry scoped to the candidate variant
        (dispatch.device@candidate), verdict loop rolls back on the
        error-rate delta, live keeps serving, zero dropped queries
        through canary start AND the rollback swap."""
        storage, srv, port, version_b = served
        # flip the candidate bad BEFORE any canary traffic flows
        faults.install(faults.FaultSpec(
            "dispatch.device", "error", 1.0, scope="candidate"
        ))
        with Hammer(port) as hammer:
            time.sleep(0.3)  # live-only traffic flows across canary start
            status, body = _post(port, "/rollout/start", {
                "version": version_b.id,
                "fraction": 0.1,
                "interval_s": 0.2,
                "window_s": 20.0,
                "min_requests": 5,
                "bake_s": 120.0,
                "max_error_delta": 0.2,
            })
            assert status == 200, body
            assert body["state"] == "canary"
            assert (
                ModelRegistry(storage).get(version_b.id).status == "canary"
            )
            _wait_for(
                lambda: _get(port, "/rollout/status")[1]["state"]
                == "rolled_back",
                timeout=90, what="automatic rollback",
            )
            time.sleep(0.5)  # post-rollback traffic across the swap
        results, transport_errors = hammer.snapshot()
        st, rollout = _get(port, "/rollout/status")
        assert rollout["state"] == "rolled_back"
        assert "error-rate" in rollout["reason"]
        assert (
            ModelRegistry(storage).get(version_b.id).status == "rolled_back"
        )
        assert ModelRegistry(storage).get(version_b.id).reason

        # zero dropped: every query got an HTTP response
        assert transport_errors == []
        assert len(results) > 50
        # the only failures are the injected candidate faults — live
        # traffic (and all traffic after rollback) served 200
        bad = [(s, b) for s, b in results if s != 200]
        assert all(
            s == 500 and "injected" in (b or {}).get("message", "")
            for s, b in bad
        ), bad[:3]
        assert any(s == 200 for s, _ in results)
        # candidate routing really happened (the verdict had evidence)
        assert rollout["candidate"]["count"] >= 5
        assert rollout["candidate"]["error_rate"] > 0.2

        # the fault spec is still installed and scoped: post-rollback
        # serving is clean because no candidate exists anymore
        tail_status, _ = _post(
            port, "/queries.json", {"user": "u1", "num": 3}
        )
        assert tail_status == 200

    def test_canary_start_failure_leaves_live_serving(self, served):
        """model.load fault at canary start: build_runtime fails, the
        rollout never attaches, live traffic is untouched."""
        storage, srv, port, version_b = served
        faults.install(faults.FaultSpec("model.load", "error", 1.0))
        with Hammer(port, n_clients=4) as hammer:
            time.sleep(0.2)
            status, body = _post(port, "/rollout/start", {
                "version": version_b.id, "fraction": 0.5,
            })
            assert status == 400
            assert "canary start failed" in body["message"]
            time.sleep(0.3)
        results, transport_errors = hammer.snapshot()
        assert transport_errors == []
        assert results and all(s == 200 for s, _ in results)
        assert _get(port, "/rollout/status")[1]["state"] == "none"
        assert srv.candidate is None
        # the version is NOT stuck in canary
        assert ModelRegistry(storage).get(version_b.id).status == "trained"

    def test_healthy_canary_promotes_with_zero_drop_hot_swap(self, served):
        """Healthy candidate bakes and auto-promotes: atomic hot-swap
        under live traffic, zero dropped queries, registry flips to
        live and the server serves the candidate's instance."""
        storage, srv, port, version_b = served
        old_instance = srv.runtime.instance.id
        with Hammer(port) as hammer:
            status, body = _post(port, "/rollout/start", {
                "version": version_b.id,
                "fraction": 0.4,
                "interval_s": 0.2,
                "window_s": 20.0,
                "min_requests": 5,
                "bake_s": 1.5,
            })
            assert status == 200, body
            _wait_for(
                lambda: _get(port, "/rollout/status")[1]["state"]
                == "promoted",
                timeout=90, what="automatic promote",
            )
            time.sleep(0.5)  # traffic across the hot-swap
        results, transport_errors = hammer.snapshot()
        assert transport_errors == []
        assert results and all(s == 200 for s, _ in results), [
            r for r in results if r[0] != 200
        ][:3]
        assert srv.runtime.instance.id == version_b.instance_id
        assert srv.runtime.instance.id != old_instance
        assert srv.candidate is None
        reg = ModelRegistry(storage)
        assert reg.get(version_b.id).status == "live"
        # per-variant metrics landed under the variant label
        hist = srv.metrics.histogram(
            "variant_serve_seconds", labelnames=("variant",)
        )
        assert hist.count_of(variant="candidate") > 0
        assert hist.count_of(variant="live") > 0

    def test_shadow_mode_mirrors_and_promotes_on_agreement(self, served):
        """Shadow rollout: candidate answers mirrored copies of live
        traffic off the response path (its own extract/supplement run),
        live serves 100% of real traffic, and identical models agree →
        auto-promote on the bake."""
        storage, srv, port, version_b = served
        with Hammer(port) as hammer:
            status, body = _post(port, "/rollout/start", {
                "version": version_b.id,
                "fraction": 0.5,
                "interval_s": 0.2,
                "window_s": 20.0,
                "min_requests": 5,
                "bake_s": 1.5,
                "shadow": True,
            })
            assert status == 200, body
            _wait_for(
                lambda: _get(port, "/rollout/status")[1]["state"]
                == "promoted",
                timeout=90, what="shadow promote",
            )
        results, transport_errors = hammer.snapshot()
        assert transport_errors == []
        assert results and all(s == 200 for s, _ in results)
        st, rollout = _get(port, "/rollout/status")
        cand = rollout["candidate"]
        assert cand.get("shadow_count", 0) >= 5
        assert cand.get("agreement", 0) > 0.9  # same blob → same answers
        assert ModelRegistry(storage).get(version_b.id).status == "live"

    def test_operator_abort_detaches_candidate(self, served):
        storage, srv, port, version_b = served
        status, body = _post(port, "/rollout/start", {
            "version": version_b.id, "fraction": 0.2, "bake_s": 300.0,
        })
        assert status == 200, body
        # double start conflicts while one is active
        status, body = _post(port, "/rollout/start", {
            "version": version_b.id,
        })
        assert status == 409
        status, body = _post(
            port, "/rollout/abort", {"reason": "bad vibes"}
        )
        assert status == 200 and body["state"] == "aborted"
        assert srv.candidate is None
        assert (
            ModelRegistry(storage).get(version_b.id).status == "rolled_back"
        )
        # nothing to abort now
        status, _ = _post(port, "/rollout/abort", {})
        assert status == 409
