"""Online learning (ISSUE 9) units: insert revisions across backends,
the fold-in solve, drift guard, durable cursor resume, WAL batch replay,
job-id version adoption, alert notification sinks, and the tenant-cache
conditional swap."""

import json
import os
import threading
import time

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.memory import MemoryEventStore
from predictionio_tpu.data.storage.sqlite import SqliteEventStore


def _ev(u="u1", i="i1", rating=5.0, name="rate"):
    return Event(
        event=name, entity_type="user", entity_id=u,
        target_entity_type="item", target_entity_id=i,
        properties={"rating": rating},
    )


# ---------------------------------------------------------------------------
# Insert revisions
# ---------------------------------------------------------------------------


def _parquet_store(tmp):
    from predictionio_tpu.data.storage.parquetfs import ParquetFSEventStore

    return ParquetFSEventStore({"PATH": str(tmp / "pq")})


def _segment_store(tmp):
    from predictionio_tpu.data.storage.segmentfs import SegmentFSEventStore

    return SegmentFSEventStore(
        {"PATH": str(tmp / "seg"), "SEAL_INTERVAL_S": "3600"}
    )


def _postgres_store(tmp):
    import fake_pg
    from predictionio_tpu.data.storage.postgres import (
        PostgresEventStore,
        _PGClient,
    )

    return PostgresEventStore(client=_PGClient(conn=fake_pg.connect()))


class TestInsertRevisions:
    @pytest.mark.parametrize("make", [
        lambda tmp: MemoryEventStore(),
        lambda tmp: SqliteEventStore({"PATH": str(tmp / "r.db")}),
        _parquet_store,
        _postgres_store,
        _segment_store,
    ], ids=["memory", "sqlite", "parquetfs", "postgres", "segmentfs"])
    def test_monotonic_and_tailable(self, tmp_path, make):
        store = make(tmp_path)
        store.init_app(1)
        for k in range(4):
            store.insert(_ev(u=f"u{k}"), 1)
        store.insert_batch([_ev(u="u9"), _ev(u="u9")], 1)
        evs = store.find_since(1, 0)
        assert [e.revision for e in evs] == [1, 2, 3, 4, 5, 6]
        assert store.latest_revision(1) == 6
        # strict tail semantics: > cursor, revision-ordered, limited
        assert [e.revision for e in store.find_since(1, 4, limit=1)] == [5]
        assert store.find_since(1, 6) == []
        assert store.find_since(1, 0, limit=0) == []  # 0 means empty
        # shard filter partitions the stream disjointly and completely
        s0 = store.find_since(1, 0, shard=(0, 2))
        s1 = store.find_since(1, 0, shard=(1, 2))
        assert len(s0) + len(s1) == 6
        assert not ({e.event_id for e in s0} & {e.event_id for e in s1})

    def test_sqlite_sequence_survives_restart(self, tmp_path):
        path = str(tmp_path / "resume.db")
        s1 = SqliteEventStore({"PATH": path})
        s1.init_app(2)
        s1.insert(_ev(), 2)
        s1.insert(_ev(), 2)
        s1.close()
        s2 = SqliteEventStore({"PATH": path})
        assert s2.latest_revision(2) == 2
        s2.insert(_ev(), 2)
        assert [e.revision for e in s2.find_since(2, 2)] == [3]

    def test_namespaces_are_independent(self):
        store = MemoryEventStore()
        store.insert(_ev(), 1)
        store.insert(_ev(), 7)
        store.insert(_ev(), 7)
        assert store.latest_revision(1) == 1
        assert store.latest_revision(7) == 2

    def test_memory_cursor_excludes_astral_event_ids(self):
        """The bisect cutoff must compare by revision alone: a consumed
        event whose client-supplied id contains a code point above
        U+FFFF must not be re-delivered forever."""
        store = MemoryEventStore()
        store.init_app(1)
        store.insert(_ev(u="a").with_id("evt-\U0001F600"), 1)
        evs = store.find_since(1, 0)
        assert len(evs) == 1
        # the cursor at this event's revision sees nothing new
        assert store.find_since(1, evs[0].revision) == []

    def test_memory_rev_log_prunes_stale_rows(self):
        """Delete-heavy namespaces (the lifecycle append+compact cycle)
        must not grow the revision log forever."""
        store = MemoryEventStore()
        store.init_app(1)
        keep = store.insert(_ev(u="keeper"), 1)
        for k in range(200):
            eid = store.insert(_ev(u=f"churn{k}"), 1)
            store.delete(eid, 1)
        key = (1, None)
        assert len(store._rev_log[key]) < 150  # pruned, not ~201
        # the survivor still tails correctly after rebuilds
        evs = store.find_since(1, 0)
        assert [e.event_id for e in evs] == [keep]

    def test_revision_survives_wire_roundtrip(self):
        from predictionio_tpu.data.storage import wire

        e = _ev().with_revision(42)
        assert wire.decode(wire.encode(e)).revision == 42
        # and the public JSON form carries it only when present
        assert "revision" not in _ev().to_json_dict()
        assert _ev().with_revision(3).to_json_dict()["revision"] == 3

    def test_remote_and_sharded_monotonicity(self):
        """ISSUE 9 satellite: revisions stay per-stream monotonic across
        remote daemons and a sharded composite; the per-shard streams
        are disjoint and complete."""
        from predictionio_tpu.data.api.storage_server import StorageServer
        from predictionio_tpu.data.storage.registry import (
            SourceConfig,
            Storage,
            StorageConfig,
        )
        from predictionio_tpu.data.storage.remote import RemoteEventStore
        from predictionio_tpu.data.storage.sharded import ShardedEventStore

        daemons, clients = [], []
        try:
            for _ in range(2):
                st = Storage(StorageConfig(
                    sources={"M": SourceConfig("M", "memory", {})},
                    repositories={
                        "METADATA": "M", "EVENTDATA": "M", "MODELDATA": "M",
                    },
                ))
                d = StorageServer(st, port=0)
                d.start()
                daemons.append(d)
                clients.append(RemoteEventStore(
                    {"HOST": "127.0.0.1", "PORT": str(d.port)}
                ))
            sharded = ShardedEventStore(stores=clients)
            ids = [
                sharded.insert(_ev(u=f"user-{k}", i=f"i{k % 5}"), 3)
                for k in range(20)
            ]
            assert len(set(ids)) == 20
            streams = sharded.revision_streams()
            assert len(streams) == 2
            seen: set[str] = set()
            for _key, stream_store, shard in streams:
                evs = stream_store.find_since(3, 0, shard=shard)
                revs = [e.revision for e in evs]
                assert revs == sorted(revs)
                assert len(revs) == len(set(revs)), "revisions not unique"
                # paging from a mid-stream cursor continues exactly
                if len(revs) >= 2:
                    tail = stream_store.find_since(3, revs[0], shard=shard)
                    assert [e.revision for e in tail] == revs[1:]
                seen |= {e.event_id for e in evs}
            assert len(seen) == 20, "shard streams lost or duplicated events"
            # the composite refuses the ambiguous single-sequence read
            from predictionio_tpu.data.storage.base import StorageError

            with pytest.raises(StorageError):
                sharded.find_since(3, 0)
        finally:
            for d in daemons:
                d.shutdown()


# ---------------------------------------------------------------------------
# Fold-in solve + warm start
# ---------------------------------------------------------------------------


class TestFoldInSolve:
    def test_implicit_matches_dense_solve(self):
        from predictionio_tpu.models import als

        rng = np.random.RandomState(0)
        k = 6
        itf = rng.standard_normal((30, k)).astype(np.float32)
        params = als.ALSParams(
            rank=k, implicit_prefs=True, cg_iterations=8, lambda_=0.05,
            alpha=2.0,
        )
        edges = [[(1, 5.0), (3, 2.0), (9, 1.0)], [(7, 1.0)]]
        out = als.fold_in_rows(itf, edges, params)
        for r, row in enumerate(edges):
            a = itf.T @ itf + params.lambda_ * np.eye(k)
            b = np.zeros(k)
            for j, v in row:
                c = 1.0 + params.alpha * abs(v)
                a += (c - 1.0) * np.outer(itf[j], itf[j])
                b += c * itf[j]
            ref = np.linalg.solve(a, b)
            np.testing.assert_allclose(out[r], ref, atol=1e-4)

    def test_explicit_matches_dense_solve(self):
        from predictionio_tpu.models import als

        rng = np.random.RandomState(1)
        k = 4
        itf = rng.standard_normal((12, k)).astype(np.float32)
        params = als.ALSParams(
            rank=k, implicit_prefs=False, cg_iterations=8, lambda_=0.1,
        )
        edges = [[(0, 4.0), (5, 2.0)]]
        out = als.fold_in_rows(itf, edges, params)
        a = (
            np.outer(itf[0], itf[0]) + np.outer(itf[5], itf[5])
            + params.lambda_ * 2 * np.eye(k)
        )
        b = 4.0 * itf[0] + 2.0 * itf[5]
        np.testing.assert_allclose(
            out[0], np.linalg.solve(a, b), atol=1e-4
        )

    def test_empty_edges_solve_to_zero(self):
        from predictionio_tpu.models import als

        itf = np.ones((4, 3), np.float32)
        params = als.ALSParams(rank=3)
        out = als.fold_in_rows(itf, [[]], params)
        np.testing.assert_array_equal(out, np.zeros((1, 3), np.float32))
        assert als.fold_in_rows(itf, [], params).shape == (0, 3)

    def test_warm_start_maps_surviving_ids(self):
        from predictionio_tpu.data.store.bimap import BiMap
        from predictionio_tpu.models import als

        params = als.ALSParams(rank=3, seed=5)
        parent = als.ALSFactors(
            user_factors=np.arange(6, dtype=np.float32).reshape(2, 3),
            item_factors=np.arange(9, dtype=np.float32).reshape(3, 3),
            user_vocab=BiMap({"a": 0, "b": 1}),
            item_vocab=BiMap({"x": 0, "y": 1, "z": 2}),
            params=params,
        )
        # new vocab: "b" moved rows, "a" dropped, "c" brand new
        uf0, itf0 = als.warm_start_factors(
            parent, BiMap({"b": 0, "c": 1}), BiMap({"z": 0, "x": 1}),
            params,
        )
        np.testing.assert_array_equal(uf0[0], parent.user_factors[1])
        assert not np.array_equal(uf0[1], parent.user_factors[0])
        np.testing.assert_array_equal(itf0[0], parent.item_factors[2])
        np.testing.assert_array_equal(itf0[1], parent.item_factors[0])


class TestDriftGuard:
    def _factors(self, seed=0, scale=1.0):
        from predictionio_tpu.data.store.bimap import BiMap
        from predictionio_tpu.models import als

        rng = np.random.RandomState(seed)
        return als.ALSFactors(
            user_factors=(
                rng.standard_normal((40, 4)).astype(np.float32) * scale
            ),
            item_factors=rng.standard_normal((60, 4)).astype(np.float32),
            user_vocab=BiMap({}),
            item_vocab=BiMap({}),
        )

    def test_identical_models_have_zero_drift(self):
        from predictionio_tpu.online import score_drift

        f = self._factors()
        assert score_drift(f, f) == pytest.approx(0.0)

    def test_scrambled_model_breaches(self):
        from predictionio_tpu.online import DriftGuard

        base = self._factors(0)
        bad = self._factors(0, scale=40.0)
        guard = DriftGuard(threshold=1.0)
        guard.rebase(base)
        assert guard.check(base) < 0.05
        assert guard.breached(bad)
        assert guard.last_drift > 1.0

    def test_growth_only_change_is_small(self):
        """Appending new rows must not read as drift: the statistic
        samples the SHARED row range only."""
        import dataclasses

        from predictionio_tpu.online import score_drift

        base = self._factors(3)
        grown = dataclasses.replace(
            base,
            user_factors=np.concatenate([
                base.user_factors,
                np.ones((5, 4), np.float32) * 9.0,
            ]),
        )
        assert score_drift(base, grown) == pytest.approx(0.0)


# ---------------------------------------------------------------------------
# Durable cursor + consumer mechanics (storage-only; no engine/jax)
# ---------------------------------------------------------------------------


class _StubHost:
    scope = "server"

    def __init__(self):
        # a runtime with no models: events consume (cursor advances)
        # without folding — the storage-only unit-test posture
        self.runtime = object()

    def current(self):
        return self.runtime

    def swap(self, old, new):
        if self.runtime is old:
            self.runtime = new
            return True
        return False


def _mem_storage():
    from predictionio_tpu.data.storage.registry import (
        SourceConfig,
        Storage,
        StorageConfig,
    )

    return Storage(StorageConfig(
        sources={"M": SourceConfig("M", "memory", {})},
        repositories={
            "METADATA": "M", "EVENTDATA": "M", "MODELDATA": "M",
        },
    ))


class TestCursorResume:
    def test_cursor_and_counters_resume_exactly(self):
        from predictionio_tpu.online import (
            OnlineConsumer,
            OnlineConsumerConfig,
        )

        storage = _mem_storage()
        store = storage.get_events()
        store.insert_batch([_ev(u=f"u{k}") for k in range(5)], 1)
        c1 = OnlineConsumer(
            storage, _StubHost(), 1, OnlineConsumerConfig(tick_s=9),
        )
        out = c1.tick()
        # no runtime → events consumed without folding, cursor advanced
        assert out["consumed"] == 5 and out["folded"] == 0
        assert c1.cursor == {"0": 5}
        c2 = OnlineConsumer(
            storage, _StubHost(), 1, OnlineConsumerConfig(tick_s=9),
        )
        assert c2.cursor == {"0": 5}
        assert c2.counters["events_consumed"] == 5
        assert c2.tick() == {"idle": "no new events"}

    def test_replica_scoped_cursor_migrates_once(self):
        """ISSUE 19 satellite: a consumer given a per-replica cursor
        name adopts the legacy un-scoped record exactly once — no
        re-consumption on the rename, and later movement of the legacy
        record never leaks into the scoped one."""
        from predictionio_tpu.online import (
            OnlineConsumer,
            OnlineConsumerConfig,
        )

        storage = _mem_storage()
        store = storage.get_events()
        store.insert_batch([_ev(u=f"u{k}") for k in range(5)], 1)
        legacy = OnlineConsumer(
            storage, _StubHost(), 1, OnlineConsumerConfig(tick_s=9),
        )
        legacy.tick()
        assert legacy.cursor == {"0": 5}
        scoped_cfg = OnlineConsumerConfig(
            tick_s=9, name="online/1/replica-a",
            migrate_from=legacy.cursor_id,
        )
        scoped = OnlineConsumer(storage, _StubHost(), 1, scoped_cfg)
        # adopted, not restarted from zero
        assert scoped.cursor == {"0": 5}
        assert scoped.counters["events_consumed"] == 5
        assert scoped.migrated_from == legacy.cursor_id
        assert scoped.tick() == {"idle": "no new events"}
        # one-shot: the scoped record exists now, so a restart reads IT
        # even when the legacy record has moved on meanwhile
        store.insert_batch([_ev(u="x1"), _ev(u="x2")], 1)
        legacy.tick()  # legacy cursor moves to 7 independently
        scoped2 = OnlineConsumer(storage, _StubHost(), 1, scoped_cfg)
        assert scoped2.cursor == {"0": 5}  # own record, not legacy's 7
        assert scoped2.migrated_from == legacy.cursor_id
        assert scoped2.tick()["consumed"] == 2
        assert scoped2.status()["migrated_from"] == legacy.cursor_id

    def test_from_latest_skips_history(self):
        from predictionio_tpu.online import (
            OnlineConsumer,
            OnlineConsumerConfig,
        )

        storage = _mem_storage()
        storage.get_events().insert_batch(
            [_ev(u=f"u{k}") for k in range(4)], 1
        )
        c = OnlineConsumer(
            storage, _StubHost(), 1,
            OnlineConsumerConfig(tick_s=9, from_latest=True),
        )
        assert c.cursor == {"0": 4}
        assert c.tick() == {"idle": "no new events"}

    def test_crash_before_persist_replays_exactly_once(self):
        """The exactly-once accounting window: a crash between apply and
        the cursor persist replays the tick; counters count each event
        once because they ride the SAME atomic record append."""
        from predictionio_tpu.online import (
            OnlineConsumer,
            OnlineConsumerConfig,
        )

        storage = _mem_storage()
        storage.get_events().insert_batch(
            [_ev(u=f"u{k}") for k in range(3)], 1
        )
        c1 = OnlineConsumer(
            storage, _StubHost(), 1, OnlineConsumerConfig(tick_s=9),
        )
        c1._crash_after_apply = True
        with pytest.raises(RuntimeError):
            c1.tick()
        assert c1.counters["events_consumed"] == 0  # nothing persisted
        # "restart": a fresh consumer resumes from the durable cursor
        c2 = OnlineConsumer(
            storage, _StubHost(), 1, OnlineConsumerConfig(tick_s=9),
        )
        assert c2.cursor == {}
        out = c2.tick()
        assert out["consumed"] == 3
        assert c2.counters["events_consumed"] == 3
        # replaying again finds nothing: no double-apply
        assert c2.tick() == {"idle": "no new events"}
        assert c2.counters["events_consumed"] == 3

    def test_cursor_record_compacts(self):
        from predictionio_tpu.deploy.registry import LifecycleRecordStore
        from predictionio_tpu.online import (
            CURSOR_ENTITY,
            OnlineConsumer,
            OnlineConsumerConfig,
        )

        storage = _mem_storage()
        store = storage.get_events()
        c = OnlineConsumer(
            storage, _StubHost(), 1,
            OnlineConsumerConfig(tick_s=9, compact_every=4),
        )
        for k in range(8):
            store.insert(_ev(u=f"u{k}"), 1)
            c.tick()
        records = LifecycleRecordStore(storage)
        from predictionio_tpu.data.storage.base import EventQuery
        from predictionio_tpu.deploy.registry import LIFECYCLE_APP_ID

        n_events = len(list(storage.get_events().find(EventQuery(
            app_id=LIFECYCLE_APP_ID, entity_type=CURSOR_ENTITY,
        ))))
        assert n_events <= 5  # 8 appends compacted twice
        rec = records.fold(CURSOR_ENTITY, c.cursor_id)[c.cursor_id]
        assert rec["events_consumed"] == 8

    def test_pause_blocks_tick_and_resume_clears(self):
        from predictionio_tpu.online import (
            OnlineConsumer,
            OnlineConsumerConfig,
        )

        storage = _mem_storage()
        storage.get_events().insert(_ev(), 1)
        c = OnlineConsumer(
            storage, _StubHost(), 1, OnlineConsumerConfig(tick_s=9),
        )
        c.pause("test pause")
        assert c.tick() == {"paused": "test pause"}
        assert c.counters["events_consumed"] == 0
        c.resume()
        assert c.tick()["consumed"] == 1


# ---------------------------------------------------------------------------
# WAL batch replay (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


class TestWalBatchReplay:
    def test_batched_replay_groups_by_namespace_in_order(self, tmp_path):
        from predictionio_tpu.resilience.wal import EventWAL

        wal = EventWAL(str(tmp_path / "wal"))
        for k in range(5):
            wal.append(_ev(u=f"a{k}"), 1, None)
        wal.append(_ev(u="b0"), 2, None)
        wal.append(_ev(u="b1"), 2, 7)
        calls = []

        def batch_fn(events, app_id, channel_id, req_id):
            calls.append((
                [e.entity_id for e in events], app_id, channel_id, req_id,
            ))

        n, err = wal.replay_batched(batch_fn, max_batch=3)
        assert err is None and n == 7
        assert [c[0] for c in calls] == [
            ["a0", "a1", "a2"], ["a3", "a4"], ["b0"], ["b1"],
        ]
        assert [c[1:3] for c in calls] == [
            (1, None), (1, None), (2, None), (2, 7),
        ]
        assert wal.pending() == 0
        # fully replayed: a second pass is a no-op
        assert wal.replay_batched(batch_fn)[0] == 0

    def test_batched_replay_stops_at_failure_and_resumes(self, tmp_path):
        from predictionio_tpu.resilience.wal import EventWAL

        wal = EventWAL(str(tmp_path / "wal"))
        for k in range(4):
            wal.append(_ev(u=f"x{k}"), 1, None)
        seen = []
        fail = {"on": True}

        def flaky(events, app_id, channel_id, req_id):
            if fail["on"] and any(e.entity_id == "x2" for e in events):
                raise OSError("storage down")
            seen.extend(e.entity_id for e in events)

        n, err = wal.replay_batched(flaky, max_batch=2)
        assert n == 2 and err is not None
        assert wal.pending() == 2
        fail["on"] = False
        n, err = wal.replay_batched(flaky, max_batch=2)
        assert n == 2 and err is None
        assert seen == ["x0", "x1", "x2", "x3"]

    def test_batch_req_id_stable_across_resend(self, tmp_path):
        """Same unacked prefix → same batch req_id: the daemon's dedupe
        sees a re-sent batch as a replay, not new work."""
        from predictionio_tpu.resilience.wal import EventWAL

        wal = EventWAL(str(tmp_path / "wal"))
        for k in range(2):
            wal.append(_ev(u=f"r{k}"), 1, None)
        req_ids = []

        def record_then_fail(events, app_id, channel_id, req_id):
            req_ids.append(req_id)
            raise OSError("lost response")

        wal.replay_batched(record_then_fail)
        wal.replay_batched(record_then_fail)
        assert len(req_ids) == 2 and req_ids[0] == req_ids[1]

    def test_spill_stamps_event_id_for_store_level_idempotence(
        self, tmp_path
    ):
        from predictionio_tpu.resilience.wal import EventWAL

        wal = EventWAL(str(tmp_path / "wal"))
        req_id = wal.append(_ev(u="s1"), 1, None)
        store = MemoryEventStore()

        def insert_twice(events, app_id, channel_id, batch_req):
            store.insert_batch(events, app_id, channel_id)
            store.insert_batch(events, app_id, channel_id)  # torn resend

        wal.replay_batched(insert_twice)
        evs = list(store.find_since(1, 0))
        assert len(evs) == 1  # overwrite, not duplicate
        assert evs[0].event_id == req_id

    def test_event_server_uses_batched_replay(self, tmp_path):
        """The ingest path end to end: spill under an injected outage,
        then one replay pass lands everything through insert_batch."""
        from predictionio_tpu.data.api.server import (
            EventServer,
            EventServerConfig,
        )
        from predictionio_tpu.resilience import faults

        storage = _mem_storage()
        from predictionio_tpu.data.storage.base import AccessKey, App

        app_id = storage.get_meta_data_apps().insert(App(0, "walapp"))
        storage.get_meta_data_access_keys().insert(
            AccessKey("k1", app_id, ())
        )
        srv = EventServer(storage, EventServerConfig(
            ip="127.0.0.1", port=0, wal_dir=str(tmp_path / "wal"),
            wal_replay_interval_s=30.0,
        ))
        port = srv.start()
        try:
            import urllib.request

            faults.install(faults.FaultSpec("event.insert", "error", 1.0))
            for k in range(3):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/events.json?accessKey=k1",
                    data=_ev(u=f"w{k}").to_json().encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=10) as r:
                    assert r.status == 202
            assert srv._server.wal.pending() == 3
            faults.clear()
            assert srv.replay_wal_once() == 3
            assert srv._server.wal.pending() == 0
            from predictionio_tpu.data.storage.base import EventQuery

            stored = list(storage.get_events().find(
                EventQuery(app_id=app_id)
            ))
            assert sorted(e.entity_id for e in stored) == ["w0", "w1", "w2"]
            # replaying again cannot duplicate
            assert srv.replay_wal_once() == 0
        finally:
            faults.clear()
            srv.stop()


# ---------------------------------------------------------------------------
# Job-id version adoption (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


class TestJobAdoption:
    def test_register_stamps_and_finds_by_job(self, fresh_storage):
        import datetime as dt

        from predictionio_tpu.data.storage.base import EngineInstance
        from predictionio_tpu.deploy.registry import ModelRegistry

        now = dt.datetime.now(dt.timezone.utc)
        inst = EngineInstance(
            id="inst-1", status="COMPLETED", start_time=now, end_time=now,
            engine_id="e", engine_version="0", engine_variant="e",
            engine_factory="f",
        )
        storage = fresh_storage
        storage.get_meta_data_engine_instances().insert(inst)
        reg = ModelRegistry(storage)
        v = reg.register(inst, job_id="job-abc")
        assert reg.find_by_job("job-abc").id == v.id
        assert reg.find_by_job("job-nope") is None
        assert reg.get(v.id).job_id == "job-abc"

    def test_retried_worker_adopts_registered_version(
        self, fresh_storage, tmp_path
    ):
        """A retried train whose previous attempt already registered a
        version writes the receipt and exits 0 WITHOUT retraining — the
        variant here is invalid, so reaching run_train would fail."""
        import datetime as dt

        from predictionio_tpu.data.storage.base import EngineInstance
        from predictionio_tpu.deploy import worker
        from predictionio_tpu.deploy.registry import ModelRegistry
        from predictionio_tpu.deploy.scheduler import (
            storage_config_to_json,
        )

        storage = fresh_storage
        now = dt.datetime.now(dt.timezone.utc)
        inst = EngineInstance(
            id="inst-2", status="COMPLETED", start_time=now, end_time=now,
            engine_id="e", engine_version="0", engine_variant="e",
            engine_factory="f",
        )
        storage.get_meta_data_engine_instances().insert(inst)
        v = ModelRegistry(storage).register(inst, job_id="job-retry")
        spec_path = tmp_path / "spec.json"
        result_path = tmp_path / "result.json"
        spec_path.write_text(json.dumps({
            "job_id": "job-retry",
            "storage": storage_config_to_json(storage.config),
            "variant": {"id": "broken", "engineFactory": "no.such.Factory"},
            "engine_id": "e",
            "result_path": str(result_path),
        }))
        rc = worker.main(["worker", str(spec_path)])
        assert rc == 0
        receipt = json.loads(result_path.read_text())
        assert receipt == {
            "instance_id": "inst-2", "model_version": v.id,
        }

    def test_rolled_back_version_is_not_adopted(
        self, fresh_storage, tmp_path
    ):
        import datetime as dt

        from predictionio_tpu.data.storage.base import EngineInstance
        from predictionio_tpu.deploy import worker
        from predictionio_tpu.deploy.registry import ModelRegistry
        from predictionio_tpu.deploy.scheduler import (
            EXIT_TRAIN_FAILED,
            storage_config_to_json,
        )

        storage = fresh_storage
        now = dt.datetime.now(dt.timezone.utc)
        inst = EngineInstance(
            id="inst-3", status="COMPLETED", start_time=now, end_time=now,
            engine_id="e", engine_version="0", engine_variant="e",
            engine_factory="f",
        )
        storage.get_meta_data_engine_instances().insert(inst)
        reg = ModelRegistry(storage)
        v = reg.register(inst, job_id="job-rb")
        reg.rollback(v.id, "judged bad")
        spec_path = tmp_path / "spec.json"
        spec_path.write_text(json.dumps({
            "job_id": "job-rb",
            "storage": storage_config_to_json(storage.config),
            "variant": {"id": "broken", "engineFactory": "no.such.Factory"},
            "engine_id": "e",
            "result_path": str(tmp_path / "r.json"),
        }))
        # falls through to training, which fails on the broken factory
        assert worker.main(["worker", str(spec_path)]) == EXIT_TRAIN_FAILED


# ---------------------------------------------------------------------------
# Alert notification sinks (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


class TestAlertNotify:
    def test_exec_sink_receives_alert_json(self, tmp_path):
        from predictionio_tpu.obs.monitor.notify import AlertNotifier

        out = tmp_path / "alert.json"
        script = tmp_path / "sink.py"
        script.write_text(
            "import os, sys\n"
            f"open({str(out)!r}, 'w').write(os.environ['PIO_ALERT_JSON'])\n"
        )
        n = AlertNotifier(exec_cmd=f"{os.sys.executable} {script}")
        n.notify({"slo": "t1", "state": "firing"})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not out.exists():
            time.sleep(0.02)
        payload = json.loads(out.read_text())
        assert payload["slo"] == "t1" and payload["state"] == "firing"

    def test_slo_engine_fires_transition_hook(self):
        from predictionio_tpu.obs.monitor.slo import SLOEngine, SLOSpec
        from predictionio_tpu.obs.monitor.tsdb import TSDB
        from predictionio_tpu.obs.registry import MetricsRegistry

        transitions = []
        engine = SLOEngine(
            TSDB(), [SLOSpec(name="hooked", objective=0.99)],
            interval_s=60.0, registry=MetricsRegistry(),
            on_transition=lambda p, old, new: transitions.append(
                (p["slo"], old, new)
            ),
        )
        engine.burn_rate = lambda spec, w, now=None: (100.0, 50.0)
        engine.evaluate_once(now=1000.0)  # inactive → pending
        engine.evaluate_once(now=1001.0)  # pending → firing (for_s=0)
        assert ("hooked", "inactive", "pending") in transitions
        assert ("hooked", "pending", "firing") in transitions

    def test_monitor_external_alerts_merge_and_notify(self):
        from predictionio_tpu.obs.monitor import Monitor

        m = Monitor()
        sent = []
        m.notifier.webhook_url = None
        m.notifier.exec_cmd = None
        m.notifier.notify = lambda alert: sent.append(alert)
        m.raise_alert("online_drift_pause", {"drift": 2.5})
        payload = m.alerts_payload()
        assert "online_drift_pause" in payload["firing"]
        assert any(
            a.get("slo") == "online_drift_pause" and a.get("external")
            for a in payload["alerts"]
        )
        # refresh while firing does NOT re-notify
        m.raise_alert("online_drift_pause", {"drift": 3.0})
        assert len(sent) == 1
        m.resolve_alert("online_drift_pause")
        assert "online_drift_pause" not in m.alerts_payload()["firing"]
        assert len(sent) == 2
        assert sent[1]["transition"] == "firing->resolved"


# ---------------------------------------------------------------------------
# Tenant-cache conditional swap + mux online lifecycle
# ---------------------------------------------------------------------------


class TestTenantOnline:
    def test_cache_swap_runtime_is_conditional(self):
        from predictionio_tpu.tenancy.cache import ModelCache

        class T:
            id = "acme"
            engine_id = "e"
            engine_version = "0"
            engine_variant = "e"

        rt1, rt2, rt3 = object(), object(), object()
        cache = ModelCache(None, capacity=2, build=lambda inst: rt1)
        cache.resolve_version = lambda tenant: ("v1", object())
        entry = cache.acquire(T())
        cache.release(entry)
        cache.pin("acme", on=True)
        assert cache.peek_runtime("acme") is rt1
        assert cache.swap_runtime("acme", rt1, rt2)
        assert cache.peek_runtime("acme") is rt2
        # pinned + version_key carry over; stale expectation refused
        assert cache._entries["acme"].pinned
        assert cache._entries["acme"].version_key == "v1"
        assert not cache.swap_runtime("acme", rt1, rt3)
        assert not cache.swap_runtime("ghost", rt1, rt3)
        assert cache.peek_runtime("acme") is rt2

    def test_cache_swap_remeasures_device_bytes(self):
        """HBM-budget mode must see fold-in growth: the swapped entry's
        bytes are re-measured, not copied from the old entry."""
        from predictionio_tpu.tenancy.cache import ModelCache

        class T:
            id = "acme"

        sizes = {}
        rt1, rt2 = object(), object()
        sizes[id(rt1)], sizes[id(rt2)] = 100.0, 250.0
        cache = ModelCache(
            None, capacity=2, build=lambda inst: rt1,
            hbm_bytes=10_000.0, measure=lambda rt: sizes[id(rt)],
            transient=lambda: 0.0,
        )
        cache.resolve_version = lambda tenant: ("v1", object())
        cache.release(cache.acquire(T()))
        assert cache.resident_bytes() == 100.0
        assert cache.swap_runtime("acme", rt1, rt2)
        assert cache.resident_bytes() == 250.0

    def test_mux_attach_online_stops_on_mux_stop(self):
        from predictionio_tpu.tenancy.mux import TenantMux
        from predictionio_tpu.tenancy.tenants import Tenant, TenantStore

        storage = _mem_storage()
        TenantStore(storage).upsert(Tenant(
            id="acme", engine_id="e", engine_version="0",
            engine_variant="e",
        ))
        mux = TenantMux(storage, cache_capacity=2)
        mux.cache._build_fn = lambda inst: object()
        mux.cache.resolve_version = lambda tenant: ("v1", object())

        class StubConsumer:
            def __init__(self):
                self.started = False
                self.stopped = False

            def start(self):
                self.started = True

            def stop(self):
                self.stopped = True

            def status(self):
                return {"cursor": {}}

        c = StubConsumer()
        mux.attach_online("acme", 1, consumer=c)
        assert c.started
        assert mux.online_status("acme")["state"] == "attached"
        assert mux.online_status("ghost")["state"] == "detached"
        mux.stop()
        assert c.stopped

    def test_tenant_apply_host_swaps_cached_runtime(self):
        from predictionio_tpu.online import TenantApplyHost
        from predictionio_tpu.tenancy.cache import ModelCache

        class T:
            id = "acme"

        rt1, rt2 = object(), object()
        cache = ModelCache(None, capacity=2, build=lambda inst: rt1)
        cache.resolve_version = lambda tenant: ("v1", object())
        cache.release(cache.acquire(T()))

        class MuxStub:
            pass

        mux = MuxStub()
        mux.cache = cache
        host = TenantApplyHost(mux, "acme")
        assert host.scope == "tenant/acme"
        assert host.current() is rt1
        assert host.swap(rt1, rt2)
        assert host.current() is rt2
        assert not host.swap(rt1, rt2)
