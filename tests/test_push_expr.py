"""Push telemetry + series algebra (ISSUE 17): the expression engine
(selectors, arithmetic with label matching, range functions, grouped
aggregation), expression recording rules vs hand-computed references,
the increase()-across-snapshot-restore regression, scraper failure
backoff, per-label-set exemplar indexing, the TelemetryShipper spool →
guarded ingest path, and the chaos e2e: a train worker whose telemetry
lands with zero polls — including a kill -9'd worker whose orphaned
spool the supervisor ships."""

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.deploy.scheduler import (
    JobQueue,
    SchedulerConfig,
    TrainScheduler,
)
from predictionio_tpu.obs import spans as _spans
from predictionio_tpu.obs import tracing as _tracing
from predictionio_tpu.obs.monitor import Monitor
from predictionio_tpu.obs.monitor.collector import TraceCollector
from predictionio_tpu.obs.monitor import expr as expr_mod
from predictionio_tpu.obs.monitor.expr import (
    ExprError,
    evaluate,
    evaluate_rows,
    parse,
)
from predictionio_tpu.obs.monitor import push as push_mod
from predictionio_tpu.obs.monitor.push import (
    PUSH_ROUTE,
    PushError,
    TelemetryShipper,
    build_payload,
    ingest,
    ship_spool,
    spool_payload,
)
from predictionio_tpu.obs.monitor.scrape import (
    FleetScraper,
    parse_exemplar_lines,
)
from predictionio_tpu.obs.monitor.tsdb import (
    TSDB,
    RecordingRule,
    evaluate_rules,
    load_snapshot,
    save_snapshot,
)
from predictionio_tpu.obs.registry import MetricsRegistry, render_families
from predictionio_tpu.utils.http import HttpError, JsonHandler, ThreadedServer

T0 = 1_700_000_000.0

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_DIR = os.path.dirname(TESTS_DIR)


def _wait_for(predicate, timeout=60.0, interval=0.1, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def _counter_walk(db, name, labels, values, step=10.0, start=T0):
    """Write a counter series one point per `step` seconds."""
    for i, v in enumerate(values):
        db.add(name, labels, float(v), "counter", start + i * step)


# ---------------------------------------------------------------------------
# the expression engine
# ---------------------------------------------------------------------------


class TestExprEngine:
    def _db(self) -> TSDB:
        db = TSDB()
        db.add("mem_bytes", {"instance": "a"}, 100.0, "gauge", T0)
        db.add("mem_bytes", {"instance": "b"}, 300.0, "gauge", T0)
        return db

    def test_scalar_arithmetic_and_precedence(self):
        db = TSDB()
        assert evaluate(db, "1 + 2 * 3", now=T0) == 7.0
        assert evaluate(db, "(1 + 2) * 3", now=T0) == 9.0
        assert evaluate(db, "-2 + 10", now=T0) == 8.0
        assert evaluate(db, "7 / 2", now=T0) == 3.5

    def test_selector_returns_latest_per_series(self):
        db = self._db()
        db.add("mem_bytes", {"instance": "a"}, 150.0, "gauge", T0 + 5)
        rows = evaluate_rows(db, "mem_bytes", now=T0 + 10)
        assert rows == [
            {"labels": {"instance": "a"}, "value": 150.0},
            {"labels": {"instance": "b"}, "value": 300.0},
        ]

    def test_selector_label_match(self):
        db = self._db()
        rows = evaluate_rows(db, 'mem_bytes{instance="b"}', now=T0 + 1)
        assert rows == [{"labels": {"instance": "b"}, "value": 300.0}]

    def test_vector_scalar_op(self):
        db = self._db()
        rows = evaluate_rows(db, "mem_bytes / 100", now=T0 + 1)
        assert [r["value"] for r in rows] == [1.0, 3.0]
        # labels survive scalar ops
        assert rows[0]["labels"] == {"instance": "a"}

    def test_vector_vector_exact_label_matching(self):
        db = TSDB()
        db.add("errs", {"i": "a"}, 2.0, "gauge", T0)
        db.add("errs", {"i": "b"}, 5.0, "gauge", T0)
        db.add("reqs", {"i": "a"}, 10.0, "gauge", T0)
        db.add("reqs", {"i": "b"}, 50.0, "gauge", T0)
        # unmatched series on either side simply drop out
        db.add("reqs", {"i": "c"}, 9.0, "gauge", T0)
        rows = evaluate_rows(db, "errs / reqs", now=T0 + 1)
        assert rows == [
            {"labels": {"i": "a"}, "value": 0.2},
            {"labels": {"i": "b"}, "value": 0.1},
        ]

    def test_division_by_zero_drops_sample(self):
        db = TSDB()
        db.add("errs", {"i": "a"}, 2.0, "gauge", T0)
        db.add("reqs", {"i": "a"}, 0.0, "gauge", T0)
        assert evaluate_rows(db, "errs / reqs", now=T0 + 1) == []

    def test_rate_and_increase(self):
        db = TSDB()
        _counter_walk(db, "c_total", {"i": "a"}, [0, 30, 60, 90])
        now = T0 + 30
        # 90 over the full 100s window → rate = increase / window
        inc = evaluate(db, 'increase(c_total[100s])', now=now)
        assert inc == [((("i", "a"),), pytest.approx(90.0))]
        rate = evaluate(db, 'rate(c_total[100s])', now=now)
        assert rate == [((("i", "a"),), pytest.approx(0.9))]

    def test_increase_is_counter_reset_aware(self):
        db = TSDB()
        _counter_walk(db, "c_total", {}, [100, 110, 5, 8])
        # 10 + (reset: 5) + 3 = 18
        val = evaluate(db, "increase(c_total[100s])", now=T0 + 30)
        assert val == [((), pytest.approx(18.0))]

    def test_quantile_over_time(self):
        db = TSDB()
        for i, v in enumerate([1.0, 2.0, 3.0, 4.0]):
            db.add("lat", {"i": "a"}, v, "gauge", T0 + i)
        val = evaluate(
            db, "quantile_over_time(0.5, lat[60s])", now=T0 + 10
        )
        assert val == [((("i", "a"),), pytest.approx(2.5))]

    def test_sum_by_groups_labels(self):
        db = TSDB()
        db.add("reqs", {"i": "a", "route": "/q"}, 1.0, "gauge", T0)
        db.add("reqs", {"i": "a", "route": "/m"}, 2.0, "gauge", T0)
        db.add("reqs", {"i": "b", "route": "/q"}, 4.0, "gauge", T0)
        rows = evaluate_rows(db, "sum by (i) (reqs)", now=T0 + 1)
        assert rows == [
            {"labels": {"i": "a"}, "value": 3.0},
            {"labels": {"i": "b"}, "value": 4.0},
        ]
        rows = evaluate_rows(db, "max by (route) (reqs)", now=T0 + 1)
        assert rows == [
            {"labels": {"route": "/m"}, "value": 2.0},
            {"labels": {"route": "/q"}, "value": 4.0},
        ]

    def test_bare_aggregation_is_scalar(self):
        db = self._db()
        assert evaluate(db, "sum(mem_bytes)", now=T0 + 1) == 400.0
        assert evaluate(db, "mean(mem_bytes)", now=T0 + 1) == 200.0
        assert evaluate(db, "max(mem_bytes)", now=T0 + 1) == 300.0

    def test_no_data_is_none_and_empty_rows(self):
        db = TSDB()
        assert evaluate(db, "nothing_here", now=T0) in (None, [])
        assert evaluate_rows(db, "nothing_here", now=T0) == []

    @pytest.mark.parametrize("bad", [
        "", "   ", "sum by (", "rate(x[abc])", "a +", "1 ** 2",
        'x{i="a"', "quantile_over_time(x[1m])",
    ])
    def test_parse_errors(self, bad):
        with pytest.raises(ExprError):
            parse(bad)

    def test_parse_cache_returns_same_ast(self):
        assert parse("sum(up)") is parse("sum(up)")


# ---------------------------------------------------------------------------
# expression recording rules (vs a hand-computed reference)
# ---------------------------------------------------------------------------


class TestExprRecordingRule:
    def _ratio_db(self) -> TSDB:
        db = TSDB()
        # per-instance counters walked over 110s, one point / 10s
        _counter_walk(db, "errors_total", {"instance": "a", "route": "/q"},
                      [i * 2 for i in range(12)])
        _counter_walk(db, "errors_total", {"instance": "b", "route": "/q"},
                      [i * 1 for i in range(12)])
        _counter_walk(db, "requests_total", {"instance": "a", "route": "/q"},
                      [i * 10 for i in range(12)])
        _counter_walk(db, "requests_total", {"instance": "b", "route": "/q"},
                      [i * 20 for i in range(12)])
        return db

    EXPR = (
        "sum by (instance) (increase(errors_total[2m]))"
        " / sum by (instance) (increase(requests_total[2m]))"
    )

    def test_cross_family_error_ratio_matches_hand_computed(self):
        db = self._ratio_db()
        now = T0 + 120
        rows = evaluate_rows(db, self.EXPR, now=now)
        # hand-computed: instance a grows 2 errors / 10 reqs per 10s
        # (22/110), instance b 1 error / 20 reqs (11/220) — ratios
        # exactly 0.2 and 0.05
        assert rows == [
            {"labels": {"instance": "a"},
             "value": pytest.approx(0.2, abs=1e-12)},
            {"labels": {"instance": "b"},
             "value": pytest.approx(0.05, abs=1e-12)},
        ]

    def test_expr_rule_records_one_gauge_per_row(self):
        db = self._ratio_db()
        now = T0 + 120
        rule = RecordingRule(
            record="fleet:error_ratio", kind="expr", expr=self.EXPR,
        )
        expected = {
            r["labels"]["instance"]: r["value"]
            for r in evaluate_rows(db, self.EXPR, now=now)
        }
        assert evaluate_rules(db, [rule], now=now) == 2
        for inst, want in expected.items():
            series = db.matching("fleet:error_ratio", {"instance": inst})
            assert len(series) == 1
            t, v = series[0].points[-1]
            assert t == now and v == pytest.approx(want)

    def test_expr_rule_static_labels_win_on_collision(self):
        db = TSDB()
        db.add("up", {"instance": "a"}, 1.0, "gauge", T0)
        rule = RecordingRule(
            record="fleet:up", kind="expr", expr="up",
            labels={"instance": "fleet", "tier": "gold"},
        )
        assert evaluate_rules(db, [rule], now=T0 + 1) == 1
        s = db.matching("fleet:up", {"tier": "gold"})
        assert len(s) == 1
        assert s[0].labels_dict() == {"instance": "fleet", "tier": "gold"}

    def test_expr_rule_validates_at_construction(self):
        with pytest.raises(ValueError):
            RecordingRule(record="r", kind="expr", expr="sum by (")
        with pytest.raises(ValueError):
            RecordingRule(record="r", kind="expr", expr="")

    def test_expr_rule_roundtrips_to_dict(self):
        rule = RecordingRule(
            record="fleet:error_ratio", kind="expr", expr=self.EXPR,
        )
        d = rule.to_dict()
        assert d["kind"] == "expr" and d["expr"] == self.EXPR
        clone = RecordingRule.from_dict(d)
        assert clone.expr == rule.expr


# ---------------------------------------------------------------------------
# increase() across a snapshot restore (the satellite-3 regression)
# ---------------------------------------------------------------------------


class TestIncreaseAcrossSnapshotRestore:
    def test_restore_after_live_points_keeps_time_order(self, tmp_path):
        now = T0
        old = TSDB()
        old.add("jobs_total", {}, 100.0, "counter", now - 60)
        old.add("jobs_total", {}, 110.0, "counter", now - 50)
        path = str(tmp_path / "tsdb.snap")
        save_snapshot(old, path)

        live = TSDB()
        # the process restarts, samples twice (counter reset to zero),
        # and only THEN the periodic restore loads yesterday's ring
        live.add("jobs_total", {}, 5.0, "counter", now - 10)
        live.add("jobs_total", {}, 8.0, "counter", now - 5)
        assert load_snapshot(live, path) > 0

        (series,) = live.matching("jobs_total")
        # ring must be in time order after the interleaved restore
        ts = [t for t, _ in series.points]
        assert ts == sorted(ts)
        # 10 (old segment) + 5 (reset) + 3 (live segment) — the broken
        # append-at-end ordering used to read 105 here
        got = live.series_increase(series, window_s=120, now=now)
        assert got == pytest.approx(18.0)
        assert evaluate(live, "increase(jobs_total[120s])", now=now) == [
            ((), pytest.approx(18.0))
        ]

    def test_out_of_order_add_single_series(self):
        db = TSDB()
        db.add("g", {}, 2.0, "gauge", T0 + 10)
        db.add("g", {}, 1.0, "gauge", T0)       # late arrival
        db.add("g", {}, 3.0, "gauge", T0 + 20)
        (s,) = db.matching("g")
        assert [v for _, v in s.points] == [1.0, 2.0, 3.0]


# ---------------------------------------------------------------------------
# scraper failure backoff
# ---------------------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestScraperBackoff:
    def _dead_target(self):
        return ("dead", f"http://127.0.0.1:{_free_port()}")

    def _points(self, db, name, instance):
        out = []
        for s in db.matching(name, {"instance": instance}):
            out.extend(s.points)
        return out

    def test_backoff_skips_http_but_still_writes_up(self):
        db = TSDB()
        sc = FleetScraper(db, [self._dead_target()], interval_s=5.0,
                          backoff_max_s=60.0)
        assert sc.scrape_once(now=T0) == {"dead": False}
        # first real attempt wrote up=0 AND a scrape duration
        assert len(self._points(db, "up", "dead")) == 1
        assert len(self._points(db, "scrape_duration_seconds", "dead")) == 1
        assert sc.backoff_remaining("dead", now=T0) == pytest.approx(10.0)

        # inside the backoff window: no HTTP attempt (no new duration
        # point) but up=0 still lands for the tick — alert freshness
        assert sc.scrape_once(now=T0 + 5) == {"dead": False}
        assert len(self._points(db, "up", "dead")) == 2
        assert len(self._points(db, "scrape_duration_seconds", "dead")) == 1

        # past the window: a real attempt again, backoff doubles
        assert sc.scrape_once(now=T0 + 11) == {"dead": False}
        assert len(self._points(db, "scrape_duration_seconds", "dead")) == 2
        assert sc.backoff_remaining("dead", now=T0 + 11) == pytest.approx(
            20.0
        )

    def test_backoff_is_capped(self):
        db = TSDB()
        sc = FleetScraper(db, [self._dead_target()], interval_s=5.0,
                          backoff_max_s=12.0)
        now = T0
        for _ in range(5):
            sc.scrape_once(now=now)
            now += sc.backoff_remaining("dead", now=now) + 0.001
        assert sc.backoff_remaining("dead", now=now - 0.001) <= 12.0

    def test_recovery_clears_backoff(self):
        class _OkMetrics(JsonHandler):
            def do_GET(self):
                self._drain_body()
                self._respond(200, "ok_total 1\n",
                              content_type="text/plain")

        srv = ThreadedServer(("127.0.0.1", 0), _OkMetrics)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            port = srv.server_address[1]
            db = TSDB()
            sc = FleetScraper(
                db, [("flaky", f"http://127.0.0.1:{port}")],
                interval_s=5.0,
            )
            # force a backed-off state by hand, past its window
            sc._fails["flaky"] = 3
            sc._not_before["flaky"] = T0 - 1
            assert sc.scrape_once() == {"flaky": True}
            assert sc.backoff_remaining("flaky") == 0.0
            assert sc._fails.get("flaky") is None
            up = db.matching("up", {"instance": "flaky"})[0]
            assert up.points[-1][1] == 1.0
        finally:
            srv.shutdown()
            srv.server_close()


# ---------------------------------------------------------------------------
# per-label-set exemplar indexing
# ---------------------------------------------------------------------------


class TestPerRouteExemplars:
    def _observe(self, fam, tid, value, **labels):
        tok = _tracing.set_trace_id(tid)
        try:
            fam.observe(value, **labels)
        finally:
            _tracing.reset_trace_id(tok)

    def test_render_parse_roundtrip_with_labels(self):
        reg = MetricsRegistry()
        fam = reg.histogram("r_seconds", "latency", ["path"])
        self._observe(fam, "tidQ", 0.25, path="/q")
        self._observe(fam, "tidM", 0.50, path="/m")
        text = render_families(reg.families())
        parsed = sorted(parse_exemplar_lines(text))
        assert [(p[0], p[1], p[2], p[4]) for p in parsed] == [
            ("r_seconds", "tidM", 0.50, {"path": "/m"}),
            ("r_seconds", "tidQ", 0.25, {"path": "/q"}),
        ]

    def test_labelless_family_renders_legacy_six_token_line(self):
        reg = MetricsRegistry()
        fam = reg.histogram("plain_seconds", "latency", [])
        self._observe(fam, "tidA", 0.1)
        line = [
            ln for ln in render_families(reg.families()).splitlines()
            if ln.startswith("# EXEMPLAR")
        ][0]
        assert len(line.split()) == 6
        assert parse_exemplar_lines(line) == [
            ("plain_seconds", "tidA", 0.1,
             pytest.approx(parse_exemplar_lines(line)[0][3]), {}),
        ]

    def test_each_label_set_keeps_its_own_slowest(self):
        reg = MetricsRegistry()
        fam = reg.histogram("r_seconds", "latency", ["path"])
        cap = fam._exemplar_cap
        # flood /metrics with slow observations; /q's one trace must
        # survive — the reservoirs no longer compete
        self._observe(fam, "tidQ", 0.001, path="/q")
        for i in range(cap + 4):
            self._observe(fam, f"m{i}", 10.0 + i, path="/metrics")
        exs = fam.exemplars()
        by_path = {}
        for ex in exs:
            by_path.setdefault(ex["labels"]["path"], []).append(ex)
        assert len(by_path["/metrics"]) == cap
        assert [e["trace_id"] for e in by_path["/q"]] == ["tidQ"]

    def test_monitor_index_filters_by_labels(self):
        mon = Monitor()
        mon.note_exemplar("r_seconds", "tidQ", 0.3,
                          labels={"path": "/q"})
        mon.note_exemplar("r_seconds", "tidM", 0.9,
                          labels={"path": "/m"})
        got = mon.exemplars(family="r_seconds", labels={"path": "/q"})
        assert [e["trace_id"] for e in got] == ["tidQ"]
        assert got[0]["labels"] == {"path": "/q"}
        # unfiltered: slowest first across label sets
        all_rows = mon.exemplars(family="r_seconds")
        assert [e["trace_id"] for e in all_rows] == ["tidM", "tidQ"]

    def test_monitor_index_bounded_per_label_set(self):
        mon = Monitor()
        cap = mon._exemplar_cap
        for i in range(cap + 5):
            mon.note_exemplar("r_seconds", f"t{i}", float(i),
                              labels={"path": "/m"})
        mon.note_exemplar("r_seconds", "tQ", 0.0, labels={"path": "/q"})
        rows = mon.exemplars(family="r_seconds", limit=cap * 3)
        by_path = {}
        for r in rows:
            by_path.setdefault(r["labels"]["path"], []).append(r)
        assert len(by_path["/m"]) == cap
        # the fastest were evicted, the slowest retained
        assert min(r["value"] for r in by_path["/m"]) == 5.0
        assert [r["trace_id"] for r in by_path["/q"]] == ["tQ"]


# ---------------------------------------------------------------------------
# push: payloads, spool durability, guarded ingest
# ---------------------------------------------------------------------------


class _IngestHandler(JsonHandler):
    """Test ingest endpoint landing pushes in `server.monitor` (a
    dedicated Monitor — the guard itself is covered separately)."""

    def do_POST(self):
        self._drain_body()
        try:
            if self.path.split("?")[0] == PUSH_ROUTE:
                try:
                    result = ingest(
                        self._json_body(), monitor=self.server.monitor
                    )
                except PushError as e:
                    raise HttpError(400, str(e))
                self._respond(200, result)
            else:
                raise HttpError(404, "Not Found")
        except HttpError as e:
            self._respond(e.status, {"message": e.message})


def _start_ingest_server(port=0):
    srv = ThreadedServer(("127.0.0.1", port), _IngestHandler)
    srv.monitor = Monitor()
    srv.monitor.set_collector(TraceCollector(targets=[], interval_s=3600))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


class TestPushIngest:
    def test_ingest_tags_series_and_backfills_sampled_at(self):
        mon = Monitor()
        payload = {
            "v": 1, "instance": "w1", "job_id": "j9",
            "sampled_at": T0,
            "series": [
                {"name": "train_runs_total", "labels": {"status": "ok"},
                 "value": 3.0, "kind": "counter"},
            ],
            "spans": [],
        }
        out = ingest(payload, monitor=mon, now=T0 + 30)
        assert out["ok"] and out["series_written"] == 1
        (s,) = mon.tsdb.matching("train_runs_total")
        assert s.labels_dict() == {
            "status": "ok", "instance": "w1", "job_id": "j9",
        }
        # the point lands at its SAMPLED time, not arrival time
        assert s.points[-1] == (T0, 3.0)
        # freshness bookkeeping: the age series exists immediately
        (age,) = mon.tsdb.matching(
            "telemetry_last_push_age_seconds", {"instance": "w1"}
        )
        assert age.points[-1][1] == pytest.approx(30.0)
        assert [r["instance"] for r in mon.push_status()] == ["w1"]

    def test_ingest_clamps_future_clocks(self):
        mon = Monitor()
        ingest({"v": 1, "instance": "w", "sampled_at": T0 + 9999,
                "series": [{"name": "g", "value": 1.0}], "spans": []},
               monitor=mon, now=T0)
        (s,) = mon.tsdb.matching("g")
        assert s.points[-1][0] <= T0 + 1.0

    def test_ingest_rejects_malformed(self):
        mon = Monitor()
        for bad in (None, [], {"v": 99},
                    {"v": 1, "series": "nope", "spans": []}):
            with pytest.raises(PushError):
                ingest(bad, monitor=mon)

    def test_ingest_spans_reach_collector_with_zero_polls(self):
        mon = Monitor()
        col = TraceCollector(targets=[], interval_s=3600)
        mon.set_collector(col)
        spans = [
            _spans.Span(trace_id="t1", span_id="s1", name="train",
                        parent_span_id=None, start=T0,
                        duration=1.0).to_dict(),
            _spans.Span(trace_id="t1", span_id="s2", name="train.read",
                        parent_span_id="s1", start=T0,
                        duration=0.5).to_dict(),
        ]
        out = ingest({"v": 1, "instance": "w", "sampled_at": T0,
                      "series": [], "spans": spans}, monitor=mon, now=T0)
        assert out["spans_ingested"] == 2
        st = col.status()
        assert st["pushed_spans"] == 2 and st["polls"] == 0
        assert st["assembled"] >= 1


class TestTelemetryShipper:
    def test_spool_files_are_durable_and_ordered(self, tmp_path):
        spool = str(tmp_path / "spool")
        sh = TelemetryShipper(spool, url="", instance="w1", job_id="j1",
                              interval_s=9.0, recorder=_spans.SpanRecorder())
        assert sh.spool_once(now=T0) is not None
        assert sh.spool_once(now=T0 + 1) is not None
        names = sorted(os.listdir(spool))
        assert len(names) == 2 and names == sorted(names)
        with open(os.path.join(spool, names[0])) as f:
            payload = json.load(f)
        assert payload["v"] == 1
        assert payload["instance"] == "w1" and payload["job_id"] == "j1"
        assert isinstance(payload["series"], list)
        # lexical order == chronological order (the ship order)
        assert names[0].split("-")[0] <= names[1].split("-")[0]

    def test_ship_spool_delivers_and_drains(self, tmp_path):
        srv, base = _start_ingest_server()
        try:
            spool = str(tmp_path / "spool")
            reg = MetricsRegistry()
            reg.counter("pushed_total", "t", []).inc(7)
            sh = TelemetryShipper(
                spool, url=base, instance="w2", job_id="j2",
                interval_s=9.0, registries=[reg],
                recorder=_spans.SpanRecorder(),
            )
            sh.spool_once(now=T0)
            assert sh.ship() == 1
            assert os.listdir(spool) == []
            (s,) = srv.monitor.tsdb.matching(
                "pushed_total", {"instance": "w2"}
            )
            assert s.points[-1][1] == 7.0
            assert s.labels_dict()["job_id"] == "j2"
        finally:
            srv.shutdown()
            srv.server_close()

    def test_ship_spool_keeps_files_when_receiver_down(self, tmp_path):
        spool = str(tmp_path / "spool")
        sh = TelemetryShipper(
            spool, url=f"http://127.0.0.1:{_free_port()}",
            instance="w3", interval_s=9.0,
            recorder=_spans.SpanRecorder(),
        )
        sh.spool_once(now=T0)
        assert sh.ship(deadline_s=0.5) == 0
        assert len(os.listdir(spool)) == 1  # durable for the supervisor

    def test_ship_spool_unlinks_poison_files(self, tmp_path):
        srv, base = _start_ingest_server()
        try:
            spool = str(tmp_path / "spool")
            os.makedirs(spool)
            with open(os.path.join(spool, "000-bad.json"), "w") as f:
                f.write("{not json")
            marker = build_payload("poison-test", now=T0)
            spool_payload(spool, marker, seq=1)
            assert ship_spool(spool, base) == 1
            assert os.listdir(spool) == []
        finally:
            srv.shutdown()
            srv.server_close()

    def test_missing_spool_dir_ships_zero(self, tmp_path):
        assert ship_spool(str(tmp_path / "nope"), "http://x") == 0

    def test_start_stop_joins_thread_and_flushes(self, tmp_path):
        srv, base = _start_ingest_server()
        try:
            sh = TelemetryShipper(
                str(tmp_path / "spool"), url=base, instance="w4",
                interval_s=30.0, recorder=_spans.SpanRecorder(),
            )
            sh.start()
            sh.stop()
            assert not any(
                t.name == TelemetryShipper.thread_name
                for t in threading.enumerate()
            )
            # the final flush shipped at least the exit snapshot
            assert sh.shipped >= 1
            assert srv.monitor.push_status()[0]["instance"] == "w4"
            sh.stop()  # idempotent
        finally:
            srv.shutdown()
            srv.server_close()

    def test_from_env_disabled_without_knobs(self, monkeypatch):
        monkeypatch.delenv("PIO_PUSH_URL", raising=False)
        monkeypatch.delenv("PIO_PUSH_SPOOL", raising=False)
        assert TelemetryShipper.from_env() is None

    def test_from_env_configured(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PIO_PUSH_URL", "http://127.0.0.1:1")
        monkeypatch.setenv("PIO_PUSH_SPOOL", str(tmp_path / "sp"))
        sh = TelemetryShipper.from_env(job_id="j7")
        assert sh is not None
        assert sh.url == "http://127.0.0.1:1" and sh.job_id == "j7"

    def test_spool_trim_bounds_disk(self, tmp_path):
        spool = str(tmp_path / "spool")
        sh = TelemetryShipper(
            spool, url="", instance="w5", interval_s=9.0,
            spool_max_bytes=4096, recorder=_spans.SpanRecorder(),
        )
        for i in range(50):
            sh.spool_once(now=T0 + i)
        total = sum(
            os.path.getsize(os.path.join(spool, n))
            for n in os.listdir(spool)
        )
        assert total <= 4096


class TestGuardedIngestEndpoint:
    """The production handler: 403 unless PIO_PUSH_INGEST=1."""

    class _Handler(JsonHandler):
        def do_POST(self):
            self._drain_body()
            try:
                if self.path.split("?")[0] == PUSH_ROUTE:
                    self._serve_telemetry_push()
                else:
                    raise HttpError(404, "Not Found")
            except HttpError as e:
                self._respond(e.status, {"message": e.message})

    def _post(self, base, payload):
        req = urllib.request.Request(
            base + PUSH_ROUTE, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                return r.status, json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read().decode())

    @pytest.fixture()
    def server(self):
        srv = ThreadedServer(("127.0.0.1", 0), self._Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        yield f"http://127.0.0.1:{srv.server_address[1]}"
        srv.shutdown()
        srv.server_close()

    def test_403_when_disabled(self, server, monkeypatch):
        monkeypatch.delenv("PIO_PUSH_INGEST", raising=False)
        status, body = self._post(server, build_payload("w", now=T0))
        assert status == 403
        assert "PIO_PUSH_INGEST" in body["message"]

    def test_200_when_enabled_and_400_on_garbage(
        self, server, monkeypatch
    ):
        monkeypatch.setenv("PIO_PUSH_INGEST", "1")
        status, body = self._post(
            server, build_payload("guard-test", now=T0)
        )
        assert status == 200 and body["ok"] is True
        assert body["instance"] == "guard-test"
        status, body = self._post(server, {"v": 99})
        assert status == 400
        assert "version" in body["message"]


# ---------------------------------------------------------------------------
# per-instance push auth (ISSUE 18)
# ---------------------------------------------------------------------------


class TestPushTokenAuth:
    SECRET = "test-push-secret"

    def test_issue_verify_roundtrip(self):
        tok = push_mod.issue_push_token("w1", self.SECRET)
        assert push_mod.verify_push_token("w1", tok, self.SECRET)
        # bound to the instance: w1's token is useless for w2
        assert not push_mod.verify_push_token("w2", tok, self.SECRET)
        assert not push_mod.verify_push_token("w1", tok, "other-secret")
        assert not push_mod.verify_push_token("w1", None, self.SECRET)
        assert not push_mod.verify_push_token("w1", "", self.SECRET)

    def test_ingest_requires_matching_token(self, monkeypatch):
        monkeypatch.setenv("PIO_PUSH_TOKEN", self.SECRET)
        mon = Monitor()
        payload = {"v": 1, "instance": "w1", "sampled_at": T0,
                   "series": [{"name": "g", "value": 1.0}], "spans": []}
        with pytest.raises(push_mod.PushAuthError):
            ingest(dict(payload), monitor=mon, now=T0)
        # a token for ANOTHER instance must not let w1's label be
        # spoofed (nor vice versa)
        other = push_mod.issue_push_token("w2", self.SECRET)
        with pytest.raises(push_mod.PushAuthError):
            ingest(dict(payload), monitor=mon, now=T0, token=other)
        good = push_mod.issue_push_token("w1", self.SECRET)
        out = ingest(dict(payload), monitor=mon, now=T0, token=good)
        assert out["ok"] and out["series_written"] == 1

    def test_ingest_open_when_secret_unset(self, monkeypatch):
        monkeypatch.delenv("PIO_PUSH_TOKEN", raising=False)
        mon = Monitor()
        out = ingest({"v": 1, "instance": "w1", "sampled_at": T0,
                      "series": [], "spans": []}, monitor=mon, now=T0)
        assert out["ok"]

    def test_http_endpoint_enforces_header(self, monkeypatch):
        monkeypatch.setenv("PIO_PUSH_INGEST", "1")
        monkeypatch.setenv("PIO_PUSH_TOKEN", self.SECRET)
        srv = ThreadedServer(
            ("127.0.0.1", 0), TestGuardedIngestEndpoint._Handler
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            base = f"http://127.0.0.1:{srv.server_address[1]}"
            payload = json.dumps(build_payload("w1", now=T0)).encode()

            def post(headers):
                req = urllib.request.Request(
                    base + PUSH_ROUTE, data=payload, method="POST",
                    headers={"Content-Type": "application/json",
                             **headers},
                )
                try:
                    with urllib.request.urlopen(req, timeout=5) as r:
                        return r.status
                except urllib.error.HTTPError as e:
                    return e.code

            assert post({}) == 403
            assert post({push_mod.TOKEN_HEADER: "bogus"}) == 403
            good = push_mod.issue_push_token("w1", self.SECRET)
            assert post({push_mod.TOKEN_HEADER: good}) == 200
        finally:
            srv.shutdown()
            srv.server_close()

    def test_ship_spool_sends_per_file_token(self, tmp_path,
                                             monkeypatch):
        """The orphan sweep ships spools from many instances — each
        request must carry the token for ITS OWN payload's instance."""
        monkeypatch.setenv("PIO_PUSH_TOKEN", self.SECRET)
        seen: list[tuple] = []

        class _Capture(JsonHandler):
            def do_POST(self):
                self._drain_body()
                body = json.loads(self._body().decode())
                seen.append((
                    body["instance"],
                    self.headers.get(push_mod.TOKEN_HEADER),
                ))
                self._respond(200, {"ok": True})

        srv = ThreadedServer(("127.0.0.1", 0), _Capture)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            spool = str(tmp_path / "spool")
            spool_payload(spool, {"v": 1, "instance": "wA",
                                  "sampled_at": T0, "series": [],
                                  "spans": []}, 1)
            spool_payload(spool, {"v": 1, "instance": "wB",
                                  "sampled_at": T0 + 1, "series": [],
                                  "spans": []}, 2)
            url = f"http://127.0.0.1:{srv.server_address[1]}"
            assert ship_spool(spool, url, deadline_s=10.0) == 2
        finally:
            srv.shutdown()
            srv.server_close()
        assert dict(seen) == {
            "wA": push_mod.issue_push_token("wA", self.SECRET),
            "wB": push_mod.issue_push_token("wB", self.SECRET),
        }


# ---------------------------------------------------------------------------
# pushed-span rate limiting (ISSUE 18)
# ---------------------------------------------------------------------------


def _span_rows(n, prefix="s"):
    return [
        _spans.Span(trace_id="t", span_id=f"{prefix}{i}", name="x",
                    parent_span_id=None, start=T0,
                    duration=0.1).to_dict()
        for i in range(n)
    ]


class TestPushSpanRateLimit:
    @pytest.fixture(autouse=True)
    def _fresh_buckets(self):
        push_mod._span_buckets.clear()
        yield
        push_mod._span_buckets.clear()

    def _mon(self):
        mon = Monitor()
        mon.set_collector(TraceCollector(targets=[], interval_s=3600))
        return mon

    def test_burst_caps_one_push(self, monkeypatch):
        monkeypatch.setenv("PIO_PUSH_SPAN_RATE", "0.0001")
        monkeypatch.setenv("PIO_PUSH_SPAN_BURST", "3")
        mon = self._mon()
        out = ingest({"v": 1, "instance": "w", "sampled_at": T0,
                      "series": [], "spans": _span_rows(10)},
                     monitor=mon, now=T0)
        assert out["spans_ingested"] == 3
        assert out["spans_dropped"] == 7
        # bucket drained: the next push within the window loses all
        out2 = ingest({"v": 1, "instance": "w", "sampled_at": T0,
                       "series": [], "spans": _span_rows(4, "z")},
                      monitor=mon, now=T0 + 1)
        assert out2["spans_ingested"] == 0 and out2["spans_dropped"] == 4

    def test_bucket_refills_and_is_per_instance(self, monkeypatch):
        monkeypatch.setenv("PIO_PUSH_SPAN_RATE", "1.0")
        monkeypatch.setenv("PIO_PUSH_SPAN_BURST", "2")
        mon = self._mon()

        def push(instance, n, now, prefix):
            return ingest(
                {"v": 1, "instance": instance, "sampled_at": now,
                 "series": [], "spans": _span_rows(n, prefix)},
                monitor=mon, now=now,
            )

        assert push("a", 2, T0, "a")["spans_ingested"] == 2
        assert push("a", 2, T0, "b")["spans_ingested"] == 0
        # instance b has its own bucket
        assert push("b", 2, T0, "c")["spans_ingested"] == 2
        # 1 token/s: two seconds later instance a may send two more
        assert push("a", 2, T0 + 2, "d")["spans_ingested"] == 2

    def test_drop_counter_exported(self, monkeypatch):
        monkeypatch.setenv("PIO_PUSH_SPAN_RATE", "0.0001")
        monkeypatch.setenv("PIO_PUSH_SPAN_BURST", "1")
        mon = self._mon()
        ingest({"v": 1, "instance": "w", "sampled_at": T0,
                "series": [], "spans": _span_rows(5)},
               monitor=mon, now=T0)
        fam = push_mod._dropped_counter()
        text = render_families([fam])
        assert 'telemetry_push_dropped_total{kind="span"}' in text

    def test_disabled_when_rate_nonpositive(self, monkeypatch):
        monkeypatch.setenv("PIO_PUSH_SPAN_RATE", "0")
        mon = self._mon()
        out = ingest({"v": 1, "instance": "w", "sampled_at": T0,
                      "series": [], "spans": _span_rows(50)},
                     monitor=mon, now=T0)
        assert out["spans_ingested"] == 50 and out["spans_dropped"] == 0


# ---------------------------------------------------------------------------
# the offset modifier (ISSUE 18)
# ---------------------------------------------------------------------------


class TestExprOffset:
    def _db(self):
        """1/s for the last hour, 2/s the hour before — rate() vs
        rate(offset 1h) must see different slopes."""
        db = TSDB(capacity=2048)
        now = T0 + 7200
        t, v = T0, 0.0
        while t <= now:
            v += 2.0 if t < T0 + 3600 else 1.0
            db.add("reqs", {}, v * 10.0, "counter", t)
            t += 10.0
        return db, now

    def test_offset_shifts_range_window(self):
        db, now = self._db()
        (r_now,) = evaluate(db, "rate(reqs[30m])", now)
        (r_old,) = evaluate(db, "rate(reqs[30m] offset 1h)", now)
        assert r_now[1] == pytest.approx(1.0, rel=0.02)
        assert r_old[1] == pytest.approx(2.0, rel=0.02)

    def test_binary_op_across_two_windows(self):
        db, now = self._db()
        (row,) = evaluate(
            db, "rate(reqs[30m]) / rate(reqs[30m] offset 1h)", now
        )
        assert row[1] == pytest.approx(0.5, rel=0.03)

    def test_instant_selector_offset(self):
        db, now = self._db()
        (cur,) = evaluate(db, "reqs", now)
        (old,) = evaluate(db, "reqs offset 30m", now)
        # 1/s * 10.0 scale * 1800s of travel between the two instants
        assert cur[1] - old[1] == pytest.approx(1800.0, abs=20.0)

    def test_offset_increase_is_reset_aware(self):
        db = TSDB(capacity=2048)
        now = T0 + 7200
        t, v = T0, 0.0
        while t <= now:
            if abs(t - (T0 + 1800)) < 5:
                v = 0.0  # the counted process restarted 90m ago
            v += 1.0
            db.add("c", {}, v, "counter", t)
            t += 10.0
        (row,) = evaluate(db, "increase(c[30m] offset 80m)", now)
        # the straddled reset must not produce a negative or zero
        # increase — post-reset accumulation counts
        assert row[1] == pytest.approx(180.0, abs=15.0)

    def test_offset_parses_units_and_defaults_seconds(self):
        for text in ("rate(x[5m] offset 1h)", "rate(x[5m] offset 300)",
                     "x offset 90s", "increase(x[1h] offset 2d)"):
            parse(text)

    def test_offset_syntax_errors(self):
        for bad in ("rate(x[5m] offset)", "x offset y",
                    "rate(x[5m] offset offset 1h)"):
            with pytest.raises(ExprError):
                parse(bad)

    def test_quantile_over_time_offset(self):
        db = TSDB(capacity=2048)
        now = T0 + 3600
        for i in range(360):
            t = T0 + i * 10.0
            # old half: values ~100, recent half: values ~1
            db.add("lat", {}, 100.0 if t < T0 + 1800 else 1.0,
                   "gauge", t)
        (recent,) = evaluate(db, "quantile_over_time(0.5, lat[20m])",
                             now)
        (old,) = evaluate(
            db, "quantile_over_time(0.5, lat[20m] offset 40m)", now
        )
        assert recent[1] == pytest.approx(1.0)
        assert old[1] == pytest.approx(100.0)


# ---------------------------------------------------------------------------
# chaos e2e: telemetry from train workers with ZERO polls
# ---------------------------------------------------------------------------


VARIANT = {
    "id": "pushlc",
    "engineFactory": "sample_engine.Engine0Factory",
    "datasource": {"params": {"id": 1}},
    "preparator": {"params": {"id": 2}},
    "algorithms": [{"name": "algo0", "params": {"id": 3}}],
    "serving": {},
}

SLOW_VARIANT = {
    "id": "pushslow",
    "engineFactory": "sample_engine.SlowEngineFactory",
    "datasource": {"params": {"id": 1, "sleep_s": 30.0}},
    "preparator": {"params": {"id": 2}},
    "algorithms": [{"name": "", "params": {"id": 3}}],
}


def _scheduler_config(tmp_path, push_url, **kw) -> SchedulerConfig:
    cfg = SchedulerConfig(
        poll_interval_s=0.1,
        heartbeat_interval_s=0.2,
        stale_after_s=1.0,
        log_dir=str(tmp_path / "job-logs"),
        child_env={
            "PYTHONPATH": os.pathsep.join([REPO_DIR, TESTS_DIR]),
            "JAX_PLATFORMS": "cpu",
            "PIO_PUSH_URL": push_url,
            "PIO_PUSH_INTERVAL_S": "0.2",
        },
    )
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


class TestTrainWorkerPushE2E:
    def test_worker_exit_before_any_scrape_lands_telemetry(
        self, fresh_storage, tmp_path
    ):
        """No scraper anywhere: the worker's train.* spans, stage series
        and devprof land purely via push (its own shipper plus the
        supervisor's residue pass)."""
        srv, base = _start_ingest_server()
        try:
            q = JobQueue(fresh_storage)
            job = q.submit(VARIANT)
            sched = TrainScheduler(
                fresh_storage, _scheduler_config(tmp_path, base)
            )
            assert sched.run_pending_once() == 1
            assert q.get(job.id).status == "completed"

            mon = srv.monitor
            # stage series arrived tagged with the worker identity
            stage = mon.tsdb.matching(
                "train_stage_seconds_count", {"job_id": job.id}
            )
            assert stage, "no train stage series pushed"
            stages = {s.labels_dict()["stage"] for s in stage}
            assert {"read", "prepare", "train"} <= stages
            instance = stage[0].labels_dict()["instance"]
            # freshness series + push_status row for the dead worker
            assert mon.tsdb.matching(
                "telemetry_last_push_age_seconds", {"instance": instance}
            )
            assert any(
                r["instance"] == instance for r in mon.push_status()
            )
            # spans assembled by the collector with ZERO polls
            st = mon.collector.status()
            assert st["polls"] == 0 and st["pushed_spans"] > 0
            rows = mon.collector.summaries()
            train_rows = [r for r in rows if r["root"] == "train"]
            assert train_rows and train_rows[0]["kept"] == "pushed"
            assert train_rows[0]["spans"] >= 4  # root + DASE stages
        finally:
            srv.shutdown()
            srv.server_close()

    def test_sigkilled_worker_spool_shipped_by_supervisor(
        self, fresh_storage, tmp_path
    ):
        """kill -9 mid-train: the worker never flushes; its durable
        spool is shipped by the next scheduler's orphan sweep — and the
        receiver never polled anything."""
        # reserve a port with NO listener: the worker's own ship
        # attempts all fail, so batches stay durably spooled
        port = _free_port()
        base = f"http://127.0.0.1:{port}"
        q = JobQueue(fresh_storage)
        job = q.submit(SLOW_VARIANT, max_attempts=1)
        cfg = _scheduler_config(tmp_path, base)
        sched1 = TrainScheduler(fresh_storage, cfg)
        sched1.start()
        spool_dir = os.path.join(
            str(tmp_path / "job-logs"), f"{job.id}.spool"
        )
        try:
            _wait_for(
                lambda: q.get(job.id).status == "running",
                timeout=30, what="job to start",
            )
            _wait_for(
                lambda: os.path.isdir(spool_dir) and os.listdir(spool_dir),
                timeout=30, what="worker to spool telemetry",
            )
        finally:
            sched1.stop(kill_child=True)  # SIGKILL, no exit flush
        assert os.listdir(spool_dir), "expected an orphaned spool"

        # the receiver comes up AFTER the worker died
        srv, _ = _start_ingest_server(port=port)
        try:
            sched2 = TrainScheduler(fresh_storage, cfg)
            assert sched2.ship_orphan_spools() >= 1
            assert not os.path.exists(spool_dir)  # drained + removed

            mon = srv.monitor
            assert mon.tsdb.matching(
                "telemetry_last_push_age_seconds"
            ), "no pushed series from the dead worker"
            assert mon.push_status(), "ingest saw no instance"
            assert mon.collector.status()["polls"] == 0
        finally:
            srv.shutdown()
            srv.server_close()

    def test_supervisor_skips_live_worker_spools(
        self, fresh_storage, tmp_path, monkeypatch
    ):
        """The orphan sweep must not steal a LIVE worker's spool."""
        sched = TrainScheduler(
            fresh_storage,
            _scheduler_config(tmp_path, "http://127.0.0.1:1"),
        )
        os.makedirs(sched._log_dir, exist_ok=True)
        live = os.path.join(sched._log_dir, "livejob.spool")
        os.makedirs(live)
        with open(os.path.join(live, "000-1-0001.json"), "w") as f:
            json.dump(build_payload("w", now=T0), f)
        with sched._child_lock:
            sched._children["livejob"] = object()
        try:
            assert sched.ship_orphan_spools() == 0
            assert os.listdir(live)
        finally:
            with sched._child_lock:
                sched._children.pop("livejob", None)
