"""Client-server storage integration: separate OS processes sharing one
app through the storage daemon — the deployment topology the reference
gets from HBase/Postgres (Storage.scala:140-142: state is shared ONLY
through the storage layer).

Covers VERDICT r1 #2: two-process sharing, env-var wiring of the `remote`
backend, and event-server ingestion through a remote storage client."""

import json
import os
import socket
import subprocess
import sys
import textwrap
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_health(port: int, timeout: float = 20.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=2
            ) as r:
                if r.status == 200:
                    return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError(f"storage server on :{port} never became healthy")


def _remote_env(tmp_path, port: int) -> dict:
    env = dict(os.environ)
    env.update(
        {
            "PYTHONPATH": str(REPO) + os.pathsep + env.get("PYTHONPATH", ""),
            "PIO_STORAGE_SOURCES_RMT_TYPE": "remote",
            "PIO_STORAGE_SOURCES_RMT_HOST": "127.0.0.1",
            "PIO_STORAGE_SOURCES_RMT_PORT": str(port),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "RMT",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "RMT",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "RMT",
        }
    )
    return env


@pytest.fixture()
def daemon(tmp_path):
    """Storage daemon as a real OS process backed by sqlite+localfs."""
    port = _free_port()
    env = dict(os.environ)
    env.update(
        {
            "PYTHONPATH": str(REPO) + os.pathsep + env.get("PYTHONPATH", ""),
            "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "shared.db"),
            "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_FS_PATH": str(tmp_path / "models"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        }
    )
    proc = subprocess.Popen(
        [
            sys.executable, "-m",
            "predictionio_tpu.data.api.storage_server",
            "--host", "127.0.0.1", "--port", str(port),
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
    )
    try:
        _wait_health(port)
        yield port
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def _run(code: str, env: dict) -> str:
    out = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(code)],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, f"child failed:\n{out.stdout}\n{out.stderr}"
    return out.stdout


def test_two_processes_share_one_app(daemon, tmp_path):
    """Writer process creates the app + events; a separate reader process
    sees them — state crosses OS process boundaries only via the daemon."""
    env = _remote_env(tmp_path, daemon)
    writer = _run(
        """
        import datetime as dt
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage.base import App, Model
        from predictionio_tpu.data.storage.registry import Storage

        s = Storage()
        app_id = s.get_meta_data_apps().insert(App(0, "sharedapp"))
        ev = s.get_events()
        ev.init_app(app_id)
        t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        ev.insert_batch(
            [
                Event(event="buy", entity_type="user", entity_id=f"u{i}",
                      target_entity_type="item", target_entity_id=f"i{i % 3}",
                      properties={"qty": i}, event_time=t0)
                for i in range(20)
            ],
            app_id,
        )
        s.get_model_data_models().insert(Model("modelX", b"\\x00blob\\xff"))
        print(app_id)
        """,
        env,
    )
    app_id = int(writer.strip().splitlines()[-1])

    reader = _run(
        f"""
        import json
        from predictionio_tpu.data.storage.base import EventQuery
        from predictionio_tpu.data.storage.registry import Storage

        s = Storage()
        app = s.get_meta_data_apps().get_by_name("sharedapp")
        assert app is not None and app.id == {app_id}
        events = list(s.get_events().find(EventQuery(app_id={app_id})))
        blob = s.get_model_data_models().get("modelX").models
        print(json.dumps({{
            "n": len(events),
            "qty_sum": sum(e.properties.get("qty") for e in events),
            "blob_ok": blob == b"\\x00blob\\xff",
        }}))
        """,
        env,
    )
    result = json.loads(reader.strip().splitlines()[-1])
    assert result == {"n": 20, "qty_sum": sum(range(20)), "blob_ok": True}


def test_event_server_ingests_through_remote_storage(daemon, tmp_path):
    """The ingestion REST server runs against a remote-backed Storage: a
    POST lands in the daemon's sqlite, visible to any other process."""
    from predictionio_tpu.data.api.server import EventServer, EventServerConfig
    from predictionio_tpu.data.storage.base import AccessKey, App, EventQuery
    from predictionio_tpu.data.storage.registry import Storage, StorageConfig

    env = _remote_env(tmp_path, daemon)
    storage = Storage(StorageConfig.from_env(env))
    app_id = storage.get_meta_data_apps().insert(App(0, "ingest"))
    storage.get_events().init_app(app_id)
    storage.get_meta_data_access_keys().insert(
        AccessKey(key="RKEY", app_id=app_id)
    )
    srv = EventServer(storage, EventServerConfig(ip="127.0.0.1", port=0))
    port = srv.start()
    try:
        body = json.dumps(
            {
                "event": "view", "entityType": "user", "entityId": "u9",
                "targetEntityType": "item", "targetEntityId": "i1",
            }
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/events.json?accessKey=RKEY",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
    finally:
        srv.stop()

    # a SECOND process reads the ingested event back through the daemon
    reader = _run(
        f"""
        from predictionio_tpu.data.storage.base import EventQuery
        from predictionio_tpu.data.storage.registry import Storage

        s = Storage()
        evs = list(s.get_events().find(EventQuery(app_id={app_id})))
        assert len(evs) == 1 and evs[0].entity_id == "u9", evs
        print("OK")
        """,
        env,
    )
    assert reader.strip().endswith("OK")
