"""Client-server storage integration: separate OS processes sharing one
app through the storage daemon — the deployment topology the reference
gets from HBase/Postgres (Storage.scala:140-142: state is shared ONLY
through the storage layer).

Covers VERDICT r1 #2: two-process sharing, env-var wiring of the `remote`
backend, and event-server ingestion through a remote storage client."""

import json
import os
import socket
import subprocess
import sys
import textwrap
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_health(port: int, timeout: float = 20.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=2
            ) as r:
                if r.status == 200:
                    return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError(f"storage server on :{port} never became healthy")


def _remote_env(tmp_path, port: int) -> dict:
    env = dict(os.environ)
    env.update(
        {
            "PYTHONPATH": str(REPO) + os.pathsep + env.get("PYTHONPATH", ""),
            "PIO_STORAGE_SOURCES_RMT_TYPE": "remote",
            "PIO_STORAGE_SOURCES_RMT_HOST": "127.0.0.1",
            "PIO_STORAGE_SOURCES_RMT_PORT": str(port),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "RMT",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "RMT",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "RMT",
        }
    )
    return env


@pytest.fixture()
def daemon(tmp_path):
    """Storage daemon as a real OS process backed by sqlite+localfs."""
    port = _free_port()
    env = dict(os.environ)
    env.update(
        {
            "PYTHONPATH": str(REPO) + os.pathsep + env.get("PYTHONPATH", ""),
            "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "shared.db"),
            "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_FS_PATH": str(tmp_path / "models"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        }
    )
    proc = subprocess.Popen(
        [
            sys.executable, "-m",
            "predictionio_tpu.data.api.storage_server",
            "--host", "127.0.0.1", "--port", str(port),
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
    )
    try:
        _wait_health(port)
        yield port
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def _run(code: str, env: dict) -> str:
    out = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(code)],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, f"child failed:\n{out.stdout}\n{out.stderr}"
    return out.stdout


def test_two_processes_share_one_app(daemon, tmp_path):
    """Writer process creates the app + events; a separate reader process
    sees them — state crosses OS process boundaries only via the daemon."""
    env = _remote_env(tmp_path, daemon)
    writer = _run(
        """
        import datetime as dt
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage.base import App, Model
        from predictionio_tpu.data.storage.registry import Storage

        s = Storage()
        app_id = s.get_meta_data_apps().insert(App(0, "sharedapp"))
        ev = s.get_events()
        ev.init_app(app_id)
        t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        ev.insert_batch(
            [
                Event(event="buy", entity_type="user", entity_id=f"u{i}",
                      target_entity_type="item", target_entity_id=f"i{i % 3}",
                      properties={"qty": i}, event_time=t0)
                for i in range(20)
            ],
            app_id,
        )
        s.get_model_data_models().insert(Model("modelX", b"\\x00blob\\xff"))
        print(app_id)
        """,
        env,
    )
    app_id = int(writer.strip().splitlines()[-1])

    reader = _run(
        f"""
        import json
        from predictionio_tpu.data.storage.base import EventQuery
        from predictionio_tpu.data.storage.registry import Storage

        s = Storage()
        app = s.get_meta_data_apps().get_by_name("sharedapp")
        assert app is not None and app.id == {app_id}
        events = list(s.get_events().find(EventQuery(app_id={app_id})))
        blob = s.get_model_data_models().get("modelX").models
        print(json.dumps({{
            "n": len(events),
            "qty_sum": sum(e.properties.get("qty") for e in events),
            "blob_ok": blob == b"\\x00blob\\xff",
        }}))
        """,
        env,
    )
    result = json.loads(reader.strip().splitlines()[-1])
    assert result == {"n": 20, "qty_sum": sum(range(20)), "blob_ok": True}


def test_event_server_ingests_through_remote_storage(daemon, tmp_path):
    """The ingestion REST server runs against a remote-backed Storage: a
    POST lands in the daemon's sqlite, visible to any other process."""
    from predictionio_tpu.data.api.server import EventServer, EventServerConfig
    from predictionio_tpu.data.storage.base import AccessKey, App, EventQuery
    from predictionio_tpu.data.storage.registry import Storage, StorageConfig

    env = _remote_env(tmp_path, daemon)
    storage = Storage(StorageConfig.from_env(env))
    app_id = storage.get_meta_data_apps().insert(App(0, "ingest"))
    storage.get_events().init_app(app_id)
    storage.get_meta_data_access_keys().insert(
        AccessKey(key="RKEY", app_id=app_id)
    )
    srv = EventServer(storage, EventServerConfig(ip="127.0.0.1", port=0))
    port = srv.start()
    try:
        body = json.dumps(
            {
                "event": "view", "entityType": "user", "entityId": "u9",
                "targetEntityType": "item", "targetEntityId": "i1",
            }
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/events.json?accessKey=RKEY",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
    finally:
        srv.stop()

    # a SECOND process reads the ingested event back through the daemon
    reader = _run(
        f"""
        from predictionio_tpu.data.storage.base import EventQuery
        from predictionio_tpu.data.storage.registry import Storage

        s = Storage()
        evs = list(s.get_events().find(EventQuery(app_id={app_id})))
        assert len(evs) == 1 and evs[0].entity_id == "u9", evs
        print("OK")
        """,
        env,
    )
    assert reader.strip().endswith("OK")


# ---------------------------------------------------------------------------
# ADVICE r2 hardening: paging, precision, ping, retry idempotency
# ---------------------------------------------------------------------------


def _inproc_server(tmp_path, **kw):
    from predictionio_tpu.data.api.storage_server import StorageServer
    from predictionio_tpu.data.storage.registry import (
        SourceConfig,
        Storage,
        StorageConfig,
    )

    cfg = StorageConfig(
        sources={
            "SQL": SourceConfig(
                "SQL", "sqlite", {"PATH": str(tmp_path / "paged.db")}
            ),
        },
        repositories={
            "METADATA": "SQL", "EVENTDATA": "SQL", "MODELDATA": "SQL",
        },
    )
    return StorageServer(Storage(cfg), host="127.0.0.1", port=0, **kw).start()


def test_find_pages_across_rpc_calls(tmp_path):
    """A result set larger than the server page limit arrives complete and
    in order, via multiple RPC round trips (ADVICE r2: the find RPC must not
    materialize train-scale reads as one JSON body)."""
    import datetime as dt

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.base import EventQuery
    from predictionio_tpu.data.storage.remote import RemoteEventStore

    server = _inproc_server(tmp_path, find_page_size=7)
    try:
        store = RemoteEventStore({"HOST": "127.0.0.1", "PORT": str(server.port)})
        store.init_app(1)
        base_t = dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc)
        events = [
            Event(event="view", entity_type="user", entity_id=f"u{i:03d}",
                  event_time=base_t + dt.timedelta(seconds=i))
            for i in range(25)
        ]
        store.insert_batch(events, 1)

        calls = {"n": 0}
        orig_call = store._client.call

        def counting_call(dao, method, *a, **kw):
            if method == "find":
                calls["n"] += 1
            return orig_call(dao, method, *a, **kw)

        store._client.call = counting_call
        got = list(store.find(EventQuery(app_id=1)))
        assert [e.entity_id for e in got] == [f"u{i:03d}" for i in range(25)]
        assert calls["n"] == 4  # ceil(25/7) pages

        # query.limit is respected across pages
        calls["n"] = 0
        got = list(store.find(EventQuery(app_id=1, limit=10)))
        assert len(got) == 10
        assert calls["n"] == 2
    finally:
        server.shutdown()


def test_event_datetimes_roundtrip_microseconds(tmp_path):
    """Wire codec keeps microsecond precision (ADVICE r2: the public JSON
    form truncates to ms; the storage RPC must not)."""
    import datetime as dt

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage import wire

    t = dt.datetime(2021, 6, 1, 12, 0, 0, 123456, tzinfo=dt.timezone.utc)
    e = Event(event="buy", entity_type="user", entity_id="u1",
              event_time=t, creation_time=t)
    rt = wire.decode(wire.encode(e))
    assert rt.event_time == t
    assert rt.creation_time == t


def test_ping_validates_health_response(tmp_path):
    """ping() is only true for a real storage daemon answering 200 with the
    health JSON — not for any listener that happens to answer (ADVICE r2)."""
    import http.server
    import threading

    from predictionio_tpu.data.storage.remote import RemoteClient

    server = _inproc_server(tmp_path)
    try:
        good = RemoteClient({"HOST": "127.0.0.1", "PORT": str(server.port)})
        assert good.ping() is True
    finally:
        server.shutdown()

    class NotFound(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = b"nope"
            self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    impostor = http.server.HTTPServer(("127.0.0.1", 0), NotFound)
    t = threading.Thread(target=impostor.serve_forever, daemon=True)
    t.start()
    try:
        bad = RemoteClient(
            {"HOST": "127.0.0.1", "PORT": str(impostor.server_address[1])}
        )
        assert bad.ping() is False
    finally:
        impostor.shutdown()

    dead = RemoteClient({"HOST": "127.0.0.1", "PORT": str(_free_port())})
    assert dead.ping() is False


def test_lost_response_insert_dedupes_on_retry(tmp_path):
    """A response-phase failure on insert retries with the same request id;
    the server replays the recorded outcome instead of applying the write
    twice (ADVICE r2 medium: non-idempotent RPCs must not duplicate)."""
    import http.client

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.base import EventQuery
    from predictionio_tpu.data.storage.remote import RemoteClient, RemoteEventStore

    server = _inproc_server(tmp_path)
    try:
        store = RemoteEventStore({"HOST": "127.0.0.1", "PORT": str(server.port)})
        store.init_app(1)

        class FlakyResponseConn:
            """Delivers the request (server applies it), then dies before
            the response arrives."""

            def __init__(self, real):
                self.real = real

            def request(self, *a, **kw):
                self.real.request(*a, **kw)

            def getresponse(self):
                self.real.getresponse().read()  # drain the real response
                raise http.client.HTTPException("connection lost mid-response")

            def close(self):
                self.real.close()

        client: RemoteClient = store._client
        real_conn = client._conn()
        client._local.conn = FlakyResponseConn(real_conn)

        e = Event(event="buy", entity_type="user", entity_id="once")
        eid = store.insert(e, 1)  # applied once; retry replays the outcome

        got = list(store.find(EventQuery(app_id=1)))
        assert len(got) == 1 and got[0].entity_id == "once"
        assert got[0].event_id == eid  # the replayed id is the applied one
    finally:
        server.shutdown()


def test_stale_keepalive_insert_retries_safely(tmp_path):
    """A zero-byte failure on a REUSED keep-alive socket means the server
    idle-closed before the request arrived — the client must retry even a
    non-idempotent insert (code-review r3: send() is buffered, so the stale
    socket surfaces in getresponse, not conn.request)."""
    import http.client

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.base import EventQuery
    from predictionio_tpu.data.storage.remote import RemoteEventStore

    server = _inproc_server(tmp_path)
    try:
        store = RemoteEventStore({"HOST": "127.0.0.1", "PORT": str(server.port)})
        store.init_app(1)  # also warms the keep-alive connection

        class IdleClosedConn:
            """Reused socket the server closed: the request never arrives,
            getresponse sees zero bytes."""

            def request(self, *a, **kw):
                pass  # written into a dead socket — not delivered

            def getresponse(self):
                raise http.client.RemoteDisconnected(
                    "Remote end closed connection without response"
                )

            def close(self):
                pass

        client = store._client
        client._local.conn = IdleClosedConn()  # reused → fresh=False

        e = Event(event="buy", entity_type="user", entity_id="retry-me")
        store.insert(e, 1)  # retries transparently on a fresh socket

        got = list(store.find(EventQuery(app_id=1)))
        assert len(got) == 1 and got[0].entity_id == "retry-me"
    finally:
        server.shutdown()


def test_paged_find_stable_under_concurrent_inserts(tmp_path):
    """Keyset continuation: rows inserted between page RPCs neither shift
    events into duplication nor skip them (code-review r3: offset pages are
    not snapshot-stable under mutation)."""
    import datetime as dt

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.base import EventQuery
    from predictionio_tpu.data.storage.remote import RemoteEventStore

    server = _inproc_server(tmp_path, find_page_size=5)
    try:
        store = RemoteEventStore({"HOST": "127.0.0.1", "PORT": str(server.port)})
        store.init_app(1)
        base_t = dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc)
        store.insert_batch(
            [
                Event(event="view", entity_type="user", entity_id=f"u{i:03d}",
                      event_time=base_t + dt.timedelta(seconds=i))
                for i in range(17)
            ],
            1,
        )

        orig_call = store._client.call
        page_no = {"n": 0}

        def interfering_call(dao, method, *a, **kw):
            result = orig_call(dao, method, *a, **kw)
            if method == "find":
                page_no["n"] += 1
                if page_no["n"] == 1:
                    # concurrent writer lands an EARLIER-timestamped event
                    # between page 1 and page 2 — with offset paging this
                    # would duplicate the page-1 boundary event
                    orig_call(
                        "events", "insert",
                        Event(event="view", entity_type="user",
                              entity_id="early-bird",
                              event_time=base_t - dt.timedelta(hours=1)),
                        1, None,
                    )
            return result

        store._client.call = interfering_call
        got = [e.entity_id for e in store.find(EventQuery(app_id=1))]
        # no duplicates, and every pre-scan event is present exactly once
        assert len(got) == len(set(got))
        assert {f"u{i:03d}" for i in range(17)} <= set(got)
    finally:
        server.shutdown()


def test_find_pages_reversed_keyset(tmp_path):
    """Reversed scans page by keyset too — descending order is preserved
    across page boundaries (code-review r3)."""
    import datetime as dt

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.base import EventQuery
    from predictionio_tpu.data.storage.remote import RemoteEventStore

    server = _inproc_server(tmp_path, find_page_size=7)
    try:
        store = RemoteEventStore({"HOST": "127.0.0.1", "PORT": str(server.port)})
        store.init_app(1)
        base_t = dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc)
        store.insert_batch(
            [
                Event(event="view", entity_type="user", entity_id=f"u{i:03d}",
                      event_time=base_t + dt.timedelta(seconds=i))
                for i in range(25)
            ],
            1,
        )
        got = [e.entity_id for e in store.find(EventQuery(app_id=1, reversed=True))]
        assert got == [f"u{i:03d}" for i in reversed(range(25))]
    finally:
        server.shutdown()


def test_concurrent_same_req_id_applies_once(tmp_path):
    """Concurrent retries with one req_id (client timeout + retry while the
    first attempt is still executing) apply the write once: later arrivals
    wait for the in-flight first attempt instead of racing it."""
    import concurrent.futures
    import http.client as hc
    import json as _json

    from predictionio_tpu.data.storage.base import EventQuery
    from predictionio_tpu.data.storage.remote import RemoteEventStore
    from predictionio_tpu.data.storage import wire
    from predictionio_tpu.data.event import Event

    server = _inproc_server(tmp_path)
    try:
        store = RemoteEventStore({"HOST": "127.0.0.1", "PORT": str(server.port)})
        store.init_app(1)
        e = Event(event="buy", entity_type="user", entity_id="racer")
        body = _json.dumps({
            "dao": "events", "method": "insert", "req_id": "fixed-req-id",
            "args": [wire.encode(e), 1, None], "kwargs": {},
        }).encode()

        def fire(_):
            conn = hc.HTTPConnection("127.0.0.1", server.port, timeout=30)
            conn.request("POST", "/rpc", body=body,
                         headers={"Content-Type": "application/json"})
            resp = _json.loads(conn.getresponse().read())
            conn.close()
            return resp

        with concurrent.futures.ThreadPoolExecutor(max_workers=20) as ex:
            results = list(ex.map(fire, range(20)))

        ids = {r["result"] for r in results if r["ok"]}
        assert len(ids) == 1  # every response replays the same applied id
        got = list(store.find(EventQuery(app_id=1)))
        assert len(got) == 1 and got[0].entity_id == "racer"
    finally:
        server.shutdown()
