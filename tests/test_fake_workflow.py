"""FakeWorkflow harness (reference FakeWorkflow.scala:25-106): arbitrary
function under the eval environment, nothing persisted."""

import numpy as np

from predictionio_tpu.workflow.fake import FakeEvalResult, run_fake_workflow


def test_runs_fn_under_eval_context(fresh_storage):
    seen = {}

    def probe(ctx):
        seen["mode"] = ctx.mode
        seen["has_storage"] = ctx.storage is not None
        # real device work is fine inside the harness
        return float(np.square(np.arange(4)).sum())

    result = run_fake_workflow(probe, storage=fresh_storage)
    assert isinstance(result, FakeEvalResult)
    assert result.no_save
    assert result.value == 14.0
    assert seen == {"mode": "eval", "has_storage": True}
    assert "FakeEvalResult" in result.to_one_liner()
    assert "14.0" in result.to_json()


def test_nothing_persisted(fresh_storage):
    before = fresh_storage.get_meta_data_evaluation_instances().get_all()
    run_fake_workflow(lambda ctx: "hello", storage=fresh_storage)
    after = fresh_storage.get_meta_data_evaluation_instances().get_all()
    assert len(before) == len(after) == 0


def test_mesh_flows_through(mesh8):
    def probe(ctx):
        return ctx.mesh.devices.size

    assert run_fake_workflow(probe, mesh=mesh8).value == 8


def test_exceptions_propagate(fresh_storage):
    import pytest

    def boom(ctx):
        raise RuntimeError("bad fn")

    with pytest.raises(RuntimeError, match="bad fn"):
        run_fake_workflow(boom, storage=fresh_storage)
    # still nothing persisted after a failure
    assert fresh_storage.get_meta_data_evaluation_instances().get_all() == []
